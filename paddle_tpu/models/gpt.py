"""GPT — the flagship hybrid-parallel model.

Reference capability anchor: the GPT-3 recipes trained by the reference's
Fleet stack (SURVEY §3.4, §6 — 1.3B/6.7B, TP×PP×DP×sharding), model code
per-op equivalent to paddlenlp GPT (fused attention + FFN blocks).

TPU-native design decisions:
- **scan-over-layers**: transformer blocks are ONE set of parameters stacked
  on a leading [L] axis, iterated with lax.scan — constant compile time in
  depth, and the natural representation for both remat and pipeline stages.
- **TP/SP/EP via PartitionSpecs**: qkv/fc1 column-sharded, proj/fc2
  row-sharded over 'mp'; activations sequence-sharded over 'sep' (Megatron
  SP); MoE experts sharded over the data axis (EP).  GSPMD inserts the
  psum/all-gather/all-to-all the reference implements as mp_ops/global_scatter.
- **PP via distributed.pipeline**: stacked layers reshape to [pp, L/pp, ...]
  and stream through the collective-permute schedule.
- **flash attention**: Pallas kernel on TPU (kernels/flash_attention.py).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.dispatch import apply_op, matmul_precision
from ..core.tensor import Parameter, Tensor
from ..distributed.env import get_mesh, hybrid_degrees
from ..distributed.sharding_utils import annotate_param
from ..kernels import paged_attention as _pa
from ..kernels._shapes import NEG_INF
from ..kernels.flash_attention import flash_attention_fwd, reference_attention
from ..kernels.rope import rope_tables
from ..nn.layer.layers import Layer


class GPTConfig:
    def __init__(self, vocab_size=50304, hidden_size=768, num_layers=12,
                 num_heads=12, max_seq_len=1024, ffn_hidden_size=None,
                 dropout=0.0, attention_dropout=0.0, use_rope=False,
                 layer_norm_epsilon=1e-5, initializer_range=0.02,
                 use_flash_attention=True, recompute=False,
                 sequence_parallel=False, context_parallel=False,
                 num_experts=0, moe_every=2,
                 moe_top_k=2, moe_capacity_factor=1.25,
                 moe_aux_weight=0.01, dtype="float32",
                 tie_word_embeddings=True,
                 pp_schedule="gpipe", virtual_pp_degree=1):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.max_seq_len = max_seq_len
        self.ffn_hidden_size = ffn_hidden_size or 4 * hidden_size
        self.dropout = dropout
        self.attention_dropout = attention_dropout
        self.use_rope = use_rope
        self.layer_norm_epsilon = layer_norm_epsilon
        self.initializer_range = initializer_range
        self.use_flash_attention = use_flash_attention
        self.recompute = recompute
        self.sequence_parallel = sequence_parallel
        # context_parallel: shard the SEQUENCE over the 'sep' mesh axis and
        # run ring attention (kernels/ring_attention.py) — the reference's
        # segment-parallel long-context capability (segment_parallel.py)
        self.context_parallel = context_parallel
        self.num_experts = num_experts
        self.moe_every = moe_every
        self.moe_top_k = moe_top_k
        self.moe_capacity_factor = moe_capacity_factor
        # gate-loss weight folded into the 1F1B objective (the schedule owns
        # the loss there; on GSPMD paths users add moe_aux_loss() manually)
        self.moe_aux_weight = moe_aux_weight
        self.dtype = dtype
        self.tie_word_embeddings = tie_word_embeddings
        # pipeline schedule: 'gpipe' | 'interleaved' (reference:
        # pipeline_parallel.py:1010 VPP) | '1f1b' (reference :459; used via
        # Pipeline1F1BTrainStep, which puts the loss inside the pipeline)
        self.pp_schedule = pp_schedule
        self.virtual_pp_degree = virtual_pp_degree

    # named sizes from the GPT-3 paper / reference recipes
    @staticmethod
    def gpt3_125m(**kw):
        return GPTConfig(hidden_size=768, num_layers=12, num_heads=12, **kw)

    @staticmethod
    def gpt3_350m(**kw):
        return GPTConfig(hidden_size=1024, num_layers=24, num_heads=16, **kw)

    @staticmethod
    def gpt3_760m(**kw):
        # "GPT-3 Large" — the largest config whose AdamW training state
        # (bf16 params + fp32 master + 2 fp32 moments ~ 10.6 GB) fits a
        # single 16G v5e chip with activation headroom
        return GPTConfig(hidden_size=1536, num_layers=24, num_heads=16, **kw)

    @staticmethod
    def gpt3_1_3b(**kw):
        return GPTConfig(hidden_size=2048, num_layers=24, num_heads=16, **kw)

    @staticmethod
    def gpt3_6_7b(**kw):
        return GPTConfig(hidden_size=4096, num_layers=32, num_heads=32, **kw)


def _sel_policy(mode):
    """Remat policy for selective recompute: which checkpoint_name'd
    activations survive to backward (the rest replay)."""
    names = (("qkv", "attn_out") if mode == "selective_lean"
             else ("qkv", "attn_out", "ffn_up"))
    return jax.checkpoint_policies.save_only_these_names(*names)


def _norm(x, w, b, eps):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, -1, keepdims=True)
    var = jnp.var(xf, -1, keepdims=True)
    return ((xf - mean) * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w + b


def _dropout(x, key, p):
    """Inverted dropout (shared by the GSPMD block and the manual-TP
    block so the two paths can never drift numerically)."""
    return jnp.where(jax.random.bernoulli(key, 1 - p, x.shape),
                     x / (1 - p), 0.0).astype(x.dtype)


def _lm_logits(c, wte, lnf_w, lnf_b, head, h_last):
    """Final norm + LM head over the last hidden states (shared by
    ``generate`` and the serving prefill/decode entry points)."""
    h_last = _norm(h_last, lnf_w, lnf_b, c.layer_norm_epsilon)
    w = wte.T if c.tie_word_embeddings else head
    return jnp.matmul(h_last, w,
                      precision=matmul_precision()).astype(jnp.float32)


def _rope_rows(x, pos, base=10000.0):
    """apply_rope for single-token rows ``x[B, 1, nh, hd]`` sitting at
    PER-ROW positions ``pos[B]`` (the serving decode twin of
    ``apply_rope(x, offset=pos)``, whose offset is one scalar)."""
    b, s, h, d = x.shape
    inv = 1.0 / (base ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    freqs = pos.astype(jnp.float32)[:, None] * inv[None, :]  # [B, d/2]
    sin = jnp.sin(freqs)[:, None, None, :]
    cos = jnp.cos(freqs)[:, None, None, :]
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., : d // 2], xf[..., d // 2:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], -1)
    return out.astype(x.dtype)


def _rope_grid(x, pos, base=10000.0):
    """apply_rope for a grid of tokens ``x[B, T, nh, hd]`` sitting at
    arbitrary PER-TOKEN positions ``pos[B, T]`` (the speculative-verify
    twin of ``_rope_rows``: each draft position gets its own rotation)."""
    b, s, h, d = x.shape
    inv = 1.0 / (base ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    freqs = pos.astype(jnp.float32)[..., None] * inv  # [B, T, d/2]
    sin = jnp.sin(freqs)[:, :, None, :]
    cos = jnp.cos(freqs)[:, :, None, :]
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., : d // 2], xf[..., d // 2:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], -1)
    return out.astype(x.dtype)


def _mm(x, lw, name):
    """Layer matmul against a decode-state weight that may be int8
    weight-only quantized (``quantization.ptq_int8_decode_state`` stores
    ``name`` as int8 plus ``name + "__scale"`` fp32 per-output-channel).
    Per-output-channel scales commute with the contraction, so dequant is
    one row-vector multiply AFTER the matmul — the int8 weight is cast
    (exact: |q| <= 127 fits every float dtype) as it is loaded, never
    rematerialized in full precision in HBM."""
    w = lw[name]
    s = lw.get(name + "__scale")
    if s is None:
        return jnp.matmul(x, w, precision=matmul_precision())
    y = jnp.matmul(x, w.astype(x.dtype), precision=matmul_precision())
    return (y * s).astype(x.dtype)


def _mm_lora(x, lw, name, al, aids):
    """:func:`_mm` plus the gathered batched low-rank update (S-LoRA /
    Punica): ``y + x @ A[ids] @ B[ids]`` where ``al`` holds this layer's
    adapter slabs ``a_<name> [n_slots, d_in, R]`` / ``b_<name>
    [n_slots, R, d_out]`` and ``aids [B]`` is the per-row int32 adapter
    slot — an OPERAND, so one compiled program serves any tenant mix.
    Slot 0 is the base model: its slab rows are zeros AND the row's
    output is selected from the un-adapted ``y`` itself (not ``y + 0``),
    so base rows are bitwise identical to an adapter-free program.
    Composes with the int8 epilogue untouched — the low-rank branch runs
    beside whatever ``_mm`` produced."""
    y = _mm(x, lw, name)
    if al is None:
        return y
    prec = matmul_precision()
    ag = al["a_" + name][aids]                        # [B, d_in, R]
    bg = al["b_" + name][aids]                        # [B, R, d_out]
    d = jnp.einsum("bti,bir->btr", x, ag, precision=prec)
    d = jnp.einsum("btr,bro->bto", d, bg, precision=prec)
    return jnp.where((aids > 0)[:, None, None], y + d.astype(y.dtype), y)


class GPTForCausalLM(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = c = config
        import numpy as np
        from ..nn.initializer import Normal, Constant
        from ..nn.functional.init_utils import param_attr_init
        H, L, V, S = c.hidden_size, c.num_layers, c.vocab_size, c.max_seq_len
        F = c.ffn_hidden_size
        init = Normal(0.0, c.initializer_range)
        zeros = Constant(0.0)
        ones = Constant(1.0)
        dt = c.dtype

        def mk(shape, ini, spec):
            p = param_attr_init(shape, jnp.dtype(dt), None, False, ini)
            annotate_param(p, spec)
            return p

        self.wte = mk((V, H), init, P("mp", None))
        if not c.use_rope:
            self.wpe = mk((S, H), init, P())
        self.ln1_w = mk((L, H), ones, P())
        self.ln1_b = mk((L, H), zeros, P())
        self.qkv_w = mk((L, H, 3 * H), init, P(None, None, "mp"))
        self.qkv_b = mk((L, 3 * H), zeros, P(None, "mp"))
        self.proj_w = mk((L, H, H), init, P(None, "mp", None))
        self.proj_b = mk((L, H), zeros, P())
        self.ln2_w = mk((L, H), ones, P())
        self.ln2_b = mk((L, H), zeros, P())
        if c.num_experts > 0:
            E = c.num_experts
            # expert dim shards over 'dp' (EP) only when divisible
            ep = "dp" if E % max(hybrid_degrees().get("dp", 1), 1) == 0 \
                else None
            self.gate_w = mk((L, H, E), init, P())
            self.fc1_w = mk((L, E, H, F), init, P(None, ep, None, "mp"))
            self.fc1_b = mk((L, E, F), zeros, P(None, ep, "mp"))
            self.fc2_w = mk((L, E, F, H), init, P(None, ep, "mp", None))
            self.fc2_b = mk((L, E, H), zeros, P(None, ep, None))
            self._moe_ep_spec = ep
        else:
            self.fc1_w = mk((L, H, F), init, P(None, None, "mp"))
            self.fc1_b = mk((L, F), zeros, P(None, "mp"))
            self.fc2_w = mk((L, F, H), init, P(None, "mp", None))
            self.fc2_b = mk((L, H), zeros, P())
        self.lnf_w = mk((H,), ones, P())
        self.lnf_b = mk((H,), zeros, P())
        if not c.tie_word_embeddings:
            self.lm_head = mk((H, V), init, P(None, "mp"))

    # -- pure block ----------------------------------------------------------
    def _block_fn(self, c, training, dkey):
        from jax.ad_checkpoint import checkpoint_name
        eps = c.layer_norm_epsilon
        nh = c.num_heads
        use_flash = c.use_flash_attention

        def attention(h, lw):
            b, s, H = h.shape
            hd = H // nh
            qkv = jnp.matmul(h, lw["qkv_w"], precision=matmul_precision()) \
                + lw["qkv_b"]
            qkv = checkpoint_name(qkv, "qkv")
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(b, s, nh, hd)
            k = k.reshape(b, s, nh, hd)
            v = v.reshape(b, s, nh, hd)
            if c.use_rope:
                from ..kernels.rope import apply_rope
                q = apply_rope(q)
                k = apply_rope(k)
            if c.context_parallel and hybrid_degrees().get("sep", 1) > 1:
                from ..kernels.ring_attention import ring_attention
                o = ring_attention(q, k, v, causal=True)
            elif use_flash:
                o = flash_attention_fwd(q, k, v, causal=True)
            else:
                o = reference_attention(q, k, v, causal=True)
            o = checkpoint_name(o.reshape(b, s, H), "attn_out")
            return jnp.matmul(o, lw["proj_w"], precision=matmul_precision()) \
                + lw["proj_b"]

        def ffn(h, lw):
            if c.num_experts > 0:
                # real top-k expert dispatch (EP): GShard one-hot
                # dispatch/combine einsums over a static capacity; the
                # expert dim is sharded over 'dp', so GSPMD inserts the
                # token all-to-all (the reference's global_scatter/
                # global_gather, moe/moe_layer.py:263).  Compute is
                # O(top_k) per token, not O(E).
                from ..incubate.moe import moe_ffn
                return moe_ffn(
                    h, lw["gate_w"], lw["fc1_w"], lw["fc1_b"],
                    lw["fc2_w"], lw["fc2_b"], top_k=c.moe_top_k,
                    capacity_factor=c.moe_capacity_factor,
                    ep_spec=getattr(self, "_moe_ep_spec", None))
            up = jnp.matmul(h, lw["fc1_w"], precision=matmul_precision()) \
                + lw["fc1_b"]
            up = checkpoint_name(up, "ffn_up")
            act = jax.nn.gelu(up)
            out = jnp.matmul(act, lw["fc2_w"],
                             precision=matmul_precision()) + lw["fc2_b"]
            return out, None

        drop = c.dropout if training else 0.0

        def block(h, lw_and_key):
            """Returns (h, aux): aux is the MoE load-balancing loss for this
            layer (None for dense FFN)."""
            lw, key = lw_and_key
            x = _norm(h, lw["ln1_w"], lw["ln1_b"], eps)
            a = attention(x, lw)
            if drop > 0:
                key, k1 = jax.random.split(key)
                a = _dropout(a, k1, drop)
            h = h + a
            x = _norm(h, lw["ln2_w"], lw["ln2_b"], eps)
            f, aux = ffn(x, lw)
            if drop > 0:
                key, k2 = jax.random.split(key)
                f = _dropout(f, k2, drop)
            h = h + f
            if c.sequence_parallel:
                mesh = get_mesh()
                if mesh is not None and isinstance(h, jax.core.Tracer):
                    h = jax.lax.with_sharding_constraint(
                        h, jax.sharding.NamedSharding(
                            mesh, P(("dp", "sharding"), "sep", None)))
            return h, aux

        return block

    def _stacked(self):
        names = ["ln1_w", "ln1_b", "qkv_w", "qkv_b", "proj_w", "proj_b",
                 "ln2_w", "ln2_b", "fc1_w", "fc1_b", "fc2_w", "fc2_b"]
        if self.config.num_experts > 0:
            names.append("gate_w")
        return names

    def forward(self, input_ids, position_ids=None):
        c = self.config
        training = self.training
        names = self._stacked()
        params = [getattr(self, n) for n in names]
        from ..tensor.random import _next_key
        dkey = _next_key() if (training and c.dropout > 0) else None
        pp = hybrid_degrees().get("pp", 1)

        def fn(ids, wte, lnf_w, lnf_b, *rest, head_w=None):
            L = c.num_layers
            if c.use_rope:
                wpe = None
                stacked = rest
            else:
                wpe = rest[0]
                stacked = rest[1:]
            lws = dict(zip(names, stacked))
            h = jnp.take(wte, ids, axis=0)
            if wpe is not None:
                pos = jnp.arange(ids.shape[1])
                h = h + jnp.take(wpe, pos, axis=0)
            block = self._block_fn(c, training, dkey)
            keys = (jax.random.split(dkey, L) if dkey is not None
                    else jnp.zeros((L, 2), jnp.uint32))

            if pp > 1:
                from ..distributed.pipeline import pipeline_apply
                V = (c.virtual_pp_degree
                     if c.pp_schedule == "interleaved" else 1)
                n_stage = pp * max(V, 1)
                if L % n_stage != 0:
                    raise ValueError(
                        f"pipeline parallel requires num_layers ({L}) "
                        f"divisible by pp*virtual_pp ({n_stage})")
                lpp = L // n_stage

                moe = c.num_experts > 0

                def stage_fn(sp, hh):
                    # aux (MoE load-balancing loss) rides the pipeline via
                    # pipeline_apply(with_aux=True) instead of being dropped;
                    # per-layer dropout keys travel in sp ('__keys') so each
                    # layer gets an independent mask (matching the pp=1 scan)
                    def body(carry, xs):
                        hh, aux_sum = carry
                        lw = {k: v for k, v in xs.items() if k != "__keys"}
                        key = xs["__keys"] if dkey is not None else None
                        hh, aux = block(hh, (lw, key))
                        if aux is not None:
                            aux_sum = aux_sum + aux
                        return (hh, aux_sum), None
                    (hh, aux), _ = jax.lax.scan(
                        body, (hh, jnp.zeros((), jnp.float32)), sp)
                    return (hh, aux) if moe else hh
                stage_params = {n: v.reshape(n_stage, lpp, *v.shape[1:])
                                for n, v in lws.items()}
                stage_params["__keys"] = keys.reshape(n_stage, lpp, 2)
                M = max(2 * pp, 1)
                # microbatches must divide batch
                while ids.shape[0] % M != 0 and M > 1:
                    M -= 1
                if M < 2 * pp:
                    import warnings
                    warnings.warn(
                        f"pipeline microbatches degraded to {M} (batch "
                        f"{ids.shape[0]} not divisible by {2 * pp}); bubble "
                        f"fraction increases — prefer batch % {2 * pp} == 0",
                        RuntimeWarning, stacklevel=2)
                sel_policy = (_sel_policy(c.recompute) if c.recompute in
                              ("selective", "selective_lean") else None)
                h = pipeline_apply(stage_fn, stage_params, h, M,
                                   remat=bool(c.recompute),
                                   schedule=c.pp_schedule
                                   if c.pp_schedule == "interleaved"
                                   else "gpipe",
                                   num_chunks=max(V, 1),
                                   remat_policy=sel_policy,
                                   with_aux=moe)
                if moe:
                    h, aux_pp = h
            else:
                def body(hh, xs):
                    lw, key = xs
                    hh, aux = block(hh, (lw, key))
                    return hh, (aux if aux is not None
                                else jnp.zeros((), jnp.float32))
                scan_body = body
                if c.recompute in ("selective", "selective_lean"):
                    # Megatron-style selective recompute (reference:
                    # fleet/recompute 'full' vs refined recompute): save only
                    # the expensive matmul outputs; ln/gelu/flash replay in
                    # bwd.  ~6% extra FLOPs for ~85% of full-remat's memory
                    # saving.  'selective_lean' also drops the 4H-wide
                    # ffn_up (halves saved bytes; fc1 replays in bwd,
                    # ~+4% step FLOPs) — it buys a bigger batch at 760M+.
                    scan_body = jax.checkpoint(
                        body, policy=_sel_policy(c.recompute))
                elif c.recompute:
                    scan_body = jax.checkpoint(body)
                h, auxs = jax.lax.scan(scan_body, h, (lws, keys))
            h = _norm(h, lnf_w, lnf_b, c.layer_norm_epsilon)
            if c.tie_word_embeddings:
                logits = jnp.matmul(h, wte.T, precision=matmul_precision())
            else:
                logits = jnp.matmul(h, head_w,
                                    precision=matmul_precision())
            mesh = get_mesh()
            if mesh is not None and isinstance(logits, jax.core.Tracer):
                logits = jax.lax.with_sharding_constraint(
                    logits, jax.sharding.NamedSharding(
                        mesh, P(("dp", "sharding"), None, "mp")))
            if c.num_experts > 0:
                return logits, (aux_pp if pp > 1 else jnp.sum(auxs))
            return logits

        args = [input_ids, self.wte, self.lnf_w, self.lnf_b]
        if not c.use_rope:
            args.append(self.wpe)
        args += params
        if not c.tie_word_embeddings:
            out = apply_op("gpt_forward",
                           lambda ids, wte, lw, lb, *st: fn(
                               ids, wte, lw, lb, *st[:-1], head_w=st[-1]),
                           *args, self.lm_head)
        else:
            out = apply_op("gpt_forward", fn, *args)
        if isinstance(out, tuple):
            logits, self._moe_aux = out
            return logits
        self._moe_aux = None
        return out

    def moe_aux_loss(self):
        """Summed MoE load-balancing loss from the last forward (0 when the
        model is dense).  Carried through the pipeline schedules via
        pipeline_apply(with_aux=True).  Add `model.moe_aux_loss() * coeff`
        to the training loss (reference trainers do the same with the gate
        loss, moe/moe_layer.py)."""
        if getattr(self, "_moe_aux", None) is None:
            return Tensor._wrap(jnp.zeros((), jnp.float32))
        return self._moe_aux


    # -- generation (KV-cached decode) ---------------------------------------
    def _cached_layers(self, c, lws, h, cache_k, cache_v, pos):
        """Run all blocks on h [B, T, H] writing K/V into the caches at
        positions [pos, pos+T) and attending to everything <= query pos.

        cache_k/cache_v: [L, B, S, nh, hd].  This is the decode twin of the
        training block (reference: masked_multihead_attention_kernel.cu /
        fused_multi_transformer's CacheKV path) — one fused scan over
        layers, dense O(S) attention against the cache, MXU-friendly
        static shapes."""
        nh = c.num_heads
        eps = c.layer_norm_epsilon
        B, T, H = h.shape
        S = cache_k.shape[2]
        hd = H // nh
        scale = 1.0 / math.sqrt(hd)
        kpos = jnp.arange(S)
        qpos = pos + jnp.arange(T)
        mask = kpos[None, :] <= qpos[:, None]          # [T, S]

        def body(hh, xs):
            lw, ck, cv = xs
            x = _norm(hh, lw["ln1_w"], lw["ln1_b"], eps)
            qkv = _mm(x, lw, "qkv_w") + lw["qkv_b"]
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(B, T, nh, hd)
            k = k.reshape(B, T, nh, hd)
            v = v.reshape(B, T, nh, hd)
            if c.use_rope:
                from ..kernels.rope import apply_rope
                q = apply_rope(q, offset=pos)
                k = apply_rope(k, offset=pos)
            ck = jax.lax.dynamic_update_slice(ck, k.astype(ck.dtype),
                                              (0, pos, 0, 0))
            cv = jax.lax.dynamic_update_slice(cv, v.astype(cv.dtype),
                                              (0, pos, 0, 0))
            logits = jnp.einsum("bqhd,bkhd->bhqk",
                                (q * scale).astype(jnp.float32),
                                ck.astype(jnp.float32))
            logits = jnp.where(mask[None, None], logits, NEG_INF)
            p = jax.nn.softmax(logits, axis=-1)
            o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(cv.dtype), cv)
            o = o.reshape(B, T, H)
            a = _mm(o, lw, "proj_w") + lw["proj_b"]
            hh = hh + a
            x = _norm(hh, lw["ln2_w"], lw["ln2_b"], eps)
            if c.num_experts > 0:
                from ..incubate.moe import moe_ffn
                f, _aux = moe_ffn(
                    x, lw["gate_w"], lw["fc1_w"], lw["fc1_b"],
                    lw["fc2_w"], lw["fc2_b"], top_k=c.moe_top_k,
                    capacity_factor=c.moe_capacity_factor)
            else:
                up = _mm(x, lw, "fc1_w") + lw["fc1_b"]
                f = _mm(jax.nn.gelu(up), lw, "fc2_w") + lw["fc2_b"]
            return hh + f, (ck, cv)

        h, (cache_k, cache_v) = jax.lax.scan(body, h,
                                             (lws, cache_k, cache_v))
        return h, cache_k, cache_v

    def _embed(self, c, wte, wpe, ids, pos):
        h = jnp.take(wte, ids, axis=0)
        if wpe is not None:
            h = h + jax.lax.dynamic_slice_in_dim(wpe, pos, ids.shape[1],
                                                 axis=0)
        return h

    # -- serving entry points (paddle_tpu.serving.LLMEngine) -----------------
    def decode_state(self):
        """Raw device weights for the serving prefill/decode programs (one
        dict the engine passes through jit unchanged — the arrays stay
        device-resident, never re-hydrated per step)."""
        c = self.config
        return {
            "lws": {n: getattr(self, n)._data for n in self._stacked()},
            "wte": self.wte._data,
            "wpe": None if c.use_rope else self.wpe._data,
            "lnf_w": self.lnf_w._data,
            "lnf_b": self.lnf_b._data,
            "head": (None if c.tie_word_embeddings else self.lm_head._data),
        }

    def prefill_slot(self, w, ids, length):
        """Pure prefill over ONE right-padded prompt ``ids[1, Sb]`` of true
        length ``length`` (traced scalar): returns K/V chunks
        ``[L, 1, Sb, nh, hd]`` zeroed beyond ``length`` plus the fp32
        next-token logits ``[1, V]`` read at position ``length - 1``.

        ``Sb`` is a power-of-two bucket, so the engine compiles
        O(log S_max) prefill programs however many prompt lengths arrive.
        Built on the same ``_cached_layers`` scan as ``generate`` — the
        engine's first token is token-identical to ``generate``'s."""
        c = self.config
        nh, H = c.num_heads, c.hidden_size
        hd = H // nh
        B, Sb = ids.shape
        dt = jnp.dtype(c.dtype)
        ck0 = jnp.zeros((c.num_layers, B, Sb, nh, hd), dt)
        cv0 = jnp.zeros((c.num_layers, B, Sb, nh, hd), dt)
        h = self._embed(c, w["wte"], w["wpe"], ids, 0)
        h, ck, cv = self._cached_layers(c, w["lws"], h, ck0, cv0, 0)
        # zero the padded tail so arena rows only ever hold live K/V
        valid = (jnp.arange(Sb) < length)[None, None, :, None, None]
        ck = jnp.where(valid, ck, jnp.zeros((), ck.dtype))
        cv = jnp.where(valid, cv, jnp.zeros((), cv.dtype))
        h_last = jax.lax.dynamic_slice_in_dim(h, length - 1, 1, axis=1)
        logits = _lm_logits(c, w["wte"], w["lnf_w"], w["lnf_b"], w["head"],
                            h_last[:, 0])
        return ck, cv, logits

    def decode_slots(self, w, tok, pos, cache_k, cache_v):
        """One decode step for B independent slot rows at PER-ROW positions
        (the serving twin of ``_cached_layers``, whose position is one
        scalar for the whole batch).

        tok ``[B]`` int32, pos ``[B]`` int32, cache_k/v ``[L, B, S, nh,
        hd]`` (the engine's KV arena).  Writes each row's K/V at
        ``pos[row]`` (one-hot select — dynamic_update_slice needs a scalar
        start), attends to ``kpos <= pos[row]``, and returns
        ``(logits [B, V] fp32, new cache_k, new cache_v)``.  Rows are
        independent, so a slot's trajectory is token-identical to a
        ``generate`` call decoding the same request alone."""
        c = self.config
        nh = c.num_heads
        eps = c.layer_norm_epsilon
        H = c.hidden_size
        hd = H // nh
        B = tok.shape[0]
        S = cache_k.shape[2]
        scale = 1.0 / math.sqrt(hd)
        h = jnp.take(w["wte"], tok, axis=0)[:, None, :]
        if w["wpe"] is not None:
            h = h + jnp.take(w["wpe"], pos, axis=0)[:, None, :]
        kpos = jnp.arange(S)
        mask = kpos[None, :] <= pos[:, None]                     # [B, S]
        write = kpos[None, :, None, None] == pos[:, None, None, None]

        def body(hh, xs):
            lw, ck, cv = xs
            x = _norm(hh, lw["ln1_w"], lw["ln1_b"], eps)
            qkv = _mm(x, lw, "qkv_w") + lw["qkv_b"]
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(B, 1, nh, hd)
            k = k.reshape(B, 1, nh, hd)
            v = v.reshape(B, 1, nh, hd)
            if c.use_rope:
                q = _rope_rows(q, pos)
                k = _rope_rows(k, pos)
            ck = jnp.where(write, k.astype(ck.dtype), ck)
            cv = jnp.where(write, v.astype(cv.dtype), cv)
            logits = jnp.einsum("bqhd,bkhd->bhqk",
                                (q * scale).astype(jnp.float32),
                                ck.astype(jnp.float32))
            logits = jnp.where(mask[:, None, None, :], logits, NEG_INF)
            p = jax.nn.softmax(logits, axis=-1)
            o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(cv.dtype), cv)
            o = o.reshape(B, 1, H)
            a = _mm(o, lw, "proj_w") + lw["proj_b"]
            hh = hh + a
            x = _norm(hh, lw["ln2_w"], lw["ln2_b"], eps)
            if c.num_experts > 0:
                from ..incubate.moe import moe_ffn
                f, _aux = moe_ffn(
                    x, lw["gate_w"], lw["fc1_w"], lw["fc1_b"],
                    lw["fc2_w"], lw["fc2_b"], top_k=c.moe_top_k,
                    capacity_factor=c.moe_capacity_factor)
            else:
                up = _mm(x, lw, "fc1_w") + lw["fc1_b"]
                f = _mm(jax.nn.gelu(up), lw, "fc2_w") + lw["fc2_b"]
            return hh + f, (ck, cv)

        h, (cache_k, cache_v) = jax.lax.scan(
            body, h, (w["lws"], cache_k, cache_v))
        logits = _lm_logits(c, w["wte"], w["lnf_w"], w["lnf_b"], w["head"],
                            h[:, 0])
        return logits, cache_k, cache_v

    def prefill_paged(self, w, ids, start, length, bt, pool_k, pool_v,
                      scale_k=None, scale_v=None, adapters=None,
                      adapter_ids=None):
        """One chunked-prefill step over a block-pool KV arena (the paged
        twin of ``prefill_slot``; see ``serving.paged``).

        ``ids[1, C]`` is one right-padded prompt chunk of true length
        ``length`` (traced scalar) whose tokens sit at logical positions
        ``[start, start + length)``; ``bt[max_blocks]`` is the request's
        int32 block table (an OPERAND — the program shape depends only
        on the chunk bucket ``C``); ``pool_k``/``pool_v`` are the shared
        donated pool ``[L, n_blocks, bs, nh, hd]``.  Each chunk token's
        K/V is scattered into block ``bt[(start+i) // bs]`` at offset
        ``(start+i) % bs``; padded tail tokens are zeroed and routed to
        the trash block 0.  Attention gathers the row's whole logical
        sequence ``bt -> [max_blocks*bs, nh, hd]`` AFTER the scatter, so
        one masked ``kpos <= qpos`` einsum covers the cached prefix
        (earlier chunks, shared prefix blocks) and the chunk itself.
        Returns ``(pool_k, pool_v, logits[1, V])`` with the fp32 logits
        read at the chunk's last valid token — the first-token sample
        point when this is the final chunk.

        Quantized-KV mode: when the engine passes per-token fp32 scale
        arenas ``scale_k``/``scale_v [L, n_blocks, bs]`` (pool dtype
        int8/fp8), each token's K/V is quantized on insert
        (``kernels.paged_attention.quantize_kv``) and the gathered view
        is dequantized for the chunk attention; the return grows to
        ``(pool_k, pool_v, scale_k, scale_v, logits)``."""
        c = self.config
        nh = c.num_heads
        eps = c.layer_norm_epsilon
        H = c.hidden_size
        hd = H // nh
        B, C = ids.shape
        n_blocks, bs = pool_k.shape[1], pool_k.shape[2]
        max_blocks = bt.shape[0]
        S = max_blocks * bs
        scale = 1.0 / math.sqrt(hd)
        h = self._embed(c, w["wte"], w["wpe"], ids, start)
        valid = jnp.arange(C) < length
        tokpos = start + jnp.arange(C)
        # padded tokens scatter (zeroed) into the trash block 0
        blk = jnp.where(valid, bt[tokpos // bs], 0)
        off = tokpos % bs
        kpos = jnp.arange(S)
        qpos = start + jnp.arange(C)
        mask = kpos[None, :] <= qpos[:, None]              # [C, S]
        quant = scale_k is not None
        kv_dt = _pa.kv_dtype_of(pool_k.dtype) if quant else None

        lora = adapters is not None
        aids = adapter_ids

        def body(hh, xs):
            if lora:
                lw, al, *rest = xs
            else:
                al = None
                lw, *rest = xs
            if quant:
                ck, cv, sk, sv = rest
            else:
                ck, cv = rest
                sk = sv = None
            x = _norm(hh, lw["ln1_w"], lw["ln1_b"], eps)
            qkv = _mm_lora(x, lw, "qkv_w", al, aids) + lw["qkv_b"]
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(B, C, nh, hd)
            k = k.reshape(B, C, nh, hd)
            v = v.reshape(B, C, nh, hd)
            if c.use_rope:
                from ..kernels.rope import apply_rope
                q = apply_rope(q, offset=start)
                k = apply_rope(k, offset=start)
            vm = valid[:, None, None]
            if quant:
                # quantize on insert: tiles in the arena dtype, one fp32
                # scale per token riding the scale arena at the same
                # (block, offset) address
                kq, ks = _pa.quantize_kv(k[0], kv_dt)
                vq, vs = _pa.quantize_kv(v[0], kv_dt)
                kz = jnp.where(vm, kq, jnp.zeros((), ck.dtype))
                vz = jnp.where(vm, vq, jnp.zeros((), cv.dtype))
                sk = sk.at[blk, off].set(jnp.where(valid, ks, 0.0))
                sv = sv.at[blk, off].set(jnp.where(valid, vs, 0.0))
            else:
                kz = jnp.where(vm, k[0].astype(ck.dtype), 0)
                vz = jnp.where(vm, v[0].astype(cv.dtype), 0)
            ck = ck.at[blk, off].set(kz)
            cv = cv.at[blk, off].set(vz)
            # gather AFTER the scatter: the logical view holds the shared
            # prefix, earlier chunks, and this chunk's own K/V
            if quant:
                gk = _pa.dequantize_kv(ck[bt], sk[bt]).reshape(
                    S, nh, hd)[None]
                gv = _pa.dequantize_kv(cv[bt], sv[bt]).reshape(
                    S, nh, hd)[None]
            else:
                gk = ck[bt].reshape(S, nh, hd)[None]
                gv = cv[bt].reshape(S, nh, hd)[None]
            logits = jnp.einsum("bqhd,bkhd->bhqk",
                                (q * scale).astype(jnp.float32),
                                gk.astype(jnp.float32))
            logits = jnp.where(mask[None, None], logits, NEG_INF)
            p = jax.nn.softmax(logits, axis=-1)
            o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(gv.dtype), gv)
            o = o.reshape(B, C, H).astype(hh.dtype)
            a = _mm_lora(o, lw, "proj_w", al, aids) + lw["proj_b"]
            hh = hh + a
            x = _norm(hh, lw["ln2_w"], lw["ln2_b"], eps)
            if c.num_experts > 0:
                from ..incubate.moe import moe_ffn
                f, _aux = moe_ffn(
                    x, lw["gate_w"], lw["fc1_w"], lw["fc1_b"],
                    lw["fc2_w"], lw["fc2_b"], top_k=c.moe_top_k,
                    capacity_factor=c.moe_capacity_factor)
            else:
                up = _mm_lora(x, lw, "fc1_w", al, aids) + lw["fc1_b"]
                f = _mm_lora(jax.nn.gelu(up), lw, "fc2_w", al,
                             aids) + lw["fc2_b"]
            return hh + f, ((ck, cv, sk, sv) if quant else (ck, cv))

        xs = ((w["lws"], adapters) if lora else (w["lws"],)) \
            + ((pool_k, pool_v, scale_k, scale_v) if quant
               else (pool_k, pool_v))
        if quant:
            h, (pool_k, pool_v, scale_k, scale_v) = jax.lax.scan(
                body, h, xs)
        else:
            h, (pool_k, pool_v) = jax.lax.scan(body, h, xs)
        h_last = jax.lax.dynamic_slice_in_dim(h, length - 1, 1, axis=1)
        logits = _lm_logits(c, w["wte"], w["lnf_w"], w["lnf_b"], w["head"],
                            h_last[:, 0])
        if quant:
            return pool_k, pool_v, scale_k, scale_v, logits
        return pool_k, pool_v, logits

    def decode_paged(self, w, tok, pos, bt, pool_k, pool_v,
                     scale_k=None, scale_v=None, kernel=None,
                     mesh=None, head_axis=None, adapters=None,
                     adapter_ids=None):
        """One decode step for B slot rows over the block-pool arena (the
        paged twin of ``decode_slots`` — identical math, the arena row is
        replaced by a block-table gather).

        tok ``[B]`` int32, pos ``[B]`` int32, bt ``[B, max_blocks]``
        int32 block tables (operands: the ONE compiled decode program
        serves every block-table content), pool_k/pool_v ``[L, n_blocks,
        bs, nh, hd]``.  Each row writes its K/V into block
        ``bt[row, pos // bs]`` at offset ``pos % bs`` (rows with nothing
        to write are tabled to the trash block 0 by the engine) and
        attends over its gathered logical sequence with ``kpos <=
        pos[row]``.  Returns ``(logits [B, V] fp32, pool_k, pool_v)``.

        ``kernel="pallas"`` routes the attention through the fused Pallas
        block-table walk (``kernels.paged_attention``) instead of the
        gather einsum — same operands, same mask, no ``[B, S]`` logical
        view in HBM.  ``kernel=None``/``"off"`` keeps the plain-XLA
        gather below as the reference twin.  Under tensor parallelism
        pass ``mesh``/``head_axis`` (the serving arena does): the pallas
        call then runs through ``shard_map`` over the KV head axis —
        each chip walks only its own ``nh/mp`` heads, and the cross-chip
        reduction happens at the following proj contraction exactly as
        in the gather twin (GSPMD partitions that twin with no help).  Quantized-KV mode mirrors
        ``prefill_paged``: per-token fp32 scale arenas ``scale_k``/
        ``scale_v [L, n_blocks, bs]`` ride the donated carry, the new
        token quantizes on insert, and the return grows to ``(logits,
        pool_k, pool_v, scale_k, scale_v)``."""
        c = self.config
        nh = c.num_heads
        eps = c.layer_norm_epsilon
        H = c.hidden_size
        hd = H // nh
        B = tok.shape[0]
        n_blocks, bs = pool_k.shape[1], pool_k.shape[2]
        max_blocks = bt.shape[1]
        S = max_blocks * bs
        scale = 1.0 / math.sqrt(hd)
        h = jnp.take(w["wte"], tok, axis=0)[:, None, :]
        if w["wpe"] is not None:
            h = h + jnp.take(w["wpe"], pos, axis=0)[:, None, :]
        kpos = jnp.arange(S)
        mask = kpos[None, :] <= pos[:, None]                     # [B, S]
        rows = jnp.arange(B)
        blk = bt[rows, pos // bs]                                # [B]
        off = pos % bs
        quant = scale_k is not None
        kv_dt = _pa.kv_dtype_of(pool_k.dtype) if quant else None
        mode = kernel or "off"
        if mode not in ("off", "pallas"):
            raise ValueError(f"decode_paged: kernel={mode!r}")
        _pa.note_program(mode)

        lora = adapters is not None
        aids = adapter_ids

        def body(hh, xs):
            if lora:
                lw, al, *rest = xs
            else:
                al = None
                lw, *rest = xs
            if quant:
                ck, cv, sk, sv = rest
            else:
                ck, cv = rest
                sk = sv = None
            x = _norm(hh, lw["ln1_w"], lw["ln1_b"], eps)
            qkv = _mm_lora(x, lw, "qkv_w", al, aids) + lw["qkv_b"]
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(B, 1, nh, hd)
            k = k.reshape(B, 1, nh, hd)
            v = v.reshape(B, 1, nh, hd)
            if c.use_rope:
                q = _rope_rows(q, pos)
                k = _rope_rows(k, pos)
            if quant:
                kq, ks = _pa.quantize_kv(k[:, 0], kv_dt)
                vq, vs = _pa.quantize_kv(v[:, 0], kv_dt)
                ck = ck.at[blk, off].set(kq)
                cv = cv.at[blk, off].set(vq)
                sk = sk.at[blk, off].set(ks)
                sv = sv.at[blk, off].set(vs)
            else:
                ck = ck.at[blk, off].set(k[:, 0].astype(ck.dtype))
                cv = cv.at[blk, off].set(v[:, 0].astype(cv.dtype))
            if mode == "pallas":
                # fused block-table walk: the arena is read in physical
                # blocks, never gathered to [B, S]
                if mesh is not None and head_axis is not None:
                    o = _pa.sharded_paged_decode_attention(
                        mesh, head_axis, q[:, 0] * scale, ck, cv, bt,
                        pos, sk, sv, scale=1.0)
                else:
                    o = _pa.paged_decode_attention(
                        q[:, 0] * scale, ck, cv, bt, pos, sk, sv,
                        scale=1.0)
                o = o.reshape(B, 1, H)
            else:
                if quant:
                    gk = _pa.dequantize_kv(ck[bt], sk[bt]).reshape(
                        B, S, nh, hd)
                    gv = _pa.dequantize_kv(cv[bt], sv[bt]).reshape(
                        B, S, nh, hd)
                else:
                    gk = ck[bt].reshape(B, S, nh, hd)
                    gv = cv[bt].reshape(B, S, nh, hd)
                logits = jnp.einsum("bqhd,bkhd->bhqk",
                                    (q * scale).astype(jnp.float32),
                                    gk.astype(jnp.float32))
                logits = jnp.where(mask[:, None, None, :], logits, NEG_INF)
                p = jax.nn.softmax(logits, axis=-1)
                o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(gv.dtype), gv)
                o = o.reshape(B, 1, H)
            o = o.astype(hh.dtype)
            a = _mm_lora(o, lw, "proj_w", al, aids) + lw["proj_b"]
            hh = hh + a
            x = _norm(hh, lw["ln2_w"], lw["ln2_b"], eps)
            if c.num_experts > 0:
                from ..incubate.moe import moe_ffn
                f, _aux = moe_ffn(
                    x, lw["gate_w"], lw["fc1_w"], lw["fc1_b"],
                    lw["fc2_w"], lw["fc2_b"], top_k=c.moe_top_k,
                    capacity_factor=c.moe_capacity_factor)
            else:
                up = _mm_lora(x, lw, "fc1_w", al, aids) + lw["fc1_b"]
                f = _mm_lora(jax.nn.gelu(up), lw, "fc2_w", al,
                             aids) + lw["fc2_b"]
            return hh + f, ((ck, cv, sk, sv) if quant else (ck, cv))

        xs = ((w["lws"], adapters) if lora else (w["lws"],)) \
            + ((pool_k, pool_v, scale_k, scale_v) if quant
               else (pool_k, pool_v))
        if quant:
            h, (pool_k, pool_v, scale_k, scale_v) = jax.lax.scan(
                body, h, xs)
        else:
            h, (pool_k, pool_v) = jax.lax.scan(body, h, xs)
        logits = _lm_logits(c, w["wte"], w["lnf_w"], w["lnf_b"], w["head"],
                            h[:, 0])
        if quant:
            return logits, pool_k, pool_v, scale_k, scale_v
        return logits, pool_k, pool_v

    def verify_paged(self, w, toks, pos0, n_valid, bt, pool_k, pool_v,
                     scale_k=None, scale_v=None, adapters=None,
                     adapter_ids=None):
        """Speculative-decoding verify step: score K+1 token positions
        per row in ONE program over the block-pool arena (the multi-query
        sibling of ``decode_paged``; see ``serving.speculative``).

        ``toks[B, K1]`` holds each row's last committed token followed by
        K draft proposals; ``pos0[B]`` is the committed token's position,
        so ``toks[b, j]`` sits at logical position ``pos0[b] + j``.
        ``n_valid[B]`` (1..K1) caps how many of the K1 positions are real
        for the row — writes for ``j >= n_valid`` are routed to the trash
        block 0 so a row near its token budget can ride the same
        fixed-shape program without its KV overrunning the blocks the
        admission reservation pinned.  Each valid token's K/V is
        scattered at ``bt[b, (pos0+j) // bs]`` offset ``(pos0+j) % bs``
        (overwriting any stale rejected-draft KV from earlier rounds —
        rollback never copies), and query ``j`` attends its own causal
        prefix ``kpos <= pos0 + j`` over the gathered logical sequence.
        Returns ``(logits[B, K1, V] fp32, pool_k, pool_v)`` — logits at
        EVERY drafted position, from which the engine's acceptance rule
        keeps a prefix of the draft and samples the correction/bonus
        token.  Quantized-KV mode mirrors ``decode_paged``: per-token
        fp32 scale arenas ride the donated carry and the return grows to
        ``(logits, pool_k, pool_v, scale_k, scale_v)``."""
        c = self.config
        nh = c.num_heads
        eps = c.layer_norm_epsilon
        H = c.hidden_size
        hd = H // nh
        B, K1 = toks.shape
        n_blocks, bs = pool_k.shape[1], pool_k.shape[2]
        max_blocks = bt.shape[1]
        S = max_blocks * bs
        scale = 1.0 / math.sqrt(hd)
        pos = pos0[:, None] + jnp.arange(K1)[None, :]            # [B, K1]
        valid = jnp.arange(K1)[None, :] < n_valid[:, None]       # [B, K1]
        h = jnp.take(w["wte"], toks, axis=0)                     # [B, K1, H]
        if w["wpe"] is not None:
            h = h + jnp.take(w["wpe"], jnp.minimum(pos, w["wpe"].shape[0] - 1),
                             axis=0)
        rows = jnp.arange(B)
        # invalid positions may index past the table; the where() routes
        # them to the trash block before any write can land
        blk = jnp.where(valid, bt[rows[:, None],
                                  jnp.minimum(pos // bs, max_blocks - 1)], 0)
        off = pos % bs
        kpos = jnp.arange(S)
        mask = kpos[None, None, :] <= pos[:, :, None]            # [B, K1, S]
        quant = scale_k is not None
        kv_dt = _pa.kv_dtype_of(pool_k.dtype) if quant else None

        lora = adapters is not None
        aids = adapter_ids

        def body(hh, xs):
            if lora:
                lw, al, *rest = xs
            else:
                al = None
                lw, *rest = xs
            if quant:
                ck, cv, sk, sv = rest
            else:
                ck, cv = rest
                sk = sv = None
            x = _norm(hh, lw["ln1_w"], lw["ln1_b"], eps)
            qkv = _mm_lora(x, lw, "qkv_w", al, aids) + lw["qkv_b"]
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(B, K1, nh, hd)
            k = k.reshape(B, K1, nh, hd)
            v = v.reshape(B, K1, nh, hd)
            if c.use_rope:
                q = _rope_grid(q, pos)
                k = _rope_grid(k, pos)
            if quant:
                kq, ks = _pa.quantize_kv(k, kv_dt)
                vq, vs = _pa.quantize_kv(v, kv_dt)
                ck = ck.at[blk, off].set(kq)
                cv = cv.at[blk, off].set(vq)
                sk = sk.at[blk, off].set(ks)
                sv = sv.at[blk, off].set(vs)
            else:
                ck = ck.at[blk, off].set(k.astype(ck.dtype))
                cv = cv.at[blk, off].set(v.astype(cv.dtype))
            # gather AFTER the scatter: query j sees the committed prefix
            # plus every draft token at or before its own position
            if quant:
                gk = _pa.dequantize_kv(ck[bt], sk[bt]).reshape(
                    B, S, nh, hd)
                gv = _pa.dequantize_kv(cv[bt], sv[bt]).reshape(
                    B, S, nh, hd)
            else:
                gk = ck[bt].reshape(B, S, nh, hd)
                gv = cv[bt].reshape(B, S, nh, hd)
            logits = jnp.einsum("bqhd,bkhd->bhqk",
                                (q * scale).astype(jnp.float32),
                                gk.astype(jnp.float32))
            logits = jnp.where(mask[:, None], logits, NEG_INF)
            p = jax.nn.softmax(logits, axis=-1)
            o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(gv.dtype), gv)
            o = o.reshape(B, K1, H).astype(hh.dtype)
            a = _mm_lora(o, lw, "proj_w", al, aids) + lw["proj_b"]
            hh = hh + a
            x = _norm(hh, lw["ln2_w"], lw["ln2_b"], eps)
            if c.num_experts > 0:
                from ..incubate.moe import moe_ffn
                f, _aux = moe_ffn(
                    x, lw["gate_w"], lw["fc1_w"], lw["fc1_b"],
                    lw["fc2_w"], lw["fc2_b"], top_k=c.moe_top_k,
                    capacity_factor=c.moe_capacity_factor)
            else:
                up = _mm_lora(x, lw, "fc1_w", al, aids) + lw["fc1_b"]
                f = _mm_lora(jax.nn.gelu(up), lw, "fc2_w", al,
                             aids) + lw["fc2_b"]
            return hh + f, ((ck, cv, sk, sv) if quant else (ck, cv))

        xs = ((w["lws"], adapters) if lora else (w["lws"],)) \
            + ((pool_k, pool_v, scale_k, scale_v) if quant
               else (pool_k, pool_v))
        if quant:
            h, (pool_k, pool_v, scale_k, scale_v) = jax.lax.scan(
                body, h, xs)
        else:
            h, (pool_k, pool_v) = jax.lax.scan(body, h, xs)
        logits = _lm_logits(c, w["wte"], w["lnf_w"], w["lnf_b"], w["head"],
                            h)
        if quant:
            return logits, pool_k, pool_v, scale_k, scale_v
        return logits, pool_k, pool_v

    def generate(self, input_ids, max_new_tokens=32, do_sample=False,
                 temperature=1.0, top_k=0, top_p=1.0, eos_token_id=None,
                 seed=None):
        """Autoregressive decoding with a static KV cache, fully compiled
        (prefill + lax.scan decode loop in ONE XLA program).

        Reference analogue: the fused decode path
        (masked_multihead_attention_kernel.cu + paddlenlp generate);
        TPU-native: static cache shapes, dynamic_update_slice writes,
        whole loop under jit.  Returns [B, T + max_new_tokens] token ids
        (after eos, the row keeps emitting eos).  Sampling shares
        ``serving.sampling`` with the continuous-batching engine, so
        ``serving.LLMEngine`` reproduces this method token for token."""
        c = self.config
        names = self._stacked()
        lws = {n: getattr(self, n)._data for n in names}
        wte = self.wte._data
        wpe = self.wpe._data if not c.use_rope else None
        head = (None if c.tie_word_embeddings else self.lm_head._data)
        lnf_w, lnf_b = self.lnf_w._data, self.lnf_b._data
        ids = input_ids._data if isinstance(input_ids, Tensor) \
            else jnp.asarray(input_ids)
        B, T = ids.shape
        S = T + int(max_new_tokens)
        if S > c.max_seq_len and not c.use_rope:
            raise ValueError(f"generation length {S} exceeds max_seq_len "
                             f"{c.max_seq_len}")
        from ..tensor.random import _DEFAULT_GEN
        key = (jax.random.key(seed) if seed is not None
               else _DEFAULT_GEN.next_key())
        eos = -1 if eos_token_id is None else int(eos_token_id)

        from ..serving.sampling import sample_tokens

        def logits_of(h_last):
            return _lm_logits(c, wte, lnf_w, lnf_b, head, h_last)

        # normalize the sampling knobs to host scalars once, outside the
        # traced body — they are trace-time constants, not traced values
        do_sample = bool(do_sample)
        temperature = float(temperature)
        top_k, top_p = int(top_k), float(top_p)

        def sample(lg, k):
            return sample_tokens(lg, k, do_sample=do_sample,
                                 temperature=temperature,
                                 top_k=top_k, top_p=top_p,
                                 out_dtype=ids.dtype)

        def run(lws, wte, wpe, lnf_w, lnf_b, head, ids, key):
            nh, H = c.num_heads, c.hidden_size
            hd = H // nh
            dt = jnp.dtype(c.dtype)
            ck0 = jnp.zeros((c.num_layers, B, S, nh, hd), dt)
            cv0 = jnp.zeros((c.num_layers, B, S, nh, hd), dt)
            h = self._embed(c, wte, wpe, ids, 0)
            h, ck, cv = self._cached_layers(c, lws, h, ck0, cv0, 0)
            key, k0 = jax.random.split(key)
            tok = sample(logits_of(h[:, -1]), k0)
            done = (tok == eos)

            def step(carry, i):
                tok, ck, cv, done, key = carry
                pos = T + i
                h = self._embed(c, wte, wpe, tok[:, None], pos)
                h, ck, cv = self._cached_layers(c, lws, h, ck, cv, pos)
                key, ks = jax.random.split(key)
                nxt = sample(logits_of(h[:, -1]), ks)
                nxt = jnp.where(done, jnp.asarray(eos, ids.dtype), nxt)
                done = done | (nxt == eos)
                return (nxt, ck, cv, done, key), tok

            (last, _, _, _, _), toks = jax.lax.scan(
                step, (tok, ck, cv, done, key),
                jnp.arange(max_new_tokens - 1))
            new = jnp.concatenate(
                [jnp.moveaxis(toks, 0, 1), last[:, None]], axis=1)
            return jnp.concatenate([ids, new], axis=1)

        # sampling params only affect the trace when do_sample is on
        cache_key = (B, T, int(max_new_tokens), eos,
                     (bool(do_sample), float(temperature), int(top_k),
                      float(top_p))
                     if do_sample else False)
        # LRU-bounded executable cache: long-running processes seeing many
        # request shapes must not leak compiled programs (the serving
        # engine avoids the per-shape explosion entirely by bucketing)
        from collections import OrderedDict
        jits = getattr(self, "_gen_cache", None)
        if jits is None:
            jits = self._gen_cache = OrderedDict()
        cap = max(1, int(getattr(self, "_gen_cache_max", 16)))
        if cache_key in jits:
            jits.move_to_end(cache_key)
        else:
            while len(jits) >= cap:
                jits.popitem(last=False)  # evict least-recently-used
            jits[cache_key] = jax.jit(run)
        out = jits[cache_key](lws, wte, wpe, lnf_w, lnf_b, head, ids, key)
        return Tensor._wrap(out)

    # -- 1F1B pipeline decomposition ----------------------------------------
    def pipeline_parts(self, tp_axis=None):
        """Split the model for the compiled 1F1B schedule
        (distributed.pipeline.pipeline_value_and_grad): embedding in the
        first stage, final-norm + head + token-sum CE loss in the last —
        mirroring the reference's PipelineLayer partition where
        SharedLayerDesc embeddings and the loss_fn live on the end stages
        (fleet/meta_parallel/parallel_layers/pp_layers.py:56).

        With ``tp_axis`` the stage bodies are MANUAL tensor-parallel over
        that mesh axis (Megatron column/row split with explicit
        copy_to_mp/reduce_from_mp, vocab-parallel embedding + parallel CE) —
        the composition the reference runs as its flagship TP x PP recipe
        (pipeline_parallel.py:459 with mp_layers).  GSPMD cannot place mp
        collectives inside the 1F1B per-stage cond dispatch, hence manual.

        Returns (first_fn, mid_fn, last_fn, stage_params, extras,
        grad_names, specs, grad_fixup): stage_params leaves are
        [pp, L/pp, ...]; extras holds the end-stage weights.  `specs` is
        None or (param_specs, extra_specs) PartitionSpec dicts for
        shard_map; `grad_fixup(name, g)` undoes any weight-layout permutation
        on the returned gradients.  Loss convention: SUM over tokens
        (divide by token count for the mean).
        """
        c = self.config
        pp = hybrid_degrees().get("pp", 1)
        L = c.num_layers
        if L % pp != 0:
            raise ValueError(f"num_layers {L} not divisible by pp {pp}")
        lpp = L // pp
        names = self._stacked()
        eps = c.layer_norm_epsilon
        tie = c.tie_word_embeddings
        use_rope = c.use_rope
        use_dropout = self.training and c.dropout > 0
        moe = c.num_experts > 0

        if tp_axis is not None:
            return self._pipeline_parts_tp(tp_axis, pp, lpp)

        block = self._block_fn(c, self.training, None)
        if use_dropout:
            from ..tensor.random import _next_key
            dkey = _next_key()

        stage_params = {
            n: getattr(self, n)._data.reshape(
                pp, lpp, *getattr(self, n)._data.shape[1:])
            for n in names}
        extras = {"wte": self.wte._data, "lnf_w": self.lnf_w._data,
                  "lnf_b": self.lnf_b._data}
        if not use_rope:
            extras["wpe"] = self.wpe._data
        if not tie:
            extras["head"] = self.lm_head._data

        def first_fn(ex, ids):
            h = jnp.take(ex["wte"], ids, axis=0)
            if not use_rope:
                h = h + jnp.take(ex["wpe"], jnp.arange(ids.shape[1]), axis=0)
            return h

        def mid_fn(sp, h, m=0):
            # per-(microbatch, global layer) dropout keys: fold_in replays
            # identically in the backward/W vjps of the schedule (the
            # reference's RNG replay, fleet/recompute/recompute.py:109)
            stage = jax.lax.axis_index("pp") if pp > 1 else 0

            def body(carry, xs):
                hh, aux_sum = carry
                lw, li = xs
                key = None
                if use_dropout:
                    key = jax.random.fold_in(
                        jax.random.fold_in(dkey, m), stage * lpp + li)
                hh, aux = block(hh, (lw, key))
                if aux is not None:
                    aux_sum = aux_sum + aux
                return (hh, aux_sum), None

            (h, aux), _ = jax.lax.scan(
                body, (h, jnp.zeros((), jnp.float32)),
                (sp, jnp.arange(lpp)))
            return (h, aux * c.moe_aux_weight) if moe else h

        mid_fn.mb_aware = use_dropout
        mid_fn.aux_aware = moe

        def last_fn(ex, h, labels):
            h = _norm(h, ex["lnf_w"], ex["lnf_b"], eps)
            w = ex["wte"].T if tie else ex["head"]
            logits = jnp.matmul(h, w,
                                precision=matmul_precision()).astype(
                                    jnp.float32)
            lse = jax.nn.logsumexp(logits, -1)
            picked = jnp.take_along_axis(
                logits, labels[..., None].astype(jnp.int32), -1)[..., 0]
            return jnp.sum(lse - picked)

        return (first_fn, mid_fn, last_fn, stage_params, extras, names,
                None, None)

    def _pipeline_parts_tp(self, ax, pp, lpp):
        """Manual-TP stage decomposition (see pipeline_parts docstring)."""
        import numpy as np
        from ..distributed.env import get_mesh
        from ..distributed.mp_ops import (copy_to_mp, reduce_from_mp,
                                          vocab_parallel_ce_sum,
                                          vocab_parallel_embedding)
        c = self.config
        if c.num_experts > 0:
            raise NotImplementedError(
                "MoE blocks under the manual-TP 1F1B path are not supported;"
                " use incubate.MoELayer with the GSPMD schedules")
        mesh = get_mesh()
        mp = mesh.shape[ax]
        H, nh, F, V = (c.hidden_size, c.num_heads, c.ffn_hidden_size,
                       c.vocab_size)
        hd = H // nh
        if nh % mp or F % mp or V % mp:
            raise ValueError(
                f"tensor parallel degree {mp} must divide num_heads {nh}, "
                f"ffn_hidden {F} and vocab {V}")
        eps = c.layer_norm_epsilon
        tie = c.tie_word_embeddings
        use_rope = c.use_rope
        use_flash = c.use_flash_attention
        names = self._stacked()

        # The fused qkv weight is laid out q|k|v along its 3H columns;
        # column-sharding that directly would give member j a mixed slice.
        # Permute to shard-major [mp, (q_j|k_j|v_j)] so the LOCAL thirds are
        # q/k/v (the reference shards q, k, v separately inside
        # ColumnParallelLinear for the same reason).
        Hm = H // mp
        perm = np.concatenate([
            np.concatenate([np.arange(j * Hm, (j + 1) * Hm) + t * H
                            for t in range(3)])
            for j in range(mp)])
        inv = np.argsort(perm)

        stage_params = {}
        for n in names:
            a = getattr(self, n)._data
            if n == "qkv_w":
                a = a[:, :, perm]
            elif n == "qkv_b":
                a = a[:, perm]
            stage_params[n] = a.reshape(pp, lpp, *a.shape[1:])
        extras = {"wte": self.wte._data, "lnf_w": self.lnf_w._data,
                  "lnf_b": self.lnf_b._data}
        if not use_rope:
            extras["wpe"] = self.wpe._data
        if not tie:
            extras["head"] = self.lm_head._data

        P_ = P
        param_specs = {
            "ln1_w": P_("pp"), "ln1_b": P_("pp"),
            "qkv_w": P_("pp", None, None, ax),
            "qkv_b": P_("pp", None, ax),
            "proj_w": P_("pp", None, ax, None), "proj_b": P_("pp"),
            "ln2_w": P_("pp"), "ln2_b": P_("pp"),
            "fc1_w": P_("pp", None, None, ax),
            "fc1_b": P_("pp", None, ax),
            "fc2_w": P_("pp", None, ax, None), "fc2_b": P_("pp"),
        }
        extra_specs = {"wte": P_(ax, None), "lnf_w": P_(), "lnf_b": P_()}
        if not use_rope:
            extra_specs["wpe"] = P_()
        if not tie:
            extra_specs["head"] = P_(None, ax)

        use_dropout = self.training and c.dropout > 0
        drop = c.dropout if use_dropout else 0.0
        if use_dropout:
            from ..tensor.random import _next_key
            dkey = _next_key()

        def block_tp(h, lw, key):
            b, s, _ = h.shape
            x = _norm(h, lw["ln1_w"], lw["ln1_b"], eps)
            x = copy_to_mp(x, ax)
            qkv = jnp.matmul(x, lw["qkv_w"],
                             precision=matmul_precision()) + lw["qkv_b"]
            q, k, v = jnp.split(qkv, 3, axis=-1)
            nh_loc = q.shape[-1] // hd
            q = q.reshape(b, s, nh_loc, hd)
            k = k.reshape(b, s, nh_loc, hd)
            v = v.reshape(b, s, nh_loc, hd)
            if use_rope:
                from ..kernels.rope import apply_rope
                q = apply_rope(q)
                k = apply_rope(k)
            if use_flash:
                o = flash_attention_fwd(q, k, v, causal=True)
            else:
                o = reference_attention(q, k, v, causal=True)
            o = o.reshape(b, s, nh_loc * hd)
            a = reduce_from_mp(
                jnp.matmul(o, lw["proj_w"], precision=matmul_precision()),
                ax) + lw["proj_b"]
            if drop > 0:
                # key depends only on (microbatch, layer): every mp member
                # draws the SAME mask on the full (post-psum) activation
                key, k1 = jax.random.split(key)
                a = _dropout(a, k1, drop)
            h = h + a
            x = _norm(h, lw["ln2_w"], lw["ln2_b"], eps)
            x = copy_to_mp(x, ax)
            up = jnp.matmul(x, lw["fc1_w"],
                            precision=matmul_precision()) + lw["fc1_b"]
            f = reduce_from_mp(
                jnp.matmul(jax.nn.gelu(up), lw["fc2_w"],
                           precision=matmul_precision()),
                ax) + lw["fc2_b"]
            if drop > 0:
                key, k2 = jax.random.split(key)
                f = _dropout(f, k2, drop)
            return h + f

        def first_fn(ex, ids):
            h = vocab_parallel_embedding(ids, ex["wte"], ax)
            if not use_rope:
                h = h + jnp.take(ex["wpe"], jnp.arange(ids.shape[1]), axis=0)
            return h

        def mid_fn(sp, h, m=0):
            stage = jax.lax.axis_index("pp")

            def body(carry, xs):
                lw, li = xs
                key = None
                if use_dropout:
                    key = jax.random.fold_in(
                        jax.random.fold_in(dkey, m), stage * lpp + li)
                return block_tp(carry, lw, key), None

            h, _ = jax.lax.scan(body, h, (sp, jnp.arange(lpp)))
            return h

        mid_fn.mb_aware = use_dropout

        def last_fn(ex, h, labels):
            hn = _norm(h, ex["lnf_w"], ex["lnf_b"], eps)
            hn = copy_to_mp(hn, ax)
            w = ex["wte"].T if tie else ex["head"]  # local [H, V/mp]
            logits = jnp.matmul(hn, w, precision=matmul_precision())
            return vocab_parallel_ce_sum(logits, labels, ax)

        def grad_fixup(n, g):
            if n == "qkv_w":
                return g[..., inv]
            if n == "qkv_b":
                return g[..., inv]
            return g

        return (first_fn, mid_fn, last_fn, stage_params, extras, names,
                (param_specs, extra_specs), grad_fixup)


class GPTPretrainingCriterion(Layer):
    """Causal-LM loss (reference: paddlenlp GPTPretrainingCriterion —
    ParallelCrossEntropy over vocab-sharded logits)."""

    def __init__(self, config=None):
        super().__init__()

    def forward(self, logits, labels, loss_mask=None):
        def fn(lg, lb, *mask):
            # lse - picked form: identical math to -log_softmax[label], but
            # XLA never materialises the [B,S,V] fp32 log-prob array (the
            # logsumexp reduction and the label gather fuse into the logits
            # producer) — measured ~4% step-time saving at GPT-125M.
            lg = lg.astype(jnp.float32)
            lse = jax.nn.logsumexp(lg, -1)
            picked = jnp.take_along_axis(
                lg, lb[..., None].astype(jnp.int32), -1)[..., 0]
            loss = lse - picked
            if mask:
                m = mask[0].astype(jnp.float32)
                return jnp.sum(loss * m) / jnp.maximum(jnp.sum(m), 1.0)
            return jnp.mean(loss)
        if loss_mask is not None:
            return apply_op("gpt_loss", fn, logits, labels, loss_mask)
        return apply_op("gpt_loss", fn, logits, labels)
