"""Define-by-run autograd over JAX VJPs.

TPU-native redesign of the reference's eager autograd engine
(``GradNodeBase`` graph, /root/reference/paddle/fluid/eager/grad_node_info.h:197;
``egr::Backward`` queue traversal, /root/reference/paddle/fluid/eager/backward.cc:105,439).

Instead of per-op hand-written grad kernels, every eager op is executed through
``jax.vjp`` which (a) runs the forward once and (b) returns a VJP closure whose
residuals are device arrays — the exact analogue of the reference's
``TensorWrapper`` saved-tensor mechanism but produced automatically by JAX's
tracing.  ``backward()`` is a reverse-topological walk accumulating cotangents.

Because the whole engine operates on ``jax.Array``/tracers, the *same* code
path works under ``jax.jit``: tracing a function that calls ``loss.backward()``
yields one fused XLA program for forward+backward (the "dy2static" story).
"""

from __future__ import annotations

import weakref

import jax.numpy as jnp


class GradNode:
    """One taped op: VJP closure + edges to parent tensors.

    Mirrors ``GradNodeBase`` (grad_node_info.h:197): ``vjp_fn`` plays the role
    of the generated ``operator()``, ``parents`` the role of
    ``SetGradOutMeta`` edges.
    """

    __slots__ = ("name", "vjp_fn", "parents", "out_avals", "out_refs",
                 "__weakref__")

    def __init__(self, name, vjp_fn, parents, out_avals):
        self.name = name
        self.vjp_fn = vjp_fn
        self.parents = parents            # list[Tensor] (diff inputs, order = vjp outputs)
        self.out_avals = out_avals        # list[(shape, dtype)]
        self.out_refs = [None] * len(out_avals)  # weakrefs to output Tensors

    def set_output(self, idx, tensor):
        self.out_refs[idx] = weakref.ref(tensor)


def _topo_order(roots):
    """Iterative post-order DFS over the node graph; returns topological list
    (parents before children is NOT needed — we process reversed post-order)."""
    order, visited, stack = [], set(), [(r, False) for r in roots]
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in visited:
            continue
        visited.add(id(node))
        stack.append((node, True))
        for p in node.parents:
            pn = p._node
            if pn is not None and id(pn) not in visited:
                stack.append((pn, False))
    return order  # post-order: parents appear before consumers


def run_backward(tensors, grad_tensors=None, retain_graph=False,
                 accumulate_into_grad=True, inputs=None):
    """Reverse-accumulate cotangents from ``tensors``.

    Reference analogue: ``egr::RunBackward`` (backward.cc:105).
    If ``inputs`` is given (paddle.grad semantics) returns their grads as raw
    arrays instead of (only) writing ``.grad``.
    """
    from .state import STATE
    from .tensor import Tensor  # late import

    # visible to hooks: paddle.grad (accumulate_into_grad=False) promises
    # not to touch .grad, so side-effecting hooks (sparse embedding's
    # SelectedRows writer) must stand down during it
    STATE.accumulating_backward = accumulate_into_grad

    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)

    # Cotangent accumulator keyed per node: {id(node): [grad|None per output]}
    accum: dict[int, list] = {}
    nodes: dict[int, GradNode] = {}
    leaf_grads: dict[int, object] = {}   # id(tensor) -> raw grad array
    roots = []

    for t, g in zip(tensors, grad_tensors):
        if g is None:
            if t._data.size != 1:
                raise RuntimeError(
                    f"backward() on non-scalar tensor shape={t.shape} requires "
                    "an explicit grad tensor")
            g = jnp.ones_like(t._data)
        else:
            g = g._data if isinstance(g, Tensor) else jnp.asarray(g)
        node = t._node
        if node is None:
            # leaf: grad is the cotangent itself
            if not t.stop_gradient:
                _leaf_accumulate(leaf_grads, t, g)
            continue
        slot = accum.setdefault(id(node), [None] * len(node.out_avals))
        slot[t._out_idx] = g if slot[t._out_idx] is None else slot[t._out_idx] + g
        nodes[id(node)] = node
        roots.append(node)

    order = _topo_order(roots)
    # process consumers first: reversed post-order
    for node in reversed(order):
        slot = accum.get(id(node))
        if slot is None:
            continue
        outgrads = tuple(
            g if g is not None else jnp.zeros(shape, dtype)
            for g, (shape, dtype) in zip(slot, node.out_avals))
        # tensor-level hooks on this node's outputs
        outgrads = list(outgrads)
        for i, ref in enumerate(node.out_refs):
            t = ref() if ref is not None else None
            if t is not None and t._hooks:
                for h in t._hooks:
                    r = h(Tensor._wrap(outgrads[i]))
                    if r is not None:
                        outgrads[i] = r._data if isinstance(r, Tensor) else r
        if node.vjp_fn is None:
            raise RuntimeError(
                f"grad graph for op '{node.name}' already freed; pass "
                "retain_graph=True to backward() to reuse it")
        ingrads = node.vjp_fn(tuple(outgrads))
        if not retain_graph:
            node.vjp_fn = None
        for parent, g in zip(node.parents, ingrads):
            if g is None or parent.stop_gradient:
                continue
            pn = parent._node
            if pn is None:
                if parent._hooks:
                    for h in parent._hooks:
                        r = h(Tensor._wrap(g))
                        if r is not None:
                            g = r._data if isinstance(r, Tensor) else r
                _leaf_accumulate(leaf_grads, parent, g)
            else:
                pslot = accum.setdefault(id(pn), [None] * len(pn.out_avals))
                i = parent._out_idx
                pslot[i] = g if pslot[i] is None else pslot[i] + g
                nodes[id(pn)] = pn

    # write .grad on leaves
    results = None
    if inputs is not None:
        results = []
        for t in inputs:
            g = leaf_grads.get(id(t))
            if g is None and t._node is not None:
                slot = accum.get(id(t._node))
                if slot is not None:
                    g = slot[t._out_idx]
            results.append(None if g is None else Tensor._wrap(g))
    if accumulate_into_grad:
        for t_id, g in leaf_grads.items():
            t = _LEAF_CACHE.pop(t_id, None)
            if t is None:
                continue
            if t.grad is None:
                t.grad = Tensor._wrap(g)
            elif hasattr(t.grad, "to_dense"):
                # SelectedRows meeting a dense contribution: merge to dense
                t.grad = Tensor._wrap(t.grad.to_dense() + g)
            else:
                t.grad = Tensor._wrap(t.grad._data + g)
    else:
        _LEAF_CACHE.clear()
    return results


_LEAF_CACHE: dict[int, object] = {}


def _leaf_accumulate(leaf_grads, t, g):
    _LEAF_CACHE[id(t)] = t
    if id(t) in leaf_grads:
        leaf_grads[id(t)] = leaf_grads[id(t)] + g
    else:
        leaf_grads[id(t)] = g
