"""SelectedRows: the sparse-gradient carrier.

Reference analogue: phi::SelectedRows
(/root/reference/paddle/phi/core/selected_rows.h — rows + value tensor +
height), produced by embedding lookup backward when ``sparse=True``
(lookup_table_v2_grad) and consumed by the sparse sgd/adam kernels.

TPU-native role: on-device it is just (int32 rows, [n, dim] values) — the
optimizer applies it with one XLA scatter-add, which is exactly what the
reference's CUDA sparse kernels hand-roll.  The win is identical: a
vocab-sized embedding with a batch touching k rows moves O(k·dim) gradient
bytes instead of O(V·dim).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


class SelectedRows:
    def __init__(self, rows, values, height):
        import jax
        if isinstance(rows, jax.core.Tracer):
            self.rows = rows.astype(jnp.int32)
        else:
            self.rows = jnp.asarray(np.asarray(rows), jnp.int32)
        self.values = values if hasattr(values, "dtype") else jnp.asarray(
            values)
        self.height = int(height)

    @property
    def shape(self):
        return [self.height] + list(self.values.shape[1:])

    @property
    def dtype(self):
        return self.values.dtype

    def to_dense(self):
        """Scatter-add into the dense twin (duplicate rows accumulate,
        matching dense embedding backward)."""
        dense = jnp.zeros((self.height,) + tuple(self.values.shape[1:]),
                          self.values.dtype)
        return dense.at[self.rows].add(self.values)

    def merge_rows(self):
        """Accumulate duplicate rows into unique rows (reference:
        phi/kernels/funcs/selected_rows_functor.h MergeAdd) — O(k·dim)
        instead of densifying to O(V·dim).  Eager-only (host unique)."""
        rows_np = np.asarray(self.rows)
        uniq, inv = np.unique(rows_np, return_inverse=True)
        if uniq.size == rows_np.size:
            return self  # already unique
        merged = jnp.zeros((uniq.size,) + tuple(self.values.shape[1:]),
                           self.values.dtype).at[inv].add(self.values)
        return SelectedRows(uniq, merged, self.height)

    def numpy(self):
        return np.asarray(self.to_dense())

    def __repr__(self):
        return (f"SelectedRows(height={self.height}, "
                f"n_rows={self.values.shape[0]}, dim={self.shape[1:]})")
