"""Thread-local interpreter state: grad mode and AMP mode.

Reference analogue: ``egr::Controller`` (AMP level consulted by every generated
ad_func, /root/reference/paddle/fluid/eager/auto_code_generator/generator/eager_gen.py:565)
and the ``no_grad`` tracer guard.  On TPU these are host-side Python state that
steer tracing — they cost nothing inside compiled programs.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager


class _State(threading.local):
    def __init__(self):
        self.grad_enabled = True
        self.amp_level = "O0"          # O0 | O1 | O2
        self.amp_dtype = "bfloat16"    # TPU-native default (fp16 supported)
        self.amp_white = set()
        self.amp_black = set()
        self.tracing_depth = 0         # >0 while inside jax.jit trace
        self.recording_program = None  # paddle.static Program under guard
        self.accumulating_backward = True  # False during paddle.grad()


STATE = _State()


@contextmanager
def no_grad_guard():
    prev = STATE.grad_enabled
    STATE.grad_enabled = False
    try:
        yield
    finally:
        STATE.grad_enabled = prev


@contextmanager
def enable_grad_guard():
    prev = STATE.grad_enabled
    STATE.grad_enabled = True
    try:
        yield
    finally:
        STATE.grad_enabled = prev


def grad_enabled() -> bool:
    return STATE.grad_enabled
