"""Thread-local interpreter state: grad mode and AMP mode.

Reference analogue: ``egr::Controller`` (AMP level consulted by every generated
ad_func, /root/reference/paddle/fluid/eager/auto_code_generator/generator/eager_gen.py:565)
and the ``no_grad`` tracer guard.  On TPU these are host-side Python state that
steer tracing — they cost nothing inside compiled programs.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager


class _State(threading.local):
    def __init__(self):
        self.grad_enabled = True
        self.amp_level = "O0"          # O0 | O1 | O2
        self.amp_dtype = "bfloat16"    # TPU-native default (fp16 supported)
        self.amp_white = set()
        self.amp_black = set()
        self.tracing_depth = 0         # >0 while inside jax.jit trace
        self.recording_program = None  # paddle.static Program under guard
        self.accumulating_backward = True  # False during paddle.grad()


STATE = _State()

# ---------------------------------------------------------------------------
# Train-state mutation version + pre-mutation barrier.
#
# Device-resident train steps (jit.CompiledTrainStep) keep the flat
# params/buffers/opt-state pytree from the previous step's OUTPUT and feed it
# straight back in, skipping the O(num_params) Layer/Optimizer dict rebuilds.
# They stay correct by watching this process-global counter: every official
# host-side mutation path (Parameter.set_value, Layer.set_state_dict,
# Layer.to(dtype), Optimizer.set_state_dict, amp.decorate, Tensor.zero_)
# calls ``bump_param_version()`` BEFORE applying its write.  The call is a
# barrier: it first flushes every live device-resident step back into the
# python objects (so the write lands on post-step values, not stale ones),
# then advances the version so those steps re-hydrate on their next call.
# Raw ``t._data = ...`` writes are NOT tracked — use the official APIs or
# call ``step.sync()`` / ``step.invalidate()`` explicitly.
# ---------------------------------------------------------------------------
_PARAM_VERSION = [0]
_PARAM_SYNC_HOOKS: list = []  # weakref.WeakMethod -> CompiledTrainStep.sync


def register_param_sync_hook(bound_sync_method):
    """Register a device-state flush callback (held weakly) that the
    mutation barrier invokes before any tracked host-side write."""
    import weakref
    _PARAM_SYNC_HOOKS.append(weakref.WeakMethod(bound_sync_method))


def bump_param_version():
    """Pre-mutation barrier: flush device-resident train state to host,
    then advance the version so compiled steps re-hydrate next call."""
    if _PARAM_SYNC_HOOKS:
        live = []
        for ref in _PARAM_SYNC_HOOKS:
            cb = ref()
            if cb is not None:
                cb()
                live.append(ref)
        _PARAM_SYNC_HOOKS[:] = live
    _PARAM_VERSION[0] += 1


def param_version() -> int:
    return _PARAM_VERSION[0]


@contextmanager
def no_grad_guard():
    prev = STATE.grad_enabled
    STATE.grad_enabled = False
    try:
        yield
    finally:
        STATE.grad_enabled = prev


@contextmanager
def enable_grad_guard():
    prev = STATE.grad_enabled
    STATE.grad_enabled = True
    try:
        yield
    finally:
        STATE.grad_enabled = prev


def grad_enabled() -> bool:
    return STATE.grad_enabled
