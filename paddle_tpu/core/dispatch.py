"""Eager op dispatch: the TPU-native ``KernelFactory``.

Reference analogue: the generated ``*_ad_func`` pipeline — AMP autocast
(eager_gen.py:565) → kernel selection (``KernelFactory::SelectKernelOrThrowError``,
/root/reference/paddle/phi/core/kernel_factory.cc:230) → kernel launch →
GradNode creation (eager_gen.py:1103).

Here a "kernel" is a jnp/lax-traceable function; dispatch is one Python call:
unwrap Tensors → AMP cast → execute (via ``jax.vjp`` when taping) → wrap
outputs + build the GradNode.  Under ``jax.jit`` the same path traces into a
single XLA program, so there is no separate static-graph dispatch tier.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import dtype as dtypes
from .autograd import GradNode
from .flags import flag
from .state import STATE, grad_enabled

# ---------------------------------------------------------------------------
# Op registry (single source of truth, YAML analogue of
# /root/reference/paddle/phi/ops/yaml/ops.yaml)
# ---------------------------------------------------------------------------
OPS: dict[str, dict] = {}


def register_op(name, fn=None, **meta):
    if name not in OPS:
        OPS[name] = {"fn": fn, **meta}
    return OPS[name]


def _amp_cast(name, datas):
    """O1/O2 autocast, mirroring amp/auto_cast.py white/black lists."""
    level = STATE.amp_level
    if level == "O0":
        return datas
    target = jnp.bfloat16 if STATE.amp_dtype == "bfloat16" else jnp.float16
    if name in STATE.amp_white:
        return [d.astype(target)
                if hasattr(d, "dtype") and d.dtype in (jnp.float32, jnp.float64)
                else d for d in datas]
    if name in STATE.amp_black:
        return [d.astype(jnp.float32)
                if hasattr(d, "dtype") and d.dtype in (jnp.float16, jnp.bfloat16)
                else d for d in datas]
    if level == "O2":
        # O2: everything not blacklisted runs in low precision
        return [d.astype(target)
                if hasattr(d, "dtype") and d.dtype in (jnp.float32,)
                else d for d in datas]
    return datas


def _is_tensor(x):
    from .tensor import Tensor
    return isinstance(x, Tensor)


def apply_op(name, fn, *args, nout=1, amp=True, **kwargs):
    """Execute op ``name`` implemented by traceable ``fn``.

    ``args`` may be an arbitrary pytree containing Tensors; ``kwargs`` are
    static attributes.  Returns Tensor or tuple of Tensors (len == nout, or
    whatever fn returns if nout is None).
    """
    from .tensor import Tensor

    register_op(name, fn)

    leaves, treedef = jax.tree_util.tree_flatten(
        args, is_leaf=lambda x: isinstance(x, Tensor))
    datas = [l._data if isinstance(l, Tensor) else l for l in leaves]
    do_amp = amp and STATE.amp_level != "O0"

    diff_pos = []
    if grad_enabled():
        for i, l in enumerate(leaves):
            if (isinstance(l, Tensor) and not l.stop_gradient
                    and dtypes.is_floating(datas[i].dtype)):
                diff_pos.append(i)

    if not diff_pos:
        if do_amp:
            datas = _amp_cast(name, datas)
        rebuilt = jax.tree_util.tree_unflatten(treedef, datas)
        out = fn(*rebuilt, **kwargs)
        wrapped = _wrap_outputs(name, out, None, nout)
        _maybe_record(name, fn, treedef, leaves, kwargs, wrapped)
        return wrapped

    def closure(*dvals):
        ds = list(datas)
        for p, v in zip(diff_pos, dvals):
            ds[p] = v
        if do_amp:
            # cast inside the closure so cotangent dtypes match the
            # (uncast) parent tensors — the cast's own VJP converts grads
            ds = _amp_cast(name, ds)
        rebuilt = jax.tree_util.tree_unflatten(treedef, ds)
        out = fn(*rebuilt, **kwargs)
        return out if isinstance(out, tuple) else (out,)

    primals = [datas[p] for p in diff_pos]
    outs, vjp_fn = jax.vjp(closure, *primals)
    parents = [leaves[p] for p in diff_pos]
    node = GradNode(name, vjp_fn, parents,
                    [(o.shape, o.dtype) for o in outs])
    wrapped = _wrap_outputs(name,
                            outs if nout != 1 or len(outs) > 1 else outs[0],
                            node, nout)
    _maybe_record(name, fn, treedef, leaves, kwargs, wrapped)
    return wrapped


def _maybe_record(name, fn, treedef, leaves, kwargs, outputs):
    """paddle.static program capture: while a Program is under
    ``program_guard``, every dispatched op is appended to its op list (the
    analogue of static-mode op registration into the current Block,
    reference: python/paddle/base/framework.py append_op)."""
    prog = STATE.recording_program
    if prog is not None and STATE.tracing_depth == 0:
        prog._record(name, fn, treedef, leaves, kwargs, outputs)


def _wrap_outputs(name, out, node, nout):
    from .tensor import Tensor

    if flag("FLAGS_check_nan_inf"):
        _check_nan_inf(name, out)

    def wrap_one(o, idx):
        t = Tensor._wrap(o)
        if node is not None:
            if dtypes.is_floating(o.dtype):
                t.stop_gradient = False
            t._node = node
            t._out_idx = idx
            node.set_output(idx, t)
        return t

    if isinstance(out, tuple):
        return tuple(wrap_one(o, i) for i, o in enumerate(out))
    return wrap_one(out, 0)


def _check_nan_inf(name, out):
    """Debug nan/inf check (FLAGS_check_nan_inf; reference:
    paddle/fluid/eager/nan_inf_utils.cc). Eager-concrete values only."""
    outs = out if isinstance(out, tuple) else (out,)
    for o in outs:
        if isinstance(o, jax.Array) and not isinstance(
                o, jax.core.Tracer) and jnp.issubdtype(o.dtype, jnp.floating):
            if bool(jnp.any(~jnp.isfinite(o))):
                raise FloatingPointError(f"op '{name}' produced NaN/Inf")


def matmul_precision():
    p = flag("FLAGS_tpu_matmul_precision")
    return {"default": None, "high": jax.lax.Precision.HIGH,
            "highest": jax.lax.Precision.HIGHEST}.get(p, None)
