"""The eager Tensor.

TPU-native redesign of ``phi::DenseTensor`` + ``paddle::Tensor``
(/root/reference/paddle/phi/core/dense_tensor.h:27,
/root/reference/paddle/phi/api/include/tensor.h:82) and the Python-side
``paddle.Tensor`` patched methods
(/root/reference/python/paddle/base/dygraph/tensor_patch_methods.py).

A Tensor wraps a ``jax.Array`` (device buffer owned by the XLA runtime — there
is no user-level allocator on TPU; cf. SURVEY §2.2 note) plus autograd
metadata (``stop_gradient``, ``grad``, GradNode edge) — the analogue of
``AutogradMeta`` (/root/reference/paddle/fluid/eager/autograd_meta.h:61).

The same object works inside ``jax.jit`` traces: ``_data`` is then a tracer
and all ops stay traceable, which is how whole-program capture (paddle.jit)
works without a second IR.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import dtype as dtypes
from .autograd import run_backward
from .dispatch import apply_op
from .state import bump_param_version, no_grad_guard

_tensor_counter = [0]


class Tensor:
    __slots__ = ("_data", "stop_gradient", "grad", "_node", "_out_idx",
                 "name", "persistable", "_hooks", "trainable", "is_dist",
                 "placements", "process_mesh", "__weakref__", "__dict__")

    def __init__(self, data, dtype=None, place=None, stop_gradient=True,
                 name=None):
        if isinstance(data, Tensor):
            data = data._data
        dt = dtypes.convert_dtype(dtype)
        if isinstance(data, (jax.Array, jax.core.Tracer)):
            self._data = data if dt is None else data.astype(dt)
        else:
            arr = np.asarray(data)
            # paddle default: python float data -> float32, int -> int64
            if dt is None and arr.dtype == np.float64:
                dt = np.dtype(np.float32)
            self._data = jnp.asarray(arr, dtype=dt)
        self.stop_gradient = stop_gradient
        self.grad = None
        self._node = None
        self._out_idx = 0
        self.persistable = False
        self.trainable = True
        self._hooks = []
        self.is_dist = False
        self.placements = None
        self.process_mesh = None
        if name is None:
            _tensor_counter[0] += 1
            name = f"generated_tensor_{_tensor_counter[0]}"
        self.name = name

    # -- construction helpers ------------------------------------------------
    @staticmethod
    def _wrap(data, stop_gradient=True):
        t = Tensor.__new__(Tensor)
        t._data = data
        t.stop_gradient = stop_gradient
        t.grad = None
        t._node = None
        t._out_idx = 0
        t.persistable = False
        t.trainable = True
        t._hooks = []
        t.is_dist = False
        t.placements = None
        t.process_mesh = None
        _tensor_counter[0] += 1
        t.name = f"generated_tensor_{_tensor_counter[0]}"
        return t

    # -- basic properties ----------------------------------------------------
    @property
    def data(self):
        return self

    @data.setter
    def data(self, value):
        self._data = value._data if isinstance(value, Tensor) else jnp.asarray(value)

    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def ndim(self):
        return self._data.ndim

    dim = ndim

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def place(self):
        from ..device import _current_place
        try:
            devs = self._data.devices()
            d = next(iter(devs))
            return f"{d.platform}:{d.id}"
        except Exception:
            return _current_place()

    @property
    def T(self):
        return apply_op("transpose", lambda x: x.T, self)

    @property
    def is_leaf(self):
        return self._node is None

    def numel(self):
        return Tensor._wrap(jnp.asarray(self.size, dtype=jnp.int64))

    # -- conversion ----------------------------------------------------------
    def numpy(self):
        return np.asarray(self._data)

    def item(self, *idx):
        a = np.asarray(self._data)
        return a.item(*idx) if idx else a.item()

    def tolist(self):
        return np.asarray(self._data).tolist()

    def __array__(self, dtype=None):
        a = np.asarray(self._data)
        return a.astype(dtype) if dtype is not None else a

    def astype(self, dtype):
        dt = dtypes.convert_dtype(dtype)
        return apply_op("cast", lambda x: x.astype(dt), self)

    cast = astype

    def clone(self):
        return apply_op("assign", jnp.copy, self)

    def detach(self):
        t = Tensor._wrap(self._data)
        t.stop_gradient = True
        return t

    def detach_(self):
        self._node = None
        self.stop_gradient = True
        return self

    def cpu(self):
        return Tensor._wrap(jax.device_put(self._data, jax.devices("cpu")[0])
                            if jax.devices()[0].platform != "cpu" else self._data)

    def to(self, *args, **kwargs):
        dtype = kwargs.get("dtype")
        for a in args:
            if isinstance(a, str) and a in ("cpu", "tpu", "gpu") or ":" in str(a):
                continue
            dtype = a
        if dtype is not None:
            return self.astype(dtype)
        return self

    cuda = to  # compat: .cuda() is a no-op move on TPU
    tpu = to

    def pin_memory(self):
        return self

    # -- autograd ------------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        run_backward([self], [grad_tensor], retain_graph=retain_graph)

    def register_hook(self, hook):
        self._hooks.append(hook)

        class _Handle:
            def remove(h):
                if hook in self._hooks:
                    self._hooks.remove(hook)
        return _Handle()

    def clear_gradient(self, set_to_zero=False):
        if set_to_zero and self.grad is not None:
            if hasattr(self.grad, "to_dense"):  # SelectedRows: drop rows
                self.grad = Tensor._wrap(jnp.zeros_like(self._data))
            else:
                self.grad = Tensor._wrap(jnp.zeros_like(self.grad._data))
        else:
            self.grad = None

    clear_grad = clear_gradient

    def zero_(self):
        """In-place fill with zeros (reference: paddle.Tensor.zero_ zeroes the
        tensor *data*, not the gradient)."""
        if self.persistable:  # parameter mutated outside the compiled step
            bump_param_version()
        self._data = jnp.zeros_like(self._data)
        return self

    @property
    def is_tensor(self):
        return True

    def _inplace_assign(self, out):
        """Rebind this tensor to ``out``'s value+node (functional in-place)."""
        self._data = out._data
        self._node = out._node
        self._out_idx = out._out_idx
        self.stop_gradient = out.stop_gradient
        if self._node is not None:
            self._node.set_output(self._out_idx, self)
        return self

    # -- operators -----------------------------------------------------------
    def _b(self, name, fn, other, reverse=False):
        if isinstance(other, (int, float, bool, complex, np.number)):
            a, b = (other, self) if reverse else (self, other)
            return apply_op(name, fn, a, b)
        other = other if isinstance(other, Tensor) else Tensor(other)
        a, b = (other, self) if reverse else (self, other)
        return apply_op(name, fn, a, b)

    def __add__(self, o):
        return self._b("add", jnp.add, o)

    __radd__ = __add__

    def __sub__(self, o):
        return self._b("subtract", jnp.subtract, o)

    def __rsub__(self, o):
        return self._b("subtract", jnp.subtract, o, reverse=True)

    def __mul__(self, o):
        return self._b("multiply", jnp.multiply, o)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._b("divide", jnp.true_divide, o)

    def __rtruediv__(self, o):
        return self._b("divide", jnp.true_divide, o, reverse=True)

    def __floordiv__(self, o):
        return self._b("floor_divide", jnp.floor_divide, o)

    def __mod__(self, o):
        return self._b("remainder", jnp.remainder, o)

    def __pow__(self, o):
        return self._b("pow", jnp.power, o)

    def __rpow__(self, o):
        return self._b("pow", jnp.power, o, reverse=True)

    def __matmul__(self, o):
        from .dispatch import matmul_precision
        return self._b("matmul",
                       lambda a, b: jnp.matmul(a, b,
                                               precision=matmul_precision()),
                       o)

    def __neg__(self):
        return apply_op("scale", jnp.negative, self)

    def __abs__(self):
        return apply_op("abs", jnp.abs, self)

    def __invert__(self):
        return apply_op("bitwise_not", jnp.invert, self)

    def _cmp(self, name, fn, o):
        o = o._data if isinstance(o, Tensor) else o
        return Tensor._wrap(fn(self._data, o))

    def __lt__(self, o):
        return self._cmp("less_than", jnp.less, o)

    def __le__(self, o):
        return self._cmp("less_equal", jnp.less_equal, o)

    def __gt__(self, o):
        return self._cmp("greater_than", jnp.greater, o)

    def __ge__(self, o):
        return self._cmp("greater_equal", jnp.greater_equal, o)

    def __eq__(self, o):
        if o is None:
            return False
        return self._cmp("equal", jnp.equal, o)

    def __ne__(self, o):
        if o is None:
            return True
        return self._cmp("not_equal", jnp.not_equal, o)

    def __hash__(self):
        return id(self)

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __bool__(self):
        return bool(self._data)

    def __int__(self):
        return int(self._data)

    def __float__(self):
        return float(self._data)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # -- indexing ------------------------------------------------------------
    @staticmethod
    def _unwrap_index(idx):
        if isinstance(idx, Tensor):
            return idx._data
        if isinstance(idx, tuple):
            return tuple(Tensor._unwrap_index(i) for i in idx)
        if isinstance(idx, list):
            return jnp.asarray(idx)
        return idx

    def __getitem__(self, idx):
        idx = Tensor._unwrap_index(idx)
        return apply_op("slice", lambda x: x[idx], self)

    def __setitem__(self, idx, value):
        idx = Tensor._unwrap_index(idx)
        value = value if isinstance(value, Tensor) else Tensor(value)
        out = apply_op("set_value",
                       lambda x, v: x.at[idx].set(v.astype(x.dtype)), self,
                       value)
        self._inplace_assign(out)

    # -- repr ----------------------------------------------------------------
    def __repr__(self):
        if isinstance(self._data, jax.core.Tracer):
            return (f"Tensor(shape={self.shape}, dtype={self.dtype}, "
                    f"<traced>)")
        from ..framework import PRINT_OPTIONS
        body = (np.array2string(np.asarray(self._data), **PRINT_OPTIONS)
                if PRINT_OPTIONS else repr(np.asarray(self._data)))
        return (f"Tensor(shape={self.shape}, dtype={dtypes.dtype_name(self.dtype)}, "
                f"stop_gradient={self.stop_gradient},\n"
                f"       {body})")

    __str__ = __repr__

    # numpy priority so np scalar * Tensor routes here
    __array_priority__ = 100


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor (reference: python/paddle/tensor/creation.py)."""
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)


class Parameter(Tensor):
    """Trainable tensor (reference: EagerParamBase,
    python/paddle/base/framework.py)."""

    def __init__(self, data, dtype=None, name=None, trainable=True):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable,
                         name=name)
        self.persistable = True
        self.trainable = trainable

    def set_value(self, value):
        bump_param_version()  # flush device-resident state, then mutate
        value = value._data if isinstance(value, Tensor) else jnp.asarray(value)
        with no_grad_guard():
            self._data = value.astype(self._data.dtype)

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()
