"""Global runtime flag registry.

TPU-native analogue of the reference's gflags-based registry
(/root/reference/paddle/common/flags.cc — 159 ``PHI_DEFINE_EXPORTED_*`` flags,
surfaced to Python via ``paddle.set_flags/get_flags``,
/root/reference/python/paddle/base/framework.py:106,131).  Here flags are a
process-local dict, seedable from ``FLAGS_*`` environment variables, consulted
by the runtime (nan/inf checks, deterministic mode, log level, ...).
"""

from __future__ import annotations

import os
from typing import Any

_REGISTRY: dict[str, dict[str, Any]] = {}
_OBSERVERS: dict[str, list] = {}


def register_flag_observer(name: str, fn, call_now: bool = True):
    """Invoke ``fn(value)`` whenever ``name`` changes via ``set_flags`` (and
    once at registration so env-seeded values propagate).  Lets hot paths
    cache a flag in a local instead of a registry lookup per event — the
    host tracer keys its fast no-op check on this."""
    _OBSERVERS.setdefault(name, []).append(fn)
    if call_now and name in _REGISTRY:
        fn(_REGISTRY[name]["value"])


def define_flag(name: str, default, help_str: str = ""):
    env = os.environ.get(name)
    value = default
    if env is not None:
        if isinstance(default, bool):
            value = env.lower() in ("1", "true", "yes", "on")
        elif isinstance(default, int):
            value = int(env)
        elif isinstance(default, float):
            value = float(env)
        else:
            value = env
    _REGISTRY[name] = {"value": value, "default": default, "help": help_str}
    return value


def set_flags(flags: dict):
    for k, v in flags.items():
        if k not in _REGISTRY:
            raise KeyError(f"unknown flag {k!r}; known: {sorted(_REGISTRY)}")
        _REGISTRY[k]["value"] = v
        for fn in _OBSERVERS.get(k, ()):
            fn(v)


def get_flags(names):
    if isinstance(names, str):
        names = [names]
    return {n: _REGISTRY[n]["value"] for n in names}


def flag(name: str):
    return _REGISTRY[name]["value"]


# Core runtime flags (subset of flags.cc that is meaningful on TPU).
define_flag("FLAGS_check_nan_inf", False,
            "Check every op output for NaN/Inf (debug; forces sync).")
define_flag("FLAGS_cudnn_deterministic", False,
            "Deterministic mode (maps to XLA deterministic ops).")
define_flag("FLAGS_embedding_deterministic", 0, "compat alias")
define_flag("FLAGS_use_stride_kernel", True, "views share memory when possible")
define_flag("FLAGS_low_precision_op_list", 0, "log amp op decisions")
define_flag("FLAGS_benchmark", False, "sync after every op for timing")
define_flag("FLAGS_log_level", 0, "verbose log level (VLOG equivalent)")
define_flag("FLAGS_allocator_strategy", "xla",
            "memory strategy: XLA owns device memory on TPU")
define_flag("FLAGS_fraction_of_gpu_memory_to_use", 0.92, "compat no-op on TPU")
define_flag("FLAGS_tpu_matmul_precision", "default",
            "default|high|highest -> jax.lax precision for matmul ops")
define_flag("FLAGS_eager_op_jit", False,
            "route eager op execution through a per-op jit cache")
define_flag("FLAGS_host_trace_level", 1,
            "host tracer verbosity (reference: FLAGS_host_trace_level, "
            "host_tracer.cc): 0 disables span recording entirely; 1 records "
            "framework phase spans; 2 adds high-frequency spans")
define_flag("FLAGS_fused_steps", 1,
            "jit.CompiledTrainStep fused-dispatch window: scan this many "
            "training steps per XLA launch (1 = one dispatch per step). "
            "Amortizes per-step python dispatch cost for short steps — the "
            "scheduling-overhead analogue of new_executor/CINN fusion.")
