"""Dtype system for paddle_tpu.

TPU-native design: dtypes are plain ``jnp.dtype`` objects (XLA's native element
types).  The reference keeps a parallel C++ enum (``phi::DataType``,
/root/reference/paddle/phi/common/data_type.h) plus a software bfloat16 type
(/root/reference/paddle/phi/common/bfloat16.h); on TPU bfloat16 is a hardware
type and JAX/ml_dtypes already provide it, so this module only supplies naming,
aliasing and the binary type-promotion table
(cf. /root/reference/paddle/phi/common/type_promotion.h).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Public dtype aliases (paddle.float32 etc.)
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
uint8 = jnp.uint8
bool_ = jnp.bool_
complex64 = jnp.complex64
complex128 = jnp.complex128
# fp8 (TPU v5+ native)
float8_e4m3fn = jnp.float8_e4m3fn
float8_e5m2 = jnp.float8_e5m2

_NAME_TO_DTYPE = {
    "float16": float16, "fp16": float16, "half": float16,
    "bfloat16": bfloat16, "bf16": bfloat16,
    "float32": float32, "fp32": float32, "float": float32,
    "float64": float64, "fp64": float64, "double": float64,
    "int8": int8, "int16": int16, "int32": int32, "int64": int64,
    "uint8": uint8, "bool": bool_,
    "complex64": complex64, "complex128": complex128,
    "float8_e4m3fn": float8_e4m3fn, "float8_e5m2": float8_e5m2,
}


def convert_dtype(dtype):
    """Normalise a user-supplied dtype (string / np / jnp) to a numpy dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype not in _NAME_TO_DTYPE:
            raise ValueError(f"unknown dtype string: {dtype!r}")
        return np.dtype(_NAME_TO_DTYPE[dtype])
    return np.dtype(dtype)


def dtype_name(dtype) -> str:
    return np.dtype(dtype).name


def is_floating(dtype) -> bool:
    return jnp.issubdtype(np.dtype(dtype), jnp.floating)


def is_integer(dtype) -> bool:
    return jnp.issubdtype(np.dtype(dtype), jnp.integer)


def is_complex(dtype) -> bool:
    return jnp.issubdtype(np.dtype(dtype), jnp.complexfloating)


# ---------------------------------------------------------------------------
# Type promotion (mirrors the semantics of phi/common/type_promotion.h:
# float wins over int, wider float wins, fp16+bf16 -> float32).
# ---------------------------------------------------------------------------
_FLOAT_ORDER = [jnp.dtype(float16), jnp.dtype(bfloat16), jnp.dtype(float32),
                jnp.dtype(float64)]


def promote_types(a, b):
    a, b = np.dtype(a), np.dtype(b)
    if a == b:
        return a
    # fp16 x bf16 promotes to fp32 (no ordering between them)
    halves = {np.dtype(np.float16), np.dtype(bfloat16)}
    if a in halves and b in halves:
        return np.dtype(np.float32)
    return np.promote_types(a, b) if not (a in halves or b in halves) else (
        _promote_with_half(a, b))


def _promote_with_half(a, b):
    half = a if a in {np.dtype(np.float16), np.dtype(bfloat16)} else b
    other = b if half is a else a
    if is_floating(other):
        # wider float wins
        if np.dtype(other).itemsize > 2:
            return np.dtype(other)
        return half
    # int/bool + half -> half
    return half
