"""paddle.inference — deployment predictor API.

Reference analogue: AnalysisPredictor/AnalysisConfig
(/root/reference/paddle/fluid/inference/api/analysis_predictor.h,
paddle_inference_api.h) — load a serialized program + params, feed named
inputs, run, fetch named outputs.

TPU-native: the serialized program IS the jit.save StableHLO artifact
(paddle_tpu/jit — jax.export); XLA plays the role of the 290 IR fusion
passes and the TensorRT engine (compilation happens on load/first run).
The Config knobs that steer CUDA/TRT specifics are accepted and recorded
but are no-ops, so reference deployment scripts run unchanged.
"""

from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


class Config:
    """reference: AnalysisConfig (paddle_inference_api.h)."""

    def __init__(self, prog_file=None, params_file=None):
        # jit.save artifacts share a prefix; accept either the prefix or
        # the explicit .pdmodel path
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self._prefix = prog_file
        self._params_file = params_file
        self._memory_optim = False
        self._device = "tpu"
        self._device_id = 0

    def model_prefix(self):
        return self._prefix

    # -- accepted-but-delegated knobs (XLA owns these decisions) ------------
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device, self._device_id = "tpu", device_id

    def disable_gpu(self):
        self._device = "cpu"

    def enable_memory_optim(self, x=True):
        self._memory_optim = x

    def switch_ir_optim(self, x=True):
        pass  # XLA always optimizes

    def enable_tensorrt_engine(self, *a, **kw):
        pass  # XLA:TPU is the engine

    def set_cpu_math_library_num_threads(self, n):
        pass

    def summary(self):
        return (f"Config(prefix={self._prefix!r}, device={self._device}:"
                f"{self._device_id}, memory_optim={self._memory_optim})")


class _Handle:
    """Input/output tensor handle (reference: ZeroCopyTensor)."""

    def __init__(self, name):
        self.name = name
        self._array = None

    def copy_from_cpu(self, arr):
        # the reference ZeroCopyTensor contract COPIES: the caller may
        # reuse/mutate its buffer before run()
        self._array = np.array(arr, copy=True, order="C")

    def copy_to_cpu(self):
        return np.asarray(self._array)

    def reshape(self, shape):
        if self._array is not None:
            self._array = self._array.reshape(shape)

    def shape(self):
        return list(self._array.shape) if self._array is not None else None


class Predictor:
    """reference: AnalysisPredictor — run() over named handles."""

    def __init__(self, config: Config):
        from ..jit import load
        if not config.model_prefix():
            raise ValueError("Config needs the jit.save artifact prefix")
        self._layer = load(config.model_prefix())
        import json
        import os
        meta_path = config.model_prefix() + ".pdmeta.json"
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                n_inputs = len(json.load(f)["inputs"])
        else:
            n_inputs = 1
        self._in_names = [f"input_{i}" for i in range(n_inputs)]
        self._inputs = {n: _Handle(n) for n in self._in_names}
        self._out_names = []
        self._outputs = {}

    def get_input_names(self):
        return list(self._in_names)

    def get_input_handle(self, name):
        return self._inputs[name]

    def run(self):
        unset = [n for n in self._in_names
                 if self._inputs[n]._array is None]
        if unset:
            raise ValueError(
                f"inference inputs not set: {unset} — call "
                "get_input_handle(name).copy_from_cpu(...) first")
        args = [Tensor(self._inputs[n].copy_to_cpu())
                for n in self._in_names]
        out = self._layer(*args)
        outs = out if isinstance(out, (list, tuple)) else [out]
        self._out_names = [f"output_{i}" for i in range(len(outs))]
        self._outputs = {}
        for n, o in zip(self._out_names, outs):
            h = _Handle(n)
            h.copy_from_cpu(np.asarray(o.numpy() if isinstance(o, Tensor)
                                       else o))
            self._outputs[n] = h
        return True

    def get_output_names(self):
        return list(self._out_names)

    def get_output_handle(self, name):
        return self._outputs[name]


def create_predictor(config: Config):
    """reference: paddle_infer::CreatePredictor."""
    return Predictor(config)


class GenerationPredictor:
    """Deployment front end for causal-LM generation that routes every
    request through ``serving.LLMEngine`` (continuous batching over a
    device-resident KV slot arena) instead of one ``GPT.generate`` program
    per request shape.

    reference analogue: the inference-deployment generation path
    (fused_multi_transformer serving); here the engine owns admission,
    batching, sampling, and eviction — the predictor is a thin façade:

        pred = inference.GenerationPredictor(model, max_slots=8)
        outs = pred.generate(prompts, max_new_tokens=64)   # blocking batch
        for tok in pred.stream(prompt, max_new_tokens=64): # token stream
            ...
    """

    def __init__(self, model, max_slots=8, max_seq_len=None, **engine_kw):
        from ..serving import LLMEngine
        self._engine = LLMEngine(model, max_slots=max_slots,
                                 max_seq_len=max_seq_len, **engine_kw)

    @property
    def engine(self):
        return self._engine

    def generate(self, prompts, **kw):
        """Blocking batch generation: list of prompts in, list of full
        np.int32 sequences (prompt + generated) out."""
        return self._engine.generate(prompts, **kw)

    def stream(self, prompt, **kw):
        """Submit one prompt and iterate its generated tokens as the
        engine produces them."""
        return iter(self._engine.add_request(prompt, **kw))

    def close(self):
        """Drain the engine: finish outstanding requests, refuse new."""
        return self._engine.drain()


def create_generation_predictor(model, **kw):
    """Build a GenerationPredictor (engine-backed generation service)."""
    return GenerationPredictor(model, **kw)
