"""StringTensor + the strings op family.

Reference analogue: phi::StringTensor
(/root/reference/paddle/phi/core/string_tensor.h) and the four
strings_ops.yaml ops (empty / empty_like / lower / upper,
/root/reference/paddle/phi/ops/yaml/strings_ops.yaml).

TPU-native position: XLA has no string element type, so string data is a
HOST-side preprocessing concern by design — StringTensor wraps a numpy
object array and the ops run vectorised on host, feeding tokenizers whose
integer output is what reaches the device (the same division of labor the
reference uses: its strings kernels are CPU-only except a thin GPU copy).
"""

from __future__ import annotations

import numpy as np


class StringTensor:
    """Dense tensor of variable-length python strings (host memory)."""

    def __init__(self, data, name=None):
        if isinstance(data, StringTensor):
            data = data._data
        arr = np.asarray(data, dtype=object)
        # normalise scalars to 0-d object arrays of str
        self._data = arr
        self.name = name

    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    def numpy(self):
        return self._data

    def tolist(self):
        return self._data.tolist()

    def __getitem__(self, idx):
        out = self._data[idx]
        return out if isinstance(out, str) else StringTensor(out)

    def __len__(self):
        return len(self._data)

    def __eq__(self, other):
        other = other._data if isinstance(other, StringTensor) else other
        return np.asarray(self._data == other)

    def __repr__(self):
        return f"StringTensor(shape={self.shape}, {self._data!r})"


def to_string_tensor(data, name=None):
    """Create a StringTensor from (nested) python strings (the analogue of
    core.eager.StringTensor construction)."""
    return StringTensor(data, name)


def empty(shape, name=None):
    """strings_ops.yaml `empty`: uninitialised (here: empty-string) string
    tensor of the given shape."""
    return StringTensor(np.full(tuple(shape), "", dtype=object))


def empty_like(x, name=None):
    """strings_ops.yaml `empty_like`."""
    return StringTensor(np.full(tuple(x.shape), "", dtype=object))


def _map(fn, x):
    return StringTensor(np.frompyfunc(fn, 1, 1)(StringTensor(x)._data))


def lower(x, use_utf8_encoding=False, name=None):
    """strings_ops.yaml `lower`.  use_utf8_encoding=False mirrors the
    reference's ascii fast path; python's str.lower is already
    unicode-correct, so both settings lower non-ascii too."""
    return _map(str.lower, x)


def upper(x, use_utf8_encoding=False, name=None):
    """strings_ops.yaml `upper`."""
    return _map(str.upper, x)
