"""Text datasets namespace (reference: python/paddle/text/). Dataset download
is gated off in this air-gapped build; classes raise on fetch."""


class _DownloadGated:
    def __init__(self, *a, **k):
        raise RuntimeError("dataset download disabled in this environment")


Conll05st = Imdb = Imikolov = Movielens = UCIHousing = WMT14 = WMT16 = _DownloadGated

from . import strings  # noqa: F401,E402
from .strings import StringTensor, to_string_tensor  # noqa: F401,E402


class ViterbiDecoder:
    """Layer form of viterbi_decode (reference:
    python/paddle/text/viterbi_decode.py ViterbiDecoder — a layer, not a
    dataset)."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """Viterbi decode over a CRF transition matrix (reference:
    python/paddle/text/viterbi_decode.py)."""
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    import jax
    pot = potentials._data
    trans = transition_params._data

    def one(seq):
        def step(carry, emit):
            score, path = carry
            cand = score[:, None] + trans
            best = jnp.argmax(cand, axis=0)
            score = jnp.max(cand, axis=0) + emit
            return (score, best), best
        (score, _), bests = jax.lax.scan(step, (seq[0], jnp.zeros_like(seq[0], jnp.int32)), seq[1:])
        last = jnp.argmax(score)
        def back(tag, best_t):
            prev = best_t[tag]
            return prev, tag
        _, tags = jax.lax.scan(back, last, bests, reverse=True)
        return jnp.max(score), jnp.concatenate([tags, last[None]])
    scores, paths = jax.vmap(one)(pot)
    return Tensor._wrap(scores), Tensor._wrap(paths)
