"""Host-side span tracer.

Reference analogue: ``HostTracer`` collecting ``RecordEvent`` annotations
(platform/profiler/host_tracer.cc) merged into an event tree and exported by
``ChromeTracingLogger`` (profiler/chrometracing_logger.h:32) plus the
aggregate stats tables.

Design: a span is a wall-clock [begin, end) interval on one thread.  Sites
call ``span("jit.step")`` in a ``with`` block; when tracing is off (either
``FLAGS_host_trace_level`` is 0 or no collection session is active) ``span``
returns a shared no-op singleton — no allocation, no record, one integer
compare — so steady-state training pays nothing.  When on, completed spans
are appended to the session list as ``(name, tid, start_ns, end_ns, depth)``
tuples; nesting depth comes from a per-thread stack, which also serves as
the "span context" the NaN/Inf guard reports.

Export: ``to_chrome_trace()`` renders the session as chrome://tracing /
perfetto "X" complete events (one pid, real thread ids, metadata rows);
``summary()`` renders the Paddle-style stats table (count/total/avg/max/min
per span name).
"""

from __future__ import annotations

import json
import os
import threading
import time

from ..core import flags as _flags

# _ENABLED[0] is the single hot-path gate: the flag level while a collection
# session is active, 0 otherwise.  Recomputed on session start/stop and on
# FLAGS_host_trace_level changes (flag observer).
_ENABLED = [0]
_LEVEL = [1]
_COLLECTING = [False]
_EVENTS: list[tuple] = []
_THREAD_NAMES: dict[int, str] = {}
_TLS = threading.local()


def _recompute():
    _ENABLED[0] = _LEVEL[0] if _COLLECTING[0] else 0


def _on_level_change(value):
    _LEVEL[0] = int(value)
    _recompute()


_flags.register_flag_observer("FLAGS_host_trace_level", _on_level_change)


def get_level() -> int:
    return _LEVEL[0]


def set_level(level: int):
    _flags.set_flags({"FLAGS_host_trace_level": int(level)})


class _NullSpan:
    """Shared do-nothing context manager — the disabled-tracing fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


class _Span:
    __slots__ = ("name", "_t0", "_depth")

    def __init__(self, name):
        self.name = name
        self._t0 = 0
        self._depth = 0

    def __enter__(self):
        stack = getattr(_TLS, "stack", None)
        if stack is None:
            stack = _TLS.stack = []
        self._depth = len(stack)
        stack.append(self.name)
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        end = time.perf_counter_ns()
        stack = _TLS.stack
        if stack and stack[-1] is self.name:
            stack.pop()
        tid = threading.get_ident()
        if tid not in _THREAD_NAMES:
            _THREAD_NAMES[tid] = threading.current_thread().name
        _EVENTS.append((self.name, tid, self._t0, end, self._depth))
        return False


def span(name: str, level: int = 1):
    """Open a trace span; returns the no-op singleton when tracing is off or
    the site's ``level`` exceeds ``FLAGS_host_trace_level``."""
    if _ENABLED[0] < level:
        return _NULL
    return _Span(name)


def enabled(level: int = 1) -> bool:
    return _ENABLED[0] >= level


def current_stack() -> list:
    """Names of the spans currently open on THIS thread, outermost first
    (the context the NaN/Inf guard attaches to its error)."""
    return list(getattr(_TLS, "stack", ()))


# -- collection sessions ----------------------------------------------------
def start():
    """Begin a collection session; drops any previous session's events."""
    _EVENTS.clear()
    _THREAD_NAMES.clear()
    _COLLECTING[0] = True
    _recompute()


def stop() -> list:
    """End the session; returns the collected event tuples."""
    _COLLECTING[0] = False
    _recompute()
    return list(_EVENTS)


def is_collecting() -> bool:
    return _COLLECTING[0]


def events() -> list:
    """Snapshot of the current session's events (live if still collecting)."""
    return list(_EVENTS)


def span_count() -> int:
    return len(_EVENTS)


# -- export -----------------------------------------------------------------
def to_chrome_trace(evts=None, process_name="paddle_tpu") -> dict:
    """Render events as a chrome://tracing trace-event JSON object
    (loadable in chrome://tracing and https://ui.perfetto.dev)."""
    if evts is None:
        evts = events()
    pid = os.getpid()
    out = [{"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": process_name}}]
    for tid, tname in sorted(_THREAD_NAMES.items()):
        out.append({"ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                    "args": {"name": tname}})
    for name, tid, t0, t1, depth in evts:
        out.append({"ph": "X", "name": name, "cat": "host", "pid": pid,
                    "tid": tid, "ts": t0 / 1000.0,
                    "dur": max(t1 - t0, 0) / 1000.0,
                    "args": {"depth": depth}})
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def export_chrome(path, evts=None):
    trace = to_chrome_trace(evts)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(trace, f)
    return path


def summary(evts=None, sorted_by="total", time_unit="ms") -> str:
    """Paddle-style aggregate stats table: per span name, call count and
    total/avg/max/min duration (reference: the profiler summary tables)."""
    if evts is None:
        evts = events()
    if not evts:
        return "(no host trace events recorded)"
    div = {"s": 1e9, "ms": 1e6, "us": 1e3, "ns": 1.0}.get(time_unit, 1e6)
    agg: dict[str, list] = {}
    for name, _tid, t0, t1, _d in evts:
        dur = max(t1 - t0, 0)
        st = agg.get(name)
        if st is None:
            agg[name] = [1, dur, dur, dur]
        else:
            st[0] += 1
            st[1] += dur
            st[2] = max(st[2], dur)
            st[3] = min(st[3], dur)
    key = {"total": lambda kv: -kv[1][1], "count": lambda kv: -kv[1][0],
           "max": lambda kv: -kv[1][2], "name": lambda kv: kv[0]}
    rows = sorted(agg.items(), key=key.get(sorted_by, key["total"]))
    wname = max(24, max(len(n) for n in agg) + 2)
    header = (f"{'Name':<{wname}}{'Calls':>8}{'Total(' + time_unit + ')':>14}"
              f"{'Avg(' + time_unit + ')':>12}{'Max(' + time_unit + ')':>12}"
              f"{'Min(' + time_unit + ')':>12}")
    bar = "-" * len(header)
    lines = [bar, "Host Tracer Summary".center(len(header)), bar, header, bar]
    for name, (cnt, tot, mx, mn) in rows:
        lines.append(f"{name:<{wname}}{cnt:>8}{tot / div:>14.3f}"
                     f"{tot / cnt / div:>12.3f}{mx / div:>12.3f}"
                     f"{mn / div:>12.3f}")
    lines.append(bar)
    return "\n".join(lines)
