"""Metrics layer: histograms, a structured train-metrics logger, and
per-compiled-program device telemetry.

Reference analogue: Paddle's always-on profiler stats + fleet metric
tables (SURVEY §"Metrics / logging / observability") — here grown into a
production telemetry subsystem on top of :mod:`profiler.counters`:

* :class:`Histogram` — fixed log2-bucket latency/occupancy histogram:
  mergeable across threads/replicas (same bucket layout everywhere),
  exact count/sum/min/max, p50/p95/p99 with bounded relative error.
  The module-level registry (:func:`observe`, :func:`get_histogram`)
  replaces bare ``*_ns`` accumulator counters for serving TTFT,
  inter-token latency, queue wait, batch occupancy and checkpoint
  save/restore latency — while ``observe(..., sum_counter=True)`` keeps
  feeding the legacy counter name as a plain sum so every existing
  ``check_counters.py`` gate stays green.
* :class:`MetricsLogger` — structured JSONL time-series of per-step train
  metrics (loss, grad global-norm, lr, scaler scale/skip, tok/s,
  step-time, MFU) with an in-memory query API (:meth:`series`,
  :meth:`latest`) and Prometheus text exposition
  (:func:`prometheus_text`).  ``jit.CompiledTrainStep(metrics=logger)``
  accumulates the device-derived scalars INSIDE the donated carry and
  hands them to the logger only at existing sync boundaries — metrics-ON
  runs add zero syncs/retraces/dispatches (counter-gated in
  ``scripts/check_counters.py``).
* device telemetry — :func:`capture_program_stats` records per-compiled-
  program HBM usage (argument/output/temp bytes from XLA memory
  analysis), compile wall-time and cost-analysis FLOPs at the compile
  sites of ``jit`` and ``serving.engine`` (gated by
  ``FLAGS_device_telemetry`` — the AOT lower+compile is a second compile,
  paid only when the flag is on), exposed as ``program.*`` gauges and a
  :func:`memory_summary` table.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time

from ..core import flags as _flags
from . import counters as _counters

# one shared bucket layout so ANY two histograms merge: bucket i holds
# values in [2**(i - _OFFSET), 2**(i - _OFFSET + 1)); i=0 additionally
# absorbs zero/negative/underflow values
_NBUCKETS = 100
_OFFSET = 36  # bucket 0 lower bound 2**-36 — covers sub-ns .. 2**64 (ns scale)


def _bucket_index(value):
    if value <= 0.0:
        return 0
    # frexp: value = m * 2**e with 0.5 <= m < 1  =>  floor(log2(v)) == e - 1
    _, e = math.frexp(value)
    i = e - 1 + _OFFSET
    if i < 0:
        return 0
    if i >= _NBUCKETS:
        return _NBUCKETS - 1
    return i


def _bucket_bounds(i):
    return 2.0 ** (i - _OFFSET), 2.0 ** (i - _OFFSET + 1)


class Histogram:
    """Fixed log2-bucket histogram: O(1) record, mergeable, percentiles.

    Every instance shares one bucket layout, so histograms recorded by
    different engine replicas (or loaded from :meth:`to_dict` bundles)
    merge by plain element-wise addition.  ``count/sum/min/max`` are
    exact; percentiles carry the bucket's <=2x relative error, clamped to
    the observed [min, max] (a single-value histogram reports exact
    percentiles)."""

    __slots__ = ("name", "unit", "_lock", "_buckets", "count", "sum",
                 "min", "max")

    def __init__(self, name="", unit=""):
        self.name = name
        self.unit = unit
        self._lock = threading.Lock()
        self._buckets = [0] * _NBUCKETS
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    def record(self, value):
        value = float(value)
        i = _bucket_index(value)
        with self._lock:
            self._buckets[i] += 1
            self.count += 1
            self.sum += value
            if value < self.min:
                self.min = value
            if value > self.max:
                self.max = value

    def merge(self, other):
        """In-place element-wise merge of ``other`` into ``self``."""
        with other._lock:
            ob = list(other._buckets)
            oc, osum, omin, omax = (other.count, other.sum, other.min,
                                    other.max)
        with self._lock:
            for i, n in enumerate(ob):
                self._buckets[i] += n
            self.count += oc
            self.sum += osum
            if omin < self.min:
                self.min = omin
            if omax > self.max:
                self.max = omax
        return self

    def copy(self):
        out = Histogram(self.name, self.unit)
        out.merge(self)
        return out

    def delta(self, prev):
        """Element-wise bucket movement since ``prev`` (a fresh
        :class:`Histogram` holding only the samples recorded after
        ``prev`` was captured).  The health plane's windowed-percentile
        primitive: ``cur.delta(prev).percentile(95)`` is the p95 of the
        WINDOW, not of process lifetime.

        Reset-safe: if ``prev`` is not a prefix of ``self`` (count or any
        bucket shrank — ``reset_metrics`` ran between the snapshots),
        ``prev`` is treated as a zero baseline and the full current state
        is returned.  Exact ``min``/``max`` of the window samples are not
        recoverable from bucket counts, so the delta's min/max are the
        bounds of its outermost non-empty buckets (keeps percentile
        clamping sane)."""
        if prev is self:
            return Histogram(self.name, self.unit)
        with prev._lock:
            pb = list(prev._buckets)
            pc, psum = prev.count, prev.sum
        with self._lock:
            cb = list(self._buckets)
            cc, csum = self.count, self.sum
        if cc < pc or any(c < p for c, p in zip(cb, pb)):
            pb = [0] * _NBUCKETS       # counter reset: restart from zero
            pc, psum = 0, 0.0
        out = Histogram(self.name, self.unit)
        out._buckets = [c - p for c, p in zip(cb, pb)]
        out.count = cc - pc
        out.sum = csum - psum
        for i, n in enumerate(out._buckets):
            if n:
                lo, hi = _bucket_bounds(i)
                out.min = min(out.min, lo)
                out.max = max(out.max, hi)
        if out.count:
            out.min = max(out.min, 0.0)
        return out

    def percentile(self, q):
        """Nearest-rank percentile from the bucket counts (0 when empty).
        ``q`` in [0, 100]."""
        with self._lock:
            if self.count == 0:
                return 0.0
            rank = max(1, math.ceil((q / 100.0) * self.count))
            cum = 0
            for i, n in enumerate(self._buckets):
                cum += n
                if cum >= rank:
                    lo, hi = _bucket_bounds(i)
                    # geometric bucket midpoint, clamped to observed range
                    mid = math.sqrt(lo * hi) if lo > 0 else 0.0
                    return min(max(mid, self.min), self.max)
            return self.max  # unreachable (cum == count by loop end)

    @property
    def mean(self):
        return self.sum / self.count if self.count else 0.0

    def summary(self):
        """Compact stats dict: count/sum/mean/min/max/p50/p95/p99."""
        if self.count == 0:
            return {"count": 0, "sum": 0.0, "mean": 0.0, "min": 0.0,
                    "max": 0.0, "p50": 0.0, "p95": 0.0, "p99": 0.0}
        return {"count": self.count, "sum": self.sum, "mean": self.mean,
                "min": self.min, "max": self.max,
                "p50": self.percentile(50), "p95": self.percentile(95),
                "p99": self.percentile(99)}

    def to_dict(self):
        """JSON-safe form (sparse buckets) — the fleet/flight wire format."""
        with self._lock:
            return {"name": self.name, "unit": self.unit,
                    "count": self.count, "sum": self.sum,
                    "min": self.min if self.count else None,
                    "max": self.max if self.count else None,
                    "buckets": {str(i): n for i, n in
                                enumerate(self._buckets) if n}}

    @classmethod
    def from_dict(cls, d):
        out = cls(d.get("name", ""), d.get("unit", ""))
        for i, n in d.get("buckets", {}).items():
            out._buckets[int(i)] = int(n)
        out.count = int(d.get("count", 0))
        out.sum = float(d.get("sum", 0.0))
        if out.count:
            out.min = float(d["min"])
            out.max = float(d["max"])
        return out


# -- module-level histogram registry ----------------------------------------
_HLOCK = threading.Lock()
_HISTS: dict[str, Histogram] = {}


def get_histogram(name: str, unit: str = "") -> Histogram:
    """The process-global histogram registered under ``name`` (created on
    first use)."""
    with _HLOCK:
        h = _HISTS.get(name)
        if h is None:
            h = _HISTS[name] = Histogram(name, unit)
        return h


def observe(name: str, value, unit: str = "", sum_counter=False,
            extra: Histogram | None = None):
    """Record ``value`` into the global histogram ``name``.

    ``sum_counter=True`` ALSO bumps the plain counter of the same name by
    ``value`` (the legacy-accumulator back-compat path for migrated
    ``*_ns`` / ``*_ms`` counters); a string bumps that counter name
    instead.  ``extra`` additionally records into a caller-scoped
    histogram (per-replica engine stats the Router later merges)."""
    get_histogram(name, unit).record(value)
    if extra is not None:
        extra.record(value)
    if sum_counter:
        _counters.inc(name if sum_counter is True else sum_counter, value)


def histograms() -> dict:
    """Point-in-time copies of every registered histogram."""
    with _HLOCK:
        items = list(_HISTS.items())
    return {k: h.copy() for k, h in items}


def histogram_summaries() -> dict:
    """``{name: summary-dict}`` for every non-empty registered histogram."""
    return {k: h.summary() for k, h in histograms().items() if h.count}


def reset_metrics():
    """Drop every registered histogram and program record (test isolation)."""
    with _HLOCK:
        _HISTS.clear()
    with _PLOCK:
        _PROGRAMS.clear()
        _CAPTURED.clear()
    try:  # lazy: devicetime imports this module
        from . import devicetime as _devicetime
        _devicetime.reset()
    except Exception:
        pass


# -- structured train-metrics logger ----------------------------------------
class MetricsLogger:
    """Structured JSONL time-series + in-memory query API.

    One :meth:`log` call is one JSONL line::

        {"ts": <unix-seconds>, "step": <int>, "<metric>": <float>, ...}

    plus one in-memory ``(step, value)`` point per metric, queryable with
    :meth:`series`/:meth:`latest`.  ``path=None`` keeps the series
    memory-only.  Thread-safe; writes are line-buffered appends (crash
    keeps every completed line).  Wire it into the train loop with
    ``jit.CompiledTrainStep(model, loss_fn, opt, metrics=logger)`` — the
    in-graph accumulation + sync-boundary harvest keeps the hot path free
    of extra syncs/dispatches."""

    def __init__(self, path=None, run=None):
        self.path = os.fspath(path) if path is not None else None
        self.run = run
        self._lock = threading.Lock()
        self._series: dict[str, list] = {}
        self._fh = None
        if self.path is not None:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            self._fh = open(self.path, "a", buffering=1)

    def log(self, step=None, **metrics):
        """Record one time point: ``logger.log(step=3, loss=2.17, lr=1e-4)``."""
        rec = {"ts": time.time()}
        if self.run is not None:
            rec["run"] = self.run
        if step is not None:
            rec["step"] = int(step)
        for k, v in metrics.items():
            if v is None:
                continue
            rec[k] = float(v)
        with self._lock:
            for k, v in rec.items():
                if k in ("ts", "run", "step"):
                    continue
                self._series.setdefault(k, []).append((rec.get("step"), v))
            if self._fh is not None:
                self._fh.write(json.dumps(rec) + "\n")
        return rec

    def series(self, name):
        """All recorded ``(step, value)`` points for one metric, in order."""
        with self._lock:
            return list(self._series.get(name, ()))

    def latest(self, name, default=None):
        with self._lock:
            pts = self._series.get(name)
            return pts[-1][1] if pts else default

    def names(self):
        with self._lock:
            return sorted(self._series)

    def summary(self):
        """``{metric: {count, last, mean, min, max}}`` over the series."""
        with self._lock:
            items = {k: [v for _, v in pts]
                     for k, pts in self._series.items()}
        return {k: {"count": len(vs), "last": vs[-1],
                    "mean": sum(vs) / len(vs), "min": min(vs),
                    "max": max(vs)}
                for k, vs in items.items() if vs}

    def close(self):
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _prom_name(name):
    out = []
    for ch in name:
        out.append(ch if (ch.isalnum() or ch == "_") else "_")
    n = "".join(out)
    return n if (n and not n[0].isdigit()) else "_" + n


def prometheus_text(logger: MetricsLogger | None = None) -> str:
    """Prometheus text exposition of the full telemetry state: every
    counter as ``counter``, every gauge as ``gauge``, every histogram as
    a spec-conformant ``histogram`` — cumulative ``_bucket{le="..."}``
    series (which Prometheus CAN aggregate/quantile across replicas,
    unlike pre-computed quantiles) plus ``_sum``/``_count`` — with the
    human-eyes quantiles kept as a separate ``<name>_quantile`` gauge
    family, and optionally the latest point of each
    :class:`MetricsLogger` series."""
    lines = []
    snap = _counters.snapshot()
    gauges = {k: snap[k] for k in snap
              if k in getattr(_counters, "_GAUGES", {})}
    for k in sorted(snap):
        pn = "ptpu_" + _prom_name(k)
        kind = "gauge" if k in gauges else "counter"
        lines.append(f"# TYPE {pn} {kind}")
        lines.append(f"{pn} {snap[k]}")
    for k, h in sorted(histograms().items()):
        if not h.count:
            continue
        pn = "ptpu_" + _prom_name(k)
        lines.append(f"# TYPE {pn} histogram")
        cum = 0
        with h._lock:
            buckets = list(h._buckets)
        for i, n in enumerate(buckets):
            if not n:
                continue
            cum += n
            _, hi = _bucket_bounds(i)
            lines.append(f'{pn}_bucket{{le="{hi:.6g}"}} {cum}')
        lines.append(f'{pn}_bucket{{le="+Inf"}} {h.count}')
        lines.append(f"{pn}_sum {h.sum}")
        lines.append(f"{pn}_count {h.count}")
        lines.append(f"# TYPE {pn}_quantile gauge")
        for q in (0.5, 0.95, 0.99):
            lines.append(
                f'{pn}_quantile{{quantile="{q}"}} {h.percentile(q * 100)}')
    if logger is not None:
        for k in logger.names():
            pn = "ptpu_metric_" + _prom_name(k)
            lines.append(f"# TYPE {pn} gauge")
            lines.append(f"{pn} {logger.latest(k)}")
    return "\n".join(lines) + "\n"


# -- per-compiled-program device telemetry ----------------------------------
_PLOCK = threading.Lock()
_PROGRAMS: dict[str, dict] = {}
_CAPTURED: set[str] = set()   # names already AOT-captured this process

_MEM_FIELDS = (("arg_bytes", "argument_size_in_bytes"),
               ("out_bytes", "output_size_in_bytes"),
               ("temp_bytes", "temp_size_in_bytes"),
               ("alias_bytes", "alias_size_in_bytes"),
               ("code_bytes", "generated_code_size_in_bytes"))


def device_telemetry_enabled() -> bool:
    return bool(_flags.flag("FLAGS_device_telemetry"))


def capture_program_stats(name, jit_fn, *args, **kwargs):
    """AOT-lower+compile ``jit_fn`` on the given abstract/concrete args and
    record HBM usage (argument/output/temp bytes from XLA memory
    analysis), compile wall-time and cost-analysis FLOPs under
    ``program.<name>.*`` gauges + the :func:`memory_summary` table.

    Gated by ``FLAGS_device_telemetry`` (this is a SECOND compile of the
    same program — jit's dispatch cache is separate from the AOT path —
    so it is paid only when telemetry is explicitly on, e.g. by the bench
    mesh legs).  Every backend quirk (CPU test backends without memory
    analysis, version-dependent cost-analysis shapes) degrades to partial
    records, never an exception on the caller's hot path.

    Idempotent per program name: re-dispatch of a cached executable (an
    engine re-created against the warm per-model program cache re-runs
    its capture hooks) returns the existing record without a second AOT
    compile and without re-recording ``program.<name>.*`` gauges or
    compile wall-time — the double-count guard the device-time ledger's
    efficiency join depends on."""
    if not device_telemetry_enabled():
        return None
    with _PLOCK:
        if name in _CAPTURED:
            return dict(_PROGRAMS.get(name, {"name": name}))
        _CAPTURED.add(name)
    rec = {"name": name, "compile_s": None, "flops": None}
    for k, _ in _MEM_FIELDS:
        rec[k] = None
    try:
        t0 = time.perf_counter()
        compiled = jit_fn.lower(*args, **kwargs).compile()
        rec["compile_s"] = time.perf_counter() - t0
        try:
            ma = compiled.memory_analysis()
            for k, attr in _MEM_FIELDS:
                v = getattr(ma, attr, None)
                if v is not None:
                    rec[k] = int(v)
        except Exception:
            pass
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)) and ca:
                ca = ca[0]
            if isinstance(ca, dict) and ca.get("flops"):
                rec["flops"] = float(ca["flops"])
        except Exception:
            pass
    except Exception as e:  # lowering itself failed — record the miss
        rec["error"] = f"{type(e).__name__}: {e}"
    record_program(name, **{k: v for k, v in rec.items() if k != "name"})
    return rec


def record_program(name, **fields):
    """Register/refresh one compiled-program telemetry record and mirror
    the byte/flops fields as ``program.<name>.*`` gauges."""
    with _PLOCK:
        rec = _PROGRAMS.setdefault(name, {"name": name})
        rec.update({k: v for k, v in fields.items() if v is not None})
    for k, v in fields.items():
        if v is not None and isinstance(v, (int, float)):
            _counters.set_gauge(f"program.{name}.{k}", v)
    return program_stats(name)


def program_stats(name=None):
    """One program's record, or ``{name: record}`` for all of them."""
    with _PLOCK:
        if name is not None:
            return dict(_PROGRAMS.get(name, {}))
        return {k: dict(v) for k, v in _PROGRAMS.items()}


def _fmt_bytes(n):
    if n is None:
        return "-"
    for u in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or u == "TiB":
            return f"{n:.1f}{u}" if u != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n:.1f}TiB"


def memory_summary() -> str:
    """Human table of per-compiled-program HBM usage / compile time /
    FLOPs — the ``paddle.device.cuda.memory_summary`` analogue for the
    XLA program set this process compiled."""
    progs = program_stats()
    if not progs:
        return "(no compiled-program telemetry recorded — set " \
               "FLAGS_device_telemetry=1 before compiling)"
    headers = ("Program", "Args", "Outputs", "Temp", "Code", "Compile(s)",
               "GFLOPs")
    rows = []
    for name in sorted(progs):
        r = progs[name]
        rows.append((
            name,
            _fmt_bytes(r.get("arg_bytes")),
            _fmt_bytes(r.get("out_bytes")),
            _fmt_bytes(r.get("temp_bytes")),
            _fmt_bytes(r.get("code_bytes")),
            f"{r['compile_s']:.3f}" if r.get("compile_s") is not None
            else "-",
            f"{r['flops'] / 1e9:.2f}" if r.get("flops") else "-"))
    widths = [max(len(h), *(len(row[i]) for row in rows))
              for i, h in enumerate(headers)]
    fmt = "  ".join("{:<%d}" % w for w in widths)
    lines = [fmt.format(*headers), fmt.format(*("-" * w for w in widths))]
    lines.extend(fmt.format(*row) for row in rows)
    return "\n".join(lines)


_flags.define_flag(
    "FLAGS_device_telemetry", False,
    "Record per-compiled-program HBM usage / compile time / FLOPs at jit "
    "and serving compile sites (metrics.capture_program_stats). Costs one "
    "extra AOT compile per program — off by default.")
_flags.define_flag(
    "FLAGS_peak_tflops", 0.0,
    "Accelerator peak TFLOP/s for MFU attribution in train metrics "
    "(0 disables the mfu field; v5e bf16 honest peak is 197).")
