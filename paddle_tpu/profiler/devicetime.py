"""Device-time & efficiency plane: the per-program device-time ledger.

Reference analogue: Paddle's profiler kernel-level device timeline +
``summary()`` tables (profiler/profiler_statistic.py) — here grown
TPU-natively on top of the per-compiled-program AOT telemetry
(:func:`metrics.capture_program_stats`) instead of CUPTI:

* **ProgramLedger** (module-level, like the counter registry): every
  dispatch site in the stack — jit single-step and fused window, slot /
  paged / speculative prefill-decode-verify, COW block copy, migration
  export/adopt, tier spill/restore — calls :func:`note` with its
  program name.  With ``FLAGS_device_time_sample=0`` (the default) a
  note is ONE cached list read and returns ``None``: zero counters
  move, zero syncs happen, steady-state parity gates stay byte-
  identical.
* **Sampling**: with ``FLAGS_device_time_sample=N`` every Nth noted
  dispatch (globally, across programs) returns a token; the site passes
  the token plus the dispatch outputs to :func:`observe`, which pays
  ONE explicit ``jax.block_until_ready`` fence, ticks
  ``jit.devicetime.sampled_syncs`` (so the zero-sync gates can budget
  it exactly: ⌈dispatches/N⌉), and records the fenced wall time into
  the per-program ledger row + log2 histogram.
* **Efficiency join**: each sample joins the program's AOT FLOPs and
  HBM bytes (``arg_bytes + out_bytes`` — the off-chip traffic floor)
  from :func:`metrics.program_stats` to publish live per-program
  gauges: achieved TFLOP/s, MFU vs ``FLAGS_peak_tflops``, HBM GB/s vs
  ``FLAGS_peak_hbm_gbps``, arithmetic intensity, and a roofline
  classification (compute-bound when AI exceeds the machine balance
  point, bandwidth-bound below it).
* **Consumers**: :func:`summary` (Paddle-profiler-style table),
  :func:`snapshot` (the ``/programs`` OpsServer endpoint +
  ``ServingFleet.stats()["devicetime"]`` roll-up), :func:`bench_block`
  (embedded in bench legs, diffed by ``bench_compare.py --attribute``),
  the flight-recorder postmortem bundle, and the ``mfu_collapse`` /
  ``device_time_regression`` health watchdogs.
* **On-demand XPlane capture**: :func:`capture_profile` drives a
  single-flight, timeout-clamped ``jax.profiler`` start/stop_trace
  window (the ``POST /profile?ms=`` endpoint) and returns the dump
  directory for offline tooling.

Timing model: the fence measures host wall time from just before the
dispatch call to device completion — on a steady async pipeline that is
(queue drain + this program's device time); with one in-flight program
(the serving engines' data-dependent loops) it is the program's device
time plus constant host overhead.  Sampled means are therefore honest
*attribution* weights (share of where time goes) rather than isolated
kernel runtimes — exactly what regression triage needs.
"""

from __future__ import annotations

import itertools
import os
import re as _re
import tempfile
import threading
import time

from ..core import flags as _flags
from . import counters as _counters
from . import metrics as _metrics

# -- ledger state ------------------------------------------------------------
_LOCK = threading.Lock()
_LEDGER: dict[str, dict] = {}
_SAMPLE = [0]          # observer-cached FLAGS_device_time_sample (hot read)
_SEQ = itertools.count()   # global dispatch sequence: every Nth is sampled
_RECENT = 8            # trailing per-program samples kept for regression ratio

_COUNTER_DISPATCHES = "jit.devicetime.dispatches"
_COUNTER_SAMPLED = "jit.devicetime.sampled_syncs"


class _Token:
    """One armed sample: carries the program name and the pre-dispatch
    timestamp from :func:`note` to :func:`observe`."""

    __slots__ = ("name", "t0")

    def __init__(self, name, t0):
        self.name = name
        self.t0 = t0


def enabled() -> bool:
    """True when device-time sampling is on (``FLAGS_device_time_sample>0``)."""
    return _SAMPLE[0] > 0


def sample_every() -> int:
    return _SAMPLE[0]


def note(name):
    """Note one dispatch of program ``name``.

    OFF (``FLAGS_device_time_sample=0``): one list read, returns ``None``
    — no counters, no locks, no allocation.  ON: counts the dispatch in
    the ledger and, for every Nth note globally, returns a :class:`_Token`
    the dispatch site must hand to :func:`observe` together with the
    dispatch outputs.  Call it immediately before the dispatch (after any
    AOT capture / audit work, so compile time never leaks into samples).
    """
    n = _SAMPLE[0]
    if n <= 0:
        return None
    _counters.inc(_COUNTER_DISPATCHES)
    with _LOCK:
        rec = _LEDGER.get(name)
        if rec is None:
            rec = _LEDGER[name] = {
                "dispatches": 0, "sampled": 0, "time_s": 0.0,
                "recent": [],
                "hist": _metrics.Histogram(f"devicetime.{name}", "ns"),
            }
        rec["dispatches"] += 1
        armed = next(_SEQ) % n == 0
    if not armed:
        return None
    return _Token(name, time.perf_counter())


def observe(token, out=None):
    """Complete a sample armed by :func:`note`: fence on ``out`` (any
    pytree of device arrays; ``None`` fences nothing) and record the
    elapsed wall time against the token's program.  No-op on ``None``
    token, so sites can write ``_dt = note(..); ...; observe(_dt, out)``
    unconditionally."""
    if token is None:
        return None
    _block(out)
    dt = time.perf_counter() - token.t0
    _record_sample(token.name, dt)
    return dt


def _block(out):
    """Explicit device fence (the one sync sampling pays)."""
    if out is None:
        return
    try:
        import jax
        jax.block_until_ready(out)
    except Exception:
        # partial/host-only outputs: fence whatever leaves we can
        try:
            import jax
            for leaf in jax.tree_util.tree_leaves(out):
                try:
                    leaf.block_until_ready()
                except Exception:
                    pass
        except Exception:
            pass


def _record_sample(name, dt_s):
    """Fold one fenced wall-time sample into the ledger and republish the
    program's efficiency gauges.  (Also the test seam: feeds the ledger
    without a real dispatch.)"""
    _counters.inc(_COUNTER_SAMPLED)
    with _LOCK:
        rec = _LEDGER.get(name)
        if rec is None:
            rec = _LEDGER[name] = {
                "dispatches": 0, "sampled": 0, "time_s": 0.0,
                "recent": [],
                "hist": _metrics.Histogram(f"devicetime.{name}", "ns"),
            }
        rec["sampled"] += 1
        rec["time_s"] += dt_s
        rec["recent"].append(dt_s)
        if len(rec["recent"]) > _RECENT:
            del rec["recent"][:len(rec["recent"]) - _RECENT]
        rec["hist"].record(dt_s * 1e9)
        mean_s = rec["time_s"] / rec["sampled"]
    eff = _efficiency(name, mean_s)
    fields = {"device_time_mean_ms": mean_s * 1e3,
              "device_time_samples": float(_samples_of(name))}
    for k in ("tflops", "mfu", "hbm_gbps", "ai"):
        if eff.get(k) is not None:
            fields[k] = eff[k]
    _metrics.record_program(name, **fields)
    if eff.get("roofline"):
        with _metrics._PLOCK:
            _metrics._PROGRAMS.setdefault(name, {"name": name})[
                "roofline"] = eff["roofline"]


def _samples_of(name):
    with _LOCK:
        rec = _LEDGER.get(name)
        return rec["sampled"] if rec else 0


# -- efficiency join ---------------------------------------------------------
def _efficiency(name, mean_s):
    """Join one program's mean device time with its AOT FLOPs/HBM bytes
    (when ``capture_program_stats`` recorded them) into achieved TFLOP/s,
    MFU, HBM GB/s, arithmetic intensity and a roofline classification.
    Missing inputs degrade field-by-field, never raise."""
    out = {"tflops": None, "mfu": None, "hbm_gbps": None, "ai": None,
           "roofline": None}
    if not mean_s or mean_s <= 0:
        return out
    stats = _metrics.program_stats(name)
    if not stats:
        # mesh-decorated ledger keys (e.g. "serving.decode_paged[mp2]")
        # fall back to the base program's AOT stats — the per-chip FLOPs
        # differ but the roofline classification and MFU trend survive,
        # and the sharded row stops silently dropping from the report
        base = _re.sub(r"\[(?:[a-z]{2,}\d+)+\]", "", name)
        if base != name:
            stats = _metrics.program_stats(base)
    flops = stats.get("flops")
    hbm = 0
    for k in ("arg_bytes", "out_bytes"):
        v = stats.get(k)
        if isinstance(v, (int, float)):
            hbm += v
    if isinstance(flops, (int, float)) and flops > 0:
        out["tflops"] = flops / mean_s / 1e12
        peak_tf = float(_flags.flag("FLAGS_peak_tflops") or 0.0)
        if peak_tf > 0:
            out["mfu"] = out["tflops"] / peak_tf
    if hbm > 0:
        out["hbm_gbps"] = hbm / mean_s / 1e9
        if isinstance(flops, (int, float)) and flops > 0:
            out["ai"] = flops / hbm
    out["roofline"] = _roofline(
        flops if isinstance(flops, (int, float)) else None,
        hbm if hbm > 0 else None)
    return out


def _roofline(flops, hbm_bytes):
    """'compute-bound' / 'bandwidth-bound' / 'unknown' from AOT stats and
    the peak flags.  A zero-FLOP program that moves bytes (COW copy,
    spill/restore) is bandwidth-bound by construction; everything else
    compares arithmetic intensity against the machine balance point
    peak_flops / peak_bw."""
    if (flops is None or flops <= 0) and hbm_bytes:
        return "bandwidth-bound"
    if not flops or not hbm_bytes:
        return "unknown"
    peak_tf = float(_flags.flag("FLAGS_peak_tflops") or 0.0)
    peak_bw = float(_flags.flag("FLAGS_peak_hbm_gbps") or 0.0)
    if peak_tf <= 0 or peak_bw <= 0:
        return "unknown"
    balance = (peak_tf * 1e12) / (peak_bw * 1e9)   # FLOP per HBM byte
    ai = flops / hbm_bytes
    return "compute-bound" if ai >= balance else "bandwidth-bound"


# -- read side ---------------------------------------------------------------
def snapshot(top=None):
    """Point-in-time ledger table: per-program dispatch/sample counts,
    mean/p50/p95 sampled ms, estimated total device seconds
    (mean x dispatches), share of the whole ledger's estimated time,
    trailing-window regression ratio, and the joined efficiency gauges.
    Rows sort by estimated total time descending; ``top`` keeps the K
    largest."""
    with _LOCK:
        items = [(name, dict(rec), rec["hist"].copy(), list(rec["recent"]))
                 for name, rec in _LEDGER.items()]
    rows = []
    for name, rec, hist, recent in items:
        sampled = rec["sampled"]
        mean_s = (rec["time_s"] / sampled) if sampled else None
        # a sampled row had at least `sampled` dispatches — the floor
        # matters when the ledger is fed through the _record_sample seam
        disp = max(rec["dispatches"], sampled)
        row = {"name": name,
               "dispatches": rec["dispatches"],
               "sampled": sampled,
               "mean_ms": mean_s * 1e3 if mean_s is not None else None,
               "p50_ms": hist.percentile(50) / 1e6 if sampled else None,
               "p95_ms": hist.percentile(95) / 1e6 if sampled else None,
               "est_total_s": (mean_s * disp)
               if mean_s is not None else 0.0,
               "regression": _regression(rec, recent)}
        eff = _efficiency(name, mean_s) if mean_s else {}
        for k in ("tflops", "mfu", "hbm_gbps", "ai", "roofline"):
            row[k] = eff.get(k)
        rows.append(row)
    rows.sort(key=lambda r: r["est_total_s"], reverse=True)
    total = sum(r["est_total_s"] for r in rows)
    for r in rows:
        r["share"] = (r["est_total_s"] / total) if total > 0 else None
    if top is not None:
        rows = rows[:top]
    return {"sample_every": _SAMPLE[0], "n_programs": len(items),
            "est_total_s": total, "programs": rows}


def _regression(rec, recent):
    """Trailing-window mean over pre-window baseline mean (None until
    both windows have samples) — the device_time_regression watchdog's
    signal."""
    n_recent = len(recent)
    n_base = rec["sampled"] - n_recent
    if n_recent == 0 or n_base <= 0:
        return None
    recent_sum = sum(recent)
    base_sum = rec["time_s"] - recent_sum
    if base_sum <= 0:
        return None
    return (recent_sum / n_recent) / (base_sum / n_base)


def summary(top=None) -> str:
    """Paddle-profiler-style device-time table (the ``memory_summary``
    sibling for where time goes)."""
    snap = snapshot(top=top)
    if not snap["programs"]:
        return ("(no device-time samples recorded — set "
                "FLAGS_device_time_sample=N and dispatch)")

    def f(v, spec="{:.3f}", none="-"):
        return spec.format(v) if v is not None else none

    headers = ("Program", "Disp", "Samp", "Mean(ms)", "P95(ms)", "Share",
               "TFLOP/s", "MFU", "GB/s", "AI", "Bound")
    rows = []
    for r in snap["programs"]:
        rows.append((
            r["name"], str(r["dispatches"]), str(r["sampled"]),
            f(r["mean_ms"]), f(r["p95_ms"]),
            f(r["share"], "{:.1%}"), f(r["tflops"], "{:.2f}"),
            f(r["mfu"], "{:.1%}"), f(r["hbm_gbps"], "{:.1f}"),
            f(r["ai"], "{:.1f}"), r["roofline"] or "-"))
    widths = [max(len(h), *(len(row[i]) for row in rows))
              for i, h in enumerate(headers)]
    fmt = "  ".join("{:<%d}" % w for w in widths)
    lines = [fmt.format(*headers), fmt.format(*("-" * w for w in widths))]
    lines.extend(fmt.format(*row) for row in rows)
    lines.append(f"sample_every={snap['sample_every']}  "
                 f"est_total={snap['est_total_s']:.3f}s  "
                 f"programs={snap['n_programs']}")
    return "\n".join(lines)


def bench_block(top=8):
    """Bench-leg embeddable block: compact per-program share / mean /
    efficiency numbers keyed by program name.  ``bench_compare.py``
    flattens it to ``devicetime.programs.<name>.share`` paths and
    classifies share as lower-is-better per program (attribution)."""
    snap = snapshot(top=top)
    progs = {}
    for r in snap["programs"]:
        blk = {}
        for k in ("share", "mean_ms", "p95_ms", "mfu", "tflops",
                  "hbm_gbps"):
            if r.get(k) is not None:
                blk[k] = round(float(r[k]), 6)
        if r.get("roofline"):
            blk["roofline"] = r["roofline"]
        progs[r["name"]] = blk
    return {"sample_every": snap["sample_every"],
            "est_total_s": round(snap["est_total_s"], 6),
            "programs": progs}


def reset():
    """Drop the ledger and re-anchor the sampling sequence so the next
    note is sample #0 (⌈D/N⌉ becomes exact over a measured window).
    Counters are NOT touched — they live in the counter registry."""
    global _SEQ
    with _LOCK:
        _LEDGER.clear()
        _SEQ = itertools.count()


# -- on-demand XPlane capture (POST /profile) --------------------------------
PROFILE_MAX_MS = 60_000
_PROFILE_LOCK = threading.Lock()
_PROFILE_SEQ = itertools.count()


class ProfileBusy(RuntimeError):
    """A profiler capture is already in flight (single-flight guard)."""


def _start_trace(path):  # test seam (monkeypatched in tests)
    import jax
    jax.profiler.start_trace(path)


def _stop_trace():  # test seam
    import jax
    jax.profiler.stop_trace()


def capture_profile(ms, out_dir=None, max_ms=PROFILE_MAX_MS):
    """Programmatic ``jax.profiler`` start/stop_trace window.

    Single-flight (concurrent calls raise :class:`ProfileBusy` — the ops
    endpoint maps it to 409) and timeout-guarded: ``ms`` is clamped to
    [1, ``max_ms``] so a fat-fingered request cannot wedge the profiler
    open.  Returns ``{"path", "ms"}`` with the XPlane dump directory."""
    ms = max(1, min(int(ms), int(max_ms)))
    if not _PROFILE_LOCK.acquire(blocking=False):
        raise ProfileBusy("profiler capture already in flight")
    try:
        if out_dir is None:
            out_dir = os.path.join(
                tempfile.gettempdir(),
                f"ptpu-profile-{os.getpid()}-{next(_PROFILE_SEQ)}")
        os.makedirs(out_dir, exist_ok=True)
        _start_trace(out_dir)
        try:
            time.sleep(ms / 1000.0)
        finally:
            _stop_trace()
        return {"path": out_dir, "ms": ms}
    finally:
        _PROFILE_LOCK.release()


# -- flags -------------------------------------------------------------------
def _on_sample_flag(v):
    try:
        n = int(v)
    except (TypeError, ValueError):
        n = 0
    # cache only — an explicit reset() is the ONLY thing that clears the
    # ledger, so turning sampling off to read results keeps them intact
    _SAMPLE[0] = max(0, n)


_flags.define_flag(
    "FLAGS_device_time_sample", 0,
    "Sample every Nth compiled-program dispatch with an explicit "
    "block-until-ready fence into the device-time ledger "
    "(profiler.devicetime). 0 (default) = off: dispatch sites pay one "
    "cached read and no counters move. Each sampled fence ticks "
    "jit.devicetime.sampled_syncs so sync budgets stay provable.")
_flags.register_flag_observer("FLAGS_device_time_sample", _on_sample_flag,
                              call_now=True)
_flags.define_flag(
    "FLAGS_peak_hbm_gbps", 0.0,
    "Accelerator peak HBM bandwidth in GB/s for the roofline "
    "classification and achieved-bandwidth gauges (0 disables; v5e "
    "honest peak is 819).")
