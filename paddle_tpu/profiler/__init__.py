"""Profiler (reference: python/paddle/profiler/profiler.py:346 — host tracer +
CUPTI merged into chrome traces; here: a real host-side span tracer with
chrome-trace export and stats tables, plus the always-on counter registry).

Pieces:

* ``host_tracer`` — thread-aware ``RecordEvent`` span collection, gated by
  ``FLAGS_host_trace_level`` (0 = zero-cost no-op), exported as valid
  chrome://tracing JSON and summarized as a Paddle-style stats table.
* ``counters`` — process-global counter/gauge registry fed by the jit /
  static / io / distributed / optimizer hot paths (compile counts, cache
  hits, retraces, host syncs, device_put bytes, prefetch stalls, ...).
* ``metrics`` — the telemetry layer on top of the registry: mergeable
  log-bucket ``Histogram`` (p50/p95/p99 for serving TTFT / inter-token
  latency / queue wait / checkpoint latency), ``MetricsLogger`` (JSONL
  per-step train metrics accumulated in-graph by
  ``jit.CompiledTrainStep(metrics=...)``), Prometheus text exposition,
  and per-compiled-program HBM/compile/FLOPs telemetry
  (``memory_summary()``, gated by ``FLAGS_device_telemetry``).
* ``devicetime`` — the device-time & efficiency plane: a per-program
  ``ProgramLedger`` noted at every compiled-program dispatch site
  (``FLAGS_device_time_sample=N`` fences every Nth dispatch; 0 = one
  cached read, zero counters), joining sampled wall time with the AOT
  FLOPs/HBM stats into live MFU / achieved-TFLOP/s / HBM-GB/s /
  roofline gauges, a Paddle-style ``summary()`` table, bench-leg
  attribution blocks, and a single-flight ``capture_profile`` XPlane
  window (``POST /profile``).
* ``flight`` — always-on flight-recorder ring buffer; faults (trainer
  recovery, nan/inf raise, fleet replica death/stall) dump a postmortem
  JSON bundle (``scripts/flight_dump.py`` pretty-prints it).
* ``trace`` — per-request distributed tracing: a ``TraceContext`` minted
  at fleet/engine admission, lifecycle child spans (queue, KV reserve,
  prefill chunks, decode iterations, re-prefill after respawn) recorded
  into per-request span trees; head sampling via
  ``FLAGS_request_trace_sample`` + tail-based keep-always for
  deadline-breaching / errored / retried requests; JSONL and merged
  chrome://tracing export on the host tracer's clock.
* ``goodput`` — ``GoodputLedger``: exclusive-time wall-clock buckets
  (compile / step / data_wait / ckpt_sync / restore_replay / recovery /
  idle) for the FaultTolerantTrainer; goodput fraction + >=99%-accounted
  chaos gate.
* ``health`` — the derived-signals layer: ``HealthMonitor`` snapshot
  ring over the whole registry, windowed deltas/rates/percentiles,
  multi-window burn-rate ``SLO`` objectives, live invariant
  ``Watchdog``s (retrace storm, KV block conservation, goodput
  accounting, speculative-acceptance collapse), alert lifecycle with
  flight-dump postmortems, and the single ``admission_level``
  recommendation (gated by ``FLAGS_health``; zero-overhead off).
* ``ops`` — ``OpsServer``: stdlib-HTTP live endpoint (``/metrics``,
  ``/healthz``, ``/goodput``, ``/traces/<id>``, ``/flight``,
  ``/alerts``, ``/slo``, ``/signals``),
  fleet-aggregated via the Router (``scripts/ops_server.py`` CLI).
* ``Profiler`` — the paddle.profiler front end: scheduler state machine,
  ``on_trace_ready`` handlers (``export_chrome_tracing``), ``summary()``,
  and ``timer_only=True`` step benchmarking (ips + reader/batch cost split).
* The ``FLAGS_check_nan_inf`` guard lives in the jit train step (it traces
  finite-ness checks into the XLA program); see jit.CompiledTrainStep.

Device-side (XPlane) tracing via ``jax.profiler`` is started only when a
device target (TPU/GPU) is explicitly requested — host tracing alone never
touches the jax profiler.
"""

from __future__ import annotations

import os
import time
from enum import Enum

from . import counters  # noqa: F401
from . import devicetime  # noqa: F401
from . import flight  # noqa: F401
from . import goodput  # noqa: F401
from . import host_tracer  # noqa: F401
from . import metrics  # noqa: F401
from . import trace  # noqa: F401
from . import health  # noqa: F401
from .goodput import GoodputLedger  # noqa: F401
from .health import SLO, HealthMonitor, Watchdog  # noqa: F401
from .host_tracer import current_stack, span  # noqa: F401
from .metrics import (Histogram, MetricsLogger, memory_summary,  # noqa: F401
                      prometheus_text)
from .ops import OpsServer  # noqa: F401
from .trace import TraceContext  # noqa: F401


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    CUSTOM_DEVICE = 3
    TPU = 4


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(*, closed, ready, record, repeat=0, skip_first=0):
    """Periodic profiling schedule (reference: profiler/utils.py
    make_scheduler): ``skip_first`` CLOSED steps, then repeating windows of
    ``closed`` CLOSED + ``ready`` READY + ``record`` RECORD steps, the last
    RECORD step of each window being RECORD_AND_RETURN."""
    if not isinstance(record, int) or record < 1:
        raise ValueError(
            f"record should be a positive integer (>= 1), but got {record}: "
            "each profiling window needs at least one RECORD step to return "
            "a trace")
    for arg_name, v in (("closed", closed), ("ready", ready),
                        ("repeat", repeat), ("skip_first", skip_first)):
        if not isinstance(v, int) or v < 0:
            raise ValueError(
                f"{arg_name} should be a non-negative integer, but got {v}")

    def scheduler(step):
        s = step - skip_first
        if s < 0:
            return ProfilerState.CLOSED
        period = closed + ready + record
        if repeat and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD
    return scheduler


def export_chrome_tracing(dir_name, worker_name=None):
    """on_trace_ready handler: write the collected host trace as
    chrome://tracing JSON into ``dir_name`` (reference: profiler.py
    export_chrome_tracing → ChromeTracingLogger)."""
    def handle(prof):
        name = worker_name or f"host_{os.getpid()}"
        path = os.path.join(dir_name, f"{name}.pt.trace.json")
        prof.export(path)
        prof._export_dir = dir_name
        prof._chrome_trace_path = path
        return path
    return handle


class _StepTimer:
    """timer_only benchmarking: per-step wall latency, ips, and the
    reader-vs-batch cost split (reader cost = movement of the io.* wait
    counters during the step, i.e. time the step spent blocked on data).

    Under fused multi-step dispatch (jit.CompiledTrainStep
    ``fused_steps=K``) call ``prof.step()`` once per window: one "step" is
    then one K-step XLA launch, so batch_cost / ips are per-window —
    divide/multiply by K for per-training-step numbers."""

    _READER_KEYS = ("io.reader_ns", "io.prefetch_stall_ns",
                    "io.queue_wait_ns")

    def __init__(self):
        self._t_last = None
        self._reader_mark = 0.0
        self._window = []          # (step_s, reader_s, num_samples)

    def _reader_ns(self):
        return float(sum(counters.get(k) for k in self._READER_KEYS))

    def begin(self):
        self._t_last = time.perf_counter()
        self._reader_mark = self._reader_ns()

    def step(self, num_samples=None):
        if self._t_last is None:
            self.begin()
            return
        now = time.perf_counter()
        r_now = self._reader_ns()
        self._window.append((now - self._t_last,
                             (r_now - self._reader_mark) / 1e9, num_samples))
        self._t_last = now
        self._reader_mark = r_now

    def step_info(self, unit=None) -> str:
        if not self._window:
            return "(no steps recorded)"
        n = len(self._window)
        batch = sum(w[0] for w in self._window) / n
        reader = sum(w[1] for w in self._window) / n
        samples = [w[2] for w in self._window if w[2] is not None]
        total_t = sum(w[0] for w in self._window)
        if samples and total_t > 0:
            ips = sum(samples) / total_t
            ips_unit = unit or "samples/s"
        elif total_t > 0:
            ips = n / total_t
            ips_unit = unit or "steps/s"
        else:
            ips, ips_unit = 0.0, unit or "steps/s"
        self._window = []  # paddle semantics: averages since the last call
        return (f"reader_cost: {reader:.5f} s batch_cost: {batch:.5f} s "
                f"ips: {ips:.3f} {ips_unit}")


_LAST_PROFILER = None


class Profiler:
    """paddle.profiler.Profiler over the host tracer (+ jax.profiler XPlane
    when a device target is requested)."""

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 record_shapes=False, profile_memory=False, timer_only=False,
                 emit_nvtx=False, custom_device_types=None, with_flops=False):
        if isinstance(scheduler, (tuple, list)):
            start_b, end_b = scheduler
            if end_b <= start_b or start_b < 0:
                raise ValueError(
                    f"scheduler=(start, end) needs 0 <= start < end, got "
                    f"{scheduler!r}")
            rec = end_b - start_b
            self._scheduler = make_scheduler(closed=max(start_b - 1, 0),
                                             ready=1 if start_b > 0 else 0,
                                             record=rec, repeat=1)
        else:
            self._scheduler = scheduler
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self._targets = list(targets) if targets else [ProfilerTarget.CPU]
        self._dir = "/tmp/paddle_tpu_profile"
        self._device_trace = False
        self._step = 0
        self._state = ProfilerState.CLOSED
        self._events: list = []
        self._timer = _StepTimer()
        self._started = False
        self._handled = False  # on_trace_ready already fired for _events

    # -- collection plumbing -------------------------------------------------
    def _collecting(self):
        return self._state in (ProfilerState.RECORD,
                               ProfilerState.RECORD_AND_RETURN)

    def _enter_state(self, new):
        was = self._collecting()
        self._state = new
        now = self._collecting()
        if now and not was and not self._timer_only:
            host_tracer.start()
        elif was and not now and not self._timer_only:
            self._events.extend(host_tracer.stop())

    def start(self):
        global _LAST_PROFILER
        _LAST_PROFILER = self
        self._started = True
        self._step = 0
        self._events = []
        self._handled = False
        self._timer.begin()
        if not self._timer_only and any(
                t in (ProfilerTarget.TPU, ProfilerTarget.GPU,
                      ProfilerTarget.CUSTOM_DEVICE) for t in self._targets):
            os.makedirs(self._dir, exist_ok=True)
            try:
                import jax
                jax.profiler.start_trace(self._dir)
                self._device_trace = True
            except Exception as e:
                import warnings
                warnings.warn(f"device trace did not start: {e} "
                              "(host tracing continues)", RuntimeWarning,
                              stacklevel=2)
        state = (self._scheduler(0) if self._scheduler is not None
                 else ProfilerState.RECORD)
        self._enter_state(state)

    def stop(self):
        if not self._started:
            return
        was_recording = self._collecting()
        self._enter_state(ProfilerState.CLOSED)
        if self._device_trace:
            import jax
            jax.profiler.stop_trace()
            self._device_trace = False
        self._started = False
        if self._on_trace_ready and (was_recording
                                     or (self._events and not self._handled)):
            self._handled = True
            self._on_trace_ready(self)

    def step(self, num_samples=None):
        """Advance the scheduler one train step (also feeds the timer)."""
        self._timer.step(num_samples)
        self._step += 1
        if self._scheduler is None:
            return
        prev = self._state
        new = self._scheduler(self._step)
        self._enter_state(new)
        if (prev == ProfilerState.RECORD_AND_RETURN
                and self._on_trace_ready is not None):
            self._handled = True
            self._on_trace_ready(self)

    def step_info(self, unit=None):
        return self._timer.step_info(unit)

    # -- results -------------------------------------------------------------
    def _all_events(self):
        evts = list(self._events)
        if self._collecting() and not self._timer_only:
            evts.extend(host_tracer.events())
        return evts

    def summary(self, sorted_by="total", op_detail=True, thread_sep=False,
                time_unit="ms"):
        if self._timer_only:
            return self._timer.step_info()
        if isinstance(sorted_by, Enum):  # paddle SortedKeys compat
            sorted_by = "total"
        return host_tracer.summary(self._all_events(), sorted_by=sorted_by,
                                   time_unit=time_unit)

    def export(self, path, format="json"):
        if format not in (None, "json"):
            raise ValueError(f"unsupported export format {format!r} "
                             "(chrome-trace 'json' only)")
        return host_tracer.export_chrome(path, self._all_events())

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


def summary(sorted_by="total", time_unit="ms"):
    """Stats table for the most recent Profiler session (module-level
    convenience; falls back to the live host-tracer session)."""
    if _LAST_PROFILER is not None:
        return _LAST_PROFILER.summary(sorted_by=sorted_by,
                                      time_unit=time_unit)
    return host_tracer.summary(sorted_by=sorted_by, time_unit=time_unit)


class RecordEvent:
    """User-facing host trace span (reference: platform/profiler
    RecordEvent).  Records into the host tracer; additionally annotates the
    XPlane timeline when a device trace is running."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._span = None
        self._ann = None

    def begin(self):
        self._span = span(self.name)
        self._span.__enter__()
        prof = _LAST_PROFILER
        if prof is not None and prof._device_trace:
            import jax
            self._ann = jax.profiler.TraceAnnotation(self.name)
            self._ann.__enter__()

    def end(self):
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            self._ann = None
        if self._span is not None:
            self._span.__exit__(None, None, None)
            self._span = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def load_profiler_result(path):
    """Load an exported chrome-trace JSON back as a dict."""
    import json
    with open(path) as f:
        return json.load(f)
