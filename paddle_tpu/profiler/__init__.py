"""Profiler (reference: python/paddle/profiler/profiler.py:346 — host tracer +
CUPTI merged into chrome traces).

TPU-native: wraps jax.profiler (XPlane → TensorBoard/perfetto) and provides
host-side RecordEvent spans via jax.profiler.TraceAnnotation."""

from __future__ import annotations

import os
import time
from enum import Enum

import jax


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    CUSTOM_DEVICE = 3
    TPU = 4


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(*, closed, ready, record, repeat=0, skip_first=0):
    def scheduler(step):
        s = step - skip_first
        if s < 0:
            return ProfilerState.CLOSED
        period = closed + ready + record
        if repeat and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD
    return scheduler


def export_chrome_tracing(dir_name, worker_name=None):
    def handle(prof):
        prof._export_dir = dir_name
    return handle


class Profiler:
    """paddle.profiler.Profiler over jax.profiler."""

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 record_shapes=False, profile_memory=False, timer_only=False,
                 emit_nvtx=False, custom_device_types=None, with_flops=False):
        self._scheduler = scheduler
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self._dir = "/tmp/paddle_tpu_profile"
        self._running = False
        self._step = 0
        self._step_times = []
        self._t0 = None

    def start(self):
        self._t0 = time.perf_counter()
        if not self._timer_only:
            os.makedirs(self._dir, exist_ok=True)
            try:
                jax.profiler.start_trace(self._dir)
                self._running = True
            except Exception as e:
                import warnings
                warnings.warn(f"profiler trace did not start: {e} "
                              "(timer-only mode continues)", RuntimeWarning,
                              stacklevel=2)
                self._running = False

    def stop(self):
        if self._running:
            jax.profiler.stop_trace()
            self._running = False
        if self._on_trace_ready:
            self._on_trace_ready(self)

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._t0 is not None:
            self._step_times.append(now - self._t0)
        self._t0 = now
        self._step += 1

    def step_info(self, unit=None):
        if not self._step_times:
            return ""
        avg = sum(self._step_times[-10:]) / len(self._step_times[-10:])
        return f"avg step time {avg*1000:.2f} ms"

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        return self.step_info()

    def export(self, path, format="json"):
        pass

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


class RecordEvent:
    """Host-side trace span (reference: platform/profiler RecordEvent)."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._ctx = None

    def begin(self):
        self._ctx = jax.profiler.TraceAnnotation(self.name)
        self._ctx.__enter__()

    def end(self):
        if self._ctx is not None:
            self._ctx.__exit__(None, None, None)
            self._ctx = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def load_profiler_result(path):
    raise NotImplementedError
