"""Flight recorder: always-on ring buffer + fault-triggered postmortem dump.

The black-box analogue for the training/serving runtime: hot paths append
tiny structured events (dispatches, request admits/finishes, heartbeats,
checkpoint commits, metric points) into a bounded ring — one GIL-atomic
``deque.append`` per event, no locks on the record path — and when
something dies, :func:`dump` writes a JSON bundle of the last N events
plus the full counter state, counter movement since startup, histogram
summaries and the active span stack.  Triggers wired in by the runtime:

* ``resilience.FaultTolerantTrainer`` recovering any fault
  (``reason="trainer_recover"``);
* ``FLAGS_check_nan_inf`` raising (``reason="nan_inf"``, names the step);
* a serving fleet replica dying (``reason="replica_died"``, names the
  replica and its in-flight request ids) — including stall-detector trips;
* anything else via an explicit ``flight.dump("my_reason", {...})``.

Bundles land in ``FLAGS_flight_dir`` (default: a per-process directory
under the system temp dir); ``scripts/flight_dump.py`` pretty-prints
them.  :func:`last_dump_path` lets chaos tests assert a dump exists.
"""

from __future__ import annotations

import collections
import itertools
import json
import os
import tempfile
import threading
import time

from ..core import flags as _flags
from . import counters as _counters
from . import host_tracer as _trace

_DEFAULT_CAPACITY = 2048

# deque.append is atomic under the GIL — the record() hot path takes no
# lock; only configure/dump/clear serialize on _LOCK
_LOCK = threading.Lock()
_RING: collections.deque = collections.deque(maxlen=_DEFAULT_CAPACITY)
_SEQ = itertools.count()
_LAST_DUMP = [None]
_BASELINE = [_counters.snapshot()]
_DIR_OVERRIDE = [None]
_HEALTH_PROVIDER = [None]


def set_health_provider(fn):
    """Register a callable returning the health plane's JSON-safe state
    (active alerts + last window) to embed into every dump bundle, or
    None when the plane is off.  ``profiler.health`` installs one at
    import; kept as a late-bound hook so flight never imports health
    (no cycle) and dumps stay health-free in processes that never load
    it."""
    _HEALTH_PROVIDER[0] = fn


def configure(directory=None, capacity=None):
    """Set the dump directory and/or ring capacity (keeps current events
    up to the new capacity)."""
    global _RING
    with _LOCK:
        if directory is not None:
            _DIR_OVERRIDE[0] = os.fspath(directory)
        if capacity is not None:
            _RING = collections.deque(_RING, maxlen=int(capacity))


def record(kind, **fields):
    """Append one event to the ring: ``flight.record("jit.dispatch",
    step=12, k=4)``.  Cheap enough for every dispatch/request — one tuple
    build + one atomic deque append."""
    _RING.append((time.time_ns(), kind, fields))


def record_point(name, value, step=None):
    """Metric-point convenience (MetricsLogger harvest feeds this)."""
    _RING.append((time.time_ns(), "metric",
                  {"name": name, "value": value, "step": step}))


def events():
    """Snapshot of the ring, oldest first."""
    return list(_RING)


def clear():
    """Drop all events and re-baseline the counter delta (test isolation)."""
    with _LOCK:
        _RING.clear()
        _BASELINE[0] = _counters.snapshot()
        _LAST_DUMP[0] = None


def dump_dir():
    d = _DIR_OVERRIDE[0] or str(_flags.flag("FLAGS_flight_dir") or "")
    if not d:
        d = os.path.join(tempfile.gettempdir(),
                         f"ptpu-flight-{os.getpid()}")
    return d


def _json_safe(obj):
    if isinstance(obj, dict):
        return {str(k): _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set)):
        return [_json_safe(v) for v in obj]
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    return repr(obj)


def dump(reason, context=None, path=None):
    """Write the postmortem bundle and return its path.

    Bundle schema::

        {"reason": str, "ts": float, "pid": int, "context": {...},
         "spans": [active span names at dump time],
         "counters": {name: value},              # full current snapshot
         "counters_delta": {name: movement},     # since startup / clear()
         "histograms": {name: {count,sum,mean,min,max,p50,p95,p99}},
         "events": [{"ts_ns": int, "kind": str, ...fields}, ...],  # oldest first
         "health": {"admission_level", "alerts", "window"},  # when plane is on
         "devicetime": {"sample_every", "est_total_s",
                        "programs": [top-K ledger rows]}}  # when sampled
    """
    from . import metrics as _metrics
    with _LOCK:
        ring = list(_RING)
        bundle = {
            "reason": str(reason),
            "ts": time.time(),
            "pid": os.getpid(),
            "context": _json_safe(context or {}),
            "spans": _trace.current_stack(),
            "counters": _json_safe(_counters.snapshot()),
            "counters_delta": _json_safe(_counters.delta(_BASELINE[0])),
            "histograms": _json_safe(_metrics.histogram_summaries()),
            "events": [dict(_json_safe(f), ts_ns=ts, kind=kind)
                       for ts, kind, f in ring],
        }
        provider = _HEALTH_PROVIDER[0]
        if provider is not None:
            try:
                hstate = provider()
            except Exception:
                hstate = None
            if hstate is not None:
                bundle["health"] = _json_safe(hstate)
        try:
            from . import devicetime as _devicetime
            dt = _devicetime.snapshot(top=8)
            if dt["programs"]:
                bundle["devicetime"] = _json_safe(dt)
        except Exception:
            pass
        if path is None:
            d = dump_dir()
            os.makedirs(d, exist_ok=True)
            path = os.path.join(
                d, f"flight-{_slug(reason)}-{next(_SEQ):04d}.json")
        with open(path, "w") as f:
            json.dump(bundle, f, indent=1)
        _LAST_DUMP[0] = path
        _counters.inc("flight.dumps")
        _counters.inc(f"flight.dumps.{_slug(reason)}")
    return path


def _slug(s):
    return "".join(ch if (ch.isalnum() or ch in "-_") else "_"
                   for ch in str(s))[:64]


def last_dump_path():
    """Path of the most recent :func:`dump` in this process (None if no
    fault has triggered one) — the chaos-test assertion hook."""
    return _LAST_DUMP[0]


def load(path):
    """Read one dump bundle back as a dict."""
    with open(path) as f:
        return json.load(f)


_flags.define_flag(
    "FLAGS_flight_dir", "",
    "Directory for flight-recorder postmortem bundles (empty: a "
    "per-process dir under the system temp dir).")
_flags.define_flag(
    "FLAGS_flight_capacity", _DEFAULT_CAPACITY,
    "Flight-recorder ring size (recent events kept for postmortems).")


def _on_capacity(v):
    try:
        v = int(v)
    except (TypeError, ValueError):
        return
    if v > 0 and v != _RING.maxlen:
        configure(capacity=v)


_flags.register_flag_observer("FLAGS_flight_capacity", _on_capacity)
