"""Per-request distributed tracing: the *causal* half of the profiler.

The aggregate half of the observability plane (counters, histograms, the
host tracer) answers "what are the p99s"; this module answers "where did
THIS request's p99 go".  A :class:`TraceContext` (trace_id + optional
parent span id) is minted at admission — ``ServingFleet.submit`` /
``Router`` dispatch, or ``LLMEngine.add_request`` for a standalone engine
— and threaded through ``FleetRequest`` → engine ``Request`` state.  Every
lifecycle hop records a child span into the per-request span tree:

  admission        router pick + dispatch onto a replica
  queue            bounded-queue wait, enqueue → slot admission
  kv.reserve       paged block-table reservation (prefix match included)
  cow.adopt        copy-on-write clone of a shared partial block
  prefill          slot-engine prefill launch (one span per request)
  prefill.chunk    paged chunked-prefill launch (one span per chunk)
  decode.iter      one batched decode launch (one span per live request
                   per iteration — the per-token hot loop)
  decode.stall     injected ``slow_decode`` stall (chaos site)
  redispatch       re-prefill after replica death, SAME trace_id
  evict            terminal transition, tagged with finish_reason

Sampling is head+tail: ``FLAGS_request_trace_sample`` is the head
probability (0 disables tracing entirely — ``new_trace`` returns None and
every record site is behind an ``is None`` check, so the off path adds no
counters, no syncs, no allocations: machine-enforced by the
``check_counters.py`` trace phase).  With sampling on, every request
records; at finish the trace is RETAINED if head-sampled **or** the
request breached its deadline/SLO, finished as an error, or was retried
across a replica death (tail-based keep-always — the tails are exactly
the traces worth keeping).

Export: :func:`export_jsonl` (one JSON span-tree per line) and
:func:`to_chrome_trace` / :func:`export_chrome`, which merge the kept
request traces with the host tracer's span events on the SAME
``time.perf_counter_ns`` clock — each trace renders as its own named
lane next to the real host threads in chrome://tracing / perfetto.

Counters: ``trace.started / finished / kept / kept.head / kept.tail /
dropped / spans`` (all zero when sampling is off).
"""

from __future__ import annotations

import itertools
import json
import os
import random
import threading
import time
from collections import OrderedDict

from ..core import flags as _flags
from . import counters as _counters
from . import host_tracer as _host

__all__ = [
    "TraceContext", "enabled", "sample_rate", "new_trace", "finish",
    "get_trace", "kept", "kept_ids", "clear", "export_jsonl",
    "to_chrome_trace", "export_chrome", "stage_breakdown", "STAGES",
]

# cached flag value: the ONE hot-path gate (flag observer keeps it fresh)
_SAMPLE = [0.0]
_KEEP_MAX = [256]          # kept-trace ring bound
_MAX_SPANS = 4096          # per-trace span cap (decode.iter is per token)

_LOCK = threading.Lock()
_KEPT: "OrderedDict[str, TraceContext]" = OrderedDict()
_TRACE_SEQ = itertools.count(1)

# finish reasons that force tail retention regardless of head sampling
TAIL_REASONS = frozenset({"deadline", "error", "retried"})

# span names whose durations make up a request's stage accounting
# (queue + prefill work + decode work ≈ TTFT + decode wall time)
STAGES = {
    "queue": ("queue",),
    "prefill": ("prefill", "prefill.chunk", "kv.reserve", "cow.adopt"),
    "decode": ("decode.iter", "decode.stall"),
}
_STAGE_OF = {n: s for s, names in STAGES.items() for n in names}


def enabled() -> bool:
    """True when request tracing is on (``FLAGS_request_trace_sample > 0``)."""
    return _SAMPLE[0] > 0.0


def sample_rate() -> float:
    return _SAMPLE[0]


class _CtxSpan:
    """Context manager recording one timed span into a TraceContext."""

    __slots__ = ("_ctx", "_name", "_parent", "_extra", "_t0")

    def __init__(self, ctx, name, parent, extra):
        self._ctx = ctx
        self._name = name
        self._parent = parent
        self._extra = extra
        self._t0 = 0

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        self._ctx.add_span(self._name, self._t0, time.perf_counter_ns(),
                           parent=self._parent, **(self._extra or {}))
        return False


class TraceContext:
    """One request's trace: identity + a flat span list forming a tree.

    Span records are ``(span_id, parent_id, name, t0_ns, t1_ns, extra)``
    tuples appended to a plain list — ``list.append`` is atomic under the
    GIL, so concurrent recorders (fleet submit thread, replica worker
    threads, the monitor) need no lock on the record path.  Span ids come
    from a per-trace ``itertools.count`` (also GIL-atomic).  ``parent_id``
    0 is the implicit root (the request's lifetime span); the clock is
    ``time.perf_counter_ns`` — the host tracer's clock, so merged chrome
    exports line up.
    """

    __slots__ = ("trace_id", "rid", "parent_span_id", "head_sampled",
                 "status", "keep_reason", "start_ns", "end_ns", "spans",
                 "dropped_spans", "finished", "_seq", "_marks")

    def __init__(self, trace_id, rid, head_sampled, parent_span_id=None):
        self.trace_id = trace_id
        self.rid = rid
        self.parent_span_id = parent_span_id
        self.head_sampled = bool(head_sampled)
        self.status = None          # finish_reason at finalize
        self.keep_reason = None     # "head" | "tail:<why>" | None (dropped)
        self.start_ns = time.perf_counter_ns()
        self.end_ns = None
        self.spans: list = []       # (sid, parent, name, t0, t1, extra)
        self.dropped_spans = 0
        self.finished = False
        self._seq = itertools.count(1)
        self._marks: dict = {}      # stamp name -> perf_counter_ns

    # -- recording -----------------------------------------------------------
    def add_span(self, name, t0_ns, t1_ns, parent=0, **extra):
        """Record one completed span; returns its span id (None when the
        trace is finished or at the span cap)."""
        if self.finished:
            return None
        if len(self.spans) >= _MAX_SPANS:
            self.dropped_spans += 1
            return None
        sid = next(self._seq)
        self.spans.append((sid, parent, name, int(t0_ns), int(t1_ns),
                           extra or None))
        return sid

    def add_event(self, name, **extra):
        """Zero-duration marker span (evict reasons, replica deaths)."""
        now = time.perf_counter_ns()
        return self.add_span(name, now, now, **extra)

    def span(self, name, parent=0, **extra):
        """``with ctx.span("prefill", bucket=64): ...`` timed recording."""
        return _CtxSpan(self, name, parent, extra)

    def stamp(self, name):
        """Remember 'now' under ``name`` for a later :meth:`span_from`."""
        self._marks[name] = time.perf_counter_ns()

    def span_from(self, mark, name, **extra):
        """Record a span from a previous :meth:`stamp` to now (falls back
        to the trace start when the stamp is missing)."""
        t0 = self._marks.pop(mark, None)
        if t0 is None:
            t0 = self.start_ns
        return self.add_span(name, t0, time.perf_counter_ns(), **extra)

    # -- accounting / export -------------------------------------------------
    def wall_ns(self):
        end = self.end_ns if self.end_ns is not None \
            else time.perf_counter_ns()
        return max(0, end - self.start_ns)

    def stage_ns(self):
        """``{stage: summed ns}`` over the stage spans (queue / prefill /
        decode) — the per-request 'where did the time go' split."""
        out = {s: 0 for s in STAGES}
        for _sid, _p, name, t0, t1, _x in self.spans:
            s = _STAGE_OF.get(name)
            if s is not None:
                out[s] += max(0, t1 - t0)
        return out

    def to_dict(self):
        """JSON-safe span tree: flat span list + nested tree under an
        implicit root covering the request lifetime."""
        spans = sorted(self.spans, key=lambda s: (s[3], s[0]))
        flat, nodes = [], {}
        for sid, parent, name, t0, t1, extra in spans:
            rec = {"span_id": sid, "parent_id": parent, "name": name,
                   "t0_ns": t0, "dur_ns": max(0, t1 - t0)}
            if extra:
                rec.update(extra)
            flat.append(rec)
            nodes[sid] = {"name": name, "span_id": sid, "t0_ns": t0,
                          "dur_ns": max(0, t1 - t0),
                          "extra": dict(extra) if extra else {},
                          "children": []}
        root = {"name": f"request[rid={self.rid}]", "span_id": 0,
                "t0_ns": self.start_ns, "dur_ns": self.wall_ns(),
                "extra": {}, "children": []}
        for sid, parent, _n, _t0, _t1, _x in spans:
            (nodes.get(parent, root))["children"].append(nodes[sid])
        stages = self.stage_ns()
        return {"trace_id": self.trace_id, "rid": self.rid,
                "parent_span_id": self.parent_span_id,
                "status": self.status, "keep_reason": self.keep_reason,
                "head_sampled": self.head_sampled,
                "start_ns": self.start_ns, "end_ns": self.end_ns,
                "wall_ns": self.wall_ns(),
                "stage_ns": stages,
                "dropped_spans": self.dropped_spans,
                "spans": flat, "tree": root}

    def __repr__(self):
        return (f"TraceContext({self.trace_id}, rid={self.rid}, "
                f"spans={len(self.spans)}, status={self.status!r}, "
                f"keep={self.keep_reason!r})")


# -- lifecycle ---------------------------------------------------------------
def new_trace(rid, parent_span_id=None, trace_id=None):
    """Mint a trace for request ``rid`` — or None when sampling is off
    (the zero-overhead fast path: callers gate every record site on the
    returned context being non-None)."""
    s = _SAMPLE[0]
    if s <= 0.0:
        return None
    head = s >= 1.0 or random.random() < s
    if trace_id is None:
        trace_id = f"t{next(_TRACE_SEQ):05d}-r{rid}"
    ctx = TraceContext(trace_id, rid, head, parent_span_id)
    _counters.inc("trace.started")
    return ctx


def finish(ctx, reason, breached=False, retried=False):
    """Finalize a trace: decide retention (head sample OR tail keep-always
    on deadline/SLO breach, error, or retry) and publish kept traces to
    the bounded registry (`/traces/<id>`).  Idempotent per trace; returns
    True when the trace was kept."""
    if ctx is None or ctx.finished:
        return False
    ctx.end_ns = time.perf_counter_ns()
    ctx.status = str(reason)
    tail = bool(breached) or bool(retried) or (str(reason) in TAIL_REASONS)
    keep = ctx.head_sampled or tail
    if tail:
        why = str(reason) if str(reason) in TAIL_REASONS else (
            "breach" if breached else "retried")
        ctx.keep_reason = f"tail:{why}"
    elif keep:
        ctx.keep_reason = "head"
    ctx.finished = True
    _counters.inc("trace.finished")
    _counters.inc("trace.spans", len(ctx.spans))
    if keep:
        _counters.inc("trace.kept")
        _counters.inc("trace.kept.tail" if tail else "trace.kept.head")
        with _LOCK:
            _KEPT[ctx.trace_id] = ctx
            while len(_KEPT) > _KEEP_MAX[0]:
                _KEPT.popitem(last=False)
    else:
        _counters.inc("trace.dropped")
    return keep


# -- registry ----------------------------------------------------------------
def kept():
    """Kept TraceContexts, oldest first (bounded ring of the last N)."""
    with _LOCK:
        return list(_KEPT.values())


def kept_ids():
    with _LOCK:
        return list(_KEPT)


def get_trace(trace_id):
    """The kept trace's span-tree dict, or None (the ``/traces/<id>``
    lookup)."""
    with _LOCK:
        ctx = _KEPT.get(trace_id)
    return None if ctx is None else ctx.to_dict()


def clear():
    """Drop every kept trace (test isolation)."""
    with _LOCK:
        _KEPT.clear()


def set_keep_max(n):
    """Resize the kept-trace ring."""
    with _LOCK:
        _KEEP_MAX[0] = max(1, int(n))
        while len(_KEPT) > _KEEP_MAX[0]:
            _KEPT.popitem(last=False)


# -- export ------------------------------------------------------------------
def export_jsonl(path, traces=None):
    """Write one JSON span-tree per line; returns the path."""
    if traces is None:
        traces = kept()
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        for ctx in traces:
            f.write(json.dumps(ctx.to_dict() if isinstance(ctx, TraceContext)
                               else ctx) + "\n")
    return path


def to_chrome_trace(traces=None, host_events=None,
                    process_name="paddle_tpu"):
    """Chrome trace-event JSON merging the host tracer's spans with the
    kept request traces — same process, same ``perf_counter_ns`` clock,
    one synthetic named lane per request trace."""
    trace = _host.to_chrome_trace(host_events, process_name=process_name)
    evs = trace["traceEvents"]
    pid = os.getpid()
    if traces is None:
        traces = kept()
    for i, ctx in enumerate(traces):
        tid = 1_000_000 + i   # synthetic lane, clear of real thread ids
        evs.append({"ph": "M", "name": "thread_name", "pid": pid,
                    "tid": tid,
                    "args": {"name": f"request {ctx.trace_id} "
                                     f"[{ctx.status}]"}})
        evs.append({"ph": "X", "name": f"request[rid={ctx.rid}]",
                    "cat": "request", "pid": pid, "tid": tid,
                    "ts": ctx.start_ns / 1000.0,
                    "dur": ctx.wall_ns() / 1000.0,
                    "args": {"trace_id": ctx.trace_id,
                             "keep": ctx.keep_reason}})
        for sid, parent, name, t0, t1, extra in ctx.spans:
            evs.append({"ph": "X", "name": name, "cat": "request",
                        "pid": pid, "tid": tid, "ts": t0 / 1000.0,
                        "dur": max(t1 - t0, 0) / 1000.0,
                        "args": dict(extra or {}, span_id=sid,
                                     parent_id=parent)})
    return trace


def export_chrome(path, traces=None, host_events=None):
    obj = to_chrome_trace(traces, host_events)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(obj, f)
    return path


def stage_breakdown(traces=None):
    """Aggregate queue/prefill/decode shares + percentiles over traces —
    the 'which hop ate the p99' view the bench serve/fleet legs and the
    ops endpoint report.  Returns ``{"requests": N, "<stage>":
    {"share", "p50_ms", "p99_ms", "max_ms"}}``."""
    if traces is None:
        traces = kept()
    per_stage = {s: [] for s in STAGES}
    for ctx in traces:
        st = ctx.stage_ns() if isinstance(ctx, TraceContext) \
            else ctx.get("stage_ns", {})
        for s in per_stage:
            per_stage[s].append(st.get(s, 0))
    n = len(traces)
    out = {"requests": n}
    total = sum(sum(v) for v in per_stage.values()) or 1
    for s, vals in per_stage.items():
        vals = sorted(vals)
        if not vals:
            out[s] = {"share": 0.0, "p50_ms": 0.0, "p99_ms": 0.0,
                      "max_ms": 0.0}
            continue
        pick = lambda q: vals[min(len(vals) - 1, int(q * len(vals)))]
        out[s] = {"share": sum(vals) / total,
                  "p50_ms": pick(0.50) / 1e6,
                  "p99_ms": pick(0.99) / 1e6,
                  "max_ms": vals[-1] / 1e6}
    return out


# -- flag --------------------------------------------------------------------
_flags.define_flag(
    "FLAGS_request_trace_sample", 0.0,
    "Per-request distributed-trace head-sampling probability in [0, 1]. "
    "0 disables request tracing entirely (zero overhead: no spans, no "
    "trace.* counters — gated by the check_counters trace phase); with "
    "any rate > 0 every request records spans and tail-based retention "
    "ALWAYS keeps deadline-breaching / errored / retried requests.")


def _on_sample(v):
    try:
        _SAMPLE[0] = max(0.0, float(v))
    except (TypeError, ValueError):
        _SAMPLE[0] = 0.0


_flags.register_flag_observer("FLAGS_request_trace_sample", _on_sample)
