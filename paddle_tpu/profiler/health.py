"""Health plane: windowed telemetry signals, SLO burn-rate alerting, and
live invariant watchdogs.

PRs 8/10 built the raw telemetry plane (counters/gauges, mergeable
histograms, request traces, goodput ledger, flight recorder, ops HTTP
endpoint); nothing in the running process *interpreted* any of it.  This
module is the derived-signals layer the ROADMAP item-3 consumers act on
(``serving.autoscale.FleetAutoscaler`` rebalances the prefill:decode
split on these burn rates; ``Router.pick`` tightens/refuses admission on
``admission_level``):

* :class:`HealthMonitor` — takes periodic immutable :class:`Snapshot`\\ s
  of the whole counter/gauge/histogram registry into a bounded ring and
  derives **windowed** deltas, rates and percentile movement from any two
  of them (:class:`Window`; histogram windows are element-wise bucket
  subtraction via :meth:`metrics.Histogram.delta`).
* :class:`SLO` — multi-window burn-rate objectives in the Google SRE
  Workbook shape: an alert fires only when the measured signal exceeds
  ``burn x target`` over the **fast** window (still happening) AND the
  **slow** window (sustained, not a blip).  Default objectives cover the
  serving latency SLOs (TTFT / inter-token / queue-wait p95), shed rate
  and error rate.
* :class:`Watchdog` — live promotions of the invariants
  ``scripts/check_counters.py`` gates offline: warm retrace storm, KV
  block-conservation drift, pool-exhaustion backpressure, goodput
  ``accounted < 0.99``, speculative-acceptance collapse, prefetch-stall
  ratio.
* Alerts have a firing/resolved lifecycle with dedupe (a rule already
  firing never re-fires or re-dumps), tick ``health.*`` counters, write a
  flight-recorder postmortem bundle naming the rule and the offending
  window on every 0->1 transition, and fold into a single
  ``admission_level`` recommendation (``ok`` / ``degraded`` /
  ``critical``) that ``ServingFleet.stats()["health"]`` and
  ``Router.stats()["health"]`` expose.  Nothing in THIS module takes a
  scaling or shedding action — the consumers do: the Router sheds on the
  admission level and the FleetAutoscaler flips/grows replica roles on
  the burn-rate alerts.

Wiring: ``ServingFleet`` owns a monitor and ticks it from its heartbeat
thread (or from every :meth:`pump` in sync mode); any other process
attaches one by hand::

    mon = HealthMonitor().attach(engine)     # or .attach(trainer)
    ...
    mon.maybe_tick()        # call from any periodic loop

The whole plane is **zero-overhead when ``FLAGS_health`` is off**:
``maybe_tick`` is one cached-bool check, no snapshot is taken, no
``health.*`` counter moves (machine-gated by the check_counters health
phase: OFF vs ON steady-state counter deltas are identical across the
train / slot / paged / fleet workloads).
"""

from __future__ import annotations

import collections
import threading
import time

from ..core import flags as _flags
from . import counters as _counters
from . import flight as _flight
from . import metrics as _metrics

__all__ = ["SLO", "Watchdog", "Alert", "Snapshot", "Window",
           "HealthMonitor", "default_slos", "default_watchdogs",
           "default_rules", "enabled"]

# admission recommendation ladder (gauge value in parentheses)
LEVELS = ("ok", "degraded", "critical")

_ENABLED = [False]          # cached FLAGS_health — the one-bool off gate
_ACTIVE = [None]            # most recently ticked monitor (flight provider)


def enabled() -> bool:
    """Cached ``FLAGS_health`` value (one list-index read)."""
    return _ENABLED[0]


class Snapshot:
    """One immutable point-in-time copy of the telemetry registries."""

    __slots__ = ("ts", "tick", "counters", "hists")

    def __init__(self, ts, tick, counters, hists):
        self.ts = ts            # monotonic seconds
        self.tick = tick        # monitor tick index at capture
        self.counters = counters
        self.hists = hists      # {name: Histogram copy}


def take_snapshot(now=None, tick=0) -> Snapshot:
    if now is None:
        now = time.monotonic()
    return Snapshot(now, tick, _counters.snapshot(), _metrics.histograms())


class Window:
    """Derived movement between two snapshots of the same process.

    ``delta`` is counter-reset safe: a counter that shrank between the
    snapshots (``counters.reset`` ran) restarts its accounting from zero,
    so the window reports the post-reset value instead of a negative."""

    __slots__ = ("start", "end")

    def __init__(self, start: Snapshot, end: Snapshot):
        self.start = start
        self.end = end

    @property
    def seconds(self) -> float:
        return max(1e-9, self.end.ts - self.start.ts)

    def delta(self, name) -> float:
        after = self.end.counters.get(name, 0)
        d = after - self.start.counters.get(name, 0)
        return after if d < 0 else d

    def rate(self, name) -> float:
        """Counter movement per second over the window."""
        return self.delta(name) / self.seconds

    def gauge(self, name, default=None):
        """The gauge's value at the END of the window (point-in-time)."""
        return self.end.counters.get(name, default)

    def hist_delta(self, name):
        """Element-wise bucket movement of one histogram over the window
        (a fresh :class:`metrics.Histogram`), or None if never recorded."""
        cur = self.end.hists.get(name)
        if cur is None:
            return None
        prev = self.start.hists.get(name)
        if prev is None:
            return cur.copy()
        return cur.delta(prev)

    def percentile(self, name, q):
        """Windowed percentile of one histogram (None: no new samples)."""
        h = self.hist_delta(name)
        if h is None or h.count <= 0:
            return None
        return h.percentile(q)

    def summary(self) -> dict:
        """JSON-safe view of everything that moved (flight/alert context)."""
        moved = {}
        for k, v in self.end.counters.items():
            d = self.delta(k)
            if d:
                moved[k] = d
        p95 = {}
        for name in self.end.hists:
            h = self.hist_delta(name)
            if h is not None and h.count > 0:
                p95[name] = h.percentile(95)
        return {"seconds": self.seconds, "start_tick": self.start.tick,
                "end_tick": self.end.tick, "delta": moved, "p95": p95}


class Alert:
    """One rule's firing/resolved lifecycle record."""

    __slots__ = ("name", "kind", "severity", "state", "since", "last",
                 "resolved_at", "detail", "fired_count")

    def __init__(self, name, kind, severity, now, detail):
        self.name = name
        self.kind = kind
        self.severity = severity
        self.state = "firing"
        self.since = now
        self.last = now
        self.resolved_at = None
        self.detail = detail
        self.fired_count = 1

    def to_dict(self):
        return {"name": self.name, "kind": self.kind,
                "severity": self.severity, "state": self.state,
                "since": self.since, "last": self.last,
                "resolved_at": self.resolved_at,
                "fired_count": self.fired_count, "detail": self.detail}


class SLO:
    """Multi-window burn-rate objective over one windowed signal.

    ``signal`` is either a spec tuple —

    * ``("hist_p95", name)`` — p95 of the histogram's windowed delta
      (requires ``min_count`` new samples, else the window abstains);
    * ``("ratio", numerator, denominator)`` — counter-delta ratio, e.g.
      shed rate = shed / (dispatched + shed);
    * ``("rate", name)`` — counter movement per second;

    — or any callable ``f(window) -> float | None`` (None = abstain).

    ``target`` is the objective for the signal; the per-window **burn**
    is ``measured / target``.  ``windows`` is a tuple of
    ``(seconds, burn_threshold)`` pairs, fast first; the alert fires only
    when EVERY window's burn exceeds its threshold (the fast window says
    it is still happening, the slow window says it is sustained).  When
    the ring does not yet span a requested window the widest available
    span is used — a fresh monitor degrades to single-window alerting
    rather than staying blind."""

    kind = "slo"

    def __init__(self, name, signal, target,
                 windows=((5.0, 1.0), (60.0, 1.0)),
                 severity="critical", min_count=4):
        self.name = name
        self.signal = signal
        self.target = float(target)
        self.windows = tuple((float(s), float(b)) for s, b in windows)
        self.severity = severity
        self.min_count = int(min_count)

    def _measure(self, w: Window):
        sig = self.signal
        if callable(sig):
            return sig(w)
        kind = sig[0]
        if kind == "hist_p95":
            h = w.hist_delta(sig[1])
            if h is None or h.count < self.min_count:
                return None
            return h.percentile(95)
        if kind == "ratio":
            den = w.delta(sig[2])
            if den <= 0:
                return None
            return w.delta(sig[1]) / den
        if kind == "rate":
            return w.rate(sig[1])
        raise ValueError(f"unknown SLO signal spec {sig!r}")

    def status(self, monitor) -> dict:
        wins = []
        for seconds, burn_thr in self.windows:
            w = monitor.window(seconds)
            if w is None:
                wins.append({"seconds": seconds, "span_s": 0.0,
                             "value": None, "burn": None,
                             "threshold": burn_thr, "burning": False})
                continue
            val = self._measure(w)
            burn = None if val is None else val / self.target
            wins.append({"seconds": seconds, "span_s": w.seconds,
                         "value": val, "burn": burn,
                         "threshold": burn_thr,
                         "burning": burn is not None and burn > burn_thr})
        return {"name": self.name, "kind": self.kind,
                "signal": (self.signal if not callable(self.signal)
                           else getattr(self.signal, "__name__", "fn")),
                "target": self.target, "severity": self.severity,
                "windows": wins,
                "firing": bool(wins) and all(x["burning"] for x in wins)}

    def evaluate(self, monitor):
        st = self.status(monitor)
        return st["firing"], {"windows": st["windows"],
                              "target": self.target}


class Watchdog:
    """A live invariant: ``fn(window, monitor) -> (firing, detail)``.

    The window handed to ``fn`` spans ``window_s`` seconds best-effort
    (the widest available span when the ring is younger)."""

    kind = "watchdog"

    def __init__(self, name, fn, window_s=15.0, severity="degraded"):
        self.name = name
        self.fn = fn
        self.window_s = float(window_s)
        self.severity = severity

    def evaluate(self, monitor):
        w = monitor.window(self.window_s)
        if w is None:
            return False, {}
        return self.fn(w, monitor)


# -- default rule set --------------------------------------------------------
def _wd_retrace_storm(w, monitor):
    """Warm retrace storm: the steady-state contract is ZERO program
    compiles, so ANY serving/jit retrace inside a post-warmup window is a
    live violation of the check_counters invariant.  Compiles that happen
    before the monitor's first snapshot (warmup) are invisible by
    construction; a replica-respawn warm shows up as a one-window burst
    that resolves on the next tick."""
    retraces = w.delta("serving.retraces") + w.delta("jit.traces")
    return retraces > 0, {"retraces": retraces,
                          "window_s": w.seconds}


def _wd_kv_conservation(w, monitor):
    """Block conservation over every attached/fleet paged engine:
    ``free + live_refcounted == capacity`` and no block may sit on the
    free list while still holding references."""
    for eng in monitor._pools():
        pool = getattr(eng, "pool", None)
        if pool is None:
            continue
        try:
            refs = list(pool._ref)
            free = list(pool._free)
        except Exception:
            continue
        live = sum(1 for b in range(1, len(refs)) if refs[b] > 0)
        freed_live = sum(1 for b in free if refs[b] > 0)
        if len(free) + live != pool.capacity or freed_live:
            return True, {"free": len(free), "live": live,
                          "capacity": pool.capacity,
                          "free_with_refs": freed_live}
    return False, {}


def _wd_kv_backpressure(w, monitor):
    """Admissions refused because the block pool could not cover the
    worst-case reservation — the live form of the pool-exhaustion gate."""
    n = w.delta("serving.kv.pool_exhausted")
    return n > 0, {"pool_exhausted": n, "window_s": w.seconds}


def _wd_goodput_accounted(w, monitor):
    """The goodput ledger must attribute >= 99% of wall-clock to SOME
    bucket (the check_counters chaos gate, live)."""
    if not w.gauge("goodput.wall_ns", 0):
        return False, {}
    acc = w.gauge("goodput.accounted")
    return (acc is not None and acc < 0.99), {"accounted": acc}


def _wd_spec_acceptance(w, monitor):
    """Speculative acceptance collapse: the draft model proposes tokens
    the target almost never accepts — every round burns K+1 draft
    launches for ~1 emitted token.  Needs real draft volume in the
    window before it may fire."""
    drafted = w.delta("serving.spec.drafted")
    acc = w.gauge("serving.spec.acceptance")
    firing = drafted >= 16 and acc is not None and acc < 0.05
    return firing, {"drafted": drafted, "acceptance": acc}


def _wd_kv_spill_burn(w, monitor):
    """Sustained host-tier spill traffic: the device pool is
    oversubscribed enough that cold-block demotion runs on the admission
    path every window.  Needs real volume (>= 8 blocks) AND a sustained
    rate (> 1 block/s) before firing, so a one-off burst when a big
    prompt lands does not flap; the autoscaler answers with
    ``grow_decode`` (more HBM beats paging churn)."""
    spilled = w.delta("serving.kv.tier.spilled_blocks")
    rate = w.rate("serving.kv.tier.spilled_blocks")
    return (spilled >= 8 and rate > 1.0), {"spilled": spilled,
                                           "rate": rate,
                                           "window_s": w.seconds}


def _wd_kv_tier_occupancy(w, monitor):
    """Host tier nearly full (>= 90% of capacity on any engine): the
    next spills will LRU-discard resident entries, turning demotions
    into data loss (replay-by-prefill).  Live early warning that the
    tier itself needs resizing."""
    for eng in monitor._pools():
        tier = getattr(eng, "_host_tier", None)
        if tier is None:
            continue
        if tier.resident >= 0.9 * tier.capacity:
            return True, {"resident": tier.resident,
                          "capacity": tier.capacity}
    return False, {}


def _wd_mfu_collapse(w, monitor):
    """A dominant program is burning device time at near-zero MFU: the
    roofline says the chip is idle inside the launch (degenerate shapes,
    a silent fallback kernel, host-bound dispatch).  Gated on real
    sampling activity in the window (the ledger only moves when
    FLAGS_device_time_sample > 0) and on sample volume per program, so a
    cold first sample cannot flap it."""
    if w.delta("jit.devicetime.sampled_syncs") <= 0:
        return False, {}
    from . import devicetime as _devicetime
    for row in _devicetime.snapshot(top=8)["programs"]:
        mfu = row.get("mfu")
        share = row.get("share") or 0.0
        if (mfu is not None and row["sampled"] >= 4 and share >= 0.25
                and mfu < 0.05):
            return True, {"program": row["name"], "mfu": mfu,
                          "share": share, "sampled": row["sampled"]}
    return False, {}


def _wd_device_time_regression(w, monitor):
    """A program's trailing-window mean device time blew past its own
    baseline (>= 2x): a shape drifted into a slower executable, a cache
    went cold, or the accelerator is being stolen.  Fires only for
    programs that carry real share, with enough samples that the
    baseline mean is meaningful."""
    if w.delta("jit.devicetime.sampled_syncs") <= 0:
        return False, {}
    from . import devicetime as _devicetime
    for row in _devicetime.snapshot(top=8)["programs"]:
        reg = row.get("regression")
        share = row.get("share") or 0.0
        if (reg is not None and reg >= 2.0 and row["sampled"] >= 12
                and share >= 0.05):
            return True, {"program": row["name"], "regression": reg,
                          "share": share, "sampled": row["sampled"]}
    return False, {}


def _wd_prefetch_stall(w, monitor):
    """Input pipeline starvation: time blocked on data dominates the
    window."""
    stall = w.delta("io.prefetch_stall_ns")
    ratio = stall / (w.seconds * 1e9)
    return (stall > 0 and ratio > 0.5), {"stall_ns": stall,
                                         "ratio": ratio}


def _wd_noisy_neighbor(w, monitor):
    """Multi-tenant isolation: one tenant bucket's windowed ITL p95 is a
    multiple of the other buckets' median — a neighbor's burn is sinking
    its SLO.  Reads the ``serving.itl_ns.tenant.<bucket>`` histograms the
    adapter-serving engine feeds per emitted token; needs >= 2 buckets
    with real traffic (>= 8 samples each) in the window, so single-tenant
    or idle fleets can never flap it."""
    p95s = {}
    for name in w.end.hists:
        if not name.startswith("serving.itl_ns.tenant."):
            continue
        h = w.hist_delta(name)
        if h is None or h.count < 8:
            continue
        p95s[name.rsplit(".", 1)[-1]] = h.percentile(95)
    if len(p95s) < 2:
        return False, {}
    worst_bucket = max(p95s, key=p95s.get)
    worst = p95s[worst_bucket]
    rest = sorted(v for k, v in p95s.items() if k != worst_bucket)
    med = rest[len(rest) // 2]
    firing = med > 0 and worst >= 4.0 * med
    return firing, {"worst_bucket": worst_bucket,
                    "worst_p95_ns": worst,
                    "median_other_p95_ns": med,
                    "buckets": len(p95s)}


def default_slos():
    """The serving SLO objectives (targets sized for the CPU test scale
    the repo's gates run at; production deployments pass their own)."""
    return [
        SLO("itl_burn", ("hist_p95", "serving.itl_ns"), 15e6),
        SLO("ttft_burn", ("hist_p95", "serving.ttft_ns"), 500e6),
        SLO("queue_wait_burn", ("hist_p95", "serving.queue_wait_ns"),
            500e6),
        SLO("shed_rate",
            lambda w: ((w.delta("serving.fleet.shed")
                        / max(1.0, w.delta("serving.fleet.dispatched")
                              + w.delta("serving.fleet.shed")))
                       if (w.delta("serving.fleet.dispatched")
                           + w.delta("serving.fleet.shed")) > 0 else None),
            0.05),
        SLO("error_rate", ("ratio", "serving.request_errors",
                           "serving.requests"), 0.01),
    ]


def default_watchdogs():
    return [
        Watchdog("retrace_storm", _wd_retrace_storm),
        Watchdog("kv_conservation", _wd_kv_conservation,
                 severity="critical"),
        Watchdog("kv_backpressure", _wd_kv_backpressure),
        Watchdog("kv_spill_burn", _wd_kv_spill_burn),
        Watchdog("kv_tier_occupancy", _wd_kv_tier_occupancy),
        Watchdog("goodput_accounted", _wd_goodput_accounted),
        Watchdog("spec_acceptance", _wd_spec_acceptance),
        Watchdog("noisy_neighbor", _wd_noisy_neighbor),
        Watchdog("prefetch_stall", _wd_prefetch_stall),
        Watchdog("mfu_collapse", _wd_mfu_collapse),
        Watchdog("device_time_regression", _wd_device_time_regression),
    ]


def default_rules():
    return default_slos() + default_watchdogs()


class HealthMonitor:
    """Snapshot ring + rule evaluation + alert lifecycle; see the module
    docstring.  Construction is cheap (no snapshot is taken) so owners
    like ``ServingFleet`` create one unconditionally and let
    :meth:`maybe_tick` gate everything on ``FLAGS_health``."""

    def __init__(self, rules=None, fleet=None, ring=256, interval_s=None,
                 signal_window_s=15.0):
        self.rules = list(rules) if rules is not None else default_rules()
        self.fleet = fleet
        self.interval_s = interval_s   # None: FLAGS_health_interval_s
        self.signal_window_s = float(signal_window_s)
        self.ticks = 0
        self._ring: collections.deque = collections.deque(
            maxlen=int(ring))
        self._alerts: dict[str, Alert] = {}
        self._attached: list = []
        self._lock = threading.Lock()
        self._last_tick_ts = None

    # -- wiring --------------------------------------------------------------
    def attach(self, obj):
        """Register an engine / trainer / fleet whose internals the
        watchdogs may probe (paged engines contribute their block pool to
        the conservation rule).  Returns self for chaining."""
        with self._lock:
            if obj is not None and obj not in self._attached:
                self._attached.append(obj)
        return self

    def _pools(self):
        """Every object that may own a paged block pool: attachments plus
        the live replica engines of an owning fleet."""
        with self._lock:
            objs = list(self._attached)
        if self.fleet is not None:
            try:
                objs.extend(rep.engine for rep in self.fleet._alive())
            except Exception:
                pass
        return objs

    # -- ticking -------------------------------------------------------------
    def maybe_tick(self, now=None):
        """Tick if the plane is on and the cadence interval elapsed; the
        OFF path is one cached-bool check and touches no registry."""
        if not _ENABLED[0]:
            return None
        if now is None:
            now = time.monotonic()
        interval = (self.interval_s if self.interval_s is not None
                    else float(_flags.flag("FLAGS_health_interval_s")))
        if (self._last_tick_ts is not None
                and now - self._last_tick_ts < interval):
            return None
        return self.tick(now)

    def tick(self, now=None):
        """Take one snapshot, evaluate every rule, update alert states,
        publish the admission level.  Returns the new snapshot."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            snap = take_snapshot(now, self.ticks)
            self._ring.append(snap)
            self.ticks += 1
            self._last_tick_ts = now
        _ACTIVE[0] = self
        _counters.inc("health.ticks")
        for rule in self.rules:
            try:
                firing, detail = rule.evaluate(self)
            except Exception as e:   # a broken rule must not kill the owner
                firing, detail = False, {"rule_error": repr(e)}
            self._transition(rule, firing, detail, now)
        level = self.admission_level()
        _counters.set_gauge("health.admission_level", LEVELS.index(level))
        return snap

    def _transition(self, rule, firing, detail, now):
        with self._lock:
            alert = self._alerts.get(rule.name)
            if firing:
                if alert is not None and alert.state == "firing":
                    alert.last = now          # dedupe: already firing
                    alert.detail = detail
                    return
                if alert is None:
                    alert = Alert(rule.name, rule.kind, rule.severity,
                                  now, detail)
                    self._alerts[rule.name] = alert
                else:                          # refire after a resolve
                    alert.state = "firing"
                    alert.since = alert.last = now
                    alert.resolved_at = None
                    alert.detail = detail
                    alert.fired_count += 1
                window = self._last_window_locked()
            else:
                if alert is None or alert.state != "firing":
                    return
                alert.state = "resolved"
                alert.resolved_at = now
                _counters.inc("health.alerts.resolved")
                _counters.inc(f"health.alerts.resolved.{rule.name}")
                _flight.record("health.alert.resolved", rule=rule.name)
                return
        # 0 -> 1 transition (outside the lock: dump() serialises on the
        # flight lock and snapshots the registries itself)
        _counters.inc("health.alerts.fired")
        _counters.inc(f"health.alerts.fired.{rule.name}")
        _flight.record("health.alert.fired", rule=rule.name,
                       rule_kind=rule.kind, severity=rule.severity)
        try:
            _flight.dump(f"health_{rule.name}", context={
                "rule": rule.name, "kind": rule.kind,
                "severity": rule.severity, "detail": detail,
                "window": window.summary() if window else None})
        except Exception:
            pass

    # -- windows -------------------------------------------------------------
    def _last_window_locked(self):
        if len(self._ring) < 2:
            return None
        return Window(self._ring[-2], self._ring[-1])

    def window(self, seconds, now=None):
        """The window ending at the latest snapshot whose span covers
        ``seconds`` — or the widest available span when the ring is
        younger than that.  None until two snapshots exist."""
        with self._lock:
            snaps = list(self._ring)
        if len(snaps) < 2:
            return None
        end = snaps[-1]
        start = snaps[0]
        for s in reversed(snaps[:-1]):
            if end.ts - s.ts >= seconds:
                start = s
                break
        return Window(start, end)

    # -- alert / status surfaces ---------------------------------------------
    def firing(self):
        with self._lock:
            return [a for a in self._alerts.values()
                    if a.state == "firing"]

    def firing_names(self):
        """Set of currently-firing rule names — the autoscaler's decision
        predicate reads this instead of re-walking Alert objects."""
        return {a.name for a in self.firing()}

    def alert_firing(self, name) -> bool:
        """True while the named rule's alert is in the firing state."""
        with self._lock:
            a = self._alerts.get(name)
            return a is not None and a.state == "firing"

    def alerts_state(self):
        """JSON-safe list of every alert ever raised, firing first."""
        with self._lock:
            alerts = sorted(self._alerts.values(),
                            key=lambda a: (a.state != "firing", a.name))
            return [a.to_dict() for a in alerts]

    def admission_level(self) -> str:
        """The single recommendation the autoscaler consumes: ``ok`` (no
        alert firing), ``degraded`` (some alert firing), ``critical``
        (a critical-severity alert firing — shed / stop admitting)."""
        firing = self.firing()
        if not firing:
            return "ok"
        if any(a.severity == "critical" for a in firing):
            return "critical"
        return "degraded"

    def slo_status(self):
        """Per-SLO burn-rate detail for every objective (``GET /slo``)."""
        return [r.status(self) for r in self.rules
                if isinstance(r, SLO)]

    def signals(self):
        """The derived windowed signals (``GET /signals``): counter rates
        for everything that moved, windowed histogram p95s, and the
        current gauge values."""
        w = self.window(self.signal_window_s)
        if w is None:
            return {"window_s": 0.0, "rates_per_s": {}, "p95": {},
                    "gauges": {}}
        rates = {}
        for k in w.end.counters:
            d = w.delta(k)
            if d:
                rates[k] = d / w.seconds
        p95 = {}
        for name in w.end.hists:
            v = w.percentile(name, 95)
            if v is not None:
                p95[name] = v
        gauges = {k: v for k, v in w.end.counters.items()
                  if k in getattr(_counters, "_GAUGES", {})}
        return {"window_s": w.seconds, "rates_per_s": rates, "p95": p95,
                "gauges": gauges}

    def summary(self):
        """The compact block ``ServingFleet.stats()['health']`` /
        ``Router.stats()['health']`` embed.  Cheap when off."""
        if not _ENABLED[0]:
            return {"enabled": False, "admission_level": "ok",
                    "alerts": [], "ticks": self.ticks}
        return {"enabled": True,
                "admission_level": self.admission_level(),
                "alerts": [a.name for a in self.firing()],
                "ticks": self.ticks}

    def flight_state(self):
        """What the flight recorder embeds into every postmortem bundle:
        the alert set and the last window's movement."""
        with self._lock:
            window = self._last_window_locked()
        return {"admission_level": self.admission_level(),
                "alerts": self.alerts_state(),
                "window": window.summary() if window else None}


def _flight_health_provider():
    mon = _ACTIVE[0]
    if mon is None or not _ENABLED[0]:
        return None
    return mon.flight_state()


_flight.set_health_provider(_flight_health_provider)

_flags.define_flag(
    "FLAGS_health", False,
    "Enable the health plane: HealthMonitor snapshot ticks, SLO burn-rate "
    "alerting and invariant watchdogs.  Off: maybe_tick() is one cached "
    "bool check and no health.* counter moves (counter-gated by the "
    "check_counters health phase).")
_flags.define_flag(
    "FLAGS_health_interval_s", 1.0,
    "Minimum seconds between HealthMonitor snapshot ticks when driven "
    "from a heartbeat/pump loop (0 ticks on every call; monitors built "
    "with interval_s= override this).")


def _on_health(v):
    _ENABLED[0] = bool(v)


_flags.register_flag_observer("FLAGS_health", _on_health)
