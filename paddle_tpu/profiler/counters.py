"""Process-global counter/gauge registry.

Reference analogue: the fleet metric tables and the profiler's aggregate
stats (SURVEY §"Metrics / logging / observability") — named monotonically
increasing counters that the runtime bumps on every hot-path event, cheap
enough to stay always-on.  Unlike host-tracer spans (gated by
``FLAGS_host_trace_level``), counters are never disabled: they are the
substrate perf contracts are asserted against (``scripts/bench_smoke.py``,
``scripts/check_counters.py``).

Well-known names (see README "Observability" for the full table):

  jit.steps / jit.traces / jit.cache_hits / jit.cache_misses
  jit.hydrates / jit.syncs
  jit.host.dispatches (XLA launches: steps/K under fused_steps=K)
  jit.fused_windows / jit.fused_fallback_steps
  jit.host.layer_state / jit.host.bind_layer_state /
  jit.host.optimizer_state / jit.host.bind_optimizer_state
  jit.nan_inf_checks / jit.nan_inf_hits (FLAGS_check_nan_inf sweeps)
  jit.devicetime.dispatches (dispatches noted by the device-time ledger
      while FLAGS_device_time_sample > 0; 0 when sampling is off)
  jit.devicetime.sampled_syncs (explicit block-until-ready fences the
      sampler paid — exactly ceil(dispatches / N) over a window started
      by devicetime.reset(); the sync-budget gate's devicetime line)
  static.runs / static.compiles / static.traces
  io.device_put_calls / io.device_put_bytes
  io.stack_windows / io.stack_batches
  io.reader_ns / io.prefetch_stall_ns / io.queue_wait_ns
  dist.collectives / dist.<op> / dist.mp_collectives
  dist.collective_launches (host-issued collective dispatches)
  dist.device_put_sharded_bytes (bytes placed via sharded device_put:
      mesh hydrate + data-parallel batch/window staging)
  optimizer.steps
  serving.requests / serving.prefill_batches / serving.decode_steps
  serving.decode_tokens / serving.evictions / serving.evictions.<reason>
  serving.retraces (serving program compiles; 0 in steady state)
  serving.queue_wait_ns
  serving.deadline_expired (queued past-deadline, evicted pre-prefill)
  serving.request_errors (poisoned requests contained to reason "error")
  serving.slot_occupancy / serving.prefill_programs (gauges)
  serving.fleet.dispatched / serving.fleet.shed (SLO load shedding)
  serving.fleet.retried (fault-driven requeues, at-most-once re-prefill)
  serving.fleet.respawns / serving.fleet.replica_deaths[.<reason>]
  serving.fleet.heartbeat_misses (stall detector trips)
  serving.fleet.completed[.<reason>] / serving.fleet.replayed_tokens
  serving.fleet.warmup_requests / serving.fleet.monitor_errors
  serving.fleet.replay_divergence (resumed stream disagreed with replay)
  serving.fleet.prefix_routed (dispatches won by prefix-cache affinity)
  serving.fleet.lost (admitted request without terminal state; MUST be 0)
  serving.fleet.replicas / serving.fleet.decode_tps (gauges)
  serving.fleet.health_shed (admissions refused because the health
      plane's admission level is critical; also counted under .shed)
  serving.fleet.migrate.requests (prefill→decode KV hand-offs completed)
  serving.fleet.migrate.blocks_copied (blocks device-copied by
      migrations: owned, non-prefix-shared blocks ONLY)
  serving.fleet.migrate.blocks_shared (blocks adopted from the
      destination's radix tree by refcount transfer — never copied)
  serving.fleet.migrate.tokens (KV tokens handed off)
  serving.fleet.migrate.deferred (hand-offs parked on decode-side
      backpressure; the request stays held on its source, KV intact,
      and the migration retries next scheduler tick)
  serving.fleet.migrate.dropped (migrations severed by the
      kv_migrate_drop fault site; request replays, nothing lost)
  serving.fleet.migrate.failed (migrations aborted: no decode capacity
      or destination pool exhausted; request replays)
  serving.autoscale.decisions[.<action>] (autoscaler actions taken:
      disaggregate / grow_prefill / grow_decode / retire)
  serving.autoscale.flips.to_prefill / serving.autoscale.flips.to_decode
      (replica role changes, by direction)
  serving.autoscale.spawns / serving.autoscale.retires (fleet-size
      changes the autoscaler made)
  serving.autoscale.prefill_replicas / serving.autoscale.decode_replicas
      (gauges: the live role split; both 0 in a unified fleet)
  serving.kv.prefix_hits / serving.kv.prefix_misses /
  serving.kv.prefix_hit_tokens (paged radix prefix-cache outcomes)
  serving.kv.cow_copies (copy-on-write partial-block adoptions)
  serving.kv.blocks_evicted / serving.kv.pool_exhausted
  serving.kv.prefill_chunks (chunked-prefill program launches)
  serving.kv.blocks_used (gauge: block-pool blocks currently owned)
  serving.kv.quant.prefill_tokens / serving.kv.quant.decode_tokens
      (tokens quantized on insert into an int8/fp8 KV arena)
  serving.kv.quant.arena_bytes / serving.kv.quant.bytes_saved (gauges:
      quantized arena+scales footprint, and savings vs the model dtype)
  serving.kv.tier.spilled_blocks / serving.kv.tier.restored_blocks
      (host-RAM KV tier traffic: device blocks demoted to pinned host
      buffers, and host entries paged back into the arena)
  serving.kv.tier.spill_drops (host copies discarded: tier LRU
      overflow, request teardown while spilled, or the kv_spill_drop
      fault; the affected tokens replay by deterministic re-prefill)
  serving.kv.tier.readopted (host-resident prefix nodes flipped back to
      device residency for free because a donor carried a live copy)
  serving.kv.tier.host_blocks (gauge: tier entries currently resident)
  serving.kv.host_arena_bytes (gauge: total pinned host bytes ever
      allocated for the tier — flat once the reuse pool is warm)
  serving.kv.host_buf_reuse (spill/restore buffers served from the
      reuse pool instead of a fresh allocation)
  serving.spec.drafted / serving.spec.accepted / serving.spec.rejected
      (speculative decoding proposal outcomes; accepted + rejected ==
      drafted, every scheduler round)
  serving.spec.draft_steps / serving.spec.verify_steps (speculative
      dispatches: K+1 draft launches + ONE verify launch per round)
  serving.spec.draft_prefill_chunks (draft-namespace chunked prefill)
  serving.spec.draft_starved (rounds a slot drafted nothing because the
      pool could not cover its draft-table growth; throughput-only)
  serving.spec.rollback_blocks (draft blocks released by post-verify
      block-table truncation — rejection rollback, no device copies)
  serving.spec.acceptance / serving.spec.yield (gauges: acceptance-rate
      EMA and emitted-tokens-per-round-per-slot EMA)
  serving.fleet.spec_acceptance (gauge: drafted-weighted fleet mean)
  serving.mesh.spec_degraded (sharding specs soft-degraded to
      replicated by the StateArena — e.g. nh not divisible by mp; 0
      when every declared leaf sharded as ruled)
  serving.arena.program_hits / serving.arena.program_misses (StateArena
      compile-cache outcomes; misses only at warmup, 0 in steady state)
  serving.arena.program_evictions (programs dropped by the arena LRU
      cap) / serving.arena.program_rebuilds (evicted keys compiled
      AGAIN — the retrace-accounting signal; MUST be 0 in steady state)
  serving.arena.programs (gauge: live programs the arena fronts)
  serving.adapter.hits / serving.adapter.misses (multi-tenant LoRA
      acquisitions served by a resident slot vs needing a page-in)
  serving.adapter.loads (tenant factor page-ins: ONE cached donated
      dispatch each — eviction-then-reuse never retraces)
  serving.adapter.evictions (refcount-0 LRU tenants displaced to make
      room for a cold page-in)
  serving.adapter.arena_exhausted (admissions deferred because every
      adapter slot is referenced by a running request)
  serving.adapter.load_drops (page-ins severed by the adapter_load_drop
      fault BEFORE any slab write; the request defers, refcounts
      reconcile, no tenant ever sees another tenant's weights)
  serving.adapter.resident (gauge: tenants currently device-resident)
  serving.adapter.arena_bytes (gauge: A/B slab HBM footprint per chip)
  serving.fleet.adapter_routed (dispatches won by tenant affinity — the
      winning replica already held the request's adapter)
  kernels.paged.pallas_programs / kernels.paged.xla_fallbacks
      (trace-time: paged decode programs compiled with the fused Pallas
      backend vs the plain-XLA gather twin; 0 in steady state)
  resilience.saves / resilience.save_ms / resilience.restores
  resilience.resharded_restores (restores onto a different mesh shape)
  resilience.retries / resilience.corrupt_detected
  resilience.recoveries / resilience.recovered.<ExcType>
  resilience.save_failures / resilience.gc_removed
  resilience.faults_injected / resilience.faults_injected.<site>
  io.skipped_batches (replay-to-offset batches skipped on resume)
  train.steps_accum / train.loss_mean / train.grad_norm_mean /
  train.skip_steps (gauges: donated in-graph metric accumulator,
      harvested by metrics_flush at sync boundaries)
  flight.dumps / flight.dumps.<reason> (postmortem bundles written)
  program.<name>.<field> (gauges: per-compiled-program HBM bytes /
      compile seconds / FLOPs under FLAGS_device_telemetry; the
      device-time ledger adds device_time_mean_ms / device_time_samples
      / tflops / mfu / hbm_gbps / ai under FLAGS_device_time_sample)
  serving.fleet.slow_decode_stalls (injected slow_decode stall beats)
  trace.started / trace.finished / trace.spans (request tracing; all 0
      when FLAGS_request_trace_sample=0 — the zero-overhead-off gate)
  trace.kept / trace.kept.head / trace.kept.tail / trace.dropped
      (retention split: head sampling vs tail keep-always on
      deadline/error/retried)
  goodput.fraction / goodput.accounted / goodput.wall_ns /
  goodput.<bucket>_ns (gauges: GoodputLedger.report() wall-clock split)
  analysis.audits (programs AOT-audited under FLAGS_program_audit)
  analysis.findings / analysis.findings.<rule> (audit invariant
      violations: donation-dropped / host-callback / dynamic-shape /
      f64-promotion / collective-budget / hbm-budget / trace-error)
  analysis.collectives_in_graph (allowlisted collective ops found in
      audited mesh programs' compiled HLO — the in-graph-collectives-
      only proof: > 0 with dist.collective_launches == 0 means every
      cross-chip reduction is GSPMD-inserted, none host-launched)
  health.ticks (HealthMonitor snapshot ticks; 0 when FLAGS_health off —
      the zero-overhead-off gate of the health plane)
  health.alerts.fired / health.alerts.fired.<rule> (0->1 alert
      transitions: one flight dump per fire, deduped while firing)
  health.alerts.resolved / health.alerts.resolved.<rule>
  health.admission_level (gauge: 0 ok / 1 degraded / 2 critical — the
      recommendation Router/fleet stats()["health"] expose)

Latency *distributions* (serving.ttft_ns, serving.itl_ns,
serving.queue_wait_ns, io.prefetch_stall_ns, resilience.save_ms, ...)
live in profiler.metrics histograms; the migrated ``*_ns``/``*_ms``
names above keep ticking here as plain sums for back-compat.
Multi-tenant serving adds per-tenant-bucket isolation histograms
(serving.ttft_ns.tenant.<bucket> and serving.itl_ns.tenant.<bucket>,
bucket = "base" or a crc32 hash bucket "t<n>") — the health plane's
noisy_neighbor watchdog reads their windowed p95s.
"""

from __future__ import annotations

import threading

_LOCK = threading.Lock()
_COUNTERS: dict[str, float] = {}
_GAUGES: dict[str, float] = {}


def inc(name: str, value=1):
    """Bump a monotonic counter (thread-safe)."""
    with _LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0) + value


def set_gauge(name: str, value):
    """Set a point-in-time gauge (last-write-wins)."""
    with _LOCK:
        _GAUGES[name] = value


def get(name: str, default=0):
    return _COUNTERS.get(name, _GAUGES.get(name, default))


def names():
    with _LOCK:
        return sorted(set(_COUNTERS) | set(_GAUGES))


def snapshot() -> dict:
    """Copy of every counter and gauge — the unit of delta accounting."""
    with _LOCK:
        out = dict(_COUNTERS)
        out.update(_GAUGES)
        return out


def delta(before: dict, after: dict | None = None) -> dict:
    """Per-name movement between two snapshots (``after`` defaults to now).
    Names absent from ``before`` count from 0; zero deltas are dropped."""
    if after is None:
        after = snapshot()
    out = {}
    for k, v in after.items():
        d = v - before.get(k, 0)
        if d != 0:
            out[k] = d
    return out


def reset(name: str | None = None):
    """Zero one counter/gauge, or all of them (test isolation)."""
    with _LOCK:
        if name is None:
            _COUNTERS.clear()
            _GAUGES.clear()
        else:
            _COUNTERS.pop(name, None)
            _GAUGES.pop(name, None)


def allreduce(group=None) -> dict:
    """Fleet view: element-wise sum of every rank's counters (reference: the
    allreduce'd fleet metric tables).  Single-process: a plain snapshot."""
    local = snapshot()
    try:
        from ..distributed import get_world_size
        if get_world_size() <= 1:
            return local
    except Exception:
        return local
    from ..distributed.communication import all_gather_object
    gathered: list = []
    all_gather_object(gathered, local, group=group)
    out: dict = {}
    for snap in gathered:
        for k, v in snap.items():
            out[k] = out.get(k, 0) + v
    return out
