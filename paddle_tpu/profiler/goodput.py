"""Goodput/badput ledger: classify trainer wall-clock into named buckets.

"Goodput" is the fraction of wall time the trainer spent on productive
step compute; everything else — compile/first-dispatch, waiting on data,
checkpoint sync, restore+replay after a fault, recovery bookkeeping,
scheduler idle — is badput with a name.  The ledger is a tiny exclusive-
time profiler: :meth:`GoodputLedger.bucket` context managers nest, and a
child's time is SUBTRACTED from its parent, so every nanosecond of wall
clock lands in exactly one bucket and the accounting closes to ~100%
(the bench_smoke goodput phase gates ``accounted >= 0.99`` in both clean
and fault-injected runs).

Buckets (the ``FaultTolerantTrainer`` wiring):

  compile         the first window's dispatch (trace + XLA compile ride it)
  step            steady-state window dispatches — the goodput numerator
  data_wait       blocking on the prefetcher for the next batch/window
  ckpt_sync       CheckpointManager.save / terminal wait
  restore_replay  checkpoint restore + replay-to-offset after a fault
  recovery        fault handling around the restore (flight dump, save
                  quiesce) — preempt/ckpt_crash chaos lands here
  idle            loop scaffolding + anything not otherwise attributed

Single-writer by design: the trainer loop is one thread.  ``report()``
may be read from other threads (the ops endpoint) — it only reads the
accumulated dict, so a torn read is at worst one bucket behind.

Gauges published by :meth:`report`: ``goodput.fraction``,
``goodput.accounted``, ``goodput.wall_ns``, ``goodput.<bucket>_ns``.
"""

from __future__ import annotations

import time

from . import counters as _counters

__all__ = ["GoodputLedger", "BUCKETS"]

BUCKETS = ("compile", "step", "data_wait", "ckpt_sync", "restore_replay",
           "recovery", "idle")

# buckets counted as productive in the goodput numerator (compile is
# badput: it is real wall time users wait through, paid once)
_GOOD = ("step",)


class _Bucket:
    """Exclusive-time context manager (re-usable, not re-entrant)."""

    __slots__ = ("_led", "_name")

    def __init__(self, ledger, name):
        self._led = ledger
        self._name = name

    def __enter__(self):
        now = time.perf_counter_ns()
        led = self._led
        stack = led._stack
        if stack:                      # pause the parent bucket's clock
            pname, t_resume = stack[-1]
            led._ns[pname] = led._ns.get(pname, 0) + (now - t_resume)
            stack[-1] = (pname, now)   # placeholder; fixed on child exit
        stack.append((self._name, now))
        return self

    def __exit__(self, *exc):
        now = time.perf_counter_ns()
        led = self._led
        name, t_resume = led._stack.pop()
        led._ns[name] = led._ns.get(name, 0) + (now - t_resume)
        if led._stack:                 # resume the parent's clock
            pname, _ = led._stack[-1]
            led._stack[-1] = (pname, now)
        return False


class GoodputLedger:
    """Wall-clock bucket accounting for one training run."""

    def __init__(self):
        self._ns: dict = {}
        self._stack: list = []
        self._t_start = None
        self._t_stop = None

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        """Begin (or restart) the accounting window."""
        self._ns = {}
        self._stack = []
        self._t_start = time.perf_counter_ns()
        self._t_stop = None
        return self

    def stop(self):
        self._t_stop = time.perf_counter_ns()
        return self

    @property
    def started(self):
        return self._t_start is not None

    def bucket(self, name):
        """``with ledger.bucket("step"): ...`` — nested buckets accrue
        exclusive time (child time never double-counts in the parent)."""
        return _Bucket(self, str(name))

    def add(self, name, ns):
        """Attribute ``ns`` nanoseconds directly (non-contextual sites)."""
        self._ns[str(name)] = self._ns.get(str(name), 0) + int(ns)

    # -- reporting -----------------------------------------------------------
    def wall_ns(self):
        if self._t_start is None:
            return 0
        end = self._t_stop if self._t_stop is not None \
            else time.perf_counter_ns()
        return max(0, end - self._t_start)

    def report(self, publish=True):
        """The ledger as a dict: per-bucket ns + seconds, goodput fraction
        (step / wall), and ``accounted`` — the fraction of wall clock
        explicitly attributed to a bucket BEFORE the idle fold (the
        >= 0.99 chaos gate).  Unattributed time is folded into ``idle``
        in the returned buckets so they always sum to the wall clock."""
        wall = max(1, self.wall_ns())
        attributed = sum(self._ns.values())
        buckets = {b: int(self._ns.get(b, 0)) for b in BUCKETS}
        for k, v in self._ns.items():          # custom bucket names pass thru
            if k not in buckets:
                buckets[k] = int(v)
        buckets["idle"] += max(0, wall - attributed)
        good = sum(self._ns.get(b, 0) for b in _GOOD)
        out = {
            "wall_ns": int(wall),
            "wall_s": wall / 1e9,
            "buckets_ns": buckets,
            "buckets_s": {k: v / 1e9 for k, v in buckets.items()},
            "goodput": good / wall,
            "badput": 1.0 - good / wall,
            "accounted": min(1.0, attributed / wall),
        }
        if publish:
            _counters.set_gauge("goodput.fraction", out["goodput"])
            _counters.set_gauge("goodput.accounted", out["accounted"])
            _counters.set_gauge("goodput.wall_ns", out["wall_ns"])
            for k, v in buckets.items():
                _counters.set_gauge(f"goodput.{k}_ns", v)
        return out

    def __repr__(self):
        r = self.report(publish=False) if self.started else None
        if r is None:
            return "GoodputLedger(unstarted)"
        return (f"GoodputLedger(goodput={r['goodput']:.3f}, "
                f"accounted={r['accounted']:.3f}, "
                f"wall_s={r['wall_s']:.3f})")
