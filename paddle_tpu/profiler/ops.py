"""Live ops endpoint: stdlib-HTTP window into the running process.

One tiny ``ThreadingHTTPServer`` (no dependencies, daemon threads) serving
the full observability plane of a live trainer/engine/fleet:

  GET /healthz            liveness + fleet/replica health (Router-aggregated)
  GET /metrics            Prometheus text exposition (counters + gauges +
                          histogram quantiles; ``metrics.prometheus_text``)
  GET /goodput            the attached GoodputLedger's bucket report
  GET /traces             kept request-trace ids + queue/prefill/decode
                          stage breakdown (``trace.stage_breakdown``)
  GET /traces/<trace_id>  one kept request's full span tree
  GET /flight             flight-recorder state: last postmortem bundle
                          path, bundle dir listing, event-ring tail
  GET /alerts             health-plane alert lifecycle + admission level
  GET /slo                per-objective multi-window burn-rate status
  GET /signals            derived windowed signals (rates / p95s / gauges)
  GET /programs           device-time ledger table: per-program dispatch/
                          sample counts, mean/p95 ms, share, MFU/roofline
                          (``devicetime.snapshot``) + AOT program stats
  POST /profile?ms=N      single-flight programmatic ``jax.profiler``
                          XPlane capture for N ms (409 while one is in
                          flight; N clamped to the timeout guard);
                          returns the dump directory path

Attach whatever the process has: ``OpsServer(fleet=...)`` aggregates
across fleet replicas via the Router (health, merged latency
histograms) and serves the fleet's :class:`health.HealthMonitor`;
``OpsServer(engine=...)`` serves a standalone engine (pass
``monitor=HealthMonitor(...)`` to expose a hand-attached monitor);
``OpsServer(ledger=...)`` exposes a trainer's goodput.  ``/healthz``
degrades to ``"degraded"`` while ANY health alert fires.  ``port=0`` binds
an ephemeral port (``server.port`` after :meth:`start`) so tests and
bench smoke-hits never collide.  ``scripts/ops_server.py`` is the CLI.
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import counters as _counters
from . import flight as _flight
from . import metrics as _metrics
from . import trace as _rtrace

__all__ = ["OpsServer"]


class OpsServer:
    """Serve the ops endpoints for this process; non-blocking."""

    def __init__(self, fleet=None, engine=None, ledger=None, logger=None,
                 monitor=None, host="127.0.0.1", port=0):
        self.fleet = fleet
        self.engine = engine
        self.ledger = ledger
        self.logger = logger
        self.monitor = monitor
        self.host = host
        self.port = int(port)
        self._srv = None
        self._thread = None

    def _monitor(self):
        """The HealthMonitor to serve: an explicit ``monitor=`` wins,
        else the attached fleet's own."""
        if self.monitor is not None:
            return self.monitor
        return getattr(self.fleet, "health", None)

    # -- endpoint payloads ---------------------------------------------------
    def healthz(self):
        out = {"status": "ok", "pid": os.getpid(),
               "flight_dumps": _counters.get("flight.dumps"),
               "traces_kept": len(_rtrace.kept_ids())}
        if self.fleet is not None:
            st = self.fleet.stats()
            out["fleet"] = {
                "alive": st["alive"],
                "replicas": len(st["replicas"]),
                "requests": st["requests"],
                "unfinished": st["unfinished"],
                "pending_retries": st["pending_retries"],
                "decode_tps": st["decode_tps"],
                "closed": st["closed"],
                "latency": st["latency"],
            }
            if st["alive"] == 0 and not st["closed"]:
                out["status"] = "degraded"
        elif self.engine is not None:
            out["engine"] = self.engine.stats()
        if self.ledger is not None and self.ledger.started:
            r = self.ledger.report(publish=False)
            out["goodput"] = {"goodput": r["goodput"],
                              "accounted": r["accounted"]}
        mon = self._monitor()
        if mon is not None:
            h = mon.summary()
            out["health"] = h
            if h["enabled"] and h["alerts"]:
                out["status"] = "degraded"
        return 200, out

    def alerts(self):
        mon = self._monitor()
        if mon is None:
            return 404, {"error": "no health monitor attached"}
        return 200, {"enabled": mon.summary()["enabled"],
                     "admission_level": mon.admission_level(),
                     "firing": [a.name for a in mon.firing()],
                     "alerts": mon.alerts_state()}

    def slo(self):
        mon = self._monitor()
        if mon is None:
            return 404, {"error": "no health monitor attached"}
        return 200, {"enabled": mon.summary()["enabled"],
                     "slos": mon.slo_status()}

    def signals(self):
        mon = self._monitor()
        if mon is None:
            return 404, {"error": "no health monitor attached"}
        return 200, mon.signals()

    def goodput(self):
        if self.ledger is None or not self.ledger.started:
            return 404, {"error": "no goodput ledger attached"}
        return 200, self.ledger.report(publish=False)

    def traces(self):
        return 200, {"count": len(_rtrace.kept_ids()),
                     "kept": _rtrace.kept_ids(),
                     "sample_rate": _rtrace.sample_rate(),
                     "breakdown": _rtrace.stage_breakdown()}

    def trace_by_id(self, trace_id):
        t = _rtrace.get_trace(trace_id)
        if t is None:
            return 404, {"error": f"unknown trace_id {trace_id!r}",
                         "kept": _rtrace.kept_ids()}
        return 200, t

    def programs(self):
        """Device-time ledger table + the AOT per-program stats it joins
        (``capture_program_stats`` records: FLOPs, HBM bytes, compile s)."""
        from . import devicetime as _devicetime
        out = _devicetime.snapshot()
        out["program_stats"] = _metrics.program_stats()
        return 200, out

    def flight_state(self, tail=50):
        d = _flight.dump_dir()
        bundles = []
        if os.path.isdir(d):
            bundles = sorted(f for f in os.listdir(d)
                             if f.startswith("flight-"))
        evs = _flight.events()[-int(tail):]
        return 200, {
            "last_dump": _flight.last_dump_path(),
            "dump_dir": d,
            "bundles": bundles,
            "events": [dict(fields, ts_ns=ts, kind=kind)
                       for ts, kind, fields in evs],
        }

    def route(self, path):
        """Dispatch one GET; returns (status, content_type, body_bytes)."""
        path = path.split("?", 1)[0].rstrip("/") or "/"
        if path == "/metrics":
            body = _metrics.prometheus_text(self.logger)
            return 200, "text/plain; version=0.0.4", body.encode()
        if path in ("/", "/healthz"):
            code, obj = self.healthz()
        elif path == "/goodput":
            code, obj = self.goodput()
        elif path == "/traces":
            code, obj = self.traces()
        elif path.startswith("/traces/"):
            code, obj = self.trace_by_id(path[len("/traces/"):])
        elif path == "/flight":
            code, obj = self.flight_state()
        elif path == "/alerts":
            code, obj = self.alerts()
        elif path == "/slo":
            code, obj = self.slo()
        elif path == "/signals":
            code, obj = self.signals()
        elif path == "/programs":
            code, obj = self.programs()
        else:
            code, obj = 404, {"error": f"unknown endpoint {path!r}",
                              "endpoints": ["/healthz", "/metrics",
                                            "/goodput", "/traces",
                                            "/traces/<trace_id>",
                                            "/flight", "/alerts",
                                            "/slo", "/signals",
                                            "/programs",
                                            "POST /profile?ms="]}
        return code, "application/json", json.dumps(obj).encode()

    def route_post(self, path):
        """Dispatch one POST; returns (status, content_type, body_bytes)."""
        from . import devicetime as _devicetime
        from urllib.parse import parse_qs, urlsplit
        parts = urlsplit(path)
        p = parts.path.rstrip("/") or "/"
        if p != "/profile":
            obj = {"error": f"unknown POST endpoint {p!r}",
                   "endpoints": ["POST /profile?ms="]}
            return 404, "application/json", json.dumps(obj).encode()
        q = parse_qs(parts.query)
        try:
            ms = int(q.get("ms", ["100"])[0])
            if ms <= 0:
                raise ValueError(ms)
        except (TypeError, ValueError):
            obj = {"error": f"bad ms={q.get('ms')!r} (want a positive "
                            "integer of milliseconds)"}
            return 400, "application/json", json.dumps(obj).encode()
        try:
            out = _devicetime.capture_profile(ms)
            code, obj = 200, out
        except _devicetime.ProfileBusy as e:
            code, obj = 409, {"error": str(e)}
        except Exception as e:
            code, obj = 500, {"error": repr(e)}
        return code, "application/json", json.dumps(obj).encode()

    # -- server lifecycle ----------------------------------------------------
    def start(self):
        """Bind + serve in a daemon thread; returns the bound port."""
        ops = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                try:
                    code, ctype, body = ops.route(self.path)
                except Exception as e:   # endpoint bug must not kill serving
                    code, ctype = 500, "application/json"
                    body = json.dumps({"error": repr(e)}).encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                try:
                    code, ctype, body = ops.route_post(self.path)
                except Exception as e:
                    code, ctype = 500, "application/json"
                    body = json.dumps({"error": repr(e)}).encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):    # keep stdout clean
                pass

        self._srv = ThreadingHTTPServer((self.host, self.port), _Handler)
        self._srv.daemon_threads = True
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        name="ops-server", daemon=True)
        self._thread.start()
        return self.port

    def stop(self):
        if self._srv is not None:
            self._srv.shutdown()
            self._srv.server_close()
            self._srv = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def url(self, path="/healthz"):
        return f"http://{self.host}:{self.port}{path}"

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
