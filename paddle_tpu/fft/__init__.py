"""Spectral ops (reference: python/paddle/fft.py, 1669 LoC over
pocketfft/cuFFT; TPU-native: jnp.fft lowered by XLA)."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import apply_op
from ..core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def _mk(name, fn):
    def op(x, n=None, axis=-1, norm="backward", name_=None):
        return apply_op(name, lambda v: fn(v, n=n, axis=axis, norm=norm), _t(x))
    op.__name__ = name
    return op


fft = _mk("fft", jnp.fft.fft)
ifft = _mk("ifft", jnp.fft.ifft)
rfft = _mk("rfft", jnp.fft.rfft)
irfft = _mk("irfft", jnp.fft.irfft)
hfft = _mk("hfft", jnp.fft.hfft)
ihfft = _mk("ihfft", jnp.fft.ihfft)


def _mkn(name, fn):
    def op(x, s=None, axes=None, norm="backward", name_=None):
        return apply_op(name, lambda v: fn(v, s=s, axes=axes, norm=norm), _t(x))
    op.__name__ = name
    return op


fft2 = _mkn("fft2", jnp.fft.fft2)
ifft2 = _mkn("ifft2", jnp.fft.ifft2)
rfft2 = _mkn("rfft2", jnp.fft.rfft2)
irfft2 = _mkn("irfft2", jnp.fft.irfft2)
fftn = _mkn("fftn", jnp.fft.fftn)
ifftn = _mkn("ifftn", jnp.fft.ifftn)
rfftn = _mkn("rfftn", jnp.fft.rfftn)
irfftn = _mkn("irfftn", jnp.fft.irfftn)


# Hermitian N-d transforms.  jnp.fft has no hfft2/hfftn; the identities
#   hfftn(x, s, axes, norm)  == irfftn(conj(x), s, axes, swap(norm))
#   ihfftn(x, s, axes, norm) == conj(rfftn(x, s, axes, swap(norm)))
# hold because hfft is the FORWARD transform of a Hermitian signal built on
# the inverse-real machinery (cf. numpy's 1-d np.fft.hfft == irfft(conj)·n);
# swapping backward<->forward moves the 1/N to the right side, ortho is
# self-inverse.  (reference: python/paddle/fft.py hfft2/ihfft2/hfftn/ihfftn)
_SWAP_NORM = {"backward": "forward", "forward": "backward", "ortho": "ortho"}


def _mk_hfftn(name, default_axes):
    def op(x, s=None, axes=default_axes, norm="backward", name_=None):
        inv = _SWAP_NORM[norm]
        return apply_op(
            name,
            lambda v: jnp.fft.irfftn(jnp.conj(v), s=s, axes=axes, norm=inv),
            _t(x))
    op.__name__ = name
    return op


def _mk_ihfftn(name, default_axes):
    def op(x, s=None, axes=default_axes, norm="backward", name_=None):
        inv = _SWAP_NORM[norm]
        return apply_op(
            name,
            lambda v: jnp.conj(jnp.fft.rfftn(v, s=s, axes=axes, norm=inv)),
            _t(x))
    op.__name__ = name
    return op


hfft2 = _mk_hfftn("hfft2", (-2, -1))
ihfft2 = _mk_ihfftn("ihfft2", (-2, -1))
hfftn = _mk_hfftn("hfftn", None)
ihfftn = _mk_ihfftn("ihfftn", None)


def fftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor._wrap(jnp.fft.fftfreq(n, d))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor._wrap(jnp.fft.rfftfreq(n, d))


def fftshift(x, axes=None, name=None):
    return apply_op("fftshift", lambda v: jnp.fft.fftshift(v, axes), _t(x))


def ifftshift(x, axes=None, name=None):
    return apply_op("ifftshift", lambda v: jnp.fft.ifftshift(v, axes), _t(x))
