"""Fused paged-attention decode kernel + quantized-KV helpers.

The plain-XLA paged decode (``GPT.decode_paged``) gathers every row's
logical sequence ``pool[bt] -> [B, S_max, nh, hd]`` per layer before the
attention einsum — O(B * S_max) HBM traffic per step however short the
sequences actually are.  The Pallas kernel here walks the int32 block
tables **directly over the block-pool arena** (vLLM's PagedAttention
shape, Kwon et al. SOSP '23): the grid is ``(B, max_blocks)``, the block
tables + positions ride as scalar-prefetch operands so each grid step
DMAs exactly ONE physical block ``pool[bt[row, j]]`` into VMEM, and an
online-softmax accumulator (flash-attention style) folds the block in —
the ``[B, S_max]`` gathered cache is never materialized, and blocks past
``ceil((pos+1)/bs)`` are skipped.

Quantized KV (int8 / fp8-e4m3) stores the arena 1 byte/value with one
fp32 scale per (layer, block, position) — per-token symmetric absmax,
quantized on insert by prefill/decode (see ``quantize_kv``).  Because
the scale is a per-key-token scalar it commutes with both attention
contractions, so the kernel dequantizes **in-register** by scaling the
``[1, bs]`` logit/probability rows — the int8 tiles themselves are never
expanded in HBM.

Backend selection is ``FLAGS_paged_kernel``:

* ``off`` (default) — the plain-XLA gather math in ``GPT.decode_paged``
  (the reference twin; also the CPU path, so tier-1 never needs a TPU).
* ``pallas`` — this kernel on TPU (or under interpret mode in tests).
  Off-TPU without interpret mode the flag falls back to the XLA twin
  (``kernels.paged.xla_fallbacks`` ticks once at trace time).

The kernel is trace-time transparent to the serving invariants: block
tables stay int32 OPERANDS, one compiled decode program serves every
table content, and ``kernels.paged.*`` counters only move when a program
is traced — steady-state windows stay counter-silent.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.flags import define_flag, flag
from ..profiler import counters
from ._shapes import check_divides, check_equal, neg_inf

_INTERPRET = [False]  # tests flip this on CPU

define_flag("FLAGS_paged_kernel", "off",
            "paged-attention decode backend: 'off' keeps the plain-XLA "
            "gather twin (reference; CPU default), 'pallas' fuses the "
            "block-table walk into one Pallas kernel on TPU")

#: serving ``kv_dtype`` string -> arena storage dtype.
KV_DTYPES = {"int8": jnp.int8, "fp8": jnp.float8_e4m3fn}

#: symmetric quantization range per kv_dtype (int8 integer grid; fp8
#: e4m3 max finite value).
KV_QMAX = {"int8": 127.0, "fp8": 448.0}


def _on_tpu():
    return jax.devices()[0].platform in ("tpu", "axon")


def kernel_mode():
    """Resolve ``FLAGS_paged_kernel`` against the platform: the mode the
    decode program will actually compile with."""
    mode = flag("FLAGS_paged_kernel")
    if mode not in ("off", "pallas"):
        raise ValueError(f"FLAGS_paged_kernel={mode!r}: want 'off' or "
                         "'pallas'")
    if mode == "pallas" and not (_on_tpu() or _INTERPRET[0]):
        return "off"
    return mode


# ---------------------------------------------------------------------------
# quantized-KV insert/load helpers (shared by prefill, decode, and the
# plain-XLA reference twin)
# ---------------------------------------------------------------------------
def quantize_kv(x, kv_dtype):
    """Per-token symmetric quantization of ``x[..., nh, hd]``: returns
    ``(q[..., nh, hd] in KV_DTYPES[kv_dtype], scale[...] fp32)`` where
    ``scale`` is one absmax-derived scalar per leading index (token).
    All-zero tokens (padded prefill tail) quantize to zeros with a unit
    epsilon scale."""
    qmax = KV_QMAX[kv_dtype]
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=(-2, -1))
    scale = jnp.maximum(amax, 1e-8) / qmax
    y = xf / scale[..., None, None]
    if kv_dtype == "int8":
        q = jnp.clip(jnp.round(y), -qmax, qmax).astype(jnp.int8)
    else:
        q = jnp.clip(y, -qmax, qmax).astype(KV_DTYPES[kv_dtype])
    return q, scale


def kv_dtype_of(dtype):
    """Map an arena storage dtype back to its ``kv_dtype`` name (None for
    unquantized full/half-precision pools)."""
    dt = jnp.dtype(dtype)
    for name, d in KV_DTYPES.items():
        if jnp.dtype(d) == dt:
            return name
    return None


def dequantize_kv(q, scale):
    """Inverse of :func:`quantize_kv`: fp32 values from quantized tiles
    ``q[..., nh, hd]`` and per-token scales ``scale[...]``."""
    return q.astype(jnp.float32) * scale[..., None, None]


# ---------------------------------------------------------------------------
# the fused decode kernel
# ---------------------------------------------------------------------------
def _dot32(a, b, tb=False):
    """Tiny fp32-accumulating dot for the per-head [1, hd] x [hd, bs]
    contractions (operands stay in their input dtype; the MXU/VPU
    accumulates fp32)."""
    cb = (1 if tb else 0,)
    return jax.lax.dot_general(a.astype(jnp.float32),
                               b.astype(jnp.float32),
                               (((1,), cb), ((), ())),
                               preferred_element_type=jnp.float32)


def _decode_kernel(bt_ref, pos_ref, q_ref, k_ref, v_ref, *rest, bs, nh,
                   scale, max_blocks, quant):
    """One grid step: fold physical block ``bt[b, j]`` into row ``b``'s
    online-softmax state.  Scratch (m, l, acc) persists across the
    ``j`` (arbitrary-semantics) grid dim; the output row is written at
    the last block."""
    from jax.experimental import pallas as pl

    if quant:
        sk_ref, sv_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
        sk_ref = sv_ref = None
    b = pl.program_id(0)
    j = pl.program_id(1)
    pos = pos_ref[b]
    nb = pos // bs + 1          # blocks holding live positions

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, neg_inf(jnp.float32))
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(j < nb)
    def _fold():
        # key positions this block covers, vs the row's live horizon
        kpos = j * bs + jax.lax.broadcasted_iota(jnp.int32, (1, bs), 1)
        live = kpos <= pos                                   # [1, bs]
        skrow = sk_ref[...] if quant else None               # [1, bs] f32
        svrow = sv_ref[...] if quant else None
        # per-head tiny matmuls, python-unrolled (nh is static + small);
        # a per-key-token scale commutes with the contraction, so the
        # quantized dequant is a [1, bs] row multiply — int8/fp8 tiles
        # are never expanded
        rows = []
        for hh in range(nh):
            qh = q_ref[0, hh:hh + 1]                          # [1, hd]
            kh = k_ref[0, :, hh, :]                           # [bs, hd]
            s_h = _dot32(qh, kh, tb=True) * scale             # [1, bs]
            if quant:
                s_h = s_h * skrow
            rows.append(jnp.where(live, s_h, neg_inf(jnp.float32)))
        s = jnp.concatenate(rows, axis=0)                     # [nh, bs]
        m_prev, l_prev = m_ref[...], l_ref[...]               # [nh, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, -1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                                # [nh, bs]
        l_ref[...] = l_prev * alpha + jnp.sum(p, -1, keepdims=True)
        m_ref[...] = m_new
        if quant:
            p = p * svrow
        prows = [_dot32(p[hh:hh + 1], v_ref[0, :, hh, :])     # [1, hd]
                 for hh in range(nh)]
        acc_ref[...] = acc_ref[...] * alpha + jnp.concatenate(prows, 0)

    @pl.when(j == max_blocks - 1)
    def _emit():
        o_ref[0] = acc_ref[...] / jnp.maximum(l_ref[...], 1e-30)


def paged_decode_attention(q, pool_k, pool_v, bt, pos, scale_k=None,
                           scale_v=None, *, scale):
    """Fused paged decode attention for B rows over the shared arena.

    q ``[B, nh, hd]`` (the rows' single query tokens, any float dtype),
    pool_k/pool_v ``[n_blocks, bs, nh, hd]`` (one layer's arena, already
    holding each row's newly scattered K/V at ``pos``), bt ``[B,
    max_blocks]`` int32, pos ``[B]`` int32.  With quantized pools,
    scale_k/scale_v ``[n_blocks, bs]`` fp32 are the per-token scales and
    dequantization happens in-register.  Returns fp32 ``[B, nh, hd]``.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, nh, hd = q.shape
    n_blocks, bs = pool_k.shape[0], pool_k.shape[1]
    max_blocks = bt.shape[1]
    quant = scale_k is not None
    check_equal(
        "paged_attention",
        pool_v_blocks=(pool_v.shape[0], n_blocks),
        pool_k_heads=(pool_k.shape[2], nh),
        pool_k_head_dim=(pool_k.shape[3], hd),
        table_rows=(bt.shape[0], B),
        pos_rows=(pos.shape[0], B),
        **({"scale_k_blocks": (scale_k.shape[0], n_blocks),
            "scale_k_positions": (scale_k.shape[1], bs)} if quant else {}))
    check_divides("paged_attention", block_size=(bs, 1))

    kernel = functools.partial(_decode_kernel, bs=bs, nh=nh, scale=scale,
                               max_blocks=max_blocks, quant=quant)
    blk = lambda b, j, bt_s, pos_s: (bt_s[b, j], 0, 0, 0)  # noqa: E731
    row = lambda b, j, bt_s, pos_s: (b, 0, 0)              # noqa: E731
    in_specs = [
        pl.BlockSpec((1, nh, hd), row),
        pl.BlockSpec((1, bs, nh, hd), blk),
        pl.BlockSpec((1, bs, nh, hd), blk),
    ]
    args = [q, pool_k, pool_v]
    if quant:
        srow = lambda b, j, bt_s, pos_s: (bt_s[b, j], 0)   # noqa: E731
        in_specs += [pl.BlockSpec((1, bs), srow),
                     pl.BlockSpec((1, bs), srow)]
        args += [scale_k, scale_v]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, max_blocks),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, nh, hd), row),
        scratch_shapes=[pltpu.VMEM((nh, 1), jnp.float32),
                        pltpu.VMEM((nh, 1), jnp.float32),
                        pltpu.VMEM((nh, hd), jnp.float32)])
    params = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, nh, hd), jnp.float32),
        compiler_params=params(
            dimension_semantics=("parallel", "arbitrary"),
            vmem_limit_bytes=64 * 1024 * 1024),
        interpret=_INTERPRET[0],
    )(bt, pos, *args)


def sharded_paged_decode_attention(mesh, axis, q, pool_k, pool_v, bt, pos,
                                   scale_k=None, scale_v=None, *, scale):
    """Head-sharded twin of :func:`paged_decode_attention`.

    The kernel's per-head matmuls are fully independent, so a pool whose
    head axis is sharded over ``axis`` (``[n_blocks, bs, nh/mp, hd]`` per
    chip) decodes with one ``shard_map`` over the heads: each chip runs
    the unmodified kernel on its head slice against the replicated block
    tables/positions/scales, and the concatenated ``[B, nh, hd]`` output
    needs no collective at all — the TP all-reduce happens later, at the
    projection contraction GSPMD partitions.
    """
    from jax.experimental.shard_map import shard_map

    hspec = P(None, axis, None)                 # q / output: heads on dim 1
    pspec = P(None, None, axis, None)           # pools: heads on dim 2
    in_specs = [hspec, pspec, pspec, P(), P()]
    args = [q, pool_k, pool_v, bt, pos]
    if scale_k is not None:
        in_specs += [P(), P()]                  # per-token scales replicate
        args += [scale_k, scale_v]

    def _local(q_, pk_, pv_, bt_, pos_, *scales):
        sk_, sv_ = scales if scales else (None, None)
        return paged_decode_attention(q_, pk_, pv_, bt_, pos_, sk_, sv_,
                                      scale=scale)

    fn = shard_map(_local, mesh=mesh, in_specs=tuple(in_specs),
                   out_specs=hspec, check_rep=False)
    return fn(*args)


def note_program(backend):
    """Trace-time breadcrumb: which backend a paged decode program was
    compiled with (never moves in a steady-state window)."""
    if backend == "pallas":
        counters.inc("kernels.paged.pallas_programs")
    else:
        counters.inc("kernels.paged.xla_fallbacks")
