"""Shared Pallas preflight checks + the mask-fill constant.

Two things every kernel in this package needs and each used to hand-roll:

* **Block-shape preflight** — Mosaic reports an illegal BlockSpec as an
  opaque lowering error deep inside XLA (BENCH_r01 died on one).  The
  validators here run *before* ``pallas_call`` and raise a ``ValueError``
  that names the offending dimension, the kernel, and the constraint, so
  a bad configuration fails at the call site in plain English.
* **``NEG_INF``** — the additive mask fill.  A hard-coded ``-1e30``
  is representable in every float dtype we use, but it is NOT the most
  negative finite value, and mask arithmetic that mixes fills from
  different sites can drift.  ``neg_inf(dtype)`` returns
  ``finfo(dtype).min`` — the most negative *finite* value, so
  ``exp(fill - m)`` underflows to exactly 0 and bf16 mask fills can
  never round to ``-inf`` (whose ``inf - inf`` arithmetic NaNs).
"""

from __future__ import annotations

import jax.numpy as jnp

#: TPU vector lane width: the last dim of every VMEM tile.
LANE = 128

#: itemsize -> minimum second-to-last (sublane) tile dim.
_MIN_SUBLANE = {4: 8, 2: 16, 1: 32}


def min_sublane(dtype) -> int:
    """Minimum sublane tile extent for ``dtype`` (fp32 8, bf16 16,
    int8/fp8 32)."""
    return _MIN_SUBLANE.get(jnp.dtype(dtype).itemsize, 8)


def neg_inf(dtype=jnp.float32) -> float:
    """Most negative finite value of ``dtype`` — the dtype-aware mask
    fill (``jnp.finfo(dtype).min``)."""
    return float(jnp.finfo(jnp.dtype(dtype)).min)


#: fp32 mask fill shared by the kernels and the jnp reference twins
#: (``gpt.decode_paged``/``decode_slots`` mask their fp32 logits with
#: this).  Use ``neg_inf(dtype)`` when filling a non-fp32 array.
NEG_INF = neg_inf(jnp.float32)


def check_divides(kernel: str, **dims):
    """Each kwarg is ``name=(size, block)``: ``block`` must be a positive
    divisor of ``size``.  Raises ``ValueError`` naming the offending dim."""
    for name, (size, block) in dims.items():
        size, block = int(size), int(block)
        if block < 1:
            raise ValueError(
                f"{kernel}: block for dim '{name}' must be >= 1, got "
                f"{block}")
        if size % block:
            raise ValueError(
                f"{kernel}: dim '{name}'={size} is not divisible by its "
                f"block shape {block} — Pallas would silently skip the "
                f"ragged tail; pick a block that divides {size}")


def check_equal(kernel: str, **dims):
    """Each kwarg is ``name=(got, want)``: operand-consistency preflight.
    Raises ``ValueError`` naming the offending dim."""
    for name, (got, want) in dims.items():
        if int(got) != int(want):
            raise ValueError(
                f"{kernel}: dim '{name}'={got} does not match the "
                f"required {want} (operand shapes disagree)")


def check_min_tile(kernel: str, dtype, *, sublane=None, lane=None,
                   sublane_name="sublane", lane_name="lane"):
    """TPU tiling minimums: the last dim must be a multiple of the
    128-wide lane, the second-to-last a multiple of the dtype's minimum
    sublane extent.  Pass only the dims the kernel actually tiles."""
    if lane is not None and int(lane) % LANE:
        raise ValueError(
            f"{kernel}: dim '{lane_name}'={lane} must be a multiple of "
            f"the {LANE}-wide TPU lane")
    ms = min_sublane(dtype)
    if sublane is not None and int(sublane) % ms:
        raise ValueError(
            f"{kernel}: dim '{sublane_name}'={sublane} must be a "
            f"multiple of the minimum sublane tile {ms} for "
            f"{jnp.dtype(dtype).name}")
