"""Ring attention — context parallelism over the 'sep' mesh axis.

Reference capability anchor: the sep (segment-parallel) axis of the hybrid
topology (fleet/base/topology.py:68,240; meta_parallel/segment_parallel.py)
— the reference scales sequence length across ranks.  SURVEY §5 requires a
ring/flash composition to match that capability on TPU.

TPU-native design: Q/K/V are sequence-sharded over 'sep'.  K/V chunks
rotate around the ring with lax.ppermute (ICI neighbor exchange); each step
computes the local-Q x visiting-KV partial attention with the Pallas flash
kernel (kernels/flash_attention.py) and merges it into a running
(acc, m, l) online-softmax state using the chunk LSE — the same merge the
flash kernel does across key blocks, lifted one level up the memory
hierarchy (VMEM tiles -> per-device sequence chunks).

Causality by global chunk position: a visiting chunk strictly older than
the local Q chunk attends in full (non-causal kernel), the diagonal chunk
attends causally, newer chunks are skipped via a lax.switch branch that
returns lse = NEG_INF (zero weight in the merge, and XLA executes only the
taken branch, so skipped pairs cost nothing — the causal ring saves ~half
the FLOPs).

Gradients flow through jax's scan/ppermute transposes + the flash kernel's
custom VJP — no hand-written backward needed.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .flash_attention import _INTERPRET, _on_tpu, reference_attention
from ._shapes import NEG_INF, check_divides, check_equal


def _chunk_attention(q, k, v, causal, scale):
    """(out, lse) for one q-chunk x kv-chunk pair, [B, S, H, D] layout.
    lse is [B, S, H] (fp32)."""
    if (_on_tpu() or _INTERPRET[0]) and q.shape[1] % 128 == 0 \
            and k.shape[1] % 128 == 0:
        from .flash_attention import flash_attention_with_lse
        qt = jnp.swapaxes(q, 1, 2)
        kt = jnp.swapaxes(k, 1, 2)
        vt = jnp.swapaxes(v, 1, 2)
        out, lse = flash_attention_with_lse(qt, kt, vt, causal, scale)
        return jnp.swapaxes(out, 1, 2), jnp.swapaxes(lse, 1, 2)
    # jnp fallback (CPU tests / odd chunk sizes)
    logits = jnp.einsum("bshd,bthd->bhst", q.astype(jnp.float32) * scale,
                        k.astype(jnp.float32))
    if causal:
        s, t = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((s, t), dtype=bool), t - s)
        logits = jnp.where(mask, logits, NEG_INF)
    m = jnp.max(logits, -1)
    p = jnp.exp(logits - m[..., None])
    l = jnp.sum(p, -1)
    o = jnp.einsum("bhst,bthd->bshd", p.astype(v.dtype), v)
    o = o / jnp.maximum(l, 1e-30).astype(o.dtype)[
        ..., None].swapaxes(1, 2)
    lse = (m + jnp.log(jnp.maximum(l, 1e-30))).swapaxes(1, 2)  # [B, S, H]
    return o, lse


def _ring_body(q, k, v, axis, axis_size, causal, scale):
    """Per-device ring loop over sequence-sharded q/k/v ([B, Sloc, H, D])."""
    my = jax.lax.axis_index(axis)
    B, Sloc, H, D = q.shape

    def full_fn(kv):
        return _chunk_attention(q, kv[0], kv[1], False, scale)

    def diag_fn(kv):
        return _chunk_attention(q, kv[0], kv[1], True, scale)

    def skip_fn(kv):
        return (jnp.zeros_like(q),
                jnp.full((B, Sloc, H), NEG_INF, jnp.float32))

    def step(carry, s):
        kc, vc, acc, m_run, l_run = carry
        src = (my - s) % axis_size  # global chunk index of the visiting KV
        if causal:
            case = jnp.where(src == my, 1, jnp.where(src < my, 0, 2))
            o_s, lse_s = jax.lax.switch(case, [full_fn, diag_fn, skip_fn],
                                        (kc, vc))
        else:
            o_s, lse_s = full_fn((kc, vc))
        m_new = jnp.maximum(m_run, lse_s)
        keep = jnp.exp(m_run - m_new)
        w = jnp.exp(lse_s - m_new)
        acc = acc * keep[..., None] + o_s.astype(jnp.float32) * w[..., None]
        l_new = l_run * keep + w
        # rotate kv to the next device (collective OUTSIDE the switch)
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        kc = jax.lax.ppermute(kc, axis, perm)
        vc = jax.lax.ppermute(vc, axis, perm)
        return (kc, vc, acc, m_new, l_new), None

    acc0 = jnp.zeros(q.shape, jnp.float32)
    m0 = jnp.full((B, Sloc, H), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, Sloc, H), jnp.float32)
    (_, _, acc, m_run, l_run), _ = jax.lax.scan(
        step, (k, v, acc0, m0, l0), jnp.arange(axis_size))
    return (acc / jnp.maximum(l_run, 1e-30)[..., None]).astype(q.dtype)


def ring_attention(q, k, v, causal=True, scale=None, axis="sep", mesh=None):
    """Context-parallel attention, [B, S, H, D] with S sharded over `axis`.

    Must run inside jit; the sequence axis S is the GLOBAL length and must
    divide by the axis size.  Other mesh axes stay GSPMD-auto.
    """
    from ..distributed.env import get_mesh
    mesh = mesh or get_mesh()
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if mesh is None or mesh.shape.get(axis, 1) == 1:
        from .flash_attention import flash_attention_fwd
        return flash_attention_fwd(q, k, v, causal=causal, scale=scale)
    n = mesh.shape[axis]
    check_equal("ring_attention",
                k_seq_len=(k.shape[1], q.shape[1]),
                v_seq_len=(v.shape[1], q.shape[1]))
    check_divides("ring_attention", seq_len=(q.shape[1], n))
    spec = P(None, axis, None, None)

    def body(ql, kl, vl):
        return _ring_body(ql, kl, vl, axis, n, causal, scale)

    return jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, axis_names={axis},
                         check_vma=False)(q, k, v)
