"""Flash attention — Pallas TPU kernel with custom VJP.

Reference analogue: phi/kernels/gpu/flash_attn_kernel.cu (wrapping the
flash-attn CUDA lib).  TPU-native design: online-softmax tiled attention where
q/k/v blocks stream HBM→VMEM and the two matmuls per tile hit the MXU;
backward recomputes attention probabilities per tile (flash-attention-2
style), avoiding O(S^2) residuals.

Layout: [B, S, H, D] (paddle convention) — internally [B, H, S, D].
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

_INTERPRET = [False]  # tests flip this on CPU


def _on_tpu():
    return jax.devices()[0].platform in ("tpu", "axon")


def reference_attention(q, k, v, causal=False, scale=None):
    """jnp reference ([B, S, H, D]); also the off-TPU fallback."""
    d = q.shape[-1]
    sc = scale if scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("bshd,bthd->bhst", q.astype(jnp.float32) * sc,
                        k.astype(jnp.float32))
    if causal:
        s, t = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((s, t), dtype=bool), t - s)
        logits = jnp.where(mask, logits, -1e30)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhst,bthd->bshd", p.astype(v.dtype), v)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                block_k, seq_len):
    from jax.experimental import pallas as pl

    q = q_ref[0, 0].astype(jnp.float32) * scale  # [block_q, d]
    block_q = q.shape[0]
    qi = pl.program_id(2)

    def body(start_k, carry):
        acc, m_prev, l_prev = carry
        k = k_ref[0, 0, pl.dslice(start_k * block_k, block_k)].astype(jnp.float32)
        v = v_ref[0, 0, pl.dslice(start_k * block_k, block_k)].astype(jnp.float32)
        s = q @ k.T  # [block_q, block_k] — MXU
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = start_k * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, -1e30)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[:, None] + p @ v
        return acc, m_new, l_new

    num_k = seq_len // block_k
    if causal:
        # only key blocks up to (and including) the diagonal participate
        num_k_run = jnp.minimum(num_k, pl.cdiv((qi + 1) * block_q, block_k))
    else:
        num_k_run = num_k
    acc0 = jnp.zeros((block_q, q_ref.shape[-1]), jnp.float32)
    m0 = jnp.full((block_q,), -1e30, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, num_k_run, body, (acc0, m0, l0))
    o_ref[0, 0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)
    # LSE is materialised as [b, h, s, 1]: a trailing singleton lane dim keeps
    # the Mosaic block shape (block_q, 1) legal (last dim == array dim; the
    # sublane dim block_q is 8-divisible), unlike a raw [b, h, s] layout.
    lse_ref[0, 0] = (m + jnp.log(jnp.maximum(l, 1e-30)))[:, None]


def _flash_fwd(q, k, v, causal, scale, block_q=128, block_k=128):
    from jax.experimental import pallas as pl

    b, h, s, d = q.shape
    grid = (b, h, s // block_q)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_k=block_k, seq_len=s)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, s, 1), jnp.float32),
        ],
        interpret=_INTERPRET[0],
    )(q, k, v)
    return out, lse


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
                   scale, causal, block_k, seq_len):
    from jax.experimental import pallas as pl

    q = q_ref[0, 0].astype(jnp.float32) * scale
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0, :, 0]
    delta = delta_ref[0, 0, :, 0]
    block_q = q.shape[0]
    qi = pl.program_id(2)

    def body(start_k, dq):
        k = k_ref[0, 0, pl.dslice(start_k * block_k, block_k)].astype(jnp.float32)
        v = v_ref[0, 0, pl.dslice(start_k * block_k, block_k)].astype(jnp.float32)
        s = q @ k.T
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = start_k * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, -1e30)
        p = jnp.exp(s - lse[:, None])
        dp = do @ v.T
        ds = p * (dp - delta[:, None])
        return dq + ds @ k

    num_k = seq_len // block_k
    if causal:
        num_k_run = jnp.minimum(num_k, pl.cdiv((qi + 1) * block_q, block_k))
    else:
        num_k_run = num_k
    dq = jax.lax.fori_loop(0, num_k_run, body,
                           jnp.zeros((block_q, q.shape[-1]), jnp.float32))
    dq_ref[0, 0] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref,
                    dv_ref, *, scale, causal, block_q, seq_len):
    from jax.experimental import pallas as pl

    k = k_ref[0, 0].astype(jnp.float32)
    v = v_ref[0, 0].astype(jnp.float32)
    block_k = k.shape[0]
    ki = pl.program_id(2)

    def body(start_q, carry):
        dk, dv = carry
        q = q_ref[0, 0, pl.dslice(start_q * block_q, block_q)].astype(
            jnp.float32) * scale
        do = do_ref[0, 0, pl.dslice(start_q * block_q, block_q)].astype(
            jnp.float32)
        lse = lse_ref[0, 0, pl.dslice(start_q * block_q, block_q), 0]
        delta = delta_ref[0, 0, pl.dslice(start_q * block_q, block_q), 0]
        s = q @ k.T  # [block_q, block_k]
        if causal:
            q_pos = start_q * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, -1e30)
        p = jnp.exp(s - lse[:, None])
        dv = dv + p.T @ do
        dp = do @ v.T
        ds = p * (dp - delta[:, None])
        # q here is already q*scale, so ds.T @ q == sum_i ds_ij * scale * q_i
        dk = dk + ds.T @ q
        return dk, dv

    num_q = seq_len // block_q
    if causal:
        start = (ki * block_k) // block_q
    else:
        start = 0
    dk0 = jnp.zeros((block_k, k.shape[-1]), jnp.float32)
    dv0 = jnp.zeros((block_k, v.shape[-1]), jnp.float32)
    dk, dv = jax.lax.fori_loop(start if causal else 0, num_q, body, (dk0, dv0))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _flash_bwd(q, k, v, out, lse, do, causal, scale, block_q=128, block_k=128):
    from jax.experimental import pallas as pl

    b, h, s, d = q.shape
    delta = jnp.sum(out.astype(jnp.float32) * do.astype(jnp.float32), axis=-1,
                    keepdims=True)  # [b, h, s, 1] — lane-aligned like lse

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_k=block_k, seq_len=s),
        grid=(b, h, s // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda bi, hi, qi: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
        interpret=_INTERPRET[0],
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, seq_len=s),
        grid=(b, h, s // block_k),
        in_specs=[
            pl.BlockSpec((1, 1, s, d), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, s, 1), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, s, 1), lambda bi, hi, ki: (bi, hi, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
        ],
        interpret=_INTERPRET[0],
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_attention_bhsd(q, k, v, causal, scale):
    out, _ = _flash_fwd(q, k, v, causal, scale)
    return out


def _flash_vjp_fwd(q, k, v, causal, scale):
    out, lse = _flash_fwd(q, k, v, causal, scale)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(causal, scale, res, do):
    q, k, v, out, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, out, lse, do, causal, scale)
    return dq, dk, dv


_flash_attention_bhsd.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention_fwd(q, k, v, causal=False, scale=None):
    """Public entry, [B, S, H, D] layout; differentiable (custom VJP)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if not (_on_tpu() or _INTERPRET[0]):
        return reference_attention(q, k, v, causal, scale)
    s = q.shape[1]
    if s % 128 != 0:
        return reference_attention(q, k, v, causal, scale)
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out = _flash_attention_bhsd(qt, kt, vt, causal, scale)
    return jnp.swapaxes(out, 1, 2)
