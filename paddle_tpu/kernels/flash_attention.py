"""Flash attention — Pallas TPU kernel with custom VJP.

Reference analogue: phi/kernels/gpu/flash_attn_kernel.cu (wrapping the
flash-attn CUDA lib).  TPU-native design: online-softmax tiled attention where
q/k/v blocks stream HBM→VMEM and the two matmuls per tile hit the MXU;
backward recomputes attention probabilities per tile (flash-attention-2
style), avoiding O(S^2) residuals.

Perf notes (v5e measurements): Mosaic grid-step overhead is ~2.4us/program,
so at short sequence lengths a naive (b, h, s/128) grid is overhead-bound —
attention at GPT-125M shapes was ~65% of forward wall-clock for ~6% of the
FLOPs.  The kernels therefore process BH heads per grid step (python-unrolled
head loop) with adaptive q/k block sizes, cutting the program count ~16x.

Layout: [B, S, H, D] (paddle convention) — internally [B, H, S, D].
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from ._shapes import NEG_INF, check_divides

_INTERPRET = [False]  # tests flip this on CPU


def _on_tpu():
    return jax.devices()[0].platform in ("tpu", "axon")


def reference_attention(q, k, v, causal=False, scale=None):
    """jnp reference ([B, S, H, D]); also the off-TPU fallback."""
    d = q.shape[-1]
    sc = scale if scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("bshd,bthd->bhst", q.astype(jnp.float32) * sc,
                        k.astype(jnp.float32))
    if causal:
        s, t = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((s, t), dtype=bool), t - s)
        logits = jnp.where(mask, logits, NEG_INF)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhst,bthd->bshd", p.astype(v.dtype), v)


def _round_to_divisor(block, s):
    """Largest multiple of 128 that is <= block and divides s (s % 128 == 0,
    so 128 always qualifies) — blocks that don't divide s would silently skip
    key blocks / leave query rows unwritten."""
    block = max(128, min(block, s))
    block -= block % 128
    while s % block:
        block -= 128
    return block


def _env_block(name, default):
    """Read a block-size override env var; fail loudly on junk values."""
    import os
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        val = int(raw)
    except ValueError:
        raise ValueError(
            f"{name}={raw!r} is not an integer; set it to a multiple of 128"
            " (e.g. 512) or unset it") from None
    if val < 128 or val % 128:
        raise ValueError(
            f"{name}={val} must be a multiple of 128 and >= 128 (TPU lane"
            " alignment)")
    return val


def _pick_blocks(h, s, d, itemsize):
    """(bh, block_q, block_k): heads per program + q/k tile sizes.

    Keeps resident VMEM for bh heads under budget while minimising the
    program count.  Worst case is the dkv kernel, which holds TWO full-seq
    arrays (q, do) plus k/v tiles per head group; `itemsize` is the input
    dtype width (fp32 attention is supported and doubles the footprint).
    """
    # 512/512 measured best on v5e for the GPT legs (r5 sweep,
    # scripts/PERF_NOTES.md): 760M batch8 0.474 vs 0.465 at 1024/512;
    # 1024/256 and 512/256 are 3-5% worse — don't shrink block_k
    block_q = _round_to_divisor(_env_block("PTPU_FA_BQ", 512), s)
    block_k = _round_to_divisor(_env_block("PTPU_FA_BK", 512), s)
    bh = 1
    for cand in (8, 4, 2):
        if h % cand == 0 and cand * (2 * s * d * itemsize) <= 6 * 1024 * 1024:
            bh = cand
            break
    return bh, block_q, block_k



def _dot_f32(a, b, ta=False, tb=False):
    """MXU matmul with fp32 accumulate.  When either operand is 16-bit the
    other is cast to bf16 too: bf16 x bf16 -> fp32 runs at full MXU rate
    (fp32 x fp32 runs at ~1/8).  Pure-fp32 inputs keep fp32 operands so
    fp32 attention stays fp32-accurate."""
    if a.dtype.itemsize <= 2 or b.dtype.itemsize <= 2:
        a = a.astype(jnp.bfloat16)
        b = b.astype(jnp.bfloat16)
    ca = (1 if not ta else 0,)
    cb = (0 if not tb else 1,)
    return jax.lax.dot_general(a, b, ((ca, cb), ((), ())),
                               preferred_element_type=jnp.float32)

# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                block_k, seq_len, bh):
    from jax.experimental import pallas as pl

    block_q = q_ref.shape[2]
    d = q_ref.shape[-1]
    qi = pl.program_id(2)
    num_k = seq_len // block_k
    if causal:
        num_k_run = jnp.minimum(num_k, pl.cdiv((qi + 1) * block_q, block_k))
    else:
        num_k_run = num_k

    for hh in range(bh):
        q = q_ref[0, hh]  # [block_q, d] bf16

        def body(start_k, carry):
            acc, m_prev, l_prev = carry
            k = k_ref[0, hh, pl.dslice(start_k * block_k, block_k)]
            v = v_ref[0, hh, pl.dslice(start_k * block_k, block_k)]
            s = _dot_f32(q, k, tb=True) * scale  # [block_q, block_k] — MXU
            if causal:
                q_pos = qi * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0)
                k_pos = start_k * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
                s = jnp.where(q_pos >= k_pos, s, NEG_INF)
            m_cur = jnp.max(s, axis=-1)
            m_new = jnp.maximum(m_prev, m_cur)
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new[:, None])
            l_new = l_prev * alpha + jnp.sum(p, axis=-1)
            acc = acc * alpha[:, None] + _dot_f32(p, v)
            return acc, m_new, l_new

        acc0 = jnp.zeros((block_q, d), jnp.float32)
        m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
        l0 = jnp.zeros((block_q,), jnp.float32)
        acc, m, l = jax.lax.fori_loop(0, num_k_run, body, (acc0, m0, l0))
        o_ref[0, hh] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(
            o_ref.dtype)
        # LSE materialised as [b, h, s, 1]: trailing singleton lane dim keeps
        # the Mosaic block shape (block_q, 1) legal.
        lse_ref[0, hh] = (m + jnp.log(jnp.maximum(l, 1e-30)))[:, None]


def _flash_fwd(q, k, v, causal, scale):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _params = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

    b, h, s, d = q.shape
    bh, block_q, block_k = _pick_blocks(h, s, d, q.dtype.itemsize)
    check_divides("flash_attention_fwd", heads=(h, bh),
                  seq_len_q=(s, block_q), seq_len_k=(s, block_k))
    grid = (b, h // bh, s // block_q)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_k=block_k, seq_len=s, bh=bh)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bh, block_q, d),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, bh, s, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, bh, s, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bh, block_q, d),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, bh, block_q, 1),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, s, 1), jnp.float32),
        ],
        compiler_params=_params(
            dimension_semantics=("parallel", "parallel", "parallel"),
            vmem_limit_bytes=64 * 1024 * 1024),
        interpret=_INTERPRET[0],
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
                   scale, causal, block_k, seq_len, bh):
    from jax.experimental import pallas as pl

    block_q = q_ref.shape[2]
    d = q_ref.shape[-1]
    qi = pl.program_id(2)
    num_k = seq_len // block_k
    if causal:
        num_k_run = jnp.minimum(num_k, pl.cdiv((qi + 1) * block_q, block_k))
    else:
        num_k_run = num_k

    for hh in range(bh):
        q = q_ref[0, hh]
        do = do_ref[0, hh]
        lse = lse_ref[0, hh, :, 0]
        delta = delta_ref[0, hh, :, 0]

        def body(start_k, dq):
            k = k_ref[0, hh, pl.dslice(start_k * block_k, block_k)]
            v = v_ref[0, hh, pl.dslice(start_k * block_k, block_k)]
            s = _dot_f32(q, k, tb=True) * scale
            if causal:
                q_pos = qi * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0)
                k_pos = start_k * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
                s = jnp.where(q_pos >= k_pos, s, NEG_INF)
            p = jnp.exp(s - lse[:, None])
            dp = _dot_f32(do, v, tb=True)
            ds = p * (dp - delta[:, None])
            return dq + _dot_f32(ds, k)

        dq = jax.lax.fori_loop(0, num_k_run, body,
                               jnp.zeros((block_q, d), jnp.float32))
        dq_ref[0, hh] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref,
                    dv_ref, *, scale, causal, block_q, seq_len, bh):
    from jax.experimental import pallas as pl

    block_k = k_ref.shape[2]
    d = k_ref.shape[-1]
    ki = pl.program_id(2)
    num_q = seq_len // block_q
    start = (ki * block_k) // block_q if causal else 0

    for hh in range(bh):
        k = k_ref[0, hh]
        v = v_ref[0, hh]

        def body(start_q, carry):
            dk, dv = carry
            q = q_ref[0, hh, pl.dslice(start_q * block_q, block_q)]
            do = do_ref[0, hh, pl.dslice(start_q * block_q, block_q)]
            lse = lse_ref[0, hh, pl.dslice(start_q * block_q, block_q), 0]
            delta = delta_ref[0, hh,
                              pl.dslice(start_q * block_q, block_q), 0]
            s = _dot_f32(q, k, tb=True) * scale  # [block_q, block_k]
            if causal:
                q_pos = start_q * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0)
                k_pos = ki * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
                s = jnp.where(q_pos >= k_pos, s, NEG_INF)
            p = jnp.exp(s - lse[:, None])
            dv = dv + _dot_f32(p, do, ta=True)
            dp = _dot_f32(do, v, tb=True)
            ds = p * (dp - delta[:, None])
            dk = dk + _dot_f32(ds, q, ta=True) * scale
            return dk, dv

        dk0 = jnp.zeros((block_k, d), jnp.float32)
        dv0 = jnp.zeros((block_k, d), jnp.float32)
        dk, dv = jax.lax.fori_loop(start, num_q, body, (dk0, dv0))
        dk_ref[0, hh] = dk.astype(dk_ref.dtype)
        dv_ref[0, hh] = dv.astype(dv_ref.dtype)


def _flash_bwd(q, k, v, out, lse, do, causal, scale, dlse=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _params = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

    b, h, s, d = q.shape
    bh, block_q, block_k = _pick_blocks(h, s, d, q.dtype.itemsize)
    check_divides("flash_attention_bwd", heads=(h, bh),
                  seq_len_q=(s, block_q), seq_len_k=(s, block_k))
    delta = jnp.sum(out.astype(jnp.float32) * do.astype(jnp.float32), axis=-1,
                    keepdims=True)  # [b, h, s, 1] — lane-aligned like lse
    if dlse is not None:
        # A cotangent g on lse enters as ds_ij += g_i * p_ij (because
        # d lse_i / d s_ij = p_ij); the kernels compute ds = p*(dp - delta),
        # so folding it in as delta' = delta - g gives p*(dp - delta + g).
        delta = delta - dlse.astype(jnp.float32)[..., None]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_k=block_k, seq_len=s, bh=bh),
        grid=(b, h // bh, s // block_q),
        in_specs=[
            pl.BlockSpec((1, bh, block_q, d),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, bh, s, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, bh, s, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, bh, block_q, d),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, bh, block_q, 1),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, bh, block_q, 1),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, bh, block_q, d),
                               lambda bi, hi, qi: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
        compiler_params=_params(
            dimension_semantics=("parallel", "parallel", "parallel"),
            vmem_limit_bytes=64 * 1024 * 1024),
        interpret=_INTERPRET[0],
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, seq_len=s, bh=bh),
        grid=(b, h // bh, s // block_k),
        in_specs=[
            pl.BlockSpec((1, bh, s, d), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((1, bh, block_k, d),
                         lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, bh, block_k, d),
                         lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, bh, s, d), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((1, bh, s, 1), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((1, bh, s, 1), lambda bi, hi, ki: (bi, hi, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bh, block_k, d),
                         lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, bh, block_k, d),
                         lambda bi, hi, ki: (bi, hi, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
        ],
        compiler_params=_params(
            dimension_semantics=("parallel", "parallel", "parallel"),
            vmem_limit_bytes=64 * 1024 * 1024),
        interpret=_INTERPRET[0],
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_attention_bhsd(q, k, v, causal, scale):
    out, _ = _flash_fwd(q, k, v, causal, scale)
    return out


def _flash_vjp_fwd(q, k, v, causal, scale):
    out, lse = _flash_fwd(q, k, v, causal, scale)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(causal, scale, res, do):
    q, k, v, out, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, out, lse, do, causal, scale)
    return dq, dk, dv


_flash_attention_bhsd.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention_with_lse(q, k, v, causal, scale):
    """(out, lse) flash attention, [B, H, S, D] layout, differentiable.

    lse is [B, H, S] fp32.  Used by ring attention (kernels/ring_attention.py)
    whose online-softmax merge needs the per-chunk LSE *and* gradients through
    both outputs — the lse cotangent folds into the flash backward via the
    delta term (see _flash_bwd)."""
    out, lse = _flash_fwd(q, k, v, causal, scale)
    return out, lse[..., 0]


def _flash_lse_vjp_fwd(q, k, v, causal, scale):
    out, lse = _flash_fwd(q, k, v, causal, scale)
    return (out, lse[..., 0]), (q, k, v, out, lse)


def _flash_lse_vjp_bwd(causal, scale, res, cot):
    do, dlse = cot
    q, k, v, out, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, out, lse, do, causal, scale, dlse=dlse)
    return dq, dk, dv


flash_attention_with_lse.defvjp(_flash_lse_vjp_fwd, _flash_lse_vjp_bwd)


_warned_fallback = [False]


def flash_attention_fwd(q, k, v, causal=False, scale=None):
    """Public entry, [B, S, H, D] layout; differentiable (custom VJP)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if not (_on_tpu() or _INTERPRET[0]):
        return reference_attention(q, k, v, causal, scale)
    s = q.shape[1]
    if s % 128 != 0:
        if _on_tpu() and not _warned_fallback[0]:
            _warned_fallback[0] = True
            import warnings
            warnings.warn(
                f"flash_attention: seq_len={s} is not a multiple of 128;"
                " falling back to O(S^2) reference attention on TPU. Pad the"
                " sequence to a 128 multiple for the Pallas kernel.",
                RuntimeWarning, stacklevel=2)
        return reference_attention(q, k, v, causal, scale)
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out = _flash_attention_bhsd(qt, kt, vt, causal, scale)
    return jnp.swapaxes(out, 1, 2)
