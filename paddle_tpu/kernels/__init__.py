"""Pallas TPU kernels (reference analogue: phi/kernels/fusion/ hand-written
CUDA kernels + the Kernel Primitive abstraction phi/kernels/primitive/).

Each kernel ships a Pallas implementation for TPU plus a jnp reference used
off-TPU and in interpret-mode tests."""

from . import _shapes, flash_attention, paged_attention, rms_norm, rope  # noqa: F401
from ._shapes import NEG_INF, neg_inf  # noqa: F401
