"""Rotary position embedding (reference CUDA:
phi/kernels/fusion/gpu/fused_rope_kernel.cu).  Pure jnp — XLA fuses the
elementwise chain; a Pallas kernel buys nothing here (bandwidth-bound,
already fused)."""

from __future__ import annotations

import jax.numpy as jnp


def rope_tables(seq_len, head_dim, base=10000.0, dtype=jnp.float32,
                offset=0):
    inv = 1.0 / (base ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32)
                          / head_dim))
    t = offset + jnp.arange(seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv)  # [S, D/2]
    return jnp.sin(freqs).astype(dtype), jnp.cos(freqs).astype(dtype)


def apply_rope(x, sin=None, cos=None, neox=True, base=10000.0, offset=0):
    """x: [B, S, H, D].  `offset` shifts the absolute positions (KV-cached
    decode: the query sits at position offset, not 0)."""
    b, s, h, d = x.shape
    if sin is None or cos is None:
        sin, cos = rope_tables(s, d, base, jnp.float32, offset=offset)
    else:
        # paddle passes [1, S, 1, D] tables with duplicated halves
        sin = sin.reshape(s, -1)[:, : d // 2].astype(jnp.float32)
        cos = cos.reshape(s, -1)[:, : d // 2].astype(jnp.float32)
    sin = sin[None, :, None, :]
    cos = cos[None, :, None, :]
    xf = x.astype(jnp.float32)
    if neox:
        x1, x2 = xf[..., : d // 2], xf[..., d // 2:]
    else:
        x1, x2 = xf[..., 0::2], xf[..., 1::2]
    r1 = x1 * cos - x2 * sin
    r2 = x2 * cos + x1 * sin
    if neox:
        out = jnp.concatenate([r1, r2], axis=-1)
    else:
        out = jnp.stack([r1, r2], axis=-1).reshape(xf.shape)
    return out.astype(x.dtype)
