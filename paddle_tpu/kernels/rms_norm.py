"""Fused RMSNorm Pallas kernel (reference CUDA:
phi/kernels/fusion/gpu/fused_rms_norm kernels / incubate fused_rms_norm).

Forward computes mean-square + normalize in one VMEM pass; backward is left
to XLA (the jnp reference) — the op is bandwidth-bound and XLA's fusion of
the backward chain is already optimal, so the kernel exists to guarantee a
single-pass forward on the inference/serving path."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_INTERPRET = [False]


def _on_tpu():
    return jax.devices()[0].platform in ("tpu", "axon")


def rms_norm_reference(x, w, eps=1e-6):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x.astype(jnp.float32) * jax.lax.rsqrt(var + eps)).astype(
        x.dtype) * w


def _rms_kernel(x_ref, w_ref, o_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    o_ref[...] = (x * jax.lax.rsqrt(var + eps)).astype(o_ref.dtype) * w_ref[...]


def rms_norm(x, w, eps=1e-6, block_rows=256):
    """x: [..., H]; w: [H]."""
    if not (_on_tpu() or _INTERPRET[0]):
        return rms_norm_reference(x, w, eps)
    from jax.experimental import pallas as pl

    orig_shape = x.shape
    h = x.shape[-1]
    rows = 1
    for s in x.shape[:-1]:
        rows *= s
    x2 = x.reshape(rows, h)
    if rows % block_rows != 0:
        block_rows = rows if rows < block_rows else 1
        while rows % block_rows != 0:
            block_rows -= 1
    out = pl.pallas_call(
        functools.partial(_rms_kernel, eps=eps),
        grid=(rows // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, h), lambda i: (i, 0)),
            pl.BlockSpec((h,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block_rows, h), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, h), x.dtype),
        interpret=_INTERPRET[0],
    )(x2, w)
    return out.reshape(orig_shape)
