"""Framework utilities: save/load, dtype defaults, seed.

Reference: python/paddle/framework/ (io.py:743,985 paddle.save/load)."""

from __future__ import annotations

import os
import pickle

import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtypes
from ..core.tensor import Parameter, Tensor

_DEFAULT_DTYPE = [np.dtype(np.float32)]


def set_default_dtype(d):
    _DEFAULT_DTYPE[0] = np.dtype(dtypes.convert_dtype(d))


def get_default_dtype():
    return _DEFAULT_DTYPE[0].name


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return ("__tensor__", np.asarray(obj._data), obj.name)
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_to_saveable(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def _from_saveable(obj, return_numpy=False):
    if isinstance(obj, tuple) and len(obj) == 3 and obj[0] == "__tensor__":
        if return_numpy:
            return obj[1]
        t = Tensor._wrap(jnp.asarray(obj[1]))
        t.name = obj[2]
        return t
    if isinstance(obj, dict):
        return {k: _from_saveable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_from_saveable(v, return_numpy) for v in obj]
    if isinstance(obj, tuple):
        return tuple(_from_saveable(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    """paddle.save (reference: python/paddle/framework/io.py:743)."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_saveable(obj), f, protocol=protocol)


def load(path, **configs):
    """paddle.load (reference: python/paddle/framework/io.py:985)."""
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _from_saveable(obj, configs.get("return_numpy", False))


def seed(value):
    from ..tensor import random as _r
    return _r.seed(value)


def get_flags(names):
    from ..core.flags import get_flags as g
    return g(names)


def set_flags(flags):
    from ..core.flags import set_flags as s
    return s(flags)


def in_dynamic_mode():
    return True


def in_pir_mode():
    return False


def in_dynamic_or_pir_mode():
    return True


def use_pir_api():
    return False
