"""Framework utilities: save/load, dtype defaults, seed.

Reference: python/paddle/framework/ (io.py:743,985 paddle.save/load)."""

from __future__ import annotations

import os
import pickle

import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtypes
from ..core.tensor import Parameter, Tensor

_DEFAULT_DTYPE = [np.dtype(np.float32)]


def set_default_dtype(d):
    _DEFAULT_DTYPE[0] = np.dtype(dtypes.convert_dtype(d))


def get_default_dtype():
    return _DEFAULT_DTYPE[0].name


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return ("__tensor__", np.asarray(obj._data), obj.name)
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_to_saveable(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def _from_saveable(obj, return_numpy=False):
    if isinstance(obj, tuple) and len(obj) == 3 and obj[0] == "__tensor__":
        if return_numpy:
            return obj[1]
        t = Tensor._wrap(jnp.asarray(obj[1]))
        t.name = obj[2]
        return t
    if isinstance(obj, dict):
        return {k: _from_saveable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_from_saveable(v, return_numpy) for v in obj]
    if isinstance(obj, tuple):
        return tuple(_from_saveable(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    """paddle.save (reference: python/paddle/framework/io.py:743)."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_saveable(obj), f, protocol=protocol)


def load(path, **configs):
    """paddle.load (reference: python/paddle/framework/io.py:985)."""
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _from_saveable(obj, configs.get("return_numpy", False))


def seed(value):
    from ..tensor import random as _r
    return _r.seed(value)


def get_flags(names):
    from ..core.flags import get_flags as g
    return g(names)


def set_flags(flags):
    from ..core.flags import set_flags as s
    return s(flags)


def in_dynamic_mode():
    return True


def in_pir_mode():
    return False


def in_dynamic_or_pir_mode():
    return True


def use_pir_api():
    return False


# -- namespace-parity utilities (reference: python/paddle/framework/) -------
class finfo:
    """paddle.finfo (reference: python/paddle/framework/dtype.py finfo) —
    float-dtype limits via jnp/ml_dtypes (covers bfloat16/fp8 natively)."""

    def __init__(self, dtype):
        import jax.numpy as jnp

        from ..core.dtype import convert_dtype
        fi = jnp.finfo(convert_dtype(dtype))
        self.dtype = str(fi.dtype)
        self.bits = fi.bits
        self.eps = float(fi.eps)
        self.min = float(fi.min)
        self.max = float(fi.max)
        self.tiny = float(fi.tiny)
        self.smallest_normal = float(fi.tiny)
        self.resolution = float(fi.resolution)


class iinfo:
    """paddle.iinfo — integer-dtype limits."""

    def __init__(self, dtype):
        import jax.numpy as jnp

        from ..core.dtype import convert_dtype
        ii = jnp.iinfo(convert_dtype(dtype))
        self.dtype = str(ii.dtype)
        self.bits = ii.bits
        self.min = int(ii.min)
        self.max = int(ii.max)


# Tensor-repr formatting options, scoped to Tensor.__repr__ only (the
# reference likewise formats only Tensor __str__, never global numpy state)
PRINT_OPTIONS: dict = {}


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Configure how Tensors print (reference:
    python/paddle/tensor/to_string.py set_printoptions).  Affects only
    Tensor reprs — the user's own numpy print options are untouched."""
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["max_line_width"] = linewidth  # np.array2string's name for it
    if sci_mode is not None:
        kw["suppress_small"] = not sci_mode
    PRINT_OPTIONS.clear()
    PRINT_OPTIONS.update(kw)


class LazyGuard:
    """reference: python/paddle/nn/initializer/lazy_init.py — defers param
    materialisation.  Params here are cheap jnp arrays initialised on
    construction; the guard is a no-op context kept for API parity."""

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


def disable_signal_handler():
    """reference: installs/removes C++ signal handlers; no native signal
    handlers exist in this runtime — no-op."""


def get_cuda_rng_state():
    """Device RNG state (the single JAX PRNG key doubles as the 'cuda'
    generator state)."""
    from ..tensor.random import get_rng_state
    return get_rng_state()


def set_cuda_rng_state(state):
    from ..tensor.random import set_rng_state
    set_rng_state(state)


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """reference: python/paddle/tensor/creation.py create_parameter."""
    from ..nn.functional.init_utils import param_attr_init
    p = param_attr_init(shape, dtype, attr, is_bias, default_initializer)
    if name:
        p.name = name
    return p


def batch(reader, batch_size, drop_last=False):
    """reference: python/paddle/reader (deprecated) — batch a sample
    generator."""
    def gen():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return gen
