"""Initializers (reference: python/paddle/nn/initializer/)."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Parameter, Tensor
from ...tensor.random import _next_key


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        return (self.mean + self.std * jax.random.normal(
            _next_key(), shape)).astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        r = jax.random.truncated_normal(
            _next_key(), (self.a - self.mean) / self.std,
            (self.b - self.mean) / self.std, shape)
        return (self.mean + self.std * r).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        return jax.random.uniform(_next_key(), shape, jnp.float32, self.low,
                                  self.high).astype(dtype)


def _fans(shape):
    if len(shape) < 2:
        return shape[0] if shape else 1, shape[0] if shape else 1
    # paddle convention: fan_in = shape[0]*receptive, fan_out = shape[1]*receptive
    receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
    return shape[0] * receptive, shape[1] * receptive


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return (std * jax.random.normal(_next_key(), shape)).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(_next_key(), shape, jnp.float32, -limit,
                                  limit).astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2)) \
            if self.nonlinearity == "leaky_relu" else math.sqrt(2.0)
        std = gain / math.sqrt(fi)
        return (std * jax.random.normal(_next_key(), shape)).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2)) \
            if self.nonlinearity == "leaky_relu" else math.sqrt(2.0)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(_next_key(), shape, jnp.float32, -limit,
                                  limit).astype(dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        v = self.value._data if isinstance(self.value, Tensor) else \
            jnp.asarray(np.asarray(self.value))
        return v.reshape(shape).astype(dtype)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        return (self.gain * jax.random.orthogonal(
            _next_key(), shape[0], shape=()
        )).astype(dtype) if len(shape) == 1 else (
            self.gain * jax.nn.initializers.orthogonal()(
                _next_key(), shape, jnp.float32)).astype(dtype)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype):
        out = np.zeros(shape, np.float32)
        oc, ic = shape[0], shape[1]
        mid = tuple(s // 2 for s in shape[2:])
        for i in range(min(oc, ic * self.groups)):
            out[(i, i % ic) + mid] = 1.0
        return jnp.asarray(out, dtype)


def calculate_gain(nonlinearity, param=None):
    if nonlinearity == "tanh":
        return 5.0 / 3
    if nonlinearity == "relu":
        return math.sqrt(2.0)
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a ** 2))
    if nonlinearity == "selu":
        return 3.0 / 4
    return 1.0


def set_global_initializer(weight_init, bias_init=None):
    from ..functional import init_utils
    init_utils._GLOBAL_WEIGHT_INIT[0] = weight_init
    init_utils._GLOBAL_BIAS_INIT[0] = bias_init
