"""Gradient clipping (reference: python/paddle/nn/clip.py —
ClipGradByGlobalNorm et al., consumed by optimizers; hybrid-parallel variant
lives in distributed/fleet)."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, Tensor._wrap(jnp.clip(g._data, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g._data.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, Tensor._wrap((g._data * scale).astype(g._data.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    """reference: nn/clip.py ClipGradByGlobalNorm; distributed variant
    allreduces the squared norms across mesh axes
    (fleet hybrid_parallel_optimizer.py:41).

    SelectedRows gradients participate like the reference: duplicate rows are
    merged first (MergeAdd), their squared values join the global norm, and
    the clip coefficient scales the sparse values in place — no densify."""

    # consumed by Optimizer.step: sparse grads may be routed through us
    _handles_selected_rows = True

    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = clip_norm

    def __call__(self, params_grads):
        from ..core.selected_rows import SelectedRows
        merged = {}
        sq = []
        for p, g in params_grads:
            if g is None or getattr(p, "need_clip", True) is False:
                continue
            if isinstance(g, SelectedRows):
                import jax
                if isinstance(g.rows, jax.core.Tracer):
                    # traced rows can't host-unique; the dense twin gives the
                    # same merged norm (duplicates accumulate) and stays
                    # traceable inside compiled train steps
                    merged[id(g)] = g
                    sq.append(jnp.sum(jnp.square(
                        g.to_dense().astype(jnp.float32))))
                else:
                    m = g.merge_rows()
                    merged[id(g)] = m
                    sq.append(jnp.sum(jnp.square(
                        m.values.astype(jnp.float32))))
            else:
                sq.append(jnp.sum(jnp.square(g._data.astype(jnp.float32))))
        if not sq:
            return params_grads
        global_norm = jnp.sqrt(sum(sq[1:], sq[0]))
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None or getattr(p, "need_clip", True) is False:
                out.append((p, g))
                continue
            if isinstance(g, SelectedRows):
                m = merged[id(g)]
                vals = (m.values.astype(jnp.float32) * scale).astype(
                    m.values.dtype)
                out.append((p, SelectedRows(m.rows, vals, m.height)))
            else:
                out.append((p, Tensor._wrap(
                    (g._data * scale).astype(g._data.dtype))))
        return out


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    params = [parameters] if isinstance(parameters, Tensor) else list(parameters)
    grads = [p.grad for p in params if p.grad is not None]
    if not grads:
        return Tensor._wrap(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g._data)) for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g._data.astype(jnp.float32)) ** norm_type)
             for g in grads])) ** (1.0 / norm_type)
    scale = jnp.minimum(max_norm / jnp.maximum(total, 1e-6), 1.0)
    for p in params:
        if p.grad is not None:
            p.grad._data = (p.grad._data * scale).astype(p.grad._data.dtype)
    return Tensor._wrap(total)


def clip_grad_value_(parameters, clip_value):
    params = [parameters] if isinstance(parameters, Tensor) else list(parameters)
    for p in params:
        if p.grad is not None:
            p.grad._data = jnp.clip(p.grad._data, -clip_value, clip_value)
