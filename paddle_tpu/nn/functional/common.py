"""Common functionals: linear, dropout, embedding, interpolate, one_hot...
(reference: python/paddle/nn/functional/common.py, input.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import apply_op, matmul_precision
from ...core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def linear(x, weight, bias=None, name=None):
    """y = x @ W (+ b).  Weight layout [in, out] as in the reference
    (python/paddle/nn/functional/common.py linear); maps to one MXU matmul."""
    if bias is None:
        return apply_op("linear",
                        lambda a, w: jnp.matmul(a, w,
                                                precision=matmul_precision()),
                        _t(x), weight)
    return apply_op(
        "linear",
        lambda a, w, b: jnp.matmul(a, w, precision=matmul_precision()) + b,
        _t(x), weight, bias)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    """Dropout with TP-deterministic keys (reference:
    python/paddle/nn/functional/common.py dropout; parallel-deterministic
    variant: fleet/layers/mpu/random.py:140)."""
    from ...tensor.random import _next_key
    if not training or p == 0:
        return _t(x)
    if p == 1:
        return apply_op("dropout", lambda v: jnp.zeros_like(v), _t(x))
    x = _t(x)
    # the key rides as an op ARGUMENT (not a closure) so static Programs
    # record it as a per-run rng leaf: Executor.run folds a fresh root key in
    # per replay instead of freezing the dispatch-time mask
    key = Tensor._wrap(_next_key(recording_ok=True))

    def fn(v, k):
        shape = list(v.shape)
        if axis is not None:
            axes = [axis] if isinstance(axis, int) else list(axis)
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(k, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, v / (1.0 - p), 0.0).astype(v.dtype)
        return jnp.where(keep, v, 0.0).astype(v.dtype)
    return apply_op("dropout", fn, x, key)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    from ...tensor.random import _next_key
    if not training or p == 0:
        return _t(x)
    x = _t(x)
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    a = (1.0 / ((1 - p) * (1 + p * alpha_p ** 2)) ** 0.5)
    b = -a * alpha_p * p
    key = Tensor._wrap(_next_key(recording_ok=True))

    def fn(v, k):
        keep = jax.random.bernoulli(k, 1.0 - p, v.shape)
        return (a * jnp.where(keep, v, alpha_p) + b).astype(v.dtype)
    return apply_op("alpha_dropout", fn, x, key)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Embedding lookup — a gather feeding the MXU-free VPU path
    (reference kernel: phi/kernels/gpu/embedding_kernel.cu)."""
    def fn(ids, w):
        out = jnp.take(w, ids, axis=0)
        if padding_idx is not None:
            mask = (ids == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out
    return apply_op("embedding", fn, _t(x), weight)


def one_hot(x, num_classes, name=None):
    return Tensor._wrap(jax.nn.one_hot(_t(x)._data, num_classes,
                                       dtype=jnp.float32))


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def fn(l, *pd):
        k = l.shape[-1]
        if pd:
            return (1 - epsilon) * l + epsilon * pd[0]
        return (1 - epsilon) * l + epsilon / k
    if prior_dist is not None:
        return apply_op("label_smooth", fn, _t(label), _t(prior_dist))
    return apply_op("label_smooth", fn, _t(label))


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    from ...tensor.manipulation import pad as _pad
    x = _t(x)
    if isinstance(pad, Tensor):
        pad = [int(v) for v in pad.numpy()]
    if len(pad) == 2 * x.ndim:
        return _pad(x, pad, mode, value)
    # nn.functional.pad semantics: pad spatial dims per data_format
    nd = x.ndim
    k = len(pad) // 2
    width = [(0, 0)] * nd
    if data_format.endswith("C"):  # NHWC/NDHWC/NLC
        spatial = list(range(1, nd - 1))
    else:  # NCHW/NCDHW/NCL
        spatial = list(range(2, nd))
    spatial = spatial[-k:][::-1]
    for i, dim in enumerate(spatial):
        width[dim] = (int(pad[2 * i]), int(pad[2 * i + 1]))
    flat = []
    for w in width:
        flat += [w[0], w[1]]
    return _pad(x, flat, mode, value)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    """Resize (reference: nn/functional/common.py interpolate → interp kernels).
    Uses jax.image.resize (XLA gather/convolution based)."""
    x = _t(x)
    nd = x.ndim
    channel_last = data_format.endswith("C")
    spatial_ndim = nd - 2
    in_spatial = (x.shape[1:-1] if channel_last else x.shape[2:])
    if size is not None:
        if isinstance(size, Tensor):
            size = [int(s) for s in size.numpy()]
        out_spatial = [int(s.item()) if isinstance(s, Tensor) else int(s)
                       for s in (size if isinstance(size, (list, tuple))
                                 else [size])]
    else:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) \
            else [scale_factor] * spatial_ndim
        out_spatial = [int(np.floor(s * f)) for s, f in zip(in_spatial, sf)]
    if channel_last:
        out_shape = (x.shape[0], *out_spatial, x.shape[-1])
    else:
        out_shape = (x.shape[0], x.shape[1], *out_spatial)
    method = {"nearest": "nearest", "bilinear": "bilinear", "linear": "linear",
              "trilinear": "trilinear", "bicubic": "cubic",
              "area": "linear"}[mode]
    if method == "trilinear":
        method = "trilinear" if spatial_ndim == 3 else "bilinear"

    def fn(v):
        return jax.image.resize(v, out_shape, method=method)
    return apply_op("interpolate", fn, x)


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


def bilinear(x1, x2, weight, bias=None, name=None):
    def fn(a, b, w, *bi):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b,
                         precision=matmul_precision())
        if bi:
            out = out + bi[0]
        return out
    if bias is not None:
        return apply_op("bilinear", fn, _t(x1), _t(x2), weight, bias)
    return apply_op("bilinear", fn, _t(x1), _t(x2), weight)


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def fn(a, b):
        num = jnp.sum(a * b, axis=axis)
        d1 = jnp.sqrt(jnp.sum(a * a, axis=axis))
        d2 = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return num / jnp.maximum(d1 * d2, eps)
    return apply_op("cosine_similarity", fn, _t(x1), _t(x2))


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def fn(v):
        n = jnp.sum(jnp.abs(v) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return v / jnp.maximum(n, epsilon)
    return apply_op("normalize", fn, _t(x))


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (reference kernel: phi/kernels/impl/unfold_kernel_impl.h)."""
    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    dh, dw = _pair(dilations)
    p = paddings
    if isinstance(p, int):
        pads = (p, p, p, p)
    elif len(p) == 2:
        pads = (p[0], p[0], p[1], p[1])
    else:
        pads = tuple(p)

    def fn(v):
        n, c, h, w = v.shape
        v = jnp.pad(v, ((0, 0), (0, 0), (pads[0], pads[1]), (pads[2], pads[3])))
        oh = (v.shape[2] - (dh * (kh - 1) + 1)) // sh + 1
        ow = (v.shape[3] - (dw * (kw - 1) + 1)) // sw + 1
        patches = jax.lax.conv_general_dilated_patches(
            v, (kh, kw), (sh, sw), "VALID", rhs_dilation=(dh, dw),
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        return patches.reshape(n, c * kh * kw, oh * ow)
    return apply_op("unfold", fn, _t(x))


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    oh, ow = _pair(output_sizes)
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    dh, dw = _pair(dilations)
    p = paddings
    if isinstance(p, int):
        pads = (p, p, p, p)
    elif len(p) == 2:
        pads = (p[0], p[0], p[1], p[1])
    else:
        pads = tuple(p)

    def fn(v):
        n, ckk, l = v.shape
        c = ckk // (kh * kw)
        out = jnp.zeros((n, c, oh + pads[0] + pads[1], ow + pads[2] + pads[3]),
                        v.dtype)
        nh = (out.shape[2] - (dh * (kh - 1) + 1)) // sh + 1
        nw = (out.shape[3] - (dw * (kw - 1) + 1)) // sw + 1
        v = v.reshape(n, c, kh, kw, nh, nw)
        for i in range(kh):
            for j in range(kw):
                out = out.at[:, :,
                             i * dh:i * dh + nh * sh:sh,
                             j * dw:j * dw + nw * sw:sw].add(v[:, :, i, j])
        return out[:, :, pads[0]:out.shape[2] - pads[1],
                   pads[2]:out.shape[3] - pads[3]]
    return apply_op("fold", fn, _t(x))


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def fn(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c // (r * r), r, r, h, w)
            v = jnp.transpose(v, (0, 1, 4, 2, 5, 3))
            return v.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = v.shape
        v = v.reshape(n, h, w, r, r, c // (r * r))
        v = jnp.transpose(v, (0, 1, 3, 2, 4, 5))
        return v.reshape(n, h * r, w * r, c // (r * r))
    return apply_op("pixel_shuffle", fn, _t(x))


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def fn(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c, h // r, r, w // r, r)
            v = jnp.transpose(v, (0, 1, 3, 5, 2, 4))
            return v.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = v.shape
        v = v.reshape(n, h // r, r, w // r, r, c)
        v = jnp.transpose(v, (0, 1, 3, 2, 4, 5))
        return v.reshape(n, h // r, w // r, c * r * r)
    return apply_op("pixel_unshuffle", fn, _t(x))


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def fn(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, groups, c // groups, h, w)
            return jnp.swapaxes(v, 1, 2).reshape(n, c, h, w)
        n, h, w, c = v.shape
        v = v.reshape(n, h, w, groups, c // groups)
        return jnp.swapaxes(v, 3, 4).reshape(n, h, w, c)
    return apply_op("channel_shuffle", fn, _t(x))


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format)
