"""Normalization functionals (reference: python/paddle/nn/functional/norm.py;
fused kernels: phi/kernels/fusion/gpu/fused_rms_norm* / layer_norm kernels).
On TPU these chains fuse in XLA; rms_norm additionally has a Pallas kernel in
paddle_tpu/kernels/rms_norm.py used on the hot path."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op
from ...core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05,
               name=None):
    ns = ((normalized_shape,) if isinstance(normalized_shape, int)
          else tuple(normalized_shape))
    axes = tuple(range(-len(ns), 0))

    def fn(v, *wb):
        mean = jnp.mean(v.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(v.astype(jnp.float32), axis=axes, keepdims=True)
        out = ((v.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + epsilon))
        out = out.astype(v.dtype)
        i = 0
        if weight is not None:
            out = out * wb[i]
            i += 1
        if bias is not None:
            out = out + wb[i]
        return out
    args = [a for a in (weight, bias) if a is not None]
    return apply_op("layer_norm", fn, _t(x), *args)


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm (reference: python/paddle/incubate/nn/functional/fused_rms_norm.py)."""
    def fn(v, *w):
        var = jnp.mean(jnp.square(v.astype(jnp.float32)), axis=-1,
                       keepdims=True)
        out = (v.astype(jnp.float32) * jax.lax.rsqrt(var + epsilon)).astype(
            v.dtype)
        if w:
            out = out * w[0]
        return out
    if weight is not None:
        return apply_op("rms_norm", fn, _t(x), weight)
    return apply_op("rms_norm", fn, _t(x))


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-05,
               data_format="NCHW", use_global_stats=None, name=None):
    """BatchNorm with running-stat update (reference:
    python/paddle/nn/functional/norm.py batch_norm → batch_norm kernel)."""
    x = _t(x)
    ch_axis = 1 if data_format.startswith("NC") and x.ndim > 1 else x.ndim - 1
    if x.ndim == 1:
        ch_axis = 0
    reduce_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    bshape = [1] * x.ndim
    bshape[ch_axis] = x.shape[ch_axis] if x.ndim > 0 else 1

    use_stats = (not training) if use_global_stats is None else use_global_stats

    if not use_stats:
        # compute batch stats eagerly (also used to update running stats)
        xf = x._data.astype(jnp.float32)
        mean = jnp.mean(xf, axis=reduce_axes)
        var = jnp.var(xf, axis=reduce_axes)
        if running_mean is not None:
            running_mean._data = (momentum * running_mean._data
                                  + (1 - momentum) * mean.astype(
                                      running_mean._data.dtype))
        if running_var is not None:
            n = xf.size / mean.size
            unbiased = var * (n / (n - 1)) if n > 1 else var
            running_var._data = (momentum * running_var._data
                                 + (1 - momentum) * unbiased.astype(
                                     running_var._data.dtype))

        def fn(v, *wb):
            vf = v.astype(jnp.float32)
            m = jnp.mean(vf, axis=reduce_axes, keepdims=True)
            va = jnp.var(vf, axis=reduce_axes, keepdims=True)
            out = (vf - m) * jax.lax.rsqrt(va + epsilon)
            out = out.astype(v.dtype)
            i = 0
            if weight is not None:
                out = out * wb[i].reshape(bshape)
                i += 1
            if bias is not None:
                out = out + wb[i].reshape(bshape)
            return out
        args = [a for a in (weight, bias) if a is not None]
        return apply_op("batch_norm", fn, x, *args)

    def fn(v, m, va, *wb):
        out = ((v.astype(jnp.float32) - m.reshape(bshape))
               * jax.lax.rsqrt(va.reshape(bshape).astype(jnp.float32) + epsilon))
        out = out.astype(v.dtype)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(bshape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(bshape)
        return out
    args = [a for a in (weight, bias) if a is not None]
    return apply_op("batch_norm", fn, x, running_mean, running_var, *args)


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-05,
                  data_format="NCHW", name=None):
    x = _t(x)
    reduce_axes = tuple(range(2, x.ndim))
    bshape = [1, x.shape[1]] + [1] * (x.ndim - 2)

    def fn(v, *wb):
        vf = v.astype(jnp.float32)
        m = jnp.mean(vf, axis=reduce_axes, keepdims=True)
        va = jnp.var(vf, axis=reduce_axes, keepdims=True)
        out = ((vf - m) * jax.lax.rsqrt(va + eps)).astype(v.dtype)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(bshape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(bshape)
        return out
    args = [a for a in (weight, bias) if a is not None]
    return apply_op("instance_norm", fn, x, *args)


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None,
               data_format="NCHW", name=None):
    x = _t(x)
    nc_first = data_format.startswith("NC")
    c = x.shape[1] if nc_first else x.shape[-1]

    def fn(v, *wb):
        if nc_first:
            n = v.shape[0]
            g = v.reshape((n, num_groups, c // num_groups) + tuple(v.shape[2:]))
            axes = tuple(range(2, g.ndim))
            gf = g.astype(jnp.float32)
            m = jnp.mean(gf, axis=axes, keepdims=True)
            va = jnp.var(gf, axis=axes, keepdims=True)
            out = ((gf - m) * jax.lax.rsqrt(va + epsilon)).astype(v.dtype)
            out = out.reshape(v.shape)
            bshape = [1, c] + [1] * (v.ndim - 2)
        else:
            n = v.shape[0]
            g = v.reshape(tuple(v.shape[:-1]) + (num_groups, c // num_groups))
            axes = tuple(range(1, g.ndim - 2)) + (g.ndim - 1,)
            gf = g.astype(jnp.float32)
            m = jnp.mean(gf, axis=axes, keepdims=True)
            va = jnp.var(gf, axis=axes, keepdims=True)
            out = ((gf - m) * jax.lax.rsqrt(va + epsilon)).astype(v.dtype)
            out = out.reshape(v.shape)
            bshape = [1] * (v.ndim - 1) + [c]
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(bshape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(bshape)
        return out
    args = [a for a in (weight, bias) if a is not None]
    return apply_op("group_norm", fn, x, *args)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    def fn(v):
        sq = jnp.square(v)
        ch_axis = 1 if data_format.startswith("NC") else v.ndim - 1
        c = v.shape[ch_axis]
        pad_lo = (size - 1) // 2
        pad_hi = size - 1 - pad_lo
        pads = [(0, 0)] * v.ndim
        pads[ch_axis] = (pad_lo, pad_hi)
        sq = jnp.pad(sq, pads)
        window = [1] * v.ndim
        window[ch_axis] = size
        s = jax.lax.reduce_window(sq, 0.0, jax.lax.add, tuple(window),
                                  (1,) * v.ndim, "VALID")
        return v / (k + alpha * s) ** beta
    return apply_op("local_response_norm", fn, _t(x))


def spectral_norm(x, weight_u, weight_v, dim=0, power_iters=1, eps=1e-12,
                  name=None):
    def fn(w, u, v):
        wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
        for _ in range(power_iters):
            v = wm.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = wm @ v
            u = u / (jnp.linalg.norm(u) + eps)
        sigma = u @ wm @ v
        return w / sigma
    return apply_op("spectral_norm", fn, _t(x), weight_u, weight_v)
