"""Activation functionals (reference: python/paddle/nn/functional/activation.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op
from ...core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def relu(x, name=None):
    return apply_op("relu", jax.nn.relu, _t(x))


def relu_(x, name=None):
    return x._inplace_assign(relu(x))


def relu6(x, name=None):
    return apply_op("relu6", jax.nn.relu6, _t(x))


def elu(x, alpha=1.0, name=None):
    return apply_op("elu", lambda v: jax.nn.elu(v, alpha), _t(x))


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply_op("selu",
                    lambda v: scale * jnp.where(v > 0, v,
                                                alpha * jnp.expm1(v)), _t(x))


def celu(x, alpha=1.0, name=None):
    return apply_op("celu", lambda v: jax.nn.celu(v, alpha), _t(x))


def gelu(x, approximate=False, name=None):
    return apply_op("gelu", lambda v: jax.nn.gelu(v, approximate=approximate),
                    _t(x))


def sigmoid(x, name=None):
    return apply_op("sigmoid", jax.nn.sigmoid, _t(x))


def hardsigmoid(x, slope=1.0 / 6, offset=0.5, name=None):
    return apply_op("hardsigmoid",
                    lambda v: jnp.clip(slope * v + offset, 0.0, 1.0), _t(x))


def hardswish(x, name=None):
    return apply_op("hardswish",
                    lambda v: v * jnp.clip(v + 3.0, 0.0, 6.0) / 6.0, _t(x))


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply_op("hardtanh", lambda v: jnp.clip(v, min, max), _t(x))


def hardshrink(x, threshold=0.5, name=None):
    return apply_op("hardshrink",
                    lambda v: jnp.where(jnp.abs(v) > threshold, v, 0.0), _t(x))


def softshrink(x, threshold=0.5, name=None):
    return apply_op(
        "softshrink",
        lambda v: jnp.where(v > threshold, v - threshold,
                            jnp.where(v < -threshold, v + threshold, 0.0)),
        _t(x))


def tanhshrink(x, name=None):
    return apply_op("tanhshrink", lambda v: v - jnp.tanh(v), _t(x))


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply_op("leaky_relu",
                    lambda v: jax.nn.leaky_relu(v, negative_slope), _t(x))


def prelu(x, weight, data_format="NCHW", name=None):
    def fn(v, w):
        if w.size == 1:
            return jnp.where(v > 0, v, w.reshape(()) * v)
        shape = [1] * v.ndim
        ch_axis = 1 if data_format == "NCHW" else v.ndim - 1
        shape[ch_axis] = w.size
        return jnp.where(v > 0, v, w.reshape(shape) * v)
    return apply_op("prelu", fn, _t(x), weight)


def rrelu(x, lower=1.0 / 8, upper=1.0 / 3, training=False, name=None):
    from ...tensor.random import _next_key
    if training:
        x = _t(x)
        a = jax.random.uniform(_next_key(), x._data.shape, jnp.float32, lower,
                               upper).astype(x.dtype)
        return apply_op("rrelu", lambda v: jnp.where(v >= 0, v, a * v), x)
    mid = (lower + upper) / 2.0
    return apply_op("rrelu", lambda v: jnp.where(v >= 0, v, mid * v), _t(x))


def log_sigmoid(x, name=None):
    return apply_op("log_sigmoid", jax.nn.log_sigmoid, _t(x))


def maxout(x, groups, axis=1, name=None):
    def fn(v):
        ax = axis if axis >= 0 else axis + v.ndim
        c = v.shape[ax]
        new_shape = v.shape[:ax] + (c // groups, groups) + v.shape[ax + 1:]
        return jnp.max(v.reshape(new_shape), axis=ax + 1)
    return apply_op("maxout", fn, _t(x))


def silu(x, name=None):
    return apply_op("silu", jax.nn.silu, _t(x))


swish = silu


def mish(x, name=None):
    return apply_op("mish", lambda v: v * jnp.tanh(jax.nn.softplus(v)), _t(x))


def softmax(x, axis=-1, dtype=None, name=None):
    from ...core import dtype as dtypes
    dt = dtypes.convert_dtype(dtype)

    def fn(v):
        if dt is not None:
            v = v.astype(dt)
        return jax.nn.softmax(v, axis=axis)
    return apply_op("softmax", fn, _t(x))


def softmax_(x, axis=-1, dtype=None, name=None):
    return x._inplace_assign(softmax(x, axis, dtype))


def log_softmax(x, axis=-1, dtype=None, name=None):
    from ...core import dtype as dtypes
    dt = dtypes.convert_dtype(dtype)

    def fn(v):
        if dt is not None:
            v = v.astype(dt)
        return jax.nn.log_softmax(v, axis=axis)
    return apply_op("log_softmax", fn, _t(x))


def softplus(x, beta=1, threshold=20, name=None):
    return apply_op(
        "softplus",
        lambda v: jnp.where(beta * v > threshold, v,
                            jax.nn.softplus(beta * v) / beta), _t(x))


def softsign(x, name=None):
    return apply_op("softsign", jax.nn.soft_sign, _t(x))


def tanh(x, name=None):
    return apply_op("tanh", jnp.tanh, _t(x))


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return apply_op("thresholded_relu",
                    lambda v: jnp.where(v > threshold, v, value), _t(x))


def glu(x, axis=-1, name=None):
    def fn(v):
        a, b = jnp.split(v, 2, axis=axis)
        return a * jax.nn.sigmoid(b)
    return apply_op("glu", fn, _t(x))


def swiglu(x, y=None, name=None):
    """Fused SwiGLU (reference: python/paddle/incubate/nn/functional/swiglu.py).
    XLA fuses this chain into one kernel on TPU."""
    if y is not None:
        return apply_op("swiglu", lambda a, b: jax.nn.silu(a) * b, _t(x), _t(y))

    def fn(v):
        a, b = jnp.split(v, 2, axis=-1)
        return jax.nn.silu(a) * b
    return apply_op("swiglu", fn, _t(x))


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...tensor.random import _next_key
    x = _t(x)
    g = jax.random.gumbel(_next_key(), x._data.shape).astype(x.dtype)

    def fn(v):
        y = jax.nn.softmax((v + g) / temperature, axis=axis)
        if hard:
            # straight-through estimator
            onehot = jax.nn.one_hot(jnp.argmax(y, axis=axis), y.shape[axis],
                                    axis=axis, dtype=y.dtype)
            return y + jax.lax.stop_gradient(onehot - y)
        return y
    return apply_op("gumbel_softmax", fn, x)
