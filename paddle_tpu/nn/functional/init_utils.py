"""ParamAttr handling + parameter creation shared by layers."""

from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Parameter

_GLOBAL_WEIGHT_INIT = [None]
_GLOBAL_BIAS_INIT = [None]


class ParamAttr:
    """reference: python/paddle/base/param_attr.py ParamAttr."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip


def param_attr_init(shape, dtype, attr, is_bias, default_initializer):
    from ..initializer import Constant, XavierUniform

    shape = tuple(int(s) for s in shape)
    init = None
    name = None
    trainable = True
    if isinstance(attr, ParamAttr):
        init = attr.initializer
        name = attr.name
        trainable = attr.trainable
    elif callable(attr):
        init = attr
    if init is None:
        init = default_initializer
    if init is None:
        glob = _GLOBAL_BIAS_INIT[0] if is_bias else _GLOBAL_WEIGHT_INIT[0]
        init = glob
    if init is None:
        init = Constant(0.0) if is_bias else XavierUniform()
    data = init(shape, dtype)
    p = Parameter(data, name=name, trainable=trainable)
    if isinstance(attr, ParamAttr):
        p.optimize_attr = {"learning_rate": attr.learning_rate}
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
    return p
