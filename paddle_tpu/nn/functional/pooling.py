"""Pooling via lax.reduce_window (reference kernels:
phi/kernels/gpudnn/pool_kernel.cu)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import apply_op
from ...core.tensor import Tensor
from .conv import _ntuple, _padding


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def _pool(x, op_name, reducer, init, kernel_size, stride, padding, spatial,
          data_format, ceil_mode=False, exclusive=True, divisor=None):
    ks = _ntuple(kernel_size, spatial)
    st = _ntuple(stride if stride is not None else kernel_size, spatial)
    pad = _padding(padding, spatial)
    nc_first = data_format.startswith("NC")
    if nc_first:
        window = (1, 1) + ks
        strides = (1, 1) + st
        pads = [(0, 0), (0, 0)] + (pad if isinstance(pad, list) else pad)
    else:
        window = (1,) + ks + (1,)
        strides = (1,) + st + (1,)
        pads = [(0, 0)] + (pad if isinstance(pad, list) else pad) + [(0, 0)]
    if isinstance(pad, str):
        pads = pad
    elif ceil_mode:
        # include the last partial window: extend the trailing pad so
        # reduce_window emits ceil((L + pb + pa - k)/s) + 1 positions
        # (reference pooling.cc ceil-mode formula); padded cells contribute
        # init (-inf for max, 0 for sum) and the avg `counts` pass sees the
        # same padding, so they never skew results
        x_sp = ([int(s) for s in _t(x).shape[2:]] if nc_first
                else [int(s) for s in _t(x).shape[1:-1]])
        off = 2 if nc_first else 1
        for i in range(spatial):
            pb, pa = pads[off + i]
            total = x_sp[i] + pb + pa - ks[i]
            rem = total % st[i]
            if rem:
                pads[off + i] = (pb, pa + (st[i] - rem))

    def fn(v):
        if reducer == "max":
            return jax.lax.reduce_window(v, -jnp.inf, jax.lax.max, window,
                                         strides, pads)
        summed = jax.lax.reduce_window(v, 0.0, jax.lax.add, window, strides,
                                       pads)
        if exclusive and not isinstance(pads, str):
            ones = jnp.ones_like(v)
            counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                           strides, pads)
            return summed / counts
        return summed / float(np.prod(ks) if divisor is None else divisor)
    return apply_op(op_name, fn, _t(x))


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    return _pool(x, "avg_pool1d", "avg", 0.0, kernel_size, stride, padding, 1,
                 "NCL", ceil_mode, exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool(x, "avg_pool2d", "avg", 0.0, kernel_size, stride, padding, 2,
                 data_format, ceil_mode, exclusive, divisor_override)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, "avg_pool3d", "avg", 0.0, kernel_size, stride, padding, 3,
                 data_format, ceil_mode, exclusive, divisor_override)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    out = _pool(x, "max_pool1d", "max", -np.inf, kernel_size, stride, padding,
                1, "NCL", ceil_mode)
    if return_mask:
        return out, _pool_mask(x, out, kernel_size, stride, padding, 1)
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    out = _pool(x, "max_pool2d", "max", -np.inf, kernel_size, stride, padding,
                2, data_format, ceil_mode)
    if return_mask:
        return out, _pool_mask(x, out, kernel_size, stride, padding, 2,
                               data_format)
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    out = _pool(x, "max_pool3d", "max", -np.inf, kernel_size, stride, padding,
                3, data_format, ceil_mode)
    if return_mask:
        return out, _pool_mask(x, out, kernel_size, stride, padding, 3,
                               data_format)
    return out


def _pool_mask(x, out, kernel_size, stride, padding, spatial,
               data_format="NCHW"):
    """Flattened-spatial input index of each window's max (the reference's
    max_pool mask output, consumed by max_unpool*d).  Gather every window's
    candidates, argmax, convert the winner's per-dim coords to a flat
    index."""
    import functools
    import operator

    ks = _ntuple(kernel_size, spatial)
    st = _ntuple(stride if stride is not None else kernel_size, spatial)
    if isinstance(padding, str):
        raise ValueError("return_mask with string padding is unsupported")
    pads = _padding(padding, spatial)   # [(before, after)] per dim
    pd = [p[0] for p in pads]           # window math uses the leading pad
    d = _t(x)._data
    od = out._data
    if not data_format.startswith("NC"):   # NHWC/NDHWC -> NC-first
        d = jnp.moveaxis(d, -1, 1)
        od = jnp.moveaxis(od, -1, 1)
    sp = d.shape[2:]
    out_sp = od.shape[2:]

    grids = []
    for i in range(spatial):
        g = (jnp.arange(out_sp[i])[:, None] * st[i] - pd[i]
             + jnp.arange(ks[i])[None, :])              # [O_i, k_i]
        shape = [1] * (2 * spatial)
        shape[i], shape[spatial + i] = g.shape
        grids.append(g.reshape(shape))
    full = tuple(out_sp) + tuple(ks)
    bc = [jnp.broadcast_to(g, full) for g in grids]
    valid = functools.reduce(operator.and_,
                             [(b >= 0) & (b < sp[i])
                              for i, b in enumerate(bc)])
    clipped = [jnp.clip(b, 0, sp[i] - 1) for i, b in enumerate(bc)]
    vals = d[(slice(None), slice(None)) + tuple(clipped)]  # [N,C,*O,*k]
    vals = jnp.where(valid, vals, -jnp.inf)
    k_total = int(np.prod(ks))
    win = jnp.argmax(vals.reshape(vals.shape[:2 + spatial] + (k_total,)),
                     axis=-1)                              # [N, C, *O]
    mult = 1
    acc = jnp.zeros(tuple(out_sp) + (k_total,), jnp.int64)
    for i in reversed(range(spatial)):
        acc = acc + clipped[i].reshape(tuple(out_sp) + (-1,)) * mult
        mult *= sp[i]
    picked = jnp.take_along_axis(
        jnp.broadcast_to(acc, win.shape + (k_total,)), win[..., None],
        axis=-1)[..., 0]
    if not data_format.startswith("NC"):
        picked = jnp.moveaxis(picked, 1, -1)
    return Tensor._wrap(picked.astype(jnp.int64))


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, output_size, 1, "avg", "NCL")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive(x, output_size, 2, "avg", data_format)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive(x, output_size, 3, "avg", data_format)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    out = _adaptive(x, output_size, 1, "max", "NCL")
    return (out, None) if return_mask else out


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out = _adaptive(x, output_size, 2, "max", "NCHW")
    return (out, None) if return_mask else out


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    out = _adaptive(x, output_size, 3, "max", "NCDHW")
    return (out, None) if return_mask else out


def _adaptive(x, output_size, spatial, mode, data_format):
    x = _t(x)
    os = _ntuple(output_size, spatial)
    nc_first = data_format.startswith("NC")
    in_spatial = x.shape[2:] if nc_first else x.shape[1:-1]
    os = tuple(in_spatial[i] if os[i] is None else os[i]
               for i in range(spatial))

    def fn(v):
        out = v
        for d in range(spatial):
            ax = (2 + d) if nc_first else (1 + d)
            in_sz, out_sz = in_spatial[d], os[d]
            if in_sz % out_sz == 0:
                k = in_sz // out_sz
                shape = list(out.shape)
                shape[ax:ax + 1] = [out_sz, k]
                r = out.reshape(shape)
                out = (jnp.max(r, axis=ax + 1) if mode == "max"
                       else jnp.mean(r, axis=ax + 1))
            else:
                # general adaptive: per-output-bin segments
                starts = [int(np.floor(i * in_sz / out_sz)) for i in range(out_sz)]
                ends = [int(np.ceil((i + 1) * in_sz / out_sz)) for i in range(out_sz)]
                segs = []
                for s, e in zip(starts, ends):
                    sl = [slice(None)] * out.ndim
                    sl[ax] = slice(s, e)
                    seg = out[tuple(sl)]
                    segs.append(jnp.max(seg, axis=ax) if mode == "max"
                                else jnp.mean(seg, axis=ax))
                out = jnp.stack(segs, axis=ax)
        return out
    return apply_op(f"adaptive_{mode}_pool{spatial}d", fn, x)


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, name=None):
    p = float(norm_type)
    ks = _ntuple(kernel_size, 1)

    def fn(v):
        s = jax.lax.reduce_window(jnp.abs(v) ** p, 0.0, jax.lax.add,
                                  (1, 1) + ks,
                                  (1, 1) + _ntuple(stride or kernel_size, 1),
                                  [(0, 0), (0, 0), (padding, padding)])
        return s ** (1.0 / p)
    return apply_op("lp_pool1d", fn, _t(x))


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW", name=None):
    p = float(norm_type)
    ks = _ntuple(kernel_size, 2)
    st = _ntuple(stride if stride is not None else kernel_size, 2)
    pad = _padding(padding, 2)

    def fn(v):
        s = jax.lax.reduce_window(jnp.abs(v) ** p, 0.0, jax.lax.add,
                                  (1, 1) + ks, (1, 1) + st,
                                  [(0, 0), (0, 0)] + pad)
        return s ** (1.0 / p)
    return apply_op("lp_pool2d", fn, _t(x))
