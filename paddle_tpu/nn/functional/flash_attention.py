"""Attention functionals (reference: python/paddle/nn/functional/flash_attention.py
— flash_attention:147, flash_attn_unpadded:455, scaled_dot_product_attention:722;
CUDA kernel: phi/kernels/gpu/flash_attn_kernel.cu wrapping third_party flashattn).

TPU-native: routes to the Pallas flash-attention kernel
(paddle_tpu/kernels/flash_attention.py) on TPU, with an XLA reference path
(jnp einsum softmax chain — XLA fuses it) elsewhere or when shapes are
unsuitable for the kernel tiling."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op
from ...core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def _use_pallas(q_data):
    if q_data.ndim != 4:
        return False
    plat = jax.devices()[0].platform
    if plat not in ("tpu", "axon"):
        return False
    b, s, h, d = q_data.shape
    return s >= 128 and s % 128 == 0 and d in (64, 128, 256)


def _sdpa_reference(q, k, v, mask, causal, dropout_p, scale=None):
    """[B, S, H, D] layout (paddle convention)."""
    d = q.shape[-1]
    sc = scale if scale is not None else 1.0 / math.sqrt(d)
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    logits = jnp.einsum("bshd,bthd->bhst", qf * sc, kf)
    if causal:
        s, t = logits.shape[-2], logits.shape[-1]
        cmask = jnp.tril(jnp.ones((s, t), dtype=bool), t - s)
        logits = jnp.where(cmask, logits, -1e30)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -1e30)
        else:
            logits = logits + mask.astype(logits.dtype)
    p = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhst,bthd->bshd", p.astype(v.dtype), v)
    return out


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """reference surface: nn/functional/flash_attention.py:722."""
    q, k, v = _t(query), _t(key), _t(value)
    if _use_pallas(q._data) and attn_mask is None and dropout_p == 0.0:
        from ...kernels.flash_attention import flash_attention_fwd
        return apply_op("flash_attention",
                        lambda a, b, c: flash_attention_fwd(a, b, c,
                                                            causal=is_causal),
                        q, k, v)
    m = attn_mask._data if isinstance(attn_mask, Tensor) else attn_mask
    return apply_op("sdpa",
                    lambda a, b, c: _sdpa_reference(a, b, c, m, is_causal,
                                                    dropout_p), q, k, v)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None):
    """reference surface: nn/functional/flash_attention.py:147.
    Returns (out, softmax_lse-like None) tuple for compat."""
    out = scaled_dot_product_attention(query, key, value, None, dropout,
                                       causal, training)
    return out, None


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale, dropout=0.0,
                        causal=False, return_softmax=False, name=None):
    """Varlen attention (reference :455). Implemented by segment-masked dense
    attention — ragged batches become one padded batch with a block-diagonal
    mask (TPU prefers static shapes over ragged kernels)."""
    q, k, v = _t(query), _t(key), _t(value)

    def fn(qd, kd, vd, cq, ck):
        total_q = qd.shape[0]
        total_k = kd.shape[0]
        seg_q = jnp.cumsum(
            jnp.zeros(total_q, jnp.int32).at[cq[1:-1]].add(1))
        seg_k = jnp.cumsum(
            jnp.zeros(total_k, jnp.int32).at[ck[1:-1]].add(1))
        logits = jnp.einsum("qhd,khd->hqk", qd.astype(jnp.float32) * scale,
                            kd.astype(jnp.float32))
        mask = seg_q[:, None] == seg_k[None, :]
        if causal:
            pos_q = jnp.arange(total_q) - jnp.take(cq, seg_q)
            pos_k = jnp.arange(total_k) - jnp.take(ck, seg_k)
            mask = mask & (pos_q[:, None] >= pos_k[None, :])
        logits = jnp.where(mask[None], logits, -1e30)
        p = jax.nn.softmax(logits, axis=-1)
        return jnp.einsum("hqk,khd->qhd", p.astype(vd.dtype), vd)
    out = apply_op("flash_attn_unpadded", fn, q, k, v, _t(cu_seqlens_q),
                   _t(cu_seqlens_k))
    return out, None


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """CSR-masked attention (reference kernel:
    phi/kernels/gpu/sparse_attention... via paddle.nn.functional
    .sparse_attention): each query row attends only to the key columns
    listed in its CSR row.

    TPU-native realisation: the CSR pattern becomes a dense boolean mask
    (one scatter) and the masked softmax-attention runs as ordinary MXU
    matmuls — XLA has no gather-attention primitive that beats the dense
    path until sparsity is extreme, and the mask build is O(nnz).
    query/key/value: [B, H, S, D]; csr offset [B, H, S+1], columns
    [B, H, nnz].  Returns [B, H, S, D].
    """
    import numpy as np

    if key_padding_mask is not None or attn_mask is not None:
        raise NotImplementedError(
            "sparse_attention: key_padding_mask/attn_mask are not applied "
            "on the TPU path — fold them into the CSR pattern instead")
    off = np.asarray((sparse_csr_offset._data
                      if isinstance(sparse_csr_offset, Tensor)
                      else sparse_csr_offset)).astype(np.int64)
    col = np.asarray((sparse_csr_columns._data
                      if isinstance(sparse_csr_columns, Tensor)
                      else sparse_csr_columns)).astype(np.int64)
    B, H, S = off.shape[0], off.shape[1], off.shape[2] - 1
    mask = np.zeros((B, H, S, S), bool)
    for b in range(B):
        for h in range(H):
            nnz = off[b, h, -1]
            rows = np.repeat(np.arange(S), np.diff(off[b, h]))
            mask[b, h, rows, col[b, h, :nnz]] = True  # one scatter per head
    mask_j = jnp.asarray(mask)

    def fn(qd, kd, vd):
        d = qd.shape[-1]
        # fp32 logits/softmax regardless of input dtype (matches
        # _sdpa_reference above; also keeps the -inf fill safe under fp16)
        logits = jnp.einsum("bhqd,bhkd->bhqk", qd.astype(jnp.float32),
                            kd.astype(jnp.float32)) / jnp.sqrt(float(d))
        logits = jnp.where(mask_j, logits, jnp.finfo(jnp.float32).min)
        p = jax.nn.softmax(logits, axis=-1)
        # fully-masked rows (empty CSR row) output zeros, not nan
        p = jnp.where(mask_j.any(-1, keepdims=True), p, 0.0)
        return jnp.einsum("bhqk,bhkd->bhqd", p.astype(vd.dtype), vd)

    return apply_op("sparse_attention", fn, _t(query), _t(key), _t(value))
