"""nn.functional breadth: the reference API surface not covered by the core
modules (reference: python/paddle/nn/functional/ — pooling.py max_unpool*,
vision.py affine_grid/grid_sample/temporal_shift, common.py
class_center_sample, loss.py multi_margin/hsigmoid, extension.py
sequence_mask/gather_tree, activation.py inplace twins)."""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op
from ...core.tensor import Tensor
from ...ops._runtime import _t
from . import activation as _act


# -- inplace activation twins ------------------------------------------------
def elu_(x, alpha=1.0, name=None):
    return x._inplace_assign(_act.elu(x, alpha))


def hardtanh_(x, min=-1.0, max=1.0, name=None):
    return x._inplace_assign(_act.hardtanh(x, min, max))


def leaky_relu_(x, negative_slope=0.01, name=None):
    return x._inplace_assign(_act.leaky_relu(x, negative_slope))


def tanh_(x, name=None):
    return x._inplace_assign(_act.tanh(x))


def thresholded_relu_(x, threshold=1.0, value=0.0, name=None):
    return x._inplace_assign(_act.thresholded_relu(x, threshold, value))


def relu_(x, name=None):
    return x._inplace_assign(_act.relu(x))


# -- sequence / beam utilities ----------------------------------------------
def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """lengths -> [.., maxlen] step-valid mask (reference:
    nn/functional/extension.py sequence_mask)."""
    from ...core import dtype as dtypes
    lens = _t(x)
    if maxlen is None:
        maxlen = int(np.asarray(lens.numpy()).max())
    dt = dtypes.convert_dtype(dtype)
    return apply_op(
        "sequence_mask",
        lambda v: (jnp.arange(maxlen) < v[..., None]).astype(dt), lens)


def gather_tree(ids, parents, name=None):
    """Reconstruct full beam paths by walking parent pointers backwards
    (reference: gather_tree op; here one lax.scan over time).
    ids/parents: [T, B, beam] int."""
    def fn(idv, pv):
        T = idv.shape[0]

        def step(next_beam, t):
            tok = jnp.take_along_axis(idv[t], next_beam, axis=-1)
            par = jnp.take_along_axis(pv[t], next_beam, axis=-1)
            return par, tok

        init = jnp.broadcast_to(jnp.arange(idv.shape[-1]), idv.shape[1:])
        _, toks = jax.lax.scan(step, init, jnp.arange(T - 1, -1, -1))
        return toks[::-1]
    return apply_op("gather_tree", fn, _t(ids), _t(parents))


# -- vision -------------------------------------------------------------------
def affine_grid(theta, out_shape, align_corners=True, name=None):
    """theta [N, 2, 3] -> sampling grid [N, H, W, 2] (reference:
    nn/functional/vision.py affine_grid)."""
    N, C, H, W = [int(s) for s in out_shape]

    def fn(th):
        if align_corners:
            xs = jnp.linspace(-1.0, 1.0, W)
            ys = jnp.linspace(-1.0, 1.0, H)
        else:
            xs = (jnp.arange(W) * 2 + 1) / W - 1.0
            ys = (jnp.arange(H) * 2 + 1) / H - 1.0
        gx, gy = jnp.meshgrid(xs, ys)               # [H, W]
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1)   # [H, W, 3]
        return jnp.einsum("hwk,nck->nhwc", base, th)
    return apply_op("affine_grid", fn, _t(theta))


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Sample x [N,C,H,W] at normalized grid [N,Ho,Wo,2] (reference:
    nn/functional/vision.py grid_sample -> grid_sample kernel).  Gather +
    lerp — XLA fuses it into the surrounding program."""
    if padding_mode not in ("zeros", "border"):
        raise NotImplementedError(
            f"grid_sample: padding_mode={padding_mode!r} is not supported "
            "(zeros/border are; reflection is not)")
    def fn(v, g):
        N, C, H, W = v.shape
        gx, gy = g[..., 0], g[..., 1]
        if align_corners:
            fx = (gx + 1) * (W - 1) / 2
            fy = (gy + 1) * (H - 1) / 2
        else:
            fx = ((gx + 1) * W - 1) / 2
            fy = ((gy + 1) * H - 1) / 2
        if mode == "nearest":
            ix = jnp.round(fx).astype(jnp.int32)
            iy = jnp.round(fy).astype(jnp.int32)
            inb = (ix >= 0) & (ix < W) & (iy >= 0) & (iy < H)
            ix = jnp.clip(ix, 0, W - 1)
            iy = jnp.clip(iy, 0, H - 1)
            out = v[jnp.arange(N)[:, None, None], :, iy, ix]
            out = jnp.moveaxis(out, -1, 1)
            if padding_mode == "zeros":
                out = out * inb[:, None].astype(v.dtype)
            return out

        x0 = jnp.floor(fx).astype(jnp.int32)
        y0 = jnp.floor(fy).astype(jnp.int32)
        wx = (fx - x0)[:, None]                      # [N,1,Ho,Wo]
        wy = (fy - y0)[:, None]

        def tap(ix, iy):
            inb = (ix >= 0) & (ix < W) & (iy >= 0) & (iy < H)
            cx = jnp.clip(ix, 0, W - 1)
            cy = jnp.clip(iy, 0, H - 1)
            val = v[jnp.arange(N)[:, None, None], :, cy, cx]  # [N,Ho,Wo,C]
            val = jnp.moveaxis(val, -1, 1)                    # [N,C,Ho,Wo]
            if padding_mode == "zeros":
                val = val * inb[:, None].astype(v.dtype)
            return val

        return (tap(x0, y0) * (1 - wx) * (1 - wy)
                + tap(x0 + 1, y0) * wx * (1 - wy)
                + tap(x0, y0 + 1) * (1 - wx) * wy
                + tap(x0 + 1, y0 + 1) * wx * wy)
    return apply_op("grid_sample", fn, _t(x), _t(grid))


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    """TSM channel shift across time (reference:
    nn/functional/extension.py temporal_shift)."""
    def fn(v):
        if data_format == "NHWC":
            v = jnp.moveaxis(v, -1, 1)
        NT, C, H, W = v.shape
        T = seg_num
        v = v.reshape(NT // T, T, C, H, W)
        fold = int(C * shift_ratio)
        back = jnp.concatenate([v[:, 1:, :fold],
                                jnp.zeros_like(v[:, :1, :fold])], axis=1)
        fwd = jnp.concatenate([jnp.zeros_like(v[:, :1, fold:2 * fold]),
                               v[:, :-1, fold:2 * fold]], axis=1)
        out = jnp.concatenate([back, fwd, v[:, :, 2 * fold:]], axis=2)
        out = out.reshape(NT, C, H, W)
        if data_format == "NHWC":
            out = jnp.moveaxis(out, 1, -1)
        return out
    return apply_op("temporal_shift", fn, _t(x))


# -- unpooling ----------------------------------------------------------------
def _max_unpool(x, indices, spatial, kernel_size, stride, padding,
                output_size, data_format, op_name):
    from .pooling import _ntuple
    ks = _ntuple(kernel_size, spatial)
    st = _ntuple(stride if stride is not None else kernel_size, spatial)
    pd = _ntuple(padding, spatial)
    xin = _t(x)
    in_sp = [int(s) for s in xin.shape[2:]]
    if output_size is None:
        out_sp = [(in_sp[i] - 1) * st[i] - 2 * pd[i] + ks[i]
                  for i in range(spatial)]
    else:
        out_sp = [int(s) for s in list(output_size)[-spatial:]]
    P = int(np.prod(out_sp))

    def fn(v, idx):
        N, C = v.shape[0], v.shape[1]
        flat_v = v.reshape(N, C, -1)
        flat_i = idx.reshape(N, C, -1)
        out = jnp.zeros((N, C, P), v.dtype)
        n_ix = jnp.arange(N)[:, None, None]
        c_ix = jnp.arange(C)[None, :, None]
        out = out.at[n_ix, c_ix, flat_i].set(flat_v)
        return out.reshape((N, C) + tuple(out_sp))
    return apply_op(op_name, fn, xin, _t(indices))


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    """Inverse of max_pool1d via the pool mask (reference:
    phi/kernels/.../unpool_kernel)."""
    return _max_unpool(x, indices, 1, kernel_size, stride, padding,
                       output_size, data_format, "max_unpool1d")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    return _max_unpool(x, indices, 2, kernel_size, stride, padding,
                       output_size, data_format, "max_unpool2d")


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    return _max_unpool(x, indices, 3, kernel_size, stride, padding,
                       output_size, data_format, "max_unpool3d")


# -- losses -------------------------------------------------------------------
def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    """Row-wise p-distance (reference: nn/functional/distance.py)."""
    def fn(a, b):
        d = jnp.power(jnp.sum(jnp.power(jnp.abs(a - b + epsilon), p),
                              axis=-1), 1.0 / p)
        return d[..., None] if keepdim else d
    return apply_op("pairwise_distance", fn, _t(x), _t(y))


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    """Multi-class margin loss (reference: nn/functional/loss.py
    multi_margin_loss)."""
    def fn(logits, lbl, *w):
        N, C = logits.shape
        correct = jnp.take_along_axis(logits, lbl[:, None], axis=1)
        m = jnp.maximum(0.0, margin - correct + logits) ** p
        if w:
            m = m * jnp.take(w[0], lbl)[:, None]
        m = m * (1 - jax.nn.one_hot(lbl, C, dtype=logits.dtype))
        per = m.sum(axis=1) / C
        if reduction == "mean":
            return per.mean()
        if reduction == "sum":
            return per.sum()
        return per
    args = [_t(input), _t(label)] + ([_t(weight)]
                                     if weight is not None else [])
    return apply_op("multi_margin_loss", fn, *args)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid over the default complete binary tree
    (reference: nn/functional/loss.py hsigmoid_loss -> hsigmoid kernel;
    custom path_table/path_code trees are rejected explicitly)."""
    if path_table is not None or path_code is not None:
        raise NotImplementedError(
            "hsigmoid_loss: custom trees (path_table/path_code) are not "
            "supported; the default complete-binary-tree layout is")
    # default tree: num_classes leaves, internal nodes = num_classes - 1,
    # leaf k's path derived from the heap layout of node (k + n_internal)
    n_internal = num_classes - 1
    codes, tables, lens = [], [], []
    for k in range(num_classes):
        node = k + n_internal
        path, code = [], []
        while node > 0:
            parent = (node - 1) // 2
            code.append(node == 2 * parent + 2)  # right child -> 1
            path.append(parent)
            node = parent
        tables.append(path[::-1])
        codes.append(code[::-1])
        lens.append(len(path))
    L = max(lens)
    tbl = np.zeros((num_classes, L), np.int32)
    cod = np.zeros((num_classes, L), np.float32)
    msk = np.zeros((num_classes, L), np.float32)
    for k in range(num_classes):
        tbl[k, :lens[k]] = tables[k]
        cod[k, :lens[k]] = codes[k]
        msk[k, :lens[k]] = 1.0
    tbl_j, cod_j, msk_j = map(jnp.asarray, (tbl, cod, msk))

    def fn(xv, lbl, w, *b):
        pt = tbl_j[lbl]                 # [N, L] node ids
        pc = cod_j[lbl]                 # [N, L] 0/1 directions
        pm = msk_j[lbl]                 # [N, L] valid
        wn = w[pt]                      # [N, L, D]
        logits = jnp.einsum("nld,nd->nl", wn, xv)
        if b:
            logits = logits + b[0][pt]
        # BCE with target = code
        loss = (jnp.maximum(logits, 0) - logits * pc
                + jnp.log1p(jnp.exp(-jnp.abs(logits))))
        return (loss * pm).sum(axis=1, keepdims=True)
    args = [_t(input), _t(label), _t(weight)] + (
        [_t(bias)] if bias is not None else [])
    return apply_op("hsigmoid_loss", fn, *args)


def class_center_sample(label, num_classes, num_samples, group=None):
    """Sample negative class centers + remap labels (PartialFC; reference:
    nn/functional/common.py class_center_sample).  Host-side: the sampled
    id set is data-dependent."""
    lbl = np.asarray(_t(label).numpy()).astype(np.int64)
    pos = np.unique(lbl)
    rest = np.setdiff1d(np.arange(num_classes), pos)
    n_extra = max(0, min(num_samples, num_classes) - pos.size)
    rng = np.random.RandomState(np.int64(lbl.sum()) % (2**31))
    extra = rng.choice(rest, size=n_extra, replace=False) \
        if n_extra else np.zeros(0, np.int64)
    sampled = np.concatenate([pos, np.sort(extra)])
    remap = {int(c): i for i, c in enumerate(sampled)}
    new_lbl = np.asarray([remap[int(v)] for v in lbl], np.int64)
    return (Tensor._wrap(jnp.asarray(new_lbl)),
            Tensor._wrap(jnp.asarray(sampled)))


# -- packed flash-attention wrappers -----------------------------------------
def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False, return_softmax=False,
                         name=None):
    """qkv [B, S, 3, H, D] packed form (reference:
    nn/functional/flash_attention.py flash_attn_qkvpacked)."""
    from .flash_attention import flash_attention
    q = qkv[:, :, 0]
    k = qkv[:, :, 1]
    v = qkv[:, :, 2]
    return flash_attention(q, k, v, dropout=dropout, causal=causal,
                           return_softmax=return_softmax)


def flash_attn_varlen_qkvpacked(qkv, cu_seqlens_q, cu_seqlens_k,
                                max_seqlen_q, max_seqlen_k, scale=None,
                                dropout=0.0, causal=False,
                                return_softmax=False, name=None):
    """Varlen packed form over the unpadded path (reference:
    flash_attn_unpadded)."""
    from .flash_attention import flash_attn_unpadded
    q = qkv[:, 0]
    k = qkv[:, 1]
    v = qkv[:, 2]
    if scale is None:
        scale = 1.0 / float(np.sqrt(int(q.shape[-1])))
    return flash_attn_unpadded(q, k, v, cu_seqlens_q, cu_seqlens_k,
                               max_seqlen_q, max_seqlen_k, scale, dropout,
                               causal, return_softmax)


def flash_attention_with_sparse_mask(query, key, value,
                                     attn_mask_start_row_indices,
                                     attn_mask_start_row=0, dropout_p=0.0,
                                     is_causal=True, name=None):
    """Row-sparse causal attention: row i attends keys
    [start_row_indices[i], i] (reference:
    flash_attention_with_sparse_mask).  Realised as a dense additive mask
    into scaled_dot_product_attention — same numerics, XLA-fused."""
    def fn(qd, kd, vd, rows):
        B, S, H = qd.shape[0], qd.shape[1], qd.shape[2]
        i = jnp.arange(S)[:, None]
        j = jnp.arange(S)[None, :]
        causal = j <= i if is_causal else jnp.ones((S, S), bool)
        # start rows: [B, H, S] (reference shape) or [S] broadcast
        start = jnp.broadcast_to(rows.reshape(rows.shape[-3:]
                                              if rows.ndim >= 3
                                              else (1, 1, S)), (B, H, S))
        # query row i attends keys j in [start[b, h, i], i]
        allowed = causal[None, None] & (
            jnp.arange(S)[None, None, None, :] >= start[..., None])
        logits_mask = jnp.where(allowed, 0.0, -jnp.inf)  # [B, H, S, S]
        d = qd.shape[-1]
        att = jnp.einsum("bshd,bthd->bhst", qd.astype(jnp.float32),
                         kd.astype(jnp.float32)) / jnp.sqrt(float(d))
        att = att + logits_mask
        p = jax.nn.softmax(att, axis=-1)
        if dropout_p:
            from ...tensor.random import _next_key
            keep = jax.random.bernoulli(_next_key(), 1.0 - dropout_p,
                                        p.shape)
            p = p * keep / (1.0 - dropout_p)
        return jnp.einsum("bhst,bthd->bshd", p.astype(vd.dtype), vd)
    return apply_op("flash_attention_with_sparse_mask", fn, _t(query),
                    _t(key), _t(value), _t(attn_mask_start_row_indices))


# -- fractional pooling -------------------------------------------------------
def _fractional_edges(in_size, out_size, u):
    """Graham's pseudo-random pooling boundaries: ceil(alpha*(i+u)) with
    alpha = in/out; strictly increasing, cover [0, in]."""
    alpha = in_size / out_size
    idx = np.arange(out_size + 1, dtype=np.float64)
    edges = np.ceil(alpha * (idx + u)).astype(np.int64) - int(
        np.ceil(alpha * u))
    edges = np.clip(edges, 0, in_size)
    edges[0], edges[-1] = 0, in_size
    return edges


def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    """Fractional max pooling (Graham 2014; reference:
    nn/functional/pooling.py fractional_max_pool2d).  Variable-width bins
    realised as a scatter-max of each input pixel into its bin."""
    if kernel_size is not None:
        raise NotImplementedError(
            "fractional_max_pool2d: kernel_size (overlapping windows) is "
            "not supported — the default disjoint-bin mode "
            "(kernel_size=None) is")
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    xin = _t(x)
    N, C, H, W = [int(s) for s in xin.shape]
    if random_u is not None:
        uh = uw = float(random_u)
    else:  # fresh draw per call AND per dim (the Graham-2014 stochasticity)
        import jax as _jax

        from ...tensor.random import _next_key
        uh, uw = np.asarray(_jax.random.uniform(
            _next_key(), (2,), minval=0.05, maxval=0.95))
    eh = _fractional_edges(H, oh, uh)
    ew = _fractional_edges(W, ow, uw)
    row_bin = np.searchsorted(eh[1:], np.arange(H), side="right")
    col_bin = np.searchsorted(ew[1:], np.arange(W), side="right")
    rb, cb = jnp.asarray(row_bin), jnp.asarray(col_bin)

    def fn(v):
        out = jnp.full((N, C, oh, ow), -jnp.inf, v.dtype)
        n_ix = jnp.arange(N)[:, None, None, None]
        c_ix = jnp.arange(C)[None, :, None, None]
        r_ix = jnp.broadcast_to(rb[None, None, :, None], v.shape)
        w_ix = jnp.broadcast_to(cb[None, None, None, :], v.shape)
        return out.at[n_ix, c_ix, r_ix, w_ix].max(v)
    out = apply_op("fractional_max_pool2d", fn, xin)
    if not return_mask:
        return out
    # mask: flat input index of each bin's max (host-side; the mask is an
    # inference artifact consumed by unpool, not a grad path)
    vnp = np.asarray(xin.numpy())
    mask = np.zeros((N, C, oh, ow), np.int64)
    for i in range(oh):
        for j in range(ow):
            blk = vnp[:, :, eh[i]:eh[i + 1], ew[j]:ew[j + 1]]
            bh = eh[i + 1] - eh[i]
            bw = ew[j + 1] - ew[j]
            am = blk.reshape(N, C, -1).argmax(-1)
            r = am // bw + eh[i]
            c = am % bw + ew[j]
            mask[:, :, i, j] = r * W + c
    return out, Tensor._wrap(jnp.asarray(mask))


def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    """3-D variant: same boundary scheme per spatial dim."""
    if kernel_size is not None:
        raise NotImplementedError(
            "fractional_max_pool3d: kernel_size (overlapping windows) is "
            "not supported — the default disjoint-bin mode is")
    if isinstance(output_size, int):
        output_size = (output_size,) * 3
    od, oh, ow = output_size
    xin = _t(x)
    N, C, D, H, W = [int(s) for s in xin.shape]
    if random_u is not None:
        ud = uh = uw = float(random_u)
    else:
        import jax as _jax

        from ...tensor.random import _next_key
        ud, uh, uw = np.asarray(_jax.random.uniform(
            _next_key(), (3,), minval=0.05, maxval=0.95))
    ed = _fractional_edges(D, od, ud)
    eh = _fractional_edges(H, oh, uh)
    ew = _fractional_edges(W, ow, uw)
    db = jnp.asarray(np.searchsorted(ed[1:], np.arange(D), side="right"))
    rb = jnp.asarray(np.searchsorted(eh[1:], np.arange(H), side="right"))
    cb = jnp.asarray(np.searchsorted(ew[1:], np.arange(W), side="right"))

    def fn(v):
        out = jnp.full((N, C, od, oh, ow), -jnp.inf, v.dtype)
        n_ix = jnp.arange(N)[:, None, None, None, None]
        c_ix = jnp.arange(C)[None, :, None, None, None]
        d_ix = jnp.broadcast_to(db[None, None, :, None, None], v.shape)
        r_ix = jnp.broadcast_to(rb[None, None, None, :, None], v.shape)
        w_ix = jnp.broadcast_to(cb[None, None, None, None, :], v.shape)
        return out.at[n_ix, c_ix, d_ix, r_ix, w_ix].max(v)
    out = apply_op("fractional_max_pool3d", fn, xin)
    if return_mask:
        raise NotImplementedError(
            "fractional_max_pool3d: return_mask is 2d-only here")
    return out


# -- RNN-T loss ---------------------------------------------------------------
def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    """RNN transducer loss (Graves 2012; reference: nn/functional/loss.py
    rnnt_loss -> warprnnt kernel).

    TPU-native: the (T, U) forward-variable DP runs as a lax.scan over T
    with a lax.scan over U inside (log-semiring first-order recurrences);
    everything is batched and traceable, no warp-level kernel needed.
    input: [B, T, U+1, V] logits; label: [B, U]."""
    def fn(logits, lbl, t_len, u_len):
        B, T, U1, V = logits.shape
        U = U1 - 1
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        blank_lp = logp[..., blank]                        # [B, T, U+1]
        lbl_lp = jnp.take_along_axis(
            logp[:, :, :U, :], jnp.broadcast_to(
                lbl[:, None, :, None], (B, T, U, 1)).astype(jnp.int32),
            axis=-1)[..., 0]                               # [B, T, U]
        if fastemit_lambda:
            # FastEmit (Yu et al. 2021): scale label-emission GRADIENTS by
            # (1+lambda) while leaving the loss value unchanged — exactly
            # what warprnnt's fastemit_lambda does.  value(x)=x,
            # grad(x)=(1+lambda)*dx:
            lbl_lp = ((1.0 + fastemit_lambda) * lbl_lp
                      - fastemit_lambda * jax.lax.stop_gradient(lbl_lp))
        NEG = jnp.float32(-1e30)

        def t_step(alpha_prev, t):
            # emit path into row t: alpha_prev[u] + blank[t-1, u]
            from_blank = jnp.where(
                t > 0, alpha_prev + blank_lp[:, jnp.maximum(t - 1, 0), :],
                jnp.where(jnp.arange(U1)[None, :] == 0, 0.0, NEG))

            # label path within row t: alpha[t, u-1] + label[t, u-1]
            def u_step(carry, u):
                lab = jnp.where(
                    u > 0, lbl_lp[:, t, jnp.maximum(u - 1, 0)], NEG)
                val = jnp.logaddexp(from_blank[:, u],
                                    jnp.where(u > 0, carry + lab, NEG))
                val = jnp.where(t == 0,
                                jnp.where(u > 0, carry + lab, 0.0), val)
                return val, val

            _, cols = jax.lax.scan(u_step, jnp.full((B,), NEG),
                                   jnp.arange(U1))
            return jnp.transpose(cols), jnp.transpose(cols)

        _, alphas = jax.lax.scan(t_step, jnp.full((B, U1), NEG),
                                 jnp.arange(T))             # [T, B, U+1]
        alphas = jnp.transpose(alphas, (1, 0, 2))           # [B, T, U+1]
        t_last = (t_len - 1).astype(jnp.int32)
        u_last = u_len.astype(jnp.int32)
        a_final = jnp.take_along_axis(
            jnp.take_along_axis(alphas, t_last[:, None, None],
                                axis=1)[:, 0, :],
            u_last[:, None], axis=1)[:, 0]
        final_blank = jnp.take_along_axis(
            jnp.take_along_axis(blank_lp, t_last[:, None, None],
                                axis=1)[:, 0, :],
            u_last[:, None], axis=1)[:, 0]
        nll = -(a_final + final_blank)
        if reduction == "mean":
            return nll.mean()
        if reduction == "sum":
            return nll.sum()
        return nll
    return apply_op("rnnt_loss", fn, _t(input), _t(label),
                    _t(input_lengths), _t(label_lengths))


# -- adaptive softmax ---------------------------------------------------------
def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None, name=None):
    """Adaptive softmax (Grave et al.; reference: nn/functional/loss.py
    adaptive_log_softmax_with_loss).  Head covers [0, cutoff0) plus one
    logit per tail cluster; cluster i projects down then scores its slice.
    Returns (per-sample log-prob of the target, mean negative loss)."""
    cutoffs = list(cutoffs)
    n_clusters = len(cutoffs)
    head_size = cutoffs[0] + n_clusters

    def fn(xv, lbl, hw, *rest):
        it = list(rest)
        hb = it.pop(0) if head_bias is not None else None
        tails = []
        while it:
            tails.append((it.pop(0), it.pop(0)))  # (proj, cls_w) per cluster
        head = xv @ hw
        if hb is not None:
            head = head + hb
        head_lp = jax.nn.log_softmax(head, axis=-1)
        out = jnp.zeros(lbl.shape, head.dtype)
        in_head = lbl < cutoffs[0]
        out = jnp.where(in_head,
                        jnp.take_along_axis(
                            head_lp, jnp.clip(lbl, 0, head_size - 1)[:, None],
                            axis=1)[:, 0],
                        out)
        # tail cluster i covers [cutoffs[i-1], cutoffs[i]) with
        # cutoffs[-1] meaning cutoffs[0] (the head boundary)
        lo = cutoffs[0]
        for ci, (proj, cls_w) in enumerate(tails):
            hi = cutoffs[ci + 1] if ci + 1 < len(cutoffs) else None
            mask = (lbl >= lo) & ((lbl < hi) if hi is not None
                                  else jnp.ones_like(lbl, bool))
            tail_lp = jax.nn.log_softmax((xv @ proj) @ cls_w, axis=-1)
            rel = jnp.clip(lbl - lo, 0, tail_lp.shape[1] - 1)
            lp = (head_lp[:, cutoffs[0] + ci]
                  + jnp.take_along_axis(tail_lp, rel[:, None], axis=1)[:, 0])
            out = jnp.where(mask, lp, out)
            lo = hi if hi is not None else lo
        return out, -out.mean()

    args = [_t(input), _t(label), _t(head_weight)]
    if head_bias is not None:
        args.append(_t(head_bias))
    for pair in tail_weights:
        args.extend([_t(pair[0]), _t(pair[1])])
    return apply_op("adaptive_log_softmax_with_loss", fn, *args, nout=2)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean", name=None):
    """ArcFace-family margin softmax (reference: nn/functional/loss.py
    margin_cross_entropy -> margin_cross_entropy kernel): target logit
    cos(theta) becomes cos(m1*theta + m2) - m3, everything scaled by s."""
    def fn(lg, lbl):
        N, C = lg.shape
        cos_t = jnp.take_along_axis(lg, lbl[:, None], axis=1)[:, 0]
        theta = jnp.arccos(jnp.clip(cos_t, -1.0 + 1e-7, 1.0 - 1e-7))
        target = jnp.cos(margin1 * theta + margin2) - margin3
        oh = jax.nn.one_hot(lbl, C, dtype=lg.dtype)
        adj = lg * (1 - oh) + target[:, None] * oh
        adj = adj * scale
        lp = jax.nn.log_softmax(adj, axis=-1)
        nll = -jnp.take_along_axis(lp, lbl[:, None], axis=1)[:, 0]
        if reduction == "mean":
            loss = nll.mean()
        elif reduction == "sum":
            loss = nll.sum()
        else:
            loss = nll[:, None]
        if return_softmax:
            return loss, jnp.exp(lp)
        return loss
    return apply_op("margin_cross_entropy", fn, _t(logits), _t(label),
                    nout=2 if return_softmax else 1)
