"""paddle_tpu.nn.functional (reference: python/paddle/nn/functional/)."""

from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import (conv1d, conv1d_transpose, conv2d, conv2d_transpose,  # noqa: F401
                   conv3d, conv3d_transpose)
from .flash_attention import (flash_attention, flash_attn_unpadded,  # noqa: F401
                              scaled_dot_product_attention, sparse_attention)
from .loss import *  # noqa: F401,F403
from .norm import (batch_norm, group_norm, instance_norm, layer_norm,  # noqa: F401
                   local_response_norm, rms_norm, spectral_norm)
from .pooling import *  # noqa: F401,F403
from .extended import (  # noqa: F401
    adaptive_log_softmax_with_loss, affine_grid, class_center_sample, elu_,
    flash_attention_with_sparse_mask, flash_attn_qkvpacked,
    flash_attn_varlen_qkvpacked, fractional_max_pool2d,
    fractional_max_pool3d, gather_tree, grid_sample, hardtanh_,
    hsigmoid_loss, leaky_relu_, margin_cross_entropy, max_unpool1d,
    max_unpool2d, max_unpool3d, multi_margin_loss, pairwise_distance,
    relu_, rnnt_loss, sequence_mask, tanh_, temporal_shift,
    thresholded_relu_)
