"""Convolutions via lax.conv_general_dilated — XLA tiles these directly onto
the MXU (reference kernels: phi/kernels/gpudnn/conv_kernel.cu)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import apply_op, matmul_precision
from ...core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def _ntuple(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(i) for i in (list(v) * n)[:n]) if len(v) == 1 else \
            tuple(int(i) for i in v)
    return (int(v),) * n


def _padding(padding, spatial, strides=None, dilations=None, ksize=None):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * spatial
    padding = list(padding)
    if len(padding) == spatial:
        if isinstance(padding[0], (list, tuple)):
            return [tuple(p) for p in padding]
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * spatial:
        return [(int(padding[2 * i]), int(padding[2 * i + 1]))
                for i in range(spatial)]
    raise ValueError(f"bad padding {padding}")


def _conv(x, weight, bias, stride, padding, dilation, groups, spatial,
          data_format, op_name):
    strides = _ntuple(stride, spatial)
    dilations = _ntuple(dilation, spatial)
    pad = _padding(padding, spatial)
    if data_format in ("NCHW", "NCL", "NCDHW"):
        ln = "NC" + "DHW"[3 - spatial:]
        dn = (ln, "OI" + "DHW"[3 - spatial:], ln)
    else:
        ln = "N" + "DHW"[3 - spatial:] + "C"
        dn = (ln, "OI" + "DHW"[3 - spatial:], ln)

    def fn(v, w, *b):
        out = jax.lax.conv_general_dilated(
            v, w, window_strides=strides, padding=pad,
            rhs_dilation=dilations, feature_group_count=groups,
            dimension_numbers=dn, precision=matmul_precision())
        if b:
            if ln.endswith("C"):
                out = out + b[0].reshape((1,) * (out.ndim - 1) + (-1,))
            else:
                out = out + b[0].reshape((1, -1) + (1,) * spatial)
        return out
    if bias is not None:
        return apply_op(op_name, fn, _t(x), weight, bias)
    return apply_op(op_name, fn, _t(x), weight)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1,
                 data_format, "conv1d")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 data_format, "conv2d")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3,
                 data_format, "conv3d")


def _conv_transpose(x, weight, bias, stride, padding, output_padding,
                    dilation, groups, spatial, data_format, op_name,
                    output_size=None):
    strides = _ntuple(stride, spatial)
    dilations = _ntuple(dilation, spatial)
    opad = _ntuple(output_padding, spatial)
    if isinstance(padding, str):
        p = padding.upper()
        if p == "VALID":
            pads = [(0, 0)] * spatial
        elif p == "SAME":
            # SAME for transpose: output = input * stride, i.e. total pad
            # k_eff - s per dim (reference: conv2d_transpose 'SAME' docs)
            pads = []
            for i in range(spatial):
                k_eff = (int(weight.shape[2 + i]) - 1) * dilations[i] + 1
                if k_eff < strides[i]:
                    raise ValueError(
                        f"{op_name}: padding='SAME' needs kernel_extent "
                        f">= stride (got {k_eff} < {strides[i]} on dim "
                        f"{i}); pass explicit padding/output_padding")
                total = k_eff - strides[i]
                pads.append((total // 2, total - total // 2))
        else:
            raise ValueError(f"{op_name}: padding={padding!r} "
                             "(expected 'SAME'/'VALID' or numbers)")
    else:
        pads = _padding(padding, spatial)
    if output_size is not None:
        if any(o != 0 for o in opad):
            raise ValueError(
                f"{op_name}: output_padding is mutually exclusive with "
                "output_size (reference conv.py raises the same)")
        # reference semantics: output_size disambiguates the
        # stride-ambiguous output dim by choosing output_padding
        # (conv2d_transpose docs: out default + opad, 0 <= opad < stride)
        out_req = _ntuple(output_size, spatial)
        in_sp = ([int(s) for s in x.shape[2:]]
                 if data_format.startswith("NC")
                 else [int(s) for s in x.shape[1:-1]])
        opad = []
        for i in range(spatial):
            k_eff = (int(weight.shape[2 + i]) - 1) * dilations[i] + 1
            base = ((in_sp[i] - 1) * strides[i] + k_eff
                    - pads[i][0] - pads[i][1])
            extra = int(out_req[i]) - base
            if not 0 <= extra < strides[i]:
                raise ValueError(
                    f"{op_name}: output_size[{i}]={out_req[i]} is not "
                    f"reachable (base {base}, stride {strides[i]}; need "
                    f"base <= output_size < base+stride)")
            opad.append(extra)
        opad = tuple(opad)
    ln = ("NC" + "DHW"[3 - spatial:]) if data_format.startswith("NC") \
        else ("N" + "DHW"[3 - spatial:] + "C")
    dn = (ln, "IO" + "DHW"[3 - spatial:], ln)

    # transposed conv = lhs-dilated conv; padding transform: k-1-p
    def fn(v, w, *b):
        kdims = w.shape[2:]
        tpads = [(dilations[i] * (kdims[i] - 1) - pads[i][0],
                  dilations[i] * (kdims[i] - 1) - pads[i][1] + opad[i])
                 for i in range(spatial)]
        # weight layout paddle: [in, out/groups, *k] = IO layout
        w_flip = jnp.flip(w, axis=tuple(range(2, 2 + spatial)))
        if groups > 1:
            ic = w.shape[0]
            ws = jnp.split(w_flip, groups, axis=0)
            vs = jnp.split(v, groups, axis=1 if ln.startswith("NC") else -1)
            outs = [jax.lax.conv_general_dilated(
                vi, jnp.swapaxes(wi, 0, 1), window_strides=(1,) * spatial,
                padding=tpads, lhs_dilation=strides, rhs_dilation=dilations,
                dimension_numbers=(ln, "OI" + "DHW"[3 - spatial:], ln),
                precision=matmul_precision()) for vi, wi in zip(vs, ws)]
            out = jnp.concatenate(outs, axis=1 if ln.startswith("NC") else -1)
        else:
            out = jax.lax.conv_general_dilated(
                v, jnp.swapaxes(w_flip, 0, 1), window_strides=(1,) * spatial,
                padding=tpads, lhs_dilation=strides, rhs_dilation=dilations,
                dimension_numbers=(ln, "OI" + "DHW"[3 - spatial:], ln),
                precision=matmul_precision())
        if b:
            if ln.endswith("C"):
                out = out + b[0].reshape((1,) * (out.ndim - 1) + (-1,))
            else:
                out = out + b[0].reshape((1, -1) + (1,) * spatial)
        return out
    if bias is not None:
        return apply_op(op_name, fn, _t(x), weight, bias)
    return apply_op(op_name, fn, _t(x), weight)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCL", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 1, data_format,
                           "conv1d_transpose", output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 2, data_format,
                           "conv2d_transpose", output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 3, data_format,
                           "conv3d_transpose", output_size)
