"""Loss functionals (reference: python/paddle/nn/functional/loss.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op
from ...core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def _reduce(v, reduction):
    if reduction == "mean":
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    return v


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    """reference: python/paddle/nn/functional/loss.py cross_entropy
    (softmax_with_cross_entropy kernel, phi/kernels/gpu/cross_entropy_kernel.cu)."""
    input, label = _t(input), _t(label)

    def fn(logits, lab, *w):
        ax = axis if axis >= 0 else logits.ndim + axis
        logp = (jax.nn.log_softmax(logits, axis=ax) if use_softmax
                else jnp.log(jnp.maximum(logits, 1e-30)))
        n_class = logits.shape[ax]
        if soft_label or (lab.ndim == logits.ndim and lab.shape[ax] == n_class
                          and jnp.issubdtype(lab.dtype, jnp.floating)):
            soft = lab.astype(logp.dtype)
            if label_smoothing > 0:
                soft = soft * (1 - label_smoothing) + label_smoothing / n_class
            loss = -jnp.sum(soft * logp, axis=ax)
        else:
            li = lab
            if li.ndim == logits.ndim:
                li = jnp.squeeze(li, ax)
            li = li.astype(jnp.int32)
            valid = li != ignore_index
            safe = jnp.where(valid, li, 0)
            picked = jnp.take_along_axis(logp, safe[..., None].astype(jnp.int32)
                                         if ax == logits.ndim - 1 else
                                         jnp.expand_dims(safe, ax), axis=ax)
            picked = jnp.squeeze(picked, ax)
            if label_smoothing > 0:
                smooth_loss = -jnp.mean(logp, axis=ax)
                loss = -(1 - label_smoothing) * picked + \
                    label_smoothing * smooth_loss
            else:
                loss = -picked
            if w:
                loss = loss * jnp.take(w[0], safe)
            loss = jnp.where(valid, loss, 0.0)
            if reduction == "mean":
                denom = (jnp.sum(jnp.take(w[0], safe) * valid) if w
                         else jnp.sum(valid))
                return jnp.sum(loss) / jnp.maximum(denom, 1)
        return _reduce(loss, reduction)
    if weight is not None:
        return apply_op("cross_entropy", fn, input, label, weight)
    return apply_op("cross_entropy", fn, input, label)


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none",
                         axis=axis)
    # paddle returns shape with trailing 1 on the class axis
    from .activation import softmax as _softmax
    from ...tensor.manipulation import unsqueeze
    loss = unsqueeze(loss, axis)
    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    def fn(logp, lab, *w):
        lab = lab.astype(jnp.int32)
        valid = lab != ignore_index
        safe = jnp.where(valid, lab, 0)
        picked = jnp.take_along_axis(logp, safe[:, None], axis=1)[:, 0] \
            if logp.ndim == 2 else \
            jnp.squeeze(jnp.take_along_axis(logp, jnp.expand_dims(safe, 1), 1), 1)
        loss = -picked
        wt = jnp.take(w[0], safe) if w else jnp.ones_like(loss)
        loss = jnp.where(valid, loss * wt, 0.0)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(wt * valid), 1e-12)
        return _reduce(loss, reduction)
    if weight is not None:
        return apply_op("nll_loss", fn, _t(input), _t(label), weight)
    return apply_op("nll_loss", fn, _t(input), _t(label))


def mse_loss(input, label, reduction="mean", name=None):
    return apply_op("mse_loss",
                    lambda a, b: _reduce(jnp.square(a - b), reduction),
                    _t(input), _t(label))


def l1_loss(input, label, reduction="mean", name=None):
    return apply_op("l1_loss",
                    lambda a, b: _reduce(jnp.abs(a - b), reduction),
                    _t(input), _t(label))


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def fn(a, b):
        d = a - b
        loss = jnp.where(jnp.abs(d) < delta, 0.5 * d * d / delta,
                         jnp.abs(d) - 0.5 * delta) * delta
        # paddle: huber variant with delta multiplier folded
        loss = jnp.where(jnp.abs(d) < delta, 0.5 * d * d,
                         delta * (jnp.abs(d) - 0.5 * delta))
        return _reduce(loss, reduction)
    return apply_op("smooth_l1_loss", fn, _t(input), _t(label))


def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    def fn(p, y, *w):
        eps = 1e-12
        loss = -(y * jnp.log(jnp.maximum(p, eps))
                 + (1 - y) * jnp.log(jnp.maximum(1 - p, eps)))
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)
    if weight is not None:
        return apply_op("bce", fn, _t(input), _t(label), weight)
    return apply_op("bce", fn, _t(input), _t(label))


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    def fn(z, y, *rest):
        i = 0
        w = None
        pw = None
        if weight is not None:
            w = rest[i]
            i += 1
        if pos_weight is not None:
            pw = rest[i]
        # numerically stable
        log_sig = jax.nn.log_sigmoid(z)
        log_sig_neg = jax.nn.log_sigmoid(-z)
        if pw is not None:
            loss = -(pw * y * log_sig + (1 - y) * log_sig_neg)
        else:
            loss = -(y * log_sig + (1 - y) * log_sig_neg)
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)
    args = [a for a in (weight, pos_weight) if a is not None]
    return apply_op("bce_with_logits", fn, _t(logit), _t(label), *args)


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def fn(lp, t):
        if log_target:
            loss = jnp.exp(t) * (t - lp)
        else:
            loss = t * (jnp.log(jnp.maximum(t, 1e-12)) - lp)
        if reduction == "batchmean":
            return jnp.sum(loss) / lp.shape[0]
        return _reduce(loss, reduction)
    return apply_op("kl_div", fn, _t(input), _t(label))


def square_error_cost(input, label):
    return apply_op("square_error_cost", lambda a, b: jnp.square(a - b),
                    _t(input), _t(label))


def log_loss(input, label, epsilon=1e-4, name=None):
    return apply_op(
        "log_loss",
        lambda p, y: -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon),
        _t(input), _t(label))


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    return apply_op(
        "margin_ranking_loss",
        lambda a, b, y: _reduce(jnp.maximum(0.0, -y * (a - b) + margin),
                                reduction),
        _t(input), _t(other), _t(label))


def cosine_embedding_loss(input1, input2, label, margin=0, reduction="mean",
                          name=None):
    def fn(a, b, y):
        sim = jnp.sum(a * b, -1) / (jnp.linalg.norm(a, axis=-1)
                                    * jnp.linalg.norm(b, axis=-1) + 1e-12)
        loss = jnp.where(y == 1, 1 - sim, jnp.maximum(0.0, sim - margin))
        return _reduce(loss, reduction)
    return apply_op("cosine_embedding_loss", fn, _t(input1), _t(input2),
                    _t(label))


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def fn(a, pos, neg):
        dp = jnp.sum(jnp.abs(a - pos) ** p, -1) ** (1 / p)
        dn = jnp.sum(jnp.abs(a - neg) ** p, -1) ** (1 / p)
        if swap:
            dn2 = jnp.sum(jnp.abs(pos - neg) ** p, -1) ** (1 / p)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(0.0, dp - dn + margin), reduction)
    return apply_op("triplet_margin_loss", fn, _t(input), _t(positive),
                    _t(negative))


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean", name=None):
    if distance_function is None:
        return triplet_margin_loss(input, positive, negative, margin,
                                   swap=swap, reduction=reduction)
    dp = distance_function(input, positive)
    dn = distance_function(input, negative)
    if swap:
        dn2 = distance_function(positive, negative)
        from ...tensor.math import minimum
        dn = minimum(dn, dn2)
    from ...tensor.math import clip
    loss = clip(dp - dn + margin, min=0.0)
    from ...tensor.math import mean as _mean, sum as _sum
    return _mean(loss) if reduction == "mean" else (
        _sum(loss) if reduction == "sum" else loss)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    return apply_op(
        "hinge_embedding_loss",
        lambda x, y: _reduce(jnp.where(y == 1, x,
                                       jnp.maximum(0.0, margin - x)), reduction),
        _t(input), _t(label))


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def fn(z, y, *n):
        p = jax.nn.sigmoid(z)
        ce = -(y * jax.nn.log_sigmoid(z) + (1 - y) * jax.nn.log_sigmoid(-z))
        pt = p * y + (1 - p) * (1 - y)
        at = alpha * y + (1 - alpha) * (1 - y)
        loss = at * ((1 - pt) ** gamma) * ce
        if n:
            loss = loss / n[0]
        return _reduce(loss, reduction)
    if normalizer is not None:
        return apply_op("sigmoid_focal_loss", fn, _t(logit), _t(label),
                        normalizer)
    return apply_op("sigmoid_focal_loss", fn, _t(logit), _t(label))


def dice_loss(input, label, epsilon=1e-5, name=None):
    def fn(p, y):
        y1 = jax.nn.one_hot(jnp.squeeze(y, -1), p.shape[-1], dtype=p.dtype)
        reduce_dims = tuple(range(1, p.ndim))
        inter = jnp.sum(p * y1, axis=reduce_dims)
        union = jnp.sum(p, axis=reduce_dims) + jnp.sum(y1, axis=reduce_dims)
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))
    return apply_op("dice_loss", fn, _t(input), _t(label))


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean",
                                 name=None):
    def fn(x, y, *w):
        loss = -(y * jax.nn.log_sigmoid(x) + (1 - y) * jax.nn.log_sigmoid(-x))
        loss = jnp.mean(loss, -1)
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)
    if weight is not None:
        return apply_op("multi_label_soft_margin_loss", fn, _t(input),
                        _t(label), weight)
    return apply_op("multi_label_soft_margin_loss", fn, _t(input), _t(label))


def soft_margin_loss(input, label, reduction="mean", name=None):
    return apply_op(
        "soft_margin_loss",
        lambda x, y: _reduce(jnp.log1p(jnp.exp(-y * x)), reduction),
        _t(input), _t(label))


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    def fn(mu, y, var):
        var = jnp.maximum(var, epsilon)
        loss = 0.5 * (jnp.log(var) + jnp.square(y - mu) / var)
        if full:
            loss = loss + 0.5 * jnp.log(2 * jnp.pi)
        return _reduce(loss, reduction)
    return apply_op("gaussian_nll_loss", fn, _t(input), _t(label), _t(variance))


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    def fn(x, y):
        if log_input:
            loss = jnp.exp(x) - y * x
        else:
            loss = x - y * jnp.log(x + epsilon)
        if full:
            stirling = y * jnp.log(y + epsilon) - y + 0.5 * jnp.log(
                2 * jnp.pi * (y + epsilon))
            loss = loss + jnp.where(y > 1, stirling, 0.0)
        return _reduce(loss, reduction)
    return apply_op("poisson_nll_loss", fn, _t(input), _t(label))


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via the standard forward algorithm in log space
    (reference: warpctc third_party dep; here a lax.scan DP — compiler-friendly
    on TPU)."""
    lp = _t(log_probs)  # [T, B, C] paddle layout
    lab = _t(labels)    # [B, S]

    def fn(logp, lbl, in_len, lab_len):
        T, B, C = logp.shape
        S = lbl.shape[1]
        ext = jnp.full((B, 2 * S + 1), blank, dtype=jnp.int32)
        ext = ext.at[:, 1::2].set(lbl.astype(jnp.int32))
        L = 2 * S + 1
        neg_inf = -1e30
        alpha0 = jnp.full((B, L), neg_inf)
        alpha0 = alpha0.at[:, 0].set(logp[0, :, blank])
        first_lab = jnp.take_along_axis(logp[0], ext[:, 1:2], axis=1)[:, 0]
        alpha0 = alpha0.at[:, 1].set(first_lab)

        same_as_prev2 = jnp.concatenate(
            [jnp.ones((B, 2), dtype=bool),
             ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha, logp_t):
            a = alpha
            a1 = jnp.concatenate([jnp.full((B, 1), neg_inf), a[:, :-1]], 1)
            a2 = jnp.concatenate([jnp.full((B, 2), neg_inf), a[:, :-2]], 1)
            a2 = jnp.where(same_as_prev2, neg_inf, a2)
            m = jnp.maximum(jnp.maximum(a, a1), a2)
            m_safe = jnp.where(m == neg_inf, 0.0, m)
            s = (jnp.exp(a - m_safe) + jnp.exp(a1 - m_safe)
                 + jnp.exp(a2 - m_safe))
            new = jnp.where(m == neg_inf, neg_inf, m_safe + jnp.log(s))
            emit = jnp.take_along_axis(logp_t, ext, axis=1)
            return new + emit, None

        alphaT, _ = jax.lax.scan(step, alpha0, logp[1:])
        # pick final two states at position 2*lab_len-1 and 2*lab_len
        idx_last = 2 * lab_len.astype(jnp.int32)
        aT = alphaT
        v1 = jnp.take_along_axis(aT, idx_last[:, None], 1)[:, 0]
        v2 = jnp.take_along_axis(aT, jnp.maximum(idx_last - 1, 0)[:, None], 1)[:, 0]
        m = jnp.maximum(v1, v2)
        ll = m + jnp.log(jnp.exp(v1 - m) + jnp.exp(v2 - m))
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lab_len.astype(loss.dtype), 1))
        return _reduce(loss, reduction)
    return apply_op("ctc_loss", fn, lp, lab, _t(input_lengths),
                    _t(label_lengths))


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    def fn(a, p, y):
        sim = a @ p.T
        B = a.shape[0]
        eq = (y[:, None] == y[None, :]).astype(sim.dtype)
        eq = eq / jnp.sum(eq, axis=1, keepdims=True)
        xent = -jnp.sum(eq * jax.nn.log_softmax(sim, axis=1), axis=1)
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, 1))
                        + jnp.mean(jnp.sum(p * p, 1))) * 0.25
        return jnp.mean(xent) + reg
    return apply_op("npair_loss", fn, _t(anchor), _t(positive), _t(labels))


def mv_loss(*args, **kwargs):
    raise NotImplementedError
