"""paddle_tpu.nn (reference: python/paddle/nn/__init__.py)."""

from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue, clip_grad_norm_, clip_grad_value_  # noqa: F401
from .functional.init_utils import ParamAttr  # noqa: F401
from .layer.activation import *  # noqa: F401,F403
from .layer.common import *  # noqa: F401,F403
from .layer.conv import (Conv1D, Conv1DTranspose, Conv2D, Conv2DTranspose,  # noqa: F401
                         Conv3D, Conv3DTranspose)
from .layer.layers import Layer, LayerList, ParameterList, Sequential  # noqa: F401
from .layer.loss import *  # noqa: F401,F403
from .layer.norm import (BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D,  # noqa: F401
                         GroupNorm, InstanceNorm1D, InstanceNorm2D,
                         InstanceNorm3D, LayerNorm, LocalResponseNorm,
                         RMSNorm, SpectralNorm, SyncBatchNorm)
from .layer.pooling import *  # noqa: F401,F403
from .layer.rnn import (GRU, LSTM, RNN, BiRNN, GRUCell, LSTMCell,  # noqa: F401
                        RNNCellBase, SimpleRNN, SimpleRNNCell)
from .layer.transformer import (MultiHeadAttention, Transformer,  # noqa: F401
                                TransformerDecoder, TransformerDecoderLayer,
                                TransformerEncoder, TransformerEncoderLayer)
from .layer.extended import (  # noqa: F401
    AdaptiveLogSoftmaxWithLoss, BeamSearchDecoder, FractionalMaxPool2D,
    FractionalMaxPool3D, HSigmoidLoss, LayerDict, MaxUnPool1D, MaxUnPool2D,
    MaxUnPool3D, MultiMarginLoss, RNNTLoss, Softmax2D,
    TripletMarginWithDistanceLoss, Unflatten, dynamic_decode)
