"""Common layers (reference: python/paddle/nn/layer/common.py)."""

from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Parameter, Tensor
from .. import functional as F
from ..initializer import Constant, Normal, XavierUniform
from ..functional.init_utils import param_attr_init
from .layers import Layer


class Linear(Layer):
    """y = xW + b, weight [in, out] (reference: nn/layer/common.py Linear)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.weight = param_attr_init((in_features, out_features),
                                      self._dtype, weight_attr, False,
                                      XavierUniform())
        if bias_attr is not False:
            self.bias = param_attr_init((out_features,), self._dtype,
                                        bias_attr, True, Constant(0.0))
        else:
            self.bias = None

    def forward(self, input):
        return F.linear(input, self.weight, self.bias)

    def extra_repr(self):
        return (f"in_features={self._in_features}, "
                f"out_features={self._out_features}")


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = padding_idx
        self._sparse = sparse
        self.weight = param_attr_init((num_embeddings, embedding_dim),
                                      self._dtype, weight_attr, False,
                                      Normal(0.0, 1.0))
        if padding_idx is not None:
            self.weight._data = self.weight._data.at[padding_idx].set(0.0)

    def forward(self, x):
        if self._sparse:
            out = self._forward_sparse(x)
            if out is not None:
                return out
        return F.embedding(x, self.weight, self._padding_idx)

    def _forward_sparse(self, x):
        """sparse=True: backward produces a SelectedRows gradient holding
        only the batch's unique rows (reference: lookup_table_v2_grad's
        is_sparse path).  Eager-only — under jit tracing ids are abstract,
        and XLA's scatter in the dense path is already the fused
        equivalent."""
        import numpy as np

        from ...core.selected_rows import SelectedRows
        from ...core.state import STATE, grad_enabled
        from ...core.tensor import Tensor

        if (STATE.tracing_depth > 0 or not grad_enabled()
                or self.weight.stop_gradient):
            return None
        ids = np.asarray(x.numpy() if hasattr(x, "numpy") else x)
        ids = ids.astype(np.int64)
        uniq, inv = np.unique(ids.reshape(-1), return_inverse=True)
        pulled = Tensor._wrap(self.weight._data[uniq])
        pulled.stop_gradient = False
        weight = self.weight
        height = self._num_embeddings

        def to_selected_rows(grad):
            import jax.numpy as jnp
            if not STATE.accumulating_backward:
                # paddle.grad() promises not to touch .grad; the weight is
                # not in grad()'s graph on the sparse path, so grad(loss,
                # [weight]) raises its usual unused-input error — use
                # sparse=False (or weight.grad via backward()) for that
                return grad
            prev = weight.grad
            if isinstance(prev, SelectedRows):  # microbatch accumulation
                weight.grad = SelectedRows(
                    jnp.concatenate([prev.rows,
                                     jnp.asarray(uniq, jnp.int32)]),
                    jnp.concatenate([prev.values, grad._data]), height)
            elif prev is not None:  # dense + sparse mix: merge to dense
                weight.grad = Tensor._wrap(
                    prev._data
                    + SelectedRows(uniq, grad._data, height).to_dense())
            else:
                weight.grad = SelectedRows(uniq, grad._data, height)
            return grad

        pulled.register_hook(to_selected_rows)
        import paddle_tpu as paddle
        out = paddle.gather(pulled,
                            paddle.to_tensor(inv.astype(np.int32)))
        out = out.reshape(list(ids.shape) + [self._embedding_dim])
        if self._padding_idx is not None:
            # cast on device so bf16/fp16 weights keep their dtype (the
            # dense path's jnp.where does the same via weak typing)
            mask = paddle.to_tensor((ids != self._padding_idx)[..., None])
            out = out * mask.astype(out.dtype)
        return out

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, input):
        return F.dropout(input, self.p, self.axis, self.training, self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, input):
        return F.dropout2d(input, self.p, self.training, self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, input):
        return F.dropout3d(input, self.p, self.training, self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, input):
        return F.alpha_dropout(input, self.p, self.training)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, input):
        from ...tensor.manipulation import flatten
        return flatten(input, self.start_axis, self.stop_axis)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.align_mode = align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode,
                             self.data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = param_attr_init((out_features, in1_features,
                                       in2_features), self._dtype,
                                      weight_attr, False, XavierUniform())
        if bias_attr is not False:
            self.bias = param_attr_init((out_features,), self._dtype,
                                        bias_attr, True, Constant(0.0))
        else:
            self.bias = None

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.downscale_factor = downscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor, self.data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups = groups
        self.data_format = data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p = p
        self.epsilon = epsilon
        self.keepdim = keepdim

    def forward(self, x, y):
        from ...core.dispatch import apply_op
        return apply_op(
            "pairwise_distance",
            lambda a, b: jnp.sum(jnp.abs(a - b + self.epsilon) ** self.p,
                                 axis=-1, keepdims=self.keepdim) ** (1 / self.p),
            x, y)


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, input):
        return input


class Pad1D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL",
                 name=None):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value, self.data_format)


class Pad2D(Pad1D):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW",
                 name=None):
        super().__init__(padding, mode, value, data_format)


class Pad3D(Pad1D):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCDHW", name=None):
        super().__init__(padding, mode, value, data_format)


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.output_sizes = output_sizes
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.fold(x, self.output_sizes, *self.args)
