"""RNN layers over lax.scan (reference: python/paddle/nn/layer/rnn.py; CUDA
used cuDNN RNN kernels — on TPU a lax.scan over fused cell matmuls is the
idiomatic lowering, keeping the whole unroll inside one XLA while-loop)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import apply_op
from ...core.tensor import Tensor
from ..functional.init_utils import param_attr_init
from ..initializer import Uniform
from .layers import Layer, LayerList


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        b = batch_ref.shape[batch_dim_idx]
        from ...tensor.creation import full
        return full([b, self.hidden_size], init_value, dtype or "float32")


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        std = 1.0 / np.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = param_attr_init((hidden_size, input_size), self._dtype,
                                         weight_ih_attr, False, init)
        self.weight_hh = param_attr_init((hidden_size, hidden_size),
                                         self._dtype, weight_hh_attr, False, init)
        self.bias_ih = param_attr_init((hidden_size,), self._dtype,
                                       bias_ih_attr, True, init)
        self.bias_hh = param_attr_init((hidden_size,), self._dtype,
                                       bias_hh_attr, True, init)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def fn(x, h, wi, wh, bi, bh):
            return act(x @ wi.T + bi + h @ wh.T + bh)
        h = apply_op("simple_rnn_cell", fn, inputs, states, self.weight_ih,
                     self.weight_hh, self.bias_ih, self.bias_hh)
        return h, h

    @property
    def state_shape(self):
        return ((self.hidden_size,),)


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 proj_size=0, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / np.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = param_attr_init((4 * hidden_size, input_size),
                                         self._dtype, weight_ih_attr, False,
                                         init)
        self.weight_hh = param_attr_init((4 * hidden_size, hidden_size),
                                         self._dtype, weight_hh_attr, False,
                                         init)
        self.bias_ih = param_attr_init((4 * hidden_size,), self._dtype,
                                       bias_ih_attr, True, init)
        self.bias_hh = param_attr_init((4 * hidden_size,), self._dtype,
                                       bias_hh_attr, True, init)

    def forward(self, inputs, states=None):
        if states is None:
            from ...tensor.creation import zeros
            b = inputs.shape[0]
            states = (zeros([b, self.hidden_size]), zeros([b, self.hidden_size]))
        h0, c0 = states

        def fn(x, h, c, wi, wh, bi, bh):
            gates = x @ wi.T + bi + h @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
            h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
            return h_new, c_new
        h, c = apply_op("lstm_cell", fn, inputs, h0, c0, self.weight_ih,
                        self.weight_hh, self.bias_ih, self.bias_hh, nout=2)
        return h, (h, c)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / np.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = param_attr_init((3 * hidden_size, input_size),
                                         self._dtype, weight_ih_attr, False,
                                         init)
        self.weight_hh = param_attr_init((3 * hidden_size, hidden_size),
                                         self._dtype, weight_hh_attr, False,
                                         init)
        self.bias_ih = param_attr_init((3 * hidden_size,), self._dtype,
                                       bias_ih_attr, True, init)
        self.bias_hh = param_attr_init((3 * hidden_size,), self._dtype,
                                       bias_hh_attr, True, init)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def fn(x, h, wi, wh, bi, bh):
            xg = x @ wi.T + bi
            hg = h @ wh.T + bh
            xr, xz, xn = jnp.split(xg, 3, -1)
            hr, hz, hn = jnp.split(hg, 3, -1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            return (1 - z) * n + z * h
        h = apply_op("gru_cell", fn, inputs, states, self.weight_ih,
                     self.weight_hh, self.bias_ih, self.bias_hh)
        return h, h

    @property
    def state_shape(self):
        return ((self.hidden_size,),)


class RNN(Layer):
    """Run a cell over time via lax.scan (reference: nn/layer/rnn.py RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...tensor.manipulation import stack, transpose, unstack
        x = inputs
        if not self.time_major:
            x = transpose(x, [1, 0, 2])
        steps = unstack(x, axis=0)
        if self.is_reverse:
            steps = steps[::-1]
        states = initial_states
        outs = []
        for s in steps:
            o, states = self.cell(s, states)
            outs.append(o)
        if self.is_reverse:
            outs = outs[::-1]
        out = stack(outs, axis=0)
        if not self.time_major:
            out = transpose(out, [1, 0, 2])
        return out, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...tensor.manipulation import concat
        s_fw, s_bw = (initial_states if initial_states is not None
                      else (None, None))
        o1, f1 = self.rnn_fw(inputs, s_fw)
        o2, f2 = self.rnn_bw(inputs, s_bw)
        return concat([o1, o2], axis=-1), (f1, f2)


class _RNNBase(Layer):
    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, proj_size=0):
        super().__init__()
        self.mode = mode
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        bidirect = direction in ("bidirect", "bidirectional")
        self.num_directions = 2 if bidirect else 1
        kw = dict(weight_ih_attr=weight_ih_attr, weight_hh_attr=weight_hh_attr,
                  bias_ih_attr=bias_ih_attr, bias_hh_attr=bias_hh_attr)
        Cell = {"LSTM": LSTMCell, "GRU": GRUCell,
                "RNN_TANH": SimpleRNNCell, "RNN_RELU": SimpleRNNCell}[mode]

        def mk(in_sz):
            if mode == "RNN_RELU":
                return Cell(in_sz, hidden_size, activation="relu", **kw)
            if mode == "RNN_TANH":
                return Cell(in_sz, hidden_size, activation="tanh", **kw)
            return Cell(in_sz, hidden_size, **kw)

        layers = []
        for i in range(num_layers):
            in_sz = input_size if i == 0 else hidden_size * self.num_directions
            if bidirect:
                layers.append(BiRNN(mk(in_sz), mk(in_sz), time_major))
            else:
                layers.append(RNN(mk(in_sz), False, time_major))
        self.rnns = LayerList(layers)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from .. import functional as Fm
        out = inputs
        finals = []
        for i, rnn in enumerate(self.rnns):
            out, st = rnn(out)
            finals.append(st)
            if self.dropout > 0 and i < self.num_layers - 1:
                out = Fm.dropout(out, self.dropout, training=self.training)
        # pack final states like paddle: [num_layers*num_directions, B, H]
        from ...tensor.manipulation import stack

        def flat(sts):
            res = []
            for s in sts:
                if isinstance(s, tuple) and len(s) == 2 and isinstance(
                        s[0], (tuple, Tensor)):
                    if isinstance(s[0], tuple):  # BiRNN of LSTM
                        res.extend([s[0], s[1]])
                    else:
                        res.append(s)
                else:
                    res.append(s)
            return res
        if self.mode == "LSTM":
            hs, cs = [], []
            for st in finals:
                items = [st] if not isinstance(st, tuple) or isinstance(
                    st[0], Tensor) else list(st)
                # each item is (h, c)
                if isinstance(st, tuple) and isinstance(st[0], tuple):
                    for sub in st:
                        hs.append(sub[0])
                        cs.append(sub[1])
                else:
                    hs.append(st[0])
                    cs.append(st[1])
            return out, (stack(hs, 0), stack(cs, 0))
        hs = []
        for st in finals:
            if isinstance(st, tuple):
                hs.extend(list(st))
            else:
                hs.append(st)
        return out, stack(hs, 0)


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kwargs):
        mode = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(mode, input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kwargs)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 proj_size=0, **kwargs):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kwargs)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kwargs):
        super().__init__("GRU", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kwargs)
