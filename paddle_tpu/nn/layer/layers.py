"""Layer base class (reference: python/paddle/nn/layer/layers.py — ``Layer``
with hooks/state_dict/sublayers; the C++ twin was dygraph VarBase tracking,
which TPU does not need: parameters are plain device arrays)."""

from __future__ import annotations

import collections

import jax.numpy as jnp
import numpy as np

from ...core import dtype as dtypes
from ...core.state import bump_param_version, no_grad_guard
from ...core.tensor import Parameter, Tensor


class HookRemoveHelper:
    def __init__(self, hooks, key):
        self._hooks, self._key = hooks, key

    def remove(self):
        self._hooks.pop(self._key, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self._parameters = collections.OrderedDict()
        self._sub_layers = collections.OrderedDict()
        self._buffers = collections.OrderedDict()
        self._non_persistable_buffer_names_set = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self.training = True
        self._dtype = dtypes.convert_dtype(dtype)
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # -- attribute plumbing --------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call super().__init__() first")
            bump_param_version()  # flush device state before the rebind
            params[name] = value
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            object.__setattr__(self, name, value)
        elif isinstance(value, Layer):
            layers[name] = value
            object.__setattr__(self, name, value)
        elif params is not None and name in params:
            if value is None:
                del params[name]
            elif isinstance(value, Tensor):
                params[name].set_value(value)
                return
            object.__setattr__(self, name, value)
        elif buffers is not None and name in buffers:
            if isinstance(value, Tensor) or value is None:
                bump_param_version()  # flush device state before the rebind
                buffers[name] = value
            object.__setattr__(self, name, value)
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
        if name in self.__dict__:
            object.__delattr__(self, name)

    # -- parameter/buffer creation ------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        from ..initializer import Constant, XavierUniform
        from ..functional.init_utils import param_attr_init
        dtype = dtypes.convert_dtype(dtype) or self._dtype
        init = default_initializer
        attr_obj = attr
        if attr_obj is False:
            return None
        return param_attr_init(shape, dtype, attr_obj, is_bias, init)

    def add_parameter(self, name, parameter):
        if parameter is None:
            self._parameters[name] = None
            object.__setattr__(self, name, None)
        else:
            setattr(self, name, parameter)
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        object.__setattr__(self, str(name), sublayer)
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names_set.add(name)
        object.__setattr__(self, name, tensor)

    # -- iteration -----------------------------------------------------------
    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, lay in (self.named_sublayers(prefix=prefix, include_self=True)
                          if include_sublayers else [(prefix, self)]):
            for pname, p in lay._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{name}.{pname}" if name else pname), p

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for name, lay in (self.named_sublayers(prefix=prefix, include_self=True)
                          if include_sublayers else [(prefix, self)]):
            for bname, b in lay._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{name}.{bname}" if name else bname), b

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        if layers_set is None:
            layers_set = set()
        if id(self) in layers_set:
            return
        layers_set.add(id(self))
        if include_self:
            yield prefix, self
        for name, lay in self._sub_layers.items():
            if lay is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from lay.named_sublayers(prefix=sub_prefix,
                                           include_self=True,
                                           layers_set=layers_set)

    def children(self):
        return iter(self._sub_layers.values())

    def named_children(self):
        return iter(self._sub_layers.items())

    def apply(self, fn):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    # -- modes ---------------------------------------------------------------
    def train(self):
        for l in self.sublayers(include_self=True):
            l.training = True
        return self

    def eval(self):
        for l in self.sublayers(include_self=True):
            l.training = False
        return self

    # -- hooks ---------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        key = len(self._forward_pre_hooks)
        self._forward_pre_hooks[key] = hook
        return HookRemoveHelper(self._forward_pre_hooks, key)

    def register_forward_post_hook(self, hook):
        key = len(self._forward_post_hooks)
        self._forward_post_hooks[key] = hook
        return HookRemoveHelper(self._forward_post_hooks, key)

    # -- call ----------------------------------------------------------------
    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            out = hook(self, inputs, outputs)
            if out is not None:
                outputs = out
        return outputs

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def extra_repr(self):
        return ""

    def __repr__(self):
        lines = []
        for name, sub in self._sub_layers.items():
            mod_str = repr(sub)
            mod_str = "\n  ".join(mod_str.split("\n"))
            lines.append(f"({name}): {mod_str}")
        main = self.__class__.__name__ + "(" + self.extra_repr()
        if lines:
            main += "\n  " + "\n  ".join(lines) + "\n"
        return main + ")"

    # -- state dict ----------------------------------------------------------
    def _sync_from_train_step(self):
        """If a device-resident train step (jit.CompiledTrainStep) owns this
        layer's live state, pull it back into the Parameter/buffer objects so
        host-side reads (state_dict, checkpointing) see post-step values."""
        src = self.__dict__.get("_train_step_owner")
        step = src() if src is not None else None
        if step is not None:
            step.sync()

    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        self._sync_from_train_step()
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters():
            dest[structured_name_prefix + name] = p
        for name, b in self.named_buffers():
            short = name.rsplit(".", 1)[-1]
            if short in self._non_persistable_buffer_names_set:
                continue
            dest[structured_name_prefix + name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        bump_param_version()  # flush device state, then load on top of it
        missing, unexpected = [], []
        own = dict(self.named_parameters())
        own.update(dict(self.named_buffers()))
        for k, v in state_dict.items():
            if k in own:
                tgt = own[k]
                val = v._data if isinstance(v, Tensor) else jnp.asarray(
                    np.asarray(v))
                if tuple(tgt._data.shape) != tuple(val.shape):
                    raise ValueError(
                        f"shape mismatch for {k}: {tgt._data.shape} vs "
                        f"{val.shape}")
                with no_grad_guard():
                    tgt._data = val.astype(tgt._data.dtype)
            else:
                unexpected.append(k)
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    load_dict = set_state_dict

    # -- dtype / device ------------------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            bump_param_version()  # flush device state, then cast on top
            dt = dtypes.convert_dtype(dtype)
            for p in self.parameters():
                p._data = p._data.astype(dt)
            for _, b in self.named_buffers():
                if dtypes.is_floating(b._data.dtype):
                    b._data = b._data.astype(dt)
            self._dtype = dt
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def half(self):
        return self.to(dtype="float16")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_gradient()

    def full_name(self):
        return self._name_scope


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        keys = list(self._sub_layers)
        return self._sub_layers[keys[idx]]

    def __setitem__(self, idx, layer):
        keys = list(self._sub_layers)
        self.add_sublayer(keys[idx], layer)

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def append(self, layer):
        self.add_sublayer(str(len(self)), layer)
        return self

    def insert(self, index, layer):
        layers = list(self._sub_layers.values())
        layers.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def extend(self, layers):
        for l in layers:
            self.append(l)
        return self


class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], collections.OrderedDict):
            for name, l in layers[0].items():
                self.add_sublayer(name, l)
        else:
            for i, l in enumerate(layers):
                if isinstance(l, tuple):
                    self.add_sublayer(l[0], l[1])
                else:
                    self.add_sublayer(str(i), l)

    def __getitem__(self, idx):
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def forward(self, input):
        for layer in self._sub_layers.values():
            input = layer(input)
        return input


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def __getitem__(self, idx):
        return self._parameters[str(idx)]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())

    def append(self, parameter):
        self.add_parameter(str(len(self._parameters)), parameter)
        return self
