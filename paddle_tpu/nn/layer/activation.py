"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""

from __future__ import annotations

from ...core.tensor import Parameter
from .. import functional as F
from ..functional.init_utils import param_attr_init
from ..initializer import Constant
from .layers import Layer


def _mk(name, fn_name, **fixed):
    def __init__(self, *args, **kwargs):
        Layer.__init__(self)
        self._kwargs = {**fixed}
        sig = _SIGS.get(fn_name, ())
        for i, a in enumerate(args):
            if i < len(sig):
                self._kwargs[sig[i]] = a
        for k, v in kwargs.items():
            if k != "name":
                self._kwargs[k] = v

    def forward(self, x):
        return getattr(F, fn_name)(x, **self._kwargs)

    return type(name, (Layer,), {"__init__": __init__, "forward": forward})


_SIGS = {
    "elu": ("alpha",),
    "celu": ("alpha",),
    "gelu": ("approximate",),
    "hardshrink": ("threshold",),
    "hardtanh": ("min", "max"),
    "hardsigmoid": ("slope", "offset"),
    "leaky_relu": ("negative_slope",),
    "log_softmax": ("axis",),
    "maxout": ("groups", "axis"),
    "softmax": ("axis",),
    "softplus": ("beta", "threshold"),
    "softshrink": ("threshold",),
    "thresholded_relu": ("threshold", "value"),
    "rrelu": ("lower", "upper"),
    "glu": ("axis",),
}

ReLU = _mk("ReLU", "relu")
ReLU6 = _mk("ReLU6", "relu6")
ELU = _mk("ELU", "elu")
CELU = _mk("CELU", "celu")
SELU = _mk("SELU", "selu")
GELU = _mk("GELU", "gelu")
Hardshrink = _mk("Hardshrink", "hardshrink")
Hardsigmoid = _mk("Hardsigmoid", "hardsigmoid")
Hardswish = _mk("Hardswish", "hardswish")
Hardtanh = _mk("Hardtanh", "hardtanh")
LeakyReLU = _mk("LeakyReLU", "leaky_relu")
LogSigmoid = _mk("LogSigmoid", "log_sigmoid")
LogSoftmax = _mk("LogSoftmax", "log_softmax")
Maxout = _mk("Maxout", "maxout")
Mish = _mk("Mish", "mish")
Sigmoid = _mk("Sigmoid", "sigmoid")
Silu = _mk("Silu", "silu")
Swish = _mk("Swish", "silu")
Softmax = _mk("Softmax", "softmax")
Softplus = _mk("Softplus", "softplus")
Softshrink = _mk("Softshrink", "softshrink")
Softsign = _mk("Softsign", "softsign")
Tanh = _mk("Tanh", "tanh")
Tanhshrink = _mk("Tanhshrink", "tanhshrink")
ThresholdedReLU = _mk("ThresholdedReLU", "thresholded_relu")
GLU = _mk("GLU", "glu")


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = param_attr_init((num_parameters,), self._dtype,
                                      weight_attr, False, Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8, upper=1.0 / 3, name=None):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, self.training)
