"""Layer breadth (reference: python/paddle/nn/layer/ — the classes wrapping
functional/extended.py plus containers and seq2seq decoding)."""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ...core.tensor import Tensor
from .. import functional as F
from ..functional.init_utils import param_attr_init
from .layers import Layer


class Softmax2D(Layer):
    """Softmax over the channel dim of NCHW (reference:
    nn/layer/activation.py Softmax2D)."""

    def forward(self, x):
        if x.ndim not in (3, 4):
            raise ValueError(f"Softmax2D expects 3D/4D input, got {x.ndim}D")
        return F.softmax(x, axis=-3)


class Unflatten(Layer):
    """Split one dim into a shape (reference: nn/layer/common.py
    Unflatten)."""

    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis = axis
        self.shape = list(shape)

    def forward(self, x):
        import paddle_tpu as paddle
        new = list(x.shape)
        ax = self.axis % len(new)
        new[ax:ax + 1] = self.shape
        return paddle.reshape(x, new)


class LayerDict(Layer):
    """Dict container of sublayers (reference: nn/layer/container.py
    LayerDict)."""

    def __init__(self, sublayers=None):
        super().__init__()
        self._dict_keys = []
        if sublayers:
            self.update(sublayers)

    def __getitem__(self, key):
        return getattr(self, key)

    def __setitem__(self, key, layer):
        if key not in self._dict_keys:
            self._dict_keys.append(key)
        setattr(self, key, layer)

    def __delitem__(self, key):
        self._dict_keys.remove(key)
        delattr(self, key)

    def __len__(self):
        return len(self._dict_keys)

    def __iter__(self):
        return iter(self._dict_keys)

    def __contains__(self, key):
        return key in self._dict_keys

    def keys(self):
        return list(self._dict_keys)

    def values(self):
        return [self[k] for k in self._dict_keys]

    def items(self):
        return [(k, self[k]) for k in self._dict_keys]

    def update(self, sublayers):
        pairs = sublayers.items() if isinstance(sublayers, dict) \
            else sublayers
        for k, v in pairs:
            self[k] = v


class _UnpoolBase(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format=None, output_size=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.data_format = data_format
        self.output_size = output_size


class MaxUnPool1D(_UnpoolBase):
    def forward(self, x, indices):
        return F.max_unpool1d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.data_format or "NCL",
                              self.output_size)


class MaxUnPool2D(_UnpoolBase):
    def forward(self, x, indices):
        return F.max_unpool2d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.data_format or "NCHW",
                              self.output_size)


class MaxUnPool3D(_UnpoolBase):
    def forward(self, x, indices):
        return F.max_unpool3d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.data_format or "NCDHW",
                              self.output_size)


class FractionalMaxPool2D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.kernel_size = kernel_size
        self.random_u = random_u
        self.return_mask = return_mask

    def forward(self, x):
        return F.fractional_max_pool2d(x, self.output_size,
                                       self.kernel_size, self.random_u,
                                       self.return_mask)


class FractionalMaxPool3D(FractionalMaxPool2D):
    def forward(self, x):
        return F.fractional_max_pool3d(x, self.output_size,
                                       self.kernel_size, self.random_u,
                                       self.return_mask)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self.p, self.margin = p, margin
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.multi_margin_loss(input, label, self.p, self.margin,
                                   self.weight, self.reduction)


class TripletMarginWithDistanceLoss(Layer):
    """Triplet loss with a pluggable distance callable (reference:
    nn/layer/loss.py TripletMarginWithDistanceLoss)."""

    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.dist = distance_function or (
            lambda a, b: F.pairwise_distance(a, b))
        self.margin, self.swap, self.reduction = margin, swap, reduction

    def forward(self, anchor, positive, negative):
        import paddle_tpu as paddle
        d_pos = self.dist(anchor, positive)
        d_neg = self.dist(anchor, negative)
        if self.swap:
            d_neg = paddle.minimum(d_neg, self.dist(positive, negative))
        loss = paddle.clip(d_pos - d_neg + self.margin, min=0.0)
        if self.reduction == "mean":
            return loss.mean()
        if self.reduction == "sum":
            return loss.sum()
        return loss


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid classifier head (reference: nn/layer/loss.py
    HSigmoidLoss — holds the internal-node weight table)."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        if is_custom:
            raise NotImplementedError("HSigmoidLoss: custom trees are not "
                                      "supported (default tree only)")
        self.num_classes = num_classes
        self.weight = param_attr_init((num_classes - 1, feature_size),
                                      self._dtype, weight_attr, False, None)
        self.bias = (param_attr_init((num_classes - 1,), self._dtype,
                                     bias_attr, True, None)
                     if bias_attr is not False else None)

    def forward(self, input, label):
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               self.bias)


class RNNTLoss(Layer):
    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self.blank = blank
        self.fastemit_lambda = fastemit_lambda
        self.reduction = reduction

    def forward(self, input, label, input_lengths, label_lengths):
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           self.blank, self.fastemit_lambda, self.reduction)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """Adaptive softmax head (reference: nn/layer/loss.py
    AdaptiveLogSoftmaxWithLoss): head covers the frequent classes + one
    logit per tail cluster; cluster i projects to in_features//div_value^i
    then scores its class slice."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, name=None):
        super().__init__()
        cutoffs = list(cutoffs)
        if not cutoffs or cutoffs != sorted(set(cutoffs)) \
                or cutoffs[-1] > n_classes:
            raise ValueError(f"bad cutoffs {cutoffs} for {n_classes}")
        if cutoffs[-1] != n_classes:
            cutoffs = cutoffs + [n_classes]
        self.cutoffs = cutoffs
        self.n_clusters = len(cutoffs) - 1
        head_size = cutoffs[0] + self.n_clusters
        self.head_weight = param_attr_init((in_features, head_size),
                                           self._dtype, None, False, None)
        self.head_bias = (param_attr_init((head_size,), self._dtype, None,
                                          True, None) if head_bias else None)
        self.tail_weights = []
        for i in range(self.n_clusters):
            hsz = max(1, int(in_features / (div_value ** (i + 1))))
            osz = cutoffs[i + 1] - cutoffs[i]
            proj = param_attr_init((in_features, hsz), self._dtype, None,
                                   False, None)
            cls_w = param_attr_init((hsz, osz), self._dtype, None, False,
                                    None)
            setattr(self, f"tail_proj_{i}", proj)
            setattr(self, f"tail_cls_{i}", cls_w)
            self.tail_weights.append((proj, cls_w))

    def forward(self, input, label):
        out, loss = F.adaptive_log_softmax_with_loss(
            input, label, self.head_weight, self.tail_weights,
            self.cutoffs[:-1] if len(self.cutoffs) > 1 else self.cutoffs,
            self.head_bias)
        return out, loss


class BeamSearchDecoder:
    """Beam-search decoder over an RNN cell (reference:
    nn/layer/rnn.py BeamSearchDecoder).  Host-driven expand/top-k per step
    (the reference's dynamic_decode loop is host-driven too); finalize
    walks parents via gather_tree."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = beam_size
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    def initialize(self, initial_cell_states):
        import paddle_tpu as paddle
        states = initial_cell_states
        B = int(jnp.asarray(states[0]._data).shape[0]) \
            if isinstance(states, (list, tuple)) else \
            int(states._data.shape[0])
        K = self.beam_size
        tok = paddle.to_tensor(np.full((B, K), self.start_token, np.int64))
        # beam 0 live, others -inf so step one expands a single beam
        lp = paddle.to_tensor(
            np.tile(np.array([[0.0] + [-1e9] * (K - 1)], np.float32),
                    (B, 1)))
        tile = (lambda s: paddle.to_tensor(np.repeat(
            np.asarray(s.numpy()), K, axis=0)))
        states = [tile(s) for s in states] \
            if isinstance(states, (list, tuple)) else tile(states)
        fin = paddle.to_tensor(np.zeros((B, K), bool))
        return tok, lp, states, fin

    def step(self, tok, log_probs, states, finished):
        import paddle_tpu as paddle
        B, K = tok.shape
        inp = self.embedding_fn(tok.reshape([B * K])) \
            if self.embedding_fn else tok.reshape([B * K, 1]).astype(
                "float32")
        out, new_states = self.cell(inp, states)
        logits = self.output_fn(out) if self.output_fn else out
        V = logits.shape[-1]
        step_lp = np.array(
            paddle.nn.functional.log_softmax(logits).numpy(),
            copy=True).reshape(B, K, V)
        # finished beams only extend with end_token at zero cost
        fin = np.asarray(finished.numpy())
        for b in range(B):
            for k in range(K):
                if fin[b, k]:
                    step_lp[b, k, :] = -1e9
                    step_lp[b, k, self.end_token] = 0.0
        total = np.asarray(log_probs.numpy())[:, :, None] + step_lp
        flat = total.reshape(B, K * V)
        top = np.argsort(-flat, axis=1)[:, :K]
        parent = top // V
        token = top % V
        new_lp = np.take_along_axis(flat, top, axis=1)
        new_fin = np.take_along_axis(fin, parent, axis=1) | (
            token == self.end_token)

        def pick(s):
            arr = np.asarray(s.numpy()).reshape((B, K) + s.numpy().shape[1:])
            out = np.stack([arr[b, parent[b]] for b in range(B)])
            return paddle.to_tensor(out.reshape((B * K,) + out.shape[2:]))
        new_states = [pick(s) for s in new_states] \
            if isinstance(new_states, (list, tuple)) else pick(new_states)
        return (paddle.to_tensor(token), paddle.to_tensor(
            new_lp.astype(np.float32)), new_states,
            paddle.to_tensor(new_fin), paddle.to_tensor(parent))


def dynamic_decode(decoder, inits=None, max_step_num=None, output_time_major
                   =False, impute_finished=False, is_test=False,
                   return_length=False, **kwargs):
    """Run a decoder until every beam finishes or max_step_num (reference:
    nn/layer/rnn.py dynamic_decode)."""
    import paddle_tpu as paddle
    tok, lp, states, finished = decoder.initialize(inits)
    ids_steps, parent_steps = [], []
    steps = max_step_num or 64
    for _ in range(steps):
        tok, lp, states, finished, parent = decoder.step(
            tok, lp, states, finished)
        ids_steps.append(np.asarray(tok.numpy()))
        parent_steps.append(np.asarray(parent.numpy()))
        if bool(np.asarray(finished.numpy()).all()):
            break
    ids = paddle.to_tensor(np.stack(ids_steps))        # [T, B, K]
    parents = paddle.to_tensor(np.stack(parent_steps))
    full = F.gather_tree(ids, parents)
    if not output_time_major:
        full = paddle.to_tensor(
            np.transpose(np.asarray(full.numpy()), (1, 2, 0)))
    if return_length:
        arr = np.asarray(full.numpy())
        time_axis = 0 if output_time_major else -1
        lens = (arr != decoder.end_token).sum(time_axis)
        return full, lp, paddle.to_tensor(lens)
    return full, lp
