"""Norm layers (reference: python/paddle/nn/layer/norm.py)."""

from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor
from .. import functional as F
from ..functional.init_utils import param_attr_init
from ..initializer import Constant
from .layers import Layer


class _NormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is not False:
            self.weight = param_attr_init((num_features,), self._dtype,
                                          weight_attr, False, Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = param_attr_init((num_features,), self._dtype,
                                        bias_attr, True, Constant(0.0))
        else:
            self.bias = None
        self.register_buffer("_mean",
                             Tensor(jnp.zeros((num_features,), jnp.float32)))
        self.register_buffer("_variance",
                             Tensor(jnp.ones((num_features,), jnp.float32)))


class BatchNorm1D(_NormBase):
    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight,
                            self.bias, self.training, self._momentum,
                            self._epsilon,
                            "NCL" if self._data_format in ("NCHW", "NCL") else "NLC",
                            self._use_global_stats)


class BatchNorm2D(_NormBase):
    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight,
                            self.bias, self.training, self._momentum,
                            self._epsilon, self._data_format,
                            self._use_global_stats)


class BatchNorm3D(_NormBase):
    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight,
                            self.bias, self.training, self._momentum,
                            self._epsilon,
                            "NCDHW" if self._data_format in ("NCHW", "NCDHW") else "NDHWC",
                            self._use_global_stats)


BatchNorm = BatchNorm2D


class SyncBatchNorm(_NormBase):
    """Cross-replica batchnorm. Under pjit/GSPMD batch stats are computed over
    the global (sharded) batch automatically — so this equals BatchNorm in
    compiled mode (reference: python/paddle/nn/layer/norm.py SyncBatchNorm)."""

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight,
                            self.bias, self.training, self._momentum,
                            self._epsilon, self._data_format,
                            self._use_global_stats)

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self._normalized_shape = ((normalized_shape,)
                                  if isinstance(normalized_shape, int)
                                  else tuple(normalized_shape))
        self._epsilon = epsilon
        if weight_attr is not False:
            self.weight = param_attr_init(self._normalized_shape, self._dtype,
                                          weight_attr, False, Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = param_attr_init(self._normalized_shape, self._dtype,
                                        bias_attr, True, Constant(0.0))
        else:
            self.bias = None

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)


class RMSNorm(Layer):
    """RMSNorm layer — TPU hot path uses the Pallas fused kernel via
    functional.rms_norm (reference: incubate fused_rms_norm)."""

    def __init__(self, normalized_shape, epsilon=1e-6, weight_attr=None,
                 name=None):
        super().__init__()
        shape = ((normalized_shape,) if isinstance(normalized_shape, int)
                 else tuple(normalized_shape))
        self._epsilon = epsilon
        self.weight = param_attr_init(shape, self._dtype, weight_attr, False,
                                      Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is not False:
            self.weight = param_attr_init((num_channels,), self._dtype,
                                          weight_attr, False, Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = param_attr_init((num_channels,), self._dtype,
                                        bias_attr, True, Constant(0.0))
        else:
            self.bias = None

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class InstanceNorm1D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is not False:
            self.scale = param_attr_init((num_features,), self._dtype,
                                         weight_attr, False, Constant(1.0))
        else:
            self.scale = None
        if bias_attr is not False:
            self.bias = param_attr_init((num_features,), self._dtype,
                                        bias_attr, True, Constant(0.0))
        else:
            self.bias = None

    def forward(self, x):
        return F.instance_norm(x, weight=self.scale, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm2D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, data_format)


class InstanceNorm3D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, data_format)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._epsilon = epsilon
        import numpy as np
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        from ..initializer import Normal
        self.weight_u = param_attr_init((h,), self._dtype, None, False,
                                        Normal(0.0, 1.0))
        self.weight_v = param_attr_init((w,), self._dtype, None, False,
                                        Normal(0.0, 1.0))

    def forward(self, x):
        return F.spectral_norm(x, self.weight_u, self.weight_v, self._dim,
                               self._power_iters, self._epsilon)
