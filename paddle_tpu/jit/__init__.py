"""paddle_tpu.jit — whole-program capture.

Reference analogue: paddle.jit (dy2static AST transpile + SOT bytecode capture,
python/paddle/jit/ — 33k LoC) feeding PIR + CINN.

TPU-native redesign: the eager layer executes jnp calls on ``Tensor._data``;
under ``jax.jit`` those same calls trace symbolically, so "dynamic-to-static"
needs no AST rewriting or frame-eval hook — ``to_static`` simply
functionalizes a Layer (parameters/buffers become pytree inputs, mutated
buffers become outputs) and hands the python callable to ``jax.jit``.  The
autograd tape also traces, so an entire train step (forward + backward +
optimizer update) compiles into ONE XLA program — the analogue of the
reference's static-graph executor running a whole Program, with XLA playing
CINN's role.  Guards/retrace are keyed by jax's abstract signature
(shape/dtype/pytree), matching SOT guard semantics.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as _P

from ..analysis import program_audit as _audit
from ..core import flags as _flags
from ..core.state import STATE, no_grad_guard
from ..core.tensor import Parameter, Tensor
from ..profiler import counters as _counters
from ..profiler import devicetime as _devicetime
from ..profiler import flight as _flight
from ..profiler import host_tracer as _trace
from ..profiler import metrics as _metrics


def _is_layer(obj):
    from ..nn.layer.layers import Layer
    return isinstance(obj, Layer)


# ---------------------------------------------------------------------------
# State (de)hydration: Layer/Optimizer <-> pytree of jax arrays
#
# The jit.host.* counters (profiler.counters) tally every hydrate/bind that
# runs as eager host work (trace-time binds inside jax.jit are one-time
# compile cost and excluded), so the perf contract of CompiledTrainStep
# ("zero per-parameter host work in steady state") is checkable:
# scripts/bench_smoke.py and scripts/check_counters.py snapshot the registry
# around steady-state steps and assert no movement.
# ---------------------------------------------------------------------------
_HOST_SYNC_KEYS = ("layer_state", "bind_layer_state", "optimizer_state",
                   "bind_optimizer_state")


def host_sync_counts():
    """Hydrate/bind call counters, as a plain dict (back-compat view over
    the jit.host.* entries of profiler.counters)."""
    return {k: _counters.get("jit.host." + k) for k in _HOST_SYNC_KEYS}


def layer_state(layer):
    if STATE.tracing_depth == 0:
        _counters.inc("jit.host.layer_state")
    params = {k: p._data for k, p in layer.named_parameters()}
    buffers = {k: b._data for k, b in layer.named_buffers()}
    return params, buffers


def bind_layer_state(layer, params, buffers):
    if STATE.tracing_depth == 0:
        _counters.inc("jit.host.bind_layer_state")
    for k, p in layer.named_parameters():
        if k in params:
            p._data = params[k]
    for k, b in layer.named_buffers():
        if k in buffers:
            b._data = buffers[k]


def optimizer_state(opt):
    if STATE.tracing_depth == 0:
        _counters.inc("jit.host.optimizer_state")
    accs = {name: dict(store) for name, store in opt._accumulators.items()}
    masters = dict(opt._master_weights)
    return {"acc": accs, "master": masters}


def bind_optimizer_state(opt, state):
    if STATE.tracing_depth == 0:
        _counters.inc("jit.host.bind_optimizer_state")
    opt._accumulators = {name: dict(store)
                         for name, store in state["acc"].items()}
    opt._master_weights = dict(state["master"])


class StaticFunction:
    """Compiled wrapper over a python function or Layer.forward
    (reference analogue: jit/dy2static/program_translator.py:321
    StaticFunction)."""

    def __init__(self, fn, layer=None, build_strategy=None,
                 full_graph=True, backend=None, input_spec=None):
        self._fn = fn
        self._layer = layer
        self._cache = {}
        functools.update_wrapper(self, fn)

    def _compiled(self, train_flag):
        if train_flag in self._cache:
            return self._cache[train_flag]

        def runner(params, buffers, args, kwargs):
            _counters.inc("jit.traces")  # body runs as python only per trace
            if self._layer is not None:
                bind_layer_state(self._layer, params, buffers)
            wargs = jax.tree_util.tree_map(
                lambda x: Tensor._wrap(x) if isinstance(
                    x, (jax.Array, jax.core.Tracer)) else x, args)
            wkwargs = jax.tree_util.tree_map(
                lambda x: Tensor._wrap(x) if isinstance(
                    x, (jax.Array, jax.core.Tracer)) else x, kwargs)
            STATE.tracing_depth += 1
            try:
                with no_grad_guard():
                    out = self._fn(*wargs, **wkwargs)
            finally:
                STATE.tracing_depth -= 1
            out_data = jax.tree_util.tree_map(
                lambda x: x._data if isinstance(x, Tensor) else x, out,
                is_leaf=lambda x: isinstance(x, Tensor))
            new_buffers = ({k: b._data for k, b in
                            self._layer.named_buffers()}
                           if self._layer is not None else {})
            return out_data, new_buffers

        jitted = jax.jit(runner)
        self._cache[train_flag] = jitted
        return jitted

    def __call__(self, *args, **kwargs):
        with _trace.span("jit.static_function"):
            params, buffers = (layer_state(self._layer)
                               if self._layer is not None else ({}, {}))
            args_data = jax.tree_util.tree_map(
                lambda x: x._data if isinstance(x, Tensor) else x, args,
                is_leaf=lambda x: isinstance(x, Tensor))
            kwargs_data = jax.tree_util.tree_map(
                lambda x: x._data if isinstance(x, Tensor) else x, kwargs,
                is_leaf=lambda x: isinstance(x, Tensor))
            training = (self._layer.training if self._layer is not None
                        else False)
            traces_before = _counters.get("jit.traces")
            out_data, new_buffers = self._compiled(training)(
                params, buffers, args_data, kwargs_data)
            _counters.inc("jit.cache_hits"
                          if _counters.get("jit.traces") == traces_before
                          else "jit.cache_misses")
            if self._layer is not None:
                for k, b in self._layer.named_buffers():
                    if k in new_buffers:
                        b._data = new_buffers[k]
            return jax.tree_util.tree_map(
                lambda x: Tensor._wrap(x) if isinstance(x, jax.Array) else x,
                out_data)

    @property
    def code(self):
        import inspect
        try:
            return inspect.getsource(self._fn)
        except OSError:
            return "<source unavailable>"

    def concrete_program(self):
        return None


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, full_graph=True):
    """paddle.jit.to_static (reference: jit/api.py to_static)."""
    def decorate(obj):
        if _is_layer(obj):
            obj.forward = StaticFunction(obj.forward, layer=obj)
            return obj
        if hasattr(obj, "__self__") and _is_layer(obj.__self__):
            return StaticFunction(obj, layer=obj.__self__)
        return StaticFunction(obj)
    if function is None:
        return decorate
    return decorate(function)


def not_to_static(fn):
    fn._not_to_static = True
    return fn


class ignore_module:
    def __init__(self, modules):
        pass


# ---------------------------------------------------------------------------
# Traced dynamic loss scaling inside the one-program train step.
#
# Reference analogue: GradScaler.step (amp/grad_scaler.py:619) — unscale,
# cross-rank found-inf reduction, conditional optimizer step, scale update.
# Here the whole sequence is part of the XLA program: found_inf is a traced
# scalar; the "skip" is realised by (a) zeroing the gradients and the lr so
# lazily-created accumulators (fp32 master weights, moments) keep their init
# values, and (b) selecting the pre-step value for every state leaf that
# existed before the update.
# ---------------------------------------------------------------------------
def _scaled_backward(model, opt, loss, lr, scale):
    """Scaled backward + in-graph unscale.  Returns found_inf (traced bool)
    and sets opt lr to 0 on overflow so the update is a no-op."""
    (loss * Tensor._wrap(scale.astype(loss._data.dtype))).backward()
    inv = 1.0 / scale
    found = jnp.zeros((), jnp.bool_)
    grads = []
    for _, p in model.named_parameters():
        if p.grad is not None:
            g32 = p.grad._data.astype(jnp.float32) * inv
            found = found | jnp.any(~jnp.isfinite(g32))
            grads.append((p, g32))
    for p, g32 in grads:
        safe = jnp.where(found, jnp.zeros_like(g32), g32)
        p.grad._data = safe.astype(p.grad._data.dtype)
    opt._learning_rate = jnp.where(found, jnp.zeros_like(lr), lr)
    return found


def _skip_select(found, old, new):
    """Leaf-wise jnp.where(found, old, new) over (possibly nested) dicts;
    leaves with no pre-step counterpart keep their new (= init) value."""
    if isinstance(new, dict):
        return {k: _skip_select(found,
                                old.get(k) if isinstance(old, dict) else None,
                                v)
                for k, v in new.items()}
    if old is None or not hasattr(new, "dtype"):
        return new
    return jnp.where(found, old, new)


class CompiledTrainStep:
    """One-XLA-program train step: forward + tape backward + optimizer update,
    compiled together with parameter/optimizer-state donation.

    This is the TPU replacement for the reference's whole static-graph
    training path (Program + StandaloneExecutor + fused optimizer ops,
    SURVEY §3.3) and the primary perf surface of the framework.

    Device-resident state: the flat params/buffers/opt-state pytree lives on
    device between steps — each call feeds the previous call's OUTPUT arrays
    straight back in (donation makes the round trip zero-copy), so the
    steady-state path does ZERO per-parameter python work: no Layer/Optimizer
    dict rebuilds, no rebinds, no per-step lr upload (the device scalar is
    cached against the scheduler's host float), no host RNG (the PRNG key is
    split in-graph and carried).  The python ``model``/``optimizer`` objects
    are therefore stale between steps; they re-converge via:

      * ``step.sync()`` — explicit flush device -> host (cheap, pointer
        rebinds only);
      * automatically before ``model.state_dict()`` /
        ``optimizer.state_dict()`` (checkpointing sees fresh values);
      * automatically when an official mutation API runs
        (``Parameter.set_value``, ``set_state_dict``, ``Layer.to(dtype)``,
        ``amp.decorate``, ``Tensor.zero_`` ...): the mutation barrier in
        ``core.state.bump_param_version`` flushes first, then the next call
        re-hydrates from host so the mutation takes effect.

    Raw ``tensor._data = ...`` pokes are NOT tracked — call
    ``step.invalidate()`` after such surgery.

    Fused multi-step dispatch: with ``fused_steps=K`` (default from
    ``FLAGS_fused_steps``) a whole K-step window compiles into ONE donated
    XLA program — ``jax.lax.scan`` over the single-step body, carry =
    (params, buffers, opt_state, scaler_state, rng_key), xs = the K-stacked
    batch pytree plus the K-vector of learning rates previewed from the
    host scheduler (``LRScheduler.peek``), ys = the per-step lazy losses.
    This amortizes per-step python dispatch/argument handling across K
    steps (the scheduling-overhead analogue of the reference's
    new_executor + CINN fusion) — the lever for short-step (small-model)
    MFU.  Feed windows via ``io.StackingPrefetcher``::

        step = CompiledTrainStep(model, loss_fn, opt, fused_steps=4)
        for w in io.StackingPrefetcher(loader, k=4):
            losses = step(*w)          # ONE dispatch, shape-[k] lazy loss

    Window semantics:

      * a window call returns the K-vector of losses (lazy; materializes on
        ``.numpy()``, which syncs the whole window);
      * ``jit.steps`` / ``optimizer._step_count`` advance by K per window;
        ``jit.host.dispatches`` advances by 1 (the counter gate is
        ``dispatches == steps / K`` in steady state);
      * GradScaler skip-steps, in-graph dropout key splitting and
        ``FLAGS_check_nan_inf`` all run per scan iteration — trajectories
        are bit-identical to K single-step dispatches, and a nan/inf raise
        names the offending step index inside the window;
      * partial windows (tail of a loader whose length is not a multiple of
        K) and the very first window (optimizer accumulators not yet
        materialized, so the scan carry structure is unknown) fall back to
        K single-step dispatches — no batch is dropped or padded;
      * ``.sync()`` and the mutation barrier land on post-window values.

    Telemetry: ``metrics=MetricsLogger(...)`` (profiler.metrics) records
    per-step loss / grad global-norm / lr / scaler scale+skip / step-time /
    tok/s / MFU.  The device-derived scalars are traced into the step
    program and accumulated in a donated on-device accumulator (part of
    the fused-window scan carry); the host harvests them only at existing
    sync boundaries (``sync()``, checkpoint export, or an explicit
    ``metrics_flush()``) — steady-state counter gates (0 retraces /
    hydrates / binds, dispatches == steps/K) hold with metrics ON, which
    ``scripts/check_counters.py`` enforces.

    With ``scaler`` (an enabled amp.GradScaler), fp16 dynamic loss scaling
    runs in-graph: scaled backward, traced found-inf, skipped update, scale
    adjustment — zero host round-trips (reference: amp/grad_scaler.py:619).
    Donation stays full (params/buffers/opt-state) even with the scaler: the
    skip-select reads the pre-step values INSIDE the program, so XLA aliasing
    of inputs to outputs remains legal.

    Multi-chip SPMD: pass ``mesh`` (a ``jax.sharding.Mesh``) to make the
    step mesh-native — every leaf of the donated carry (params, buffers,
    optimizer accumulators/master weights, GradScaler state, RNG chain) is
    placed with a ``NamedSharding`` at hydrate time and its output sharding
    is pinned inside the traced program, so input/output layouts match and
    donation, the retrace budget, and the zero-host-sync steady state hold
    UNCHANGED on the mesh path (same counter gates).  Per-leaf specs
    resolve as: ``shard_rules`` (ordered ``(regex, PartitionSpec)`` pairs
    matched on the parameter/buffer name, see
    ``distributed.sharding_utils.infer_partition_specs``) > the
    PartitionSpec recorded by ``annotate_param`` (model-declared TP
    placements, e.g. GPT's qkv/mlp ``"mp"`` splits) > replicated.  The
    batch dimension of the step args is constrained onto ``batch_axes``
    (default: every data-ish mesh axis — ``dp``/``sharding`` — of size >
    1), which makes GSPMD insert the gradient all-reduce automatically:
    dp=N training is N shards of the global batch with psum'd grads, and a
    1-device mesh is bit-identical to the single-device path.
    """

    def __init__(self, model, loss_fn, optimizer, scaler=None, donate=True,
                 fused_steps=None, mesh=None, shard_rules=None,
                 batch_axes=None, metrics=None):
        import weakref
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        # per-step train telemetry (profiler.metrics.MetricsLogger): the
        # device-derived scalars (loss / grad global-norm / scaler state)
        # accumulate INSIDE the donated carry and per-dispatch lazy refs,
        # harvested only at sync boundaries — metrics ON adds zero
        # syncs/retraces/dispatches (gated in scripts/check_counters.py)
        self.metrics = (_metrics.MetricsLogger() if metrics is True
                        else metrics)
        self._macc = None            # donated device metric accumulator
        self._pending = []           # un-harvested per-dispatch metric refs
        self._pending_cap = 512      # auto-harvest backstop
        self._last_dispatch_t = None
        self._tokens_per_step = None
        self._tok_cached = False
        self._n_params = None
        self.scaler = scaler if (scaler is not None
                                 and scaler.is_enable()) else None
        if fused_steps is None:
            fused_steps = int(_flags.flag("FLAGS_fused_steps"))
        if int(fused_steps) < 1:
            raise ValueError(f"fused_steps must be >= 1, got {fused_steps}")
        self.fused_steps = int(fused_steps)
        # keyed by the FLAGS_check_nan_inf value the program was traced
        # under: the guard's finite-ness checks are part of the XLA program,
        # so flag-off runs execute a program with zero check overhead
        self._jits = {}
        # fused window programs, keyed by (check_nan_inf, window length)
        self._fused_jits = {}
        self._donate = donate
        # (params, buffers, opt_state, sstate, rng_carry) — device resident
        self._state = None
        self._seen_version = -1
        self._synced = True
        self._lr_host = None
        self._lr_dev = None
        self._lrs_host = None  # lr vector of the last fused window
        self._lrs_dev = None
        self.mesh = mesh
        if mesh is not None:
            self._init_mesh(shard_rules, batch_axes)
        # state_dict() on the model/optimizer/scaler auto-syncs through this
        model.__dict__["_train_step_owner"] = weakref.ref(self)
        optimizer.__dict__["_train_step_owner"] = weakref.ref(self)
        if self.scaler is not None:
            self.scaler.__dict__["_train_step_owner"] = weakref.ref(self)
        from ..core.state import register_param_sync_hook
        register_param_sync_hook(self.sync)

    # -- mesh plumbing -------------------------------------------------------
    def _init_mesh(self, shard_rules, batch_axes):
        """Resolve one PartitionSpec per carry leaf.  Precedence per
        parameter/buffer name: ``shard_rules`` regex > ``annotate_param``
        placements > replicated; optimizer accumulators and master weights
        inherit their parameter's spec (matched by ``id``, the accumulator
        store key)."""
        from ..distributed.sharding_utils import (infer_partition_specs,
                                                  validate_spec)
        mesh = self.mesh
        self._rep = NamedSharding(mesh, _P())
        if batch_axes is None:
            batch_axes = tuple(a for a in ("dp", "sharding", "batch", "data")
                               if a in mesh.shape and mesh.shape[a] > 1)
        elif isinstance(batch_axes, str):
            batch_axes = (batch_axes,)
        self._batch_axes = tuple(batch_axes)
        div = 1
        for a in self._batch_axes:
            div *= mesh.shape[a]
        self._batch_div = div
        self._batch_spec = (_P(self._batch_axes if len(self._batch_axes) > 1
                               else self._batch_axes[0])
                            if self._batch_axes else None)
        named_p = list(self.model.named_parameters())
        named_b = list(self.model.named_buffers())
        flat = {k: p._data for k, p in named_p}
        flat.update({k: b._data for k, b in named_b})
        ruled = infer_partition_specs(flat, mesh, shard_rules or (),
                                      default=None)
        self._param_specs, self._buffer_specs, self._byid = {}, {}, {}
        for k, p in named_p:
            spec = ruled[k]
            if spec is None:
                placed = getattr(p, "placements", None)
                spec = validate_spec(placed, p._data.shape, mesh, name=k,
                                     quiet=placed is None)
            self._param_specs[k] = spec
            self._byid[id(p)] = spec
        for k, b in named_b:
            spec = ruled[k]
            if spec is None:
                spec = validate_spec(getattr(b, "placements", None),
                                     b._data.shape, mesh, name=k, quiet=True)
            self._buffer_specs[k] = spec

    def _fit_spec(self, spec, shape):
        """Quiet shape-compatibility filter used inside traced code — a
        param-shaped spec applied to a scalar accumulator (beta pows, ...)
        degrades to replicated without warning spam."""
        from ..distributed.sharding_utils import validate_spec
        return validate_spec(spec, shape, self.mesh, quiet=True)

    def _pin(self, x, spec):
        """with_sharding_constraint a traced carry leaf to its resolved
        spec — pinning every OUTPUT leaf to the same sharding its input was
        hydrated with keeps donation aliasing legal and the program cache
        stable (no propagation-chosen layout drift => no retraces)."""
        if not hasattr(x, "shape"):
            return x
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, self._fit_spec(spec, x.shape)))

    def _pin_carry(self, new_params, new_buffers, new_opt):
        new_params = {k: self._pin(v, self._param_specs.get(k))
                      for k, v in new_params.items()}
        new_buffers = {k: self._pin(v, self._buffer_specs.get(k))
                       for k, v in new_buffers.items()}
        new_opt = {
            "acc": {an: {pid: self._pin(v, self._byid.get(pid))
                         for pid, v in store.items()}
                    for an, store in new_opt["acc"].items()},
            "master": {pid: self._pin(v, self._byid.get(pid))
                       for pid, v in new_opt["master"].items()}}
        return new_params, new_buffers, new_opt

    def _constrain_batch(self, args):
        """Pin the leading (batch) axis of every compatible array leaf of
        the step args to the data-parallel mesh axes, inside the traced
        program — GSPMD then runs the forward/backward on batch shards and
        inserts the gradient all-reduce."""
        if self._batch_spec is None:
            return args
        sharding = NamedSharding(self.mesh, self._batch_spec)

        def pin(x):
            shape = getattr(x, "shape", None)
            if (shape is None or len(shape) < 1
                    or shape[0] % self._batch_div != 0):
                return x
            return jax.lax.with_sharding_constraint(x, sharding)

        return jax.tree_util.tree_map(pin, args)

    def _mesh_put(self, x, spec):
        """Sharded ``device_put`` of one state leaf onto the mesh (hydrate/
        warmup path only, never steady state)."""
        if not hasattr(x, "shape"):
            return x
        sharding = NamedSharding(self.mesh, self._fit_spec(spec, x.shape))
        if isinstance(x, jax.Array) and x.sharding == sharding:
            return x
        out = jax.device_put(x, sharding)
        _counters.inc("dist.device_put_sharded_bytes",
                      int(getattr(out, "nbytes", 0) or 0))
        return out

    def _place_mesh_state(self):
        """Place the freshly-hydrated state tuple onto the mesh: params and
        buffers per their resolved specs, optimizer accumulators / master
        weights like their parameter, GradScaler state and the RNG carry
        replicated."""
        params, buffers, opt_state, sstate, key = self._state
        params = {k: self._mesh_put(v, self._param_specs.get(k))
                  for k, v in params.items()}
        buffers = {k: self._mesh_put(v, self._buffer_specs.get(k))
                   for k, v in buffers.items()}
        opt_state = {
            "acc": {an: {pid: self._mesh_put(v, self._byid.get(pid))
                         for pid, v in store.items()}
                    for an, store in opt_state["acc"].items()},
            "master": {pid: self._mesh_put(v, self._byid.get(pid))
                       for pid, v in opt_state["master"].items()}}
        sstate = jax.tree_util.tree_map(
            lambda v: self._mesh_put(v, None), sstate)
        key = jax.device_put(key, self._rep)
        self._state = (params, buffers, opt_state, sstate, key)

    # -- host <-> device state management -----------------------------------
    def _hydrate(self):
        """Read the python objects into the device-resident state tuple."""
        from ..core.state import param_version
        from ..tensor.random import _DEFAULT_GEN
        with _trace.span("jit.hydrate"):
            _counters.inc("jit.hydrates")
            params, buffers = layer_state(self.model)
            opt_state = optimizer_state(self.optimizer)
            sstate = (self.scaler._traced_state() if self.scaler is not None
                      else {})
            self._state = (params, buffers, opt_state, sstate,
                           _DEFAULT_GEN.next_key())
            self._seen_version = param_version()
            self._synced = True
            if self.mesh is not None:
                self._place_mesh_state()

    def sync(self):
        """Flush the device-resident state back into the python
        model/optimizer/scaler objects (pointer rebinds, no host transfer).
        An existing sync boundary is also where pending train metrics are
        harvested into the MetricsLogger (no extra ``jit.syncs``)."""
        if self.metrics is not None:
            self.metrics_flush()
        if self._state is None or self._synced:
            return
        with _trace.span("jit.sync"):
            _counters.inc("jit.syncs")
            params, buffers, opt_state, sstate, _ = self._state
            bind_layer_state(self.model, params, buffers)
            bind_optimizer_state(self.optimizer, opt_state)
            if self.scaler is not None:
                self.scaler._absorb(sstate)
            self._synced = True

    def invalidate(self):
        """Drop the device-resident state; the next call re-hydrates from the
        python objects.  Use after untracked ``t._data = ...`` surgery."""
        self.sync()
        self._state = None

    def export_resume_state(self):
        """Checkpoint hook (``resilience.CheckpointManager``): converge the
        python model/optimizer/scaler objects with the device-resident state
        via ONE counter-gated :meth:`sync`, and return the in-graph RNG
        carry key as raw key data (uint32 ndarray) so an exact-resume
        restore can continue the per-dispatch key chain bit-identically."""
        import numpy as np
        self._ensure_state()
        self.sync()
        return np.array(jax.random.key_data(self._state[4]), copy=True)

    def restore_resume_state(self, rng_carry=None):
        """Rebuild the device-resident state from the (just restored) python
        model/optimizer/scaler objects and install the saved RNG carry key.

        The re-hydrate draws (and discards) one key from the global
        generator, so callers restoring ``paddle.get_rng_state()`` must do
        so AFTER this call for bit-identical resume.  The lr dispatch
        caches are reset so the first resumed dispatch re-reads the
        (restored) scheduler."""
        self._state = None
        self._hydrate()
        if rng_carry is not None:
            params, buffers, opt_state, sstate, _ = self._state
            key = jax.random.wrap_key_data(
                jnp.asarray(rng_carry, jnp.uint32))
            if self.mesh is not None:
                key = jax.device_put(key, self._rep)
            self._state = (params, buffers, opt_state, sstate, key)
        self._lr_host = self._lr_dev = None
        self._lrs_host = self._lrs_dev = None
        # the restored run starts a fresh metric accumulator; un-harvested
        # refs from the faulted timeline are dropped (the flight recorder
        # already captured them at dump time)
        self._macc = None
        self._pending = []
        self._last_dispatch_t = None

    def _step_body(self, check_nan_inf, metrics_on, params, buffers,
                   opt_state, lr, rng_key, sstate, args):
        """One training step as a pure traceable function — the body shared
        by the single-step program and each ``lax.scan`` iteration of a
        fused window.  Returns (loss, params', buffers', opt_state',
        sstate', rng_carry', checks, mets); ``mets`` carries the traced
        per-step telemetry scalars (grad global-norm, scaler scale/skip)
        when ``metrics_on``, else is empty."""
        model, loss_fn, opt = self.model, self.loss_fn, self.optimizer
        scaler = self.scaler
        from ..tensor import random as _rnd
        _counters.inc("jit.traces")  # body runs as python only per trace
        # save the concrete host bindings: they are restored in the
        # finally block so tracers never leak into Parameter._data /
        # optimizer accumulators after the trace finishes
        saved_params = [(p, p._data) for _, p in model.named_parameters()]
        saved_buffers = [(b, b._data) for _, b in model.named_buffers()]
        saved_accs = opt._accumulators
        saved_masters = opt._master_weights
        prev_lr = opt._learning_rate
        prev_step_count = opt._step_count
        prev_grad_mode = STATE.grad_enabled
        prev_chain = _rnd._TRACE_CHAIN[0]
        use_key, carry_key = jax.random.split(rng_key)
        _rnd._TRACE_CHAIN[0] = _rnd._TraceKeyChain(use_key)
        STATE.tracing_depth += 1
        try:
            bind_layer_state(model, params, buffers)
            bind_optimizer_state(opt, opt_state)
            opt._learning_rate = lr
            if self.mesh is not None:
                args = self._constrain_batch(args)
            wargs = jax.tree_util.tree_map(
                lambda x: Tensor._wrap(x) if isinstance(
                    x, (jax.Array, jax.core.Tracer)) else x, args)
            STATE.grad_enabled = True
            loss = loss_fn(model, *wargs)
            if scaler is not None:
                found = _scaled_backward(model, opt, loss, lr,
                                         sstate["scale"])
            else:
                loss.backward()
            checks = {}
            if check_nan_inf:
                # FLAGS_check_nan_inf (reference: eager nan_inf_utils.cc
                # hook): finite-ness of loss / per-param grads / updated
                # params traced INTO the program; host side raises with
                # span context.  Under a GradScaler the grads seen here
                # are post-unscale safe values and found_inf reports the
                # overflow the scaler already handles.
                checks["loss"] = jnp.all(jnp.isfinite(
                    loss._data.astype(jnp.float32)))
                for k, p in model.named_parameters():
                    if p.grad is not None:
                        checks["grad:" + k] = jnp.all(jnp.isfinite(
                            p.grad._data.astype(jnp.float32)))
            mets = {}
            if metrics_on:
                # grad global-norm over the (post-unscale) grads the
                # optimizer is about to consume — traced into the program,
                # so metrics-on costs one fused reduction, zero host work
                sq = jnp.zeros((), jnp.float32)
                for _, p in model.named_parameters():
                    if p.grad is not None:
                        g32 = p.grad._data.astype(jnp.float32)
                        sq = sq + jnp.sum(g32 * g32)
                mets["grad_norm"] = jnp.sqrt(sq)
            opt.step()
            opt.clear_grad()
            new_params = {k: p._data for k, p in model.named_parameters()}
            new_buffers = {k: b._data for k, b in model.named_buffers()}
            new_opt = optimizer_state(opt)
            if scaler is not None:
                new_params = _skip_select(found, params, new_params)
                new_opt = _skip_select(found, opt_state, new_opt)
                sstate = scaler._traced_update(sstate, found)
            if self.mesh is not None:
                new_params, new_buffers, new_opt = self._pin_carry(
                    new_params, new_buffers, new_opt)
            if check_nan_inf:
                for k, v in new_params.items():
                    checks["param:" + k] = jnp.all(jnp.isfinite(
                        v.astype(jnp.float32)))
                if scaler is not None:
                    checks["found_inf"] = found
            if metrics_on:
                if scaler is not None:
                    mets["skip"] = found.astype(jnp.float32)
                    mets["scale"] = jnp.reshape(jnp.asarray(
                        sstate["scale"], jnp.float32), (-1,))[0]
                else:
                    mets["skip"] = jnp.zeros((), jnp.float32)
                    mets["scale"] = jnp.ones((), jnp.float32)
            loss_data = loss._data
        finally:
            STATE.tracing_depth -= 1
            _rnd._TRACE_CHAIN[0] = prev_chain
            opt._learning_rate = prev_lr
            # the host step counter is owned by __call__ (one bump per
            # step); the trace-time opt.step() bump must not stick
            opt._step_count = prev_step_count
            STATE.grad_enabled = prev_grad_mode
            for p, d in saved_params:
                p._data = d
                p.grad = None
            for b, d in saved_buffers:
                b._data = d
            opt._accumulators = saved_accs
            opt._master_weights = saved_masters
        return (loss_data, new_params, new_buffers, new_opt, sstate,
                carry_key, checks, mets)

    def _donate_argnums(self):
        # full donation including the scaler path: _skip_select consumes
        # the pre-step values inside the program, so aliasing params/
        # buffers/opt-state buffers to the outputs is still legal
        return (0, 1, 2) if self._donate else ()

    _MACC_KEYS = ("steps", "loss_sum", "grad_norm_sum", "skip_sum")

    def _macc_add(self, macc, loss, mets):
        """Fold one step's traced scalars into the donated metric
        accumulator (running totals ride the carry; harvested at sync
        boundaries by :meth:`metrics_flush`)."""
        loss32 = jnp.mean(loss.astype(jnp.float32))
        out = {"steps": macc["steps"] + 1.0,
               "loss_sum": macc["loss_sum"] + loss32,
               "grad_norm_sum": macc["grad_norm_sum"] + mets["grad_norm"],
               "skip_sum": macc["skip_sum"] + mets["skip"]}
        if self.mesh is not None:
            out = {k: self._pin(v, None) for k, v in out.items()}
        return out

    def _make_jit(self, check_nan_inf=False, metrics_on=False):
        if not metrics_on:
            def step_fn(params, buffers, opt_state, lr, rng_key, sstate,
                        args):
                return self._step_body(check_nan_inf, False, params, buffers,
                                       opt_state, lr, rng_key, sstate,
                                       args)[:7]

            return jax.jit(step_fn, donate_argnums=self._donate_argnums())

        def step_fn(params, buffers, opt_state, lr, rng_key, sstate, args,
                    macc):
            (loss, params, buffers, opt_state, sstate, rng_key, checks,
             mets) = self._step_body(check_nan_inf, True, params, buffers,
                                     opt_state, lr, rng_key, sstate, args)
            return (loss, params, buffers, opt_state, sstate, rng_key,
                    checks, self._macc_add(macc, loss, mets), mets)

        # NB: `donate + (7,) if donate else ()` would parse as
        # `(donate + (7,)) if donate else ()` (PT003) — keep the ternary
        # inside the sum so the macc arg's donation tracks the carry's
        donate = self._donate_argnums()
        return jax.jit(step_fn,
                       donate_argnums=donate + ((7,) if donate else ()))

    def _make_fused_jit(self, check_nan_inf, k, metrics_on=False):
        """Fused window program: ``jax.lax.scan`` of the single-step body
        over K stacked batches and a K-vector of learning rates — forward +
        backward + optimizer update for all K steps in ONE donated XLA
        launch.  Requires the optimizer accumulators to already exist (the
        scan carry structure must be invariant), so the first-ever window
        runs through the single-step fallback instead.  With metrics on,
        the metric accumulator joins the scan carry and the per-step
        telemetry scalars come back stacked as extra ys."""

        if not metrics_on:
            def window_fn(params, buffers, opt_state, lrs, rng_key, sstate,
                          stacked_args):
                def body(carry, xs):
                    params, buffers, opt_state, sstate, rng_key = carry
                    lr, args = xs
                    (loss, params, buffers, opt_state, sstate, rng_key,
                     checks, _) = self._step_body(check_nan_inf, False,
                                                  params, buffers, opt_state,
                                                  lr, rng_key, sstate, args)
                    return ((params, buffers, opt_state, sstate, rng_key),
                            (loss, checks))

                init = (params, buffers, opt_state, sstate, rng_key)
                ((params, buffers, opt_state, sstate, rng_key),
                 (losses, checks)) = jax.lax.scan(body, init,
                                                  (lrs, stacked_args),
                                                  length=k)
                return (losses, params, buffers, opt_state, sstate, rng_key,
                        checks)

            return jax.jit(window_fn,
                           donate_argnums=self._donate_argnums())

        def window_fn(params, buffers, opt_state, lrs, rng_key, sstate,
                      stacked_args, macc):
            def body(carry, xs):
                params, buffers, opt_state, sstate, rng_key, macc = carry
                lr, args = xs
                (loss, params, buffers, opt_state, sstate, rng_key,
                 checks, mets) = self._step_body(check_nan_inf, True,
                                                 params, buffers, opt_state,
                                                 lr, rng_key, sstate, args)
                macc = self._macc_add(macc, loss, mets)
                return ((params, buffers, opt_state, sstate, rng_key, macc),
                        (loss, checks, mets))

            init = (params, buffers, opt_state, sstate, rng_key, macc)
            ((params, buffers, opt_state, sstate, rng_key, macc),
             (losses, checks, mets)) = jax.lax.scan(body, init,
                                                    (lrs, stacked_args),
                                                    length=k)
            return (losses, params, buffers, opt_state, sstate, rng_key,
                    checks, macc, mets)

        donate = self._donate_argnums()
        return jax.jit(window_fn,
                       donate_argnums=donate + ((7,) if donate else ()))

    def __call__(self, *args):
        with _trace.span("jit.step"):
            from ..io import Window
            if len(args) == 1 and isinstance(args[0], Window):
                return self._call_window(tuple(args[0]), args[0].k)
            if self.fused_steps > 1:
                # fused mode: every call takes a K-stacked window (leading
                # axis = window length on every array leaf)
                return self._call_window(args, None)
            return self._call_impl(args)

    def _ensure_state(self):
        from ..core.state import param_version
        if self._state is None or param_version() != self._seen_version:
            self._hydrate()
            return True
        return False

    @staticmethod
    def _strip(args):
        return jax.tree_util.tree_map(
            lambda x: x._data if isinstance(x, Tensor) else x, args,
            is_leaf=lambda x: isinstance(x, Tensor))

    @staticmethod
    def _window_len(args_data):
        for leaf in jax.tree_util.tree_leaves(args_data):
            if hasattr(leaf, "shape") and len(leaf.shape) >= 1:
                return int(leaf.shape[0])
        raise ValueError(
            "cannot infer the window length: no array leaf with a leading "
            "axis in the window args (stack batches or pass an io.Window)")

    def _call_impl(self, args):
        hydrated = self._ensure_state()
        loss = self._dispatch_single(self._strip(args),
                                     self.optimizer.get_lr())
        if hydrated:
            # first call after (re)hydration: keep the python objects fresh
            # so "step once, then inspect" retains eager semantics; the
            # steady-state path skips this entirely
            self.sync()
        from ..distributed.elastic import heartbeat
        heartbeat()  # no-op unless under the elastic launcher
        return Tensor._wrap(loss)

    def _call_window(self, args, k=None):
        """Train on a window of K stacked batches: ONE fused dispatch when
        the window is full-size and the carry structure is known, K
        single-step dispatches otherwise (first-ever window, partial tail).
        Returns the [k] vector of per-step lazy losses."""
        args_data = self._strip(args)
        if k is None:
            k = self._window_len(args_data)
        k = int(k)
        if k < 1:
            raise ValueError(f"empty dispatch window (k={k})")
        hydrated = self._ensure_state()
        # per-step lr vector, previewed WITHOUT mutating the host scheduler
        # (the scheduler advances under user control, after the window)
        lrs = self.optimizer._peek_lrs(k)
        # fused dispatch needs an invariant scan carry structure, so the
        # very first window (lazy optimizer accumulators not yet
        # materialized) runs as single steps, like any partial tail window
        if (k == self.fused_steps and k > 1
                and self.optimizer._step_count > 0):
            losses = self._dispatch_window(args_data, lrs, k)
        else:
            with _trace.span("jit.window_fallback"):
                _counters.inc("jit.fused_fallback_steps", k)
                per_step = []
                for i in range(k):
                    sliced = jax.tree_util.tree_map(
                        lambda x, _i=i: x[_i] if hasattr(x, "shape") else x,
                        args_data)
                    per_step.append(self._dispatch_single(sliced, lrs[i]))
                losses = jnp.stack(per_step)
        if hydrated:
            self.sync()
        from ..distributed.elastic import heartbeat
        heartbeat()  # no-op unless under the elastic launcher
        return Tensor._wrap(losses)

    def _ensure_macc(self):
        if self._macc is None:
            z = {k: jnp.zeros((), jnp.float32) for k in self._MACC_KEYS}
            if self.mesh is not None:
                z = jax.device_put(z, self._rep)
            self._macc = z

    def _dispatch_single(self, args_data, lr_val):
        """One single-step XLA dispatch on raw array args -> raw loss."""
        _counters.inc("jit.steps")
        check = bool(_flags.flag("FLAGS_check_nan_inf"))
        mon = self.metrics is not None
        key = (check, True) if mon else check
        jit_fn = self._jits.get(key)
        fresh = jit_fn is None
        if fresh:
            jit_fn = self._jits[key] = self._make_jit(check, mon)
        if self._lr_dev is None or lr_val != self._lr_host:
            self._lr_host = lr_val
            self._lr_dev = jnp.asarray(lr_val, jnp.float32)
            if self.mesh is not None:
                # the whole carry is mesh-committed; an uncommitted
                # single-device lr scalar would make the dispatch mix
                # device sets — replicate it once per scheduler value
                self._lr_dev = jax.device_put(self._lr_dev, self._rep)
        if mon:
            self._ensure_macc()
        params, buffers, opt_state, sstate, rng_key = self._state
        if fresh and (_metrics.device_telemetry_enabled()
                      or _audit.audit_enabled()):
            cargs = (params, buffers, opt_state, self._lr_dev, rng_key,
                     sstate, args_data) + ((self._macc,) if mon else ())
            pname = f"jit.step[check={int(check)},metrics={int(mon)}]"
            if _metrics.device_telemetry_enabled():
                _metrics.capture_program_stats(pname, jit_fn, *cargs)
            donate = self._donate_argnums()
            _audit.maybe_audit(
                pname, jit_fn, *cargs,
                donate_argnums=donate + ((7,) if donate and mon else ()),
                expect_no_collectives=self.mesh is None)
        traces_before = _counters.get("jit.traces")
        _dt = (_devicetime.note(
            f"jit.step[check={int(check)},metrics={int(mon)}]")
            if _devicetime.enabled() else None)
        with _trace.span("jit.dispatch"):
            _counters.inc("jit.host.dispatches")
            _flight.record("jit.dispatch",
                           step=self.optimizer._step_count + 1, k=1)
            if mon:
                (loss, new_params, new_buffers, new_opt, new_sstate,
                 new_rng, checks, new_macc, mets) = jit_fn(
                     params, buffers, opt_state, self._lr_dev, rng_key,
                     sstate, args_data, self._macc)
                self._macc = new_macc
            else:
                (loss, new_params, new_buffers, new_opt, new_sstate,
                 new_rng, checks) = jit_fn(params, buffers, opt_state,
                                           self._lr_dev, rng_key, sstate,
                                           args_data)
        if _dt is not None:
            _devicetime.observe(_dt, (loss, new_params, new_opt))
        _counters.inc("jit.cache_hits"
                      if _counters.get("jit.traces") == traces_before
                      else "jit.cache_misses")
        # bump AFTER the call: at trace time opt.step() does its own bump, so
        # t-based rules (NAdam/RAdam) see the same count an eager step would
        self.optimizer._step_count += 1
        self._state = (new_params, new_buffers, new_opt, new_sstate, new_rng)
        self._synced = False
        if mon:
            self._note_metrics(loss, mets, (lr_val,), 1, args_data,
                               stacked=False)
        if check and checks:
            self._raise_if_nonfinite(checks)
        return loss

    def _dispatch_window(self, args_data, lrs, k):
        """One fused K-step XLA dispatch on K-stacked args -> raw [k]
        losses."""
        _counters.inc("jit.steps", k)
        _counters.inc("jit.fused_windows")
        check = bool(_flags.flag("FLAGS_check_nan_inf"))
        mon = self.metrics is not None
        cache_key = (check, k, True) if mon else (check, k)
        jit_fn = self._fused_jits.get(cache_key)
        fresh = jit_fn is None
        if fresh:
            jit_fn = self._fused_jits[cache_key] = \
                self._make_fused_jit(check, k, mon)
        lrs_t = tuple(float(v) for v in lrs)
        if self._lrs_dev is None or lrs_t != self._lrs_host:
            self._lrs_host = lrs_t
            self._lrs_dev = jnp.asarray(lrs_t, jnp.float32)
            if self.mesh is not None:
                self._lrs_dev = jax.device_put(self._lrs_dev, self._rep)
        if mon:
            self._ensure_macc()
        params, buffers, opt_state, sstate, rng_key = self._state
        if fresh and (_metrics.device_telemetry_enabled()
                      or _audit.audit_enabled()):
            cargs = (params, buffers, opt_state, self._lrs_dev, rng_key,
                     sstate, args_data) + ((self._macc,) if mon else ())
            pname = f"jit.window[check={int(check)},k={k},metrics={int(mon)}]"
            if _metrics.device_telemetry_enabled():
                _metrics.capture_program_stats(pname, jit_fn, *cargs)
            donate = self._donate_argnums()
            _audit.maybe_audit(
                pname, jit_fn, *cargs,
                donate_argnums=donate + ((7,) if donate and mon else ()),
                expect_no_collectives=self.mesh is None)
        traces_before = _counters.get("jit.traces")
        _dt = (_devicetime.note(
            f"jit.window[check={int(check)},k={k},metrics={int(mon)}]")
            if _devicetime.enabled() else None)
        with _trace.span("jit.dispatch"):
            _counters.inc("jit.host.dispatches")
            _flight.record("jit.dispatch",
                           step=self.optimizer._step_count + k, k=k)
            if mon:
                (losses, new_params, new_buffers, new_opt, new_sstate,
                 new_rng, checks, new_macc, mets) = jit_fn(
                     params, buffers, opt_state, self._lrs_dev, rng_key,
                     sstate, args_data, self._macc)
                self._macc = new_macc
            else:
                (losses, new_params, new_buffers, new_opt, new_sstate,
                 new_rng, checks) = jit_fn(params, buffers, opt_state,
                                           self._lrs_dev, rng_key, sstate,
                                           args_data)
        if _dt is not None:
            _devicetime.observe(_dt, (losses, new_params, new_opt))
        _counters.inc("jit.cache_hits"
                      if _counters.get("jit.traces") == traces_before
                      else "jit.cache_misses")
        self.optimizer._step_count += k
        self._state = (new_params, new_buffers, new_opt, new_sstate, new_rng)
        self._synced = False
        if mon:
            self._note_metrics(losses, mets, lrs_t, k, args_data,
                               stacked=True)
        if check and checks:
            self._raise_if_nonfinite(checks, window=k)
        return losses

    # -- train-metrics harvest (profiler.metrics) ----------------------------
    def _infer_tokens(self, args_data, stacked):
        """Tokens per training step from the batch shape: B*S of the first
        >=2-D array leaf (ids [B, S]), else the leading batch size; with a
        K-stacked window the leading window axis is skipped."""
        skip = 1 if stacked else 0
        for leaf in jax.tree_util.tree_leaves(args_data):
            shape = getattr(leaf, "shape", None)
            if shape is None or len(shape) <= skip:
                continue
            dims = shape[skip:]
            if len(dims) >= 2:
                return int(dims[0]) * int(dims[1])
            return int(dims[0])
        return None

    def _count_params(self):
        if self._n_params is None:
            import math
            self._n_params = sum(
                int(math.prod(p._data.shape))
                for _, p in self.model.named_parameters())
        return self._n_params

    def _note_metrics(self, loss, mets, lrs, k, args_data, stacked):
        """Queue one dispatch's lazy metric refs (device arrays — NOT read
        here) plus host-side context; :meth:`metrics_flush` materializes
        them at the next sync boundary."""
        import time
        if not self._tok_cached:
            self._tokens_per_step = self._infer_tokens(args_data, stacked)
            self._tok_cached = True
        now = time.perf_counter()
        dt = (now - self._last_dispatch_t
              if self._last_dispatch_t is not None else None)
        self._last_dispatch_t = now
        self._pending.append({
            "gstep0": self.optimizer._step_count - k + 1, "k": k,
            "loss": loss, "mets": mets, "lrs": lrs, "dt": dt,
            "tokens": self._tokens_per_step,
        })
        if len(self._pending) >= self._pending_cap:
            # backstop for loops that never hit a sync boundary: one host
            # readback of tiny scalars (no jit.syncs, no state rebind)
            self.metrics_flush()

    def metrics_flush(self):
        """Harvest pending per-step metrics into the MetricsLogger: one
        host readback of the queued scalar refs + the donated accumulator.
        Runs automatically at every existing sync boundary (``sync()``,
        ``export_resume_state()``) — never adds a ``jit.syncs`` tick or an
        extra dispatch."""
        if self.metrics is None or (not self._pending
                                    and self._macc is None):
            return
        import numpy as np
        pending, self._pending = self._pending, []
        peak_tflops = float(_flags.flag("FLAGS_peak_tflops") or 0.0)
        n_params = self._count_params()
        for rec in pending:
            k = rec["k"]
            loss = np.atleast_1d(np.asarray(rec["loss"], np.float64))
            mvals = {name: np.atleast_1d(np.asarray(v, np.float64))
                     for name, v in rec["mets"].items()}
            step_time = rec["dt"] / k if rec["dt"] is not None else None
            tokens = rec["tokens"]
            tok_s = (tokens / step_time
                     if tokens and step_time and step_time > 0 else None)
            mfu = (6.0 * n_params * tok_s / (peak_tflops * 1e12)
                   if tok_s and n_params and peak_tflops > 0 else None)
            for i in range(k):
                gstep = rec["gstep0"] + i

                def _at(a):
                    return float(a[i] if a.size > 1 else a[0])

                self.metrics.log(
                    step=gstep, loss=_at(loss),
                    grad_norm=_at(mvals["grad_norm"]),
                    lr=float(rec["lrs"][i if len(rec["lrs"]) > 1 else 0]),
                    scaler_scale=_at(mvals["scale"]),
                    scaler_skip=_at(mvals["skip"]),
                    step_time_s=step_time, tok_s=tok_s, mfu=mfu)
            _flight.record_point("loss", float(loss[-1]),
                                 step=rec["gstep0"] + k - 1)
        if self._macc is not None:
            acc = {name: float(np.asarray(v))
                   for name, v in self._macc.items()}
            steps = acc["steps"]
            if steps > 0:
                _counters.set_gauge("train.steps_accum", steps)
                _counters.set_gauge("train.loss_mean",
                                    acc["loss_sum"] / steps)
                _counters.set_gauge("train.grad_norm_mean",
                                    acc["grad_norm_sum"] / steps)
                _counters.set_gauge("train.skip_steps", acc["skip_sum"])

    def _raise_if_nonfinite(self, checks, window=1):
        """FLAGS_check_nan_inf host side: pull the traced finite-ness bits
        (a deliberate host sync — this is a debug mode) and raise with the
        offending phase names, the step index inside a fused window, and
        the current span context."""
        import numpy as np
        with _trace.span("jit.nan_inf_check"):
            _counters.inc("jit.nan_inf_checks")
            finfo = checks.get("found_inf")
            overflow = (np.atleast_1d(np.asarray(finfo))
                        if (self.scaler is not None and finfo is not None)
                        else None)
            bad_by_step = {}
            for name in sorted(checks):
                if name == "found_inf":
                    continue
                arr = np.atleast_1d(np.asarray(checks[name]))
                for i, ok in enumerate(arr):
                    if bool(ok):
                        continue
                    if overflow is not None and bool(
                            overflow[i if overflow.size > 1 else 0]):
                        # fp16 overflow step: the scaler skipped the update
                        # and will shrink the scale — expected dynamics,
                        # not a defect
                        continue
                    bad_by_step.setdefault(i, []).append(name)
            if not bad_by_step:
                return
            _counters.inc("jit.nan_inf_hits")
            i = min(bad_by_step)
            bad = bad_by_step[i]
            shown = ", ".join(bad[:8]) + (f" (+{len(bad) - 8} more)"
                                          if len(bad) > 8 else "")
            gstep = self.optimizer._step_count - window + i + 1
            where = (f"train step {gstep} (step {i} of a {window}-step "
                     f"fused window)" if window > 1
                     else f"train step {gstep}")
            stack = _trace.current_stack()
            ctx = f" [active spans: {' > '.join(stack)}]" if stack else ""
            # postmortem before the raise: the flight bundle names the
            # failing step and the non-finite tensors
            _flight.dump("nan_inf", {
                "step": gstep, "window": window, "window_index": i,
                "bad": bad[:32], "where": where})
            raise FloatingPointError(
                f"FLAGS_check_nan_inf: non-finite values at {where}: "
                f"{shown}{ctx}")


import contextlib


@contextlib.contextmanager
def eval_mode(layer):
    """Temporarily put a Layer in eval mode, restoring the EXACT
    per-sublayer training flags afterwards (a bare .train() would flatten
    mixed-mode models — e.g. re-enable a deliberately frozen BatchNorm)."""
    states = [(sub, sub.training)
              for _, sub in layer.named_sublayers(include_self=True)]
    layer.eval()
    try:
        yield
    finally:
        for sub, was in states:
            sub.training = was


def functional_forward(layer, fn=None):
    """The functionalize-a-Layer trace harness shared by jit.save and
    hapi.flops: returns pure(params, buffers, *xs) -> pytree of raw
    arrays, with parameters bound, tracing depth set, and grad off."""
    call = fn if fn is not None else layer.forward

    def pure(params, buffers, *xs):
        bind_layer_state(layer, params, buffers)
        STATE.tracing_depth += 1
        try:
            with no_grad_guard():
                out = call(*[Tensor._wrap(x) for x in xs])
        finally:
            STATE.tracing_depth -= 1
        return jax.tree_util.tree_map(
            lambda t: t._data if isinstance(t, Tensor) else t, out,
            is_leaf=lambda t: isinstance(t, Tensor))

    return pure


# ---------------------------------------------------------------------------
# save / load — serialized-program deployment artifact.
#
# Reference analogue: paddle.jit.save → a Program + params that
# fluid/jit/layer.h:44 (jit::Layer) reloads and runs WITHOUT the original
# python class.  TPU-native twin: jax.export serializes the traced
# StableHLO module (+ input/output tree specs) to `path + ".pdmodel"`, the
# weights go to `path + ".pdparams"` (npz); `load` deserializes into a
# TranslatedLayer whose __call__ executes the compiled program — no source
# class needed, loadable in a fresh process.
# ---------------------------------------------------------------------------
def save(layer, path, input_spec=None, **configs):
    """Export `layer.forward` (or a StaticFunction) as a deployment artifact.

    input_spec: list of paddle_tpu.static.InputSpec (or Tensors /
    ShapeDtypeStructs) describing the forward arguments.  Required unless
    the layer was called at least once through to_static (then the traced
    signature is reused is NOT implemented — pass input_spec).
    """
    import json
    import numpy as np
    from jax import export as jexport

    fn = layer.forward if _is_layer(layer) else layer
    target = layer if _is_layer(layer) else getattr(layer, "_layer", None)
    if target is None:
        raise ValueError("jit.save needs a Layer (or to_static-wrapped "
                         "Layer method)")
    if input_spec is None:
        raise ValueError(
            "jit.save requires input_spec=[InputSpec(shape, dtype), ...] "
            "describing the forward arguments (reference: jit/api.py save)")

    _sym_counter = [0]

    def _to_struct(s):
        if hasattr(s, "shape") and hasattr(s, "dtype"):
            dims = []
            for d in list(s.shape):
                if d is None or (isinstance(d, int) and d < 0):
                    # dynamic dim → jax.export symbolic dimension, so the
                    # artifact accepts any size at that axis (paddle's
                    # InputSpec([None, H]) dynamic-batch idiom)
                    _sym_counter[0] += 1
                    dims.append(f"_dyn{_sym_counter[0]}")
                else:
                    dims.append(str(int(d)))
            dt = str(s.dtype)
            if "int64" in dt:
                # x64 is disabled framework-wide: int64 tensors ARE int32
                import warnings
                warnings.warn("jit.save: int64 input_spec exported as int32 "
                              "(jax x64 disabled)", RuntimeWarning,
                              stacklevel=3)
            dt = {"paddle.float32": "float32", "paddle.int64": "int32",
                  "int64": "int32"}.get(dt, dt)
            from jax import export as jexport
            shape = jexport.symbolic_shape(", ".join(dims)) \
                if any(d.startswith("_dyn") for d in dims) \
                else tuple(int(d) for d in dims)
            return jax.ShapeDtypeStruct(shape, jnp.dtype(dt))
        raise TypeError(f"unsupported input_spec entry: {s!r}")

    structs = [_to_struct(s) for s in input_spec]
    params, buffers = layer_state(target)
    pure = functional_forward(target, fn)
    with eval_mode(target):
        try:
            p_structs = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params)
            b_structs = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), buffers)
            exported = jexport.export(jax.jit(pure))(p_structs, b_structs,
                                                     *structs)
            blob = exported.serialize()
        finally:
            bind_layer_state(target, params, buffers)
    with open(path + ".pdmodel", "wb") as f:
        f.write(blob)
    np.savez(path + ".pdparams",
             **{f"p##{k}": np.asarray(v) for k, v in params.items()},
             **{f"b##{k}": np.asarray(v) for k, v in buffers.items()})
    with open(path + ".pdmeta.json", "w") as f:
        json.dump({"inputs": [[[str(d) for d in s.shape], str(s.dtype)]
                              for s in structs],
                   "format": "stablehlo-v1"}, f)


class TranslatedLayer:
    """Runs a deserialized exported program (reference: jit::Layer,
    fluid/jit/layer.h:44 + python TranslatedLayer, jit/translated_layer.py).
    Holds weights + the compiled StableHLO module; no original class."""

    def __init__(self, exported, params, buffers):
        self._exported = exported
        self._params = params
        self._buffers = buffers
        self.training = False

    def __call__(self, *args):
        xs = [a._data if isinstance(a, Tensor) else jnp.asarray(a)
              for a in args]
        out = self._exported.call(self._params, self._buffers, *xs)
        return jax.tree_util.tree_map(
            lambda a: Tensor._wrap(a) if isinstance(a, jax.Array) else a,
            out)

    forward = __call__

    def eval(self):
        return self

    def state_dict(self):
        d = {k: Tensor._wrap(v) for k, v in self._params.items()}
        d.update({k: Tensor._wrap(v) for k, v in self._buffers.items()})
        return d


def load(path, **configs):
    """Load a jit.save artifact into a TranslatedLayer — works in a fresh
    process without the original model class on the path."""
    import numpy as np
    from jax import export as jexport
    with open(path + ".pdmodel", "rb") as f:
        blob = f.read()
    if blob[:1] == b"\x80":  # legacy pickle artifact (pre-stablehlo)
        raise RuntimeError(
            "this artifact was written by the old pickle-based jit.save; "
            "re-export with the current version")
    exported = jexport.deserialize(blob)
    params, buffers = {}, {}
    with np.load(path + ".pdparams.npz") as z:
        for k in z.files:
            kind, name = k.split("##", 1)
            (params if kind == "p" else buffers)[name] = jnp.asarray(z[k])
    return TranslatedLayer(exported, params, buffers)


_static_mode = [False]


def enable_static():
    _static_mode[0] = True


def disable_static():
    _static_mode[0] = False


def in_static_mode():
    return _static_mode[0]


def enable_to_static(flag=True):
    pass


# -- dy2static logging knobs (reference: jit/dy2static/logging_utils.py) ----
_CODE_LEVEL = [0]
_VERBOSITY = [0]


def set_code_level(level=100, also_to_stdout=False):
    """API-parity knob.  The reference's dy2static prints the transformed
    source at this level; here tracing is jax.jit, so there is no
    transformed source to print — the value is stored for introspection
    only."""
    _CODE_LEVEL[0] = level


def set_verbosity(level=0, also_to_stdout=False):
    _VERBOSITY[0] = level
