"""paddle_tpu.jit — whole-program capture.

Reference analogue: paddle.jit (dy2static AST transpile + SOT bytecode capture,
python/paddle/jit/ — 33k LoC) feeding PIR + CINN.

TPU-native redesign: the eager layer executes jnp calls on ``Tensor._data``;
under ``jax.jit`` those same calls trace symbolically, so "dynamic-to-static"
needs no AST rewriting or frame-eval hook — ``to_static`` simply
functionalizes a Layer (parameters/buffers become pytree inputs, mutated
buffers become outputs) and hands the python callable to ``jax.jit``.  The
autograd tape also traces, so an entire train step (forward + backward +
optimizer update) compiles into ONE XLA program — the analogue of the
reference's static-graph executor running a whole Program, with XLA playing
CINN's role.  Guards/retrace are keyed by jax's abstract signature
(shape/dtype/pytree), matching SOT guard semantics.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..core.state import STATE, no_grad_guard
from ..core.tensor import Parameter, Tensor


def _is_layer(obj):
    from ..nn.layer.layers import Layer
    return isinstance(obj, Layer)


# ---------------------------------------------------------------------------
# State (de)hydration: Layer/Optimizer <-> pytree of jax arrays
# ---------------------------------------------------------------------------
def layer_state(layer):
    params = {k: p._data for k, p in layer.named_parameters()}
    buffers = {k: b._data for k, b in layer.named_buffers()}
    return params, buffers


def bind_layer_state(layer, params, buffers):
    for k, p in layer.named_parameters():
        if k in params:
            p._data = params[k]
    for k, b in layer.named_buffers():
        if k in buffers:
            b._data = buffers[k]


def optimizer_state(opt):
    accs = {name: dict(store) for name, store in opt._accumulators.items()}
    masters = dict(opt._master_weights)
    return {"acc": accs, "master": masters}


def bind_optimizer_state(opt, state):
    opt._accumulators = {name: dict(store)
                         for name, store in state["acc"].items()}
    opt._master_weights = dict(state["master"])


class StaticFunction:
    """Compiled wrapper over a python function or Layer.forward
    (reference analogue: jit/dy2static/program_translator.py:321
    StaticFunction)."""

    def __init__(self, fn, layer=None, build_strategy=None,
                 full_graph=True, backend=None, input_spec=None):
        self._fn = fn
        self._layer = layer
        self._cache = {}
        functools.update_wrapper(self, fn)

    def _compiled(self, train_flag):
        if train_flag in self._cache:
            return self._cache[train_flag]

        def runner(params, buffers, args, kwargs):
            if self._layer is not None:
                bind_layer_state(self._layer, params, buffers)
            wargs = jax.tree_util.tree_map(
                lambda x: Tensor._wrap(x) if isinstance(
                    x, (jax.Array, jax.core.Tracer)) else x, args)
            wkwargs = jax.tree_util.tree_map(
                lambda x: Tensor._wrap(x) if isinstance(
                    x, (jax.Array, jax.core.Tracer)) else x, kwargs)
            STATE.tracing_depth += 1
            try:
                with no_grad_guard():
                    out = self._fn(*wargs, **wkwargs)
            finally:
                STATE.tracing_depth -= 1
            out_data = jax.tree_util.tree_map(
                lambda x: x._data if isinstance(x, Tensor) else x, out,
                is_leaf=lambda x: isinstance(x, Tensor))
            new_buffers = ({k: b._data for k, b in
                            self._layer.named_buffers()}
                           if self._layer is not None else {})
            return out_data, new_buffers

        jitted = jax.jit(runner)
        self._cache[train_flag] = jitted
        return jitted

    def __call__(self, *args, **kwargs):
        params, buffers = (layer_state(self._layer) if self._layer is not None
                           else ({}, {}))
        args_data = jax.tree_util.tree_map(
            lambda x: x._data if isinstance(x, Tensor) else x, args,
            is_leaf=lambda x: isinstance(x, Tensor))
        kwargs_data = jax.tree_util.tree_map(
            lambda x: x._data if isinstance(x, Tensor) else x, kwargs,
            is_leaf=lambda x: isinstance(x, Tensor))
        training = self._layer.training if self._layer is not None else False
        out_data, new_buffers = self._compiled(training)(
            params, buffers, args_data, kwargs_data)
        if self._layer is not None:
            for k, b in self._layer.named_buffers():
                if k in new_buffers:
                    b._data = new_buffers[k]
        return jax.tree_util.tree_map(
            lambda x: Tensor._wrap(x) if isinstance(x, jax.Array) else x,
            out_data)

    @property
    def code(self):
        import inspect
        try:
            return inspect.getsource(self._fn)
        except OSError:
            return "<source unavailable>"

    def concrete_program(self):
        return None


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, full_graph=True):
    """paddle.jit.to_static (reference: jit/api.py to_static)."""
    def decorate(obj):
        if _is_layer(obj):
            obj.forward = StaticFunction(obj.forward, layer=obj)
            return obj
        if hasattr(obj, "__self__") and _is_layer(obj.__self__):
            return StaticFunction(obj, layer=obj.__self__)
        return StaticFunction(obj)
    if function is None:
        return decorate
    return decorate(function)


def not_to_static(fn):
    fn._not_to_static = True
    return fn


class ignore_module:
    def __init__(self, modules):
        pass


# ---------------------------------------------------------------------------
# Traced dynamic loss scaling inside the one-program train step.
#
# Reference analogue: GradScaler.step (amp/grad_scaler.py:619) — unscale,
# cross-rank found-inf reduction, conditional optimizer step, scale update.
# Here the whole sequence is part of the XLA program: found_inf is a traced
# scalar; the "skip" is realised by (a) zeroing the gradients and the lr so
# lazily-created accumulators (fp32 master weights, moments) keep their init
# values, and (b) selecting the pre-step value for every state leaf that
# existed before the update.
# ---------------------------------------------------------------------------
def _scaled_backward(model, opt, loss, lr, scale):
    """Scaled backward + in-graph unscale.  Returns found_inf (traced bool)
    and sets opt lr to 0 on overflow so the update is a no-op."""
    (loss * Tensor._wrap(scale.astype(loss._data.dtype))).backward()
    inv = 1.0 / scale
    found = jnp.zeros((), jnp.bool_)
    grads = []
    for _, p in model.named_parameters():
        if p.grad is not None:
            g32 = p.grad._data.astype(jnp.float32) * inv
            found = found | jnp.any(~jnp.isfinite(g32))
            grads.append((p, g32))
    for p, g32 in grads:
        safe = jnp.where(found, jnp.zeros_like(g32), g32)
        p.grad._data = safe.astype(p.grad._data.dtype)
    opt._learning_rate = jnp.where(found, jnp.zeros_like(lr), lr)
    return found


def _skip_select(found, old, new):
    """Leaf-wise jnp.where(found, old, new) over (possibly nested) dicts;
    leaves with no pre-step counterpart keep their new (= init) value."""
    if isinstance(new, dict):
        return {k: _skip_select(found,
                                old.get(k) if isinstance(old, dict) else None,
                                v)
                for k, v in new.items()}
    if old is None or not hasattr(new, "dtype"):
        return new
    return jnp.where(found, old, new)


class CompiledTrainStep:
    """One-XLA-program train step: forward + tape backward + optimizer update,
    compiled together with parameter/optimizer-state donation.

    This is the TPU replacement for the reference's whole static-graph
    training path (Program + StandaloneExecutor + fused optimizer ops,
    SURVEY §3.3) and the primary perf surface of the framework.

    With ``scaler`` (an enabled amp.GradScaler), fp16 dynamic loss scaling
    runs in-graph: scaled backward, traced found-inf, skipped update, scale
    adjustment — zero host round-trips (reference: amp/grad_scaler.py:619).
    """

    def __init__(self, model, loss_fn, optimizer, scaler=None, donate=True):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.scaler = scaler if (scaler is not None
                                 and scaler.is_enable()) else None
        self._jit = None
        self._struct = None
        self._donate = donate

    def _make_jit(self):
        model, loss_fn, opt = self.model, self.loss_fn, self.optimizer
        scaler = self.scaler

        def step_fn(params, buffers, opt_state, lr, rng_key, sstate, args):
            from ..tensor import random as _rnd
            bind_layer_state(model, params, buffers)
            bind_optimizer_state(opt, opt_state)
            prev_lr = opt._learning_rate
            prev_grad_mode = STATE.grad_enabled
            opt._learning_rate = lr
            _rnd._TRACE_CHAIN[0] = _rnd._TraceKeyChain(rng_key)
            STATE.tracing_depth += 1
            try:
                wargs = jax.tree_util.tree_map(
                    lambda x: Tensor._wrap(x) if isinstance(
                        x, (jax.Array, jax.core.Tracer)) else x, args)
                STATE.grad_enabled = True
                loss = loss_fn(model, *wargs)
                if scaler is not None:
                    found = _scaled_backward(model, opt, loss, lr,
                                             sstate["scale"])
                else:
                    loss.backward()
                opt.step()
                opt.clear_grad()
            finally:
                STATE.tracing_depth -= 1
                _rnd._TRACE_CHAIN[0] = None
                opt._learning_rate = prev_lr
                STATE.grad_enabled = prev_grad_mode
            new_params = {k: p._data for k, p in model.named_parameters()}
            new_buffers = {k: b._data for k, b in model.named_buffers()}
            new_opt = optimizer_state(opt)
            if scaler is not None:
                new_params = _skip_select(found, params, new_params)
                new_opt = _skip_select(found, opt_state, new_opt)
                sstate = scaler._traced_update(sstate, found)
            return loss._data, new_params, new_buffers, new_opt, sstate

        donate = ()
        if self._donate:
            # with a scaler the pre-step params/opt-state feed the skip
            # select, so only buffers are donatable
            donate = (1,) if scaler is not None else (0, 1, 2)
        return jax.jit(step_fn, donate_argnums=donate)

    def __call__(self, *args):
        params, buffers = layer_state(self.model)
        opt_state = optimizer_state(self.optimizer)
        struct = jax.tree_util.tree_structure(opt_state)
        if self._jit is None or struct != self._struct:
            self._jit = self._make_jit()
            self._struct = struct
        args_data = jax.tree_util.tree_map(
            lambda x: x._data if isinstance(x, Tensor) else x, args,
            is_leaf=lambda x: isinstance(x, Tensor))
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        self.optimizer._step_count += 1
        from ..tensor.random import _DEFAULT_GEN
        rng_key = _DEFAULT_GEN.next_key()
        sstate = (self.scaler._traced_state() if self.scaler is not None
                  else {})
        loss, new_params, new_buffers, new_opt, new_sstate = self._jit(
            params, buffers, opt_state, lr, rng_key, sstate, args_data)
        bind_layer_state(self.model, new_params, new_buffers)
        bind_optimizer_state(self.optimizer, new_opt)
        if self.scaler is not None:
            self.scaler._absorb(new_sstate)
        if isinstance(self.optimizer._learning_rate, object) and hasattr(
                self.optimizer._learning_rate, "step"):
            pass  # scheduler stepped by user (paddle semantics)
        return Tensor._wrap(loss)


# ---------------------------------------------------------------------------
# save / load (reference: paddle.jit.save → program + params;
# here: state_dict + layer pickle)
# ---------------------------------------------------------------------------
def save(layer, path, input_spec=None, **configs):
    import pickle
    import numpy as np
    state = {k: np.asarray(v._data) for k, v in layer.state_dict().items()}
    meta = {"class": type(layer).__module__ + "." + type(layer).__qualname__}
    with open(path + ".pdparams", "wb") as f:
        pickle.dump(state, f)
    try:
        with open(path + ".pdmodel", "wb") as f:
            pickle.dump(layer, f)
    except Exception:
        with open(path + ".pdmodel", "wb") as f:
            pickle.dump(meta, f)


def load(path, **configs):
    import pickle
    import numpy as np
    with open(path + ".pdmodel", "rb") as f:
        obj = pickle.load(f)
    with open(path + ".pdparams", "rb") as f:
        state = pickle.load(f)
    if _is_layer(obj):
        obj.set_state_dict({k: jnp.asarray(v) for k, v in state.items()})
        return obj
    raise RuntimeError(
        "paddle_tpu.jit.load: saved artifact is not reconstructible; "
        "re-create the Layer and use set_state_dict")


class TranslatedLayer:
    pass


_static_mode = [False]


def enable_static():
    _static_mode[0] = True


def disable_static():
    _static_mode[0] = False


def in_static_mode():
    return _static_mode[0]


def enable_to_static(flag=True):
    pass
