"""Sharding-propagation rules for the YAML op corpus.

Reference analogue: the per-op ``spmd_rule:`` entries in
/root/reference/paddle/phi/ops/yaml/ops.yaml (e.g. ``ElementwiseInferSpmd``,
``ReductionInferSpmd`` in paddle/phi/infermeta/spmd_rules/).  There the rules
*drive* partitioning decisions; on TPU GSPMD already propagates shardings
through the whole XLA program, so this table's role is (a) a queryable,
documented statement of how each op treats shardings — used by
``paddle.static``'s program printer and available to auto-parallel tooling —
and (b) a consistency check: tests/test_generated_ops.py asserts these
predictions match GSPMD's actual output shardings on a real mesh.
"""

from __future__ import annotations

from jax.sharding import PartitionSpec


def _norm(spec, ndim):
    """PartitionSpec -> length-ndim tuple of axis-name-or-None."""
    entries = tuple(spec) if spec is not None else ()
    entries = entries + (None,) * (ndim - len(entries))
    return entries[:ndim]


def _merge_dim(a, b):
    if a is None:
        return b
    if b is None or a == b:
        return a
    raise ValueError(f"conflicting shardings on one dim: {a} vs {b}")


def elementwise(input_specs, input_ndims, **attrs):
    """Broadcast-aware elementwise: align dims from the trailing side, merge
    per-dim (first non-replicated wins; conflicting mesh axes is an error)."""
    out_ndim = max(input_ndims) if input_ndims else 0
    out = [None] * out_ndim
    for spec, nd in zip(input_specs, input_ndims):
        dims = _norm(spec, nd)
        for i, d in enumerate(dims):
            oi = i + (out_ndim - nd)  # right-aligned (numpy broadcasting)
            out[oi] = _merge_dim(out[oi], d)
    return PartitionSpec(*out)


def reduction(input_specs, input_ndims, axis=None, keepdim=False, **attrs):
    """Reduce over ``axis``: reduced dims lose their sharding (GSPMD inserts
    the psum/all-reduce); kept dims propagate."""
    nd = input_ndims[0]
    dims = _norm(input_specs[0], nd)
    if axis is None:
        red = set(range(nd))
    elif isinstance(axis, (tuple, list)):
        red = {a % nd for a in axis}
    else:
        red = {axis % nd}
    out = []
    for i, d in enumerate(dims):
        if i in red:
            if keepdim:
                out.append(None)
        else:
            out.append(d)
    return PartitionSpec(*out)


def matmul(input_specs, input_ndims, **attrs):
    """(…, m, k) × (…, k, n): the contracted dim's sharding is consumed
    (GSPMD emits the reduce-scatter/all-reduce); m/n shardings propagate."""
    a, b = _norm(input_specs[0], input_ndims[0]), _norm(input_specs[1],
                                                       input_ndims[1])
    batch = a[:-2] if len(a) > 2 else ()
    return PartitionSpec(*batch, a[-2], b[-1])


def replicated(input_specs, input_ndims, **attrs):
    return PartitionSpec()


RULES = {
    "elementwise": elementwise,
    "reduction": reduction,
    "matmul": matmul,
    "replicated": replicated,
}


def propagate(op_name, input_specs, input_ndims, **attrs):
    """Predict the output PartitionSpec of ``op_name`` given input specs.

    ``input_specs``: list of PartitionSpec (None = replicated);
    ``input_ndims``: rank of each input; ``attrs``: op attributes the rule
    needs (reduction: axis/keepdim).
    """
    from ._generated import SPMD_RULES
    rule = SPMD_RULES.get(op_name)
    if rule is None:
        raise KeyError(f"op '{op_name}' has no spmd_rule in ops.yaml")
    return RULES[rule](list(input_specs), list(input_ndims), **attrs)
