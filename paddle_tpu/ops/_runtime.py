"""Shared runtime helpers for the YAML-generated op API (_generated.py).

The generated functions are thin: argument normalisation lives here so the
emitted code stays readable and the YAML specs stay declarative.
"""

from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def _axis(axis):
    """Normalise paddle's axis argument (None | int | list | Tensor)."""
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        a = axis.numpy()
        return tuple(int(v) for v in np.atleast_1d(a))
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _wrap_logic(fn, x, y=None, out=None):
    """Comparison/bitwise ops: no autograd tape (discrete outputs), but the
    same Tensor-in/Tensor-out contract.  Mirrors the reference's logic ops,
    which register no grad kernels (phi/ops/yaml/ops.yaml has no
    equal_grad/bitwise_and_grad entries).  Still records into a
    paddle.static Program so comparisons are replayed, not baked in."""
    from ..core.state import STATE
    if STATE.recording_program is None:  # common eager path: no bookkeeping
        if y is None:
            r = Tensor._wrap(fn(_t(x)._data))
        else:
            yd = y if isinstance(y, (int, float, bool)) else _t(y)._data
            r = Tensor._wrap(fn(_t(x)._data, yd))
        if out is not None:
            out._data = r._data
            return out
        return r

    import jax.tree_util as jtu

    from ..core.dispatch import _maybe_record

    if y is None:
        leaves = [_t(x)]
        r = Tensor._wrap(fn(leaves[0]._data))
    else:
        yt = y if isinstance(y, (int, float, bool)) else _t(y)
        leaves = [_t(x), yt]
        yd = yt._data if isinstance(yt, Tensor) else yt
        r = Tensor._wrap(fn(leaves[0]._data, yd))
    if out is not None:
        out._data = r._data
        r = out
    treedef = jtu.tree_structure(tuple(leaves),
                                 is_leaf=lambda v: isinstance(v, Tensor))
    _maybe_record(getattr(fn, "__name__", "logic"), fn, treedef, leaves, {},
                  r)
    return r
