"""Shared runtime helpers for the YAML-generated op API (_generated.py).

The generated functions are thin: argument normalisation lives here so the
emitted code stays readable and the YAML specs stay declarative.
"""

from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def _axis(axis):
    """Normalise paddle's axis argument (None | int | list | Tensor)."""
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        a = axis.numpy()
        return tuple(int(v) for v in np.atleast_1d(a))
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _wrap_logic(fn, x, y=None, out=None):
    """Comparison/bitwise ops: no autograd tape (discrete outputs), but the
    same Tensor-in/Tensor-out contract.  Mirrors the reference's logic ops,
    which register no grad kernels (phi/ops/yaml/ops.yaml has no
    equal_grad/bitwise_and_grad entries)."""
    if y is None:
        r = Tensor._wrap(fn(_t(x)._data))
    else:
        yd = y if isinstance(y, (int, float, bool)) else _t(y)._data
        r = Tensor._wrap(fn(_t(x)._data, yd))
    if out is not None:
        out._data = r._data
        return out
    return r
