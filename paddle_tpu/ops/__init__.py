"""Op definition layer (L3): YAML specs -> generated API + metadata tables.

Reference analogue: /root/reference/paddle/phi/ops/yaml/ (ops.yaml 434 ops,
backward.yaml 323 grad ops) + the generators in paddle/phi/api/generator/.
Here one spec in ``ops.yaml`` generates (via scripts/gen_ops.py, output
checked in as ``_generated.py``):

  - the public API function (exported through paddle.tensor namespaces),
  - ``KERNELS`` (traceable kernel table),
  - ``META`` + :func:`infer_meta` (shape/dtype inference via jax.eval_shape —
    the InferMeta analogue),
  - ``SPMD_RULES`` + :func:`spmd.propagate` (sharding propagation table),
  - ``OP_SPECS`` (introspection; drives the auto parity suite in
    tests/test_generated_ops.py).
"""

from __future__ import annotations

import jax

from . import spmd  # noqa: F401
from ._generated import *  # noqa: F401,F403
from ._generated import KERNELS, META, OP_SPECS, SPMD_RULES  # noqa: F401
from .spmd import propagate  # noqa: F401


def infer_meta(op_name, *args, **attrs):
    """Shape/dtype inference without execution (InferMeta analogue).

    ``args`` are arrays or ``jax.ShapeDtypeStruct``s; returns the op's output
    as ``jax.ShapeDtypeStruct``(s).  Implemented as ``jax.eval_shape`` over
    the op's kernel — the compiler's abstract interpreter IS the shape
    function, so it can never drift from the kernel (the reference maintains
    434 hand-written C++ InferMeta functions for this,
    /root/reference/paddle/phi/infermeta/).
    """
    fn = META.get(op_name)
    if fn is None:
        raise KeyError(f"op '{op_name}' has no meta entry in ops.yaml")
    return jax.eval_shape(lambda *xs: fn(*xs, **attrs), *args)
