"""paddle.onnx namespace (reference: python/paddle/onnx/export.py).

The reference's ``paddle.onnx.export`` is a thin delegation to the external
``paddle2onnx`` package and raises if it is not installed
(export.py: ``import paddle2onnx`` guarded with an install hint).  This
build mirrors that contract: ONNX serialisation needs the ``onnx`` package,
which is not part of this environment (zero egress), so ``export`` converts
when it is importable and otherwise raises with the TPU-native alternative —
``paddle_tpu.jit.save``'s StableHLO artifact, which loads and runs in a
fresh process without the model class (the deployment property ONNX export
exists to provide).
"""

from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """Export ``layer`` to ONNX at ``path``.onnx (reference:
    python/paddle/onnx/export.py export)."""
    try:
        import onnx  # noqa: F401
    except ImportError:
        raise RuntimeError(
            "paddle.onnx.export needs the 'onnx' package, which is not "
            "available in this environment (the reference likewise "
            "requires the external paddle2onnx package).  For a deployable "
            "artifact use paddle_tpu.jit.save(layer, path, input_spec=...) "
            "— a StableHLO program + weights that jit.load runs in a fresh "
            "process without the model class.") from None
    raise NotImplementedError(
        "onnx package detected but the StableHLO->ONNX converter is not "
        "implemented; use paddle_tpu.jit.save for deployment")
