"""Statistics ops (reference: python/paddle/tensor/stat.py)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply_op
from ..core.tensor import Tensor
from .math import _axis, _t, mean, sum  # noqa: F401


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _axis(axis)
    return apply_op("var",
                    lambda v: jnp.var(v, axis=ax, ddof=1 if unbiased else 0,
                                      keepdims=keepdim), _t(x))


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _axis(axis)
    return apply_op("std",
                    lambda v: jnp.std(v, axis=ax, ddof=1 if unbiased else 0,
                                      keepdims=keepdim), _t(x))


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    ax = _axis(axis)

    def fn(v):
        if mode == "avg":
            return jnp.median(v, axis=ax, keepdims=keepdim)
        # 'min' mode: lower of the two middle elements
        vv = jnp.sort(v if ax is not None else v.reshape(-1), axis=ax if ax is not None else 0)
        n = vv.shape[ax if ax is not None else 0]
        return jnp.take(vv, (n - 1) // 2, axis=ax if ax is not None else 0)
    out = apply_op("median", fn, _t(x))
    return out


def nanmedian(x, axis=None, keepdim=False, mode="avg", name=None):
    ax = _axis(axis)
    return apply_op("nanmedian",
                    lambda v: jnp.nanmedian(v, axis=ax, keepdims=keepdim), _t(x))


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    ax = _axis(axis)
    qs = q._data if isinstance(q, Tensor) else jnp.asarray(q)
    return apply_op("quantile",
                    lambda v: jnp.quantile(v, qs, axis=ax, keepdims=keepdim,
                                           method=interpolation), _t(x))


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear",
                name=None):
    ax = _axis(axis)
    qs = q._data if isinstance(q, Tensor) else jnp.asarray(q)
    return apply_op("nanquantile",
                    lambda v: jnp.nanquantile(v, qs, axis=ax, keepdims=keepdim,
                                              method=interpolation), _t(x))


def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                name=None):
    d = np.asarray(x._data)
    w = np.asarray(weights._data) if weights is not None else None
    h, edges = np.histogramdd(d, bins=bins, range=ranges, density=density,
                              weights=w)
    return (Tensor._wrap(jnp.asarray(h)),
            [Tensor._wrap(jnp.asarray(e)) for e in edges])
