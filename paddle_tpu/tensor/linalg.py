"""Linear algebra ops (reference: python/paddle/tensor/linalg.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply_op
from ..core.tensor import Tensor
from .math import matmul, mm, bmm, dot, inner, outer  # noqa: F401


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def norm(x, p=None, axis=None, keepdim=False, name=None):
    x = _t(x)

    def fn(v):
        if p is None or p == "fro":
            if axis is None:
                return jnp.sqrt(jnp.sum(v * v))
            return jnp.linalg.norm(v, "fro" if isinstance(axis, (list, tuple))
                                   else None, axis=tuple(axis) if isinstance(axis, (list, tuple)) else axis,
                                   keepdims=keepdim)
        if p == np.inf or p == float("inf"):
            return jnp.max(jnp.abs(v), axis=None if axis is None else axis,
                           keepdims=keepdim)
        if p == -np.inf or p == float("-inf"):
            return jnp.min(jnp.abs(v), axis=None if axis is None else axis,
                           keepdims=keepdim)
        if p == 0:
            return jnp.sum((v != 0).astype(v.dtype),
                           axis=None if axis is None else axis, keepdims=keepdim)
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        return jnp.sum(jnp.abs(v) ** p, axis=ax, keepdims=keepdim) ** (1.0 / p)
    return apply_op("p_norm", fn, x)


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    return norm(x, p, axis, keepdim)


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    return apply_op("matrix_norm",
                    lambda v: jnp.linalg.norm(v, p, axis=tuple(axis),
                                              keepdims=keepdim), _t(x))


def dist(x, y, p=2, name=None):
    return norm(x - y, p)


def t(input, name=None):
    return apply_op("t", lambda v: v.T, _t(input))


def transpose(x, perm, name=None):
    from .manipulation import transpose as _tr
    return _tr(x, perm)


def cross(x, y, axis=9, name=None):
    ax = axis if axis != 9 else (-1 if x.shape[-1] == 3 else
                                 next(i for i, s in enumerate(x.shape) if s == 3))
    return apply_op("cross", lambda a, b: jnp.cross(a, b, axis=ax), _t(x), _t(y))


def cholesky(x, upper=False, name=None):
    return apply_op("cholesky",
                    lambda v: jnp.linalg.cholesky(v).swapaxes(-1, -2).conj()
                    if upper else jnp.linalg.cholesky(v), _t(x))


def cholesky_solve(x, y, upper=False, name=None):
    def fn(b, L):
        return jax.scipy.linalg.cho_solve((L, not upper), b)
    return apply_op("cholesky_solve", fn, _t(x), _t(y))


def cholesky_inverse(x, upper=False, name=None):
    def fn(L):
        n = L.shape[-1]
        return jax.scipy.linalg.cho_solve((L, not upper), jnp.eye(n, dtype=L.dtype))
    return apply_op("cholesky_inverse", fn, _t(x))


def inv(x, name=None):
    return apply_op("inverse", jnp.linalg.inv, _t(x))


inverse = inv


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply_op("pinv", lambda v: jnp.linalg.pinv(v, rcond=rcond,
                                                      hermitian=hermitian), _t(x))


def det(x, name=None):
    return apply_op("det", jnp.linalg.det, _t(x))


def slogdet(x, name=None):
    sign, logdet = jnp.linalg.slogdet(_t(x)._data)
    out = apply_op("slogdet", lambda v: jnp.stack(jnp.linalg.slogdet(v)), _t(x))
    return out


def matrix_power(x, n, name=None):
    return apply_op("matrix_power", lambda v: jnp.linalg.matrix_power(v, n), _t(x))


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return Tensor._wrap(jnp.linalg.matrix_rank(_t(x)._data, tol=tol))


def qr(x, mode="reduced", name=None):
    outs = apply_op("qr", lambda v: tuple(jnp.linalg.qr(v, mode=mode)), _t(x),
                    nout=2)
    return outs


def svd(x, full_matrices=False, name=None):
    return apply_op("svd",
                    lambda v: tuple(jnp.linalg.svd(v, full_matrices=full_matrices)),
                    _t(x), nout=3)


def svdvals(x, name=None):
    return apply_op("svdvals",
                    lambda v: jnp.linalg.svd(v, compute_uv=False), _t(x))


def eig(x, name=None):
    w, v = np.linalg.eig(np.asarray(_t(x)._data))
    return Tensor._wrap(jnp.asarray(w)), Tensor._wrap(jnp.asarray(v))


def eigh(x, UPLO="L", name=None):
    return apply_op("eigh", lambda v: tuple(jnp.linalg.eigh(v,
                                                            symmetrize_input=True)),
                    _t(x), nout=2)


def eigvals(x, name=None):
    w = np.linalg.eigvals(np.asarray(_t(x)._data))
    return Tensor._wrap(jnp.asarray(w))


def eigvalsh(x, UPLO="L", name=None):
    return apply_op("eigvalsh", lambda v: jnp.linalg.eigvalsh(v), _t(x))


def lu(x, pivot=True, get_infos=False, name=None):
    lu_, piv = jax.scipy.linalg.lu_factor(_t(x)._data)
    info = Tensor._wrap(jnp.zeros((), jnp.int32))
    if get_infos:
        return Tensor._wrap(lu_), Tensor._wrap(piv + 1), info
    return Tensor._wrap(lu_), Tensor._wrap(piv + 1)


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    lu_, piv = np.asarray(x._data), np.asarray(y._data) - 1
    n = lu_.shape[-2]
    P = np.eye(n)
    perm = np.arange(n)
    for i, p in enumerate(piv):
        perm[[i, p]] = perm[[p, i]]
    P = P[perm]
    L = np.tril(lu_, -1) + np.eye(n)
    U = np.triu(lu_)
    return (Tensor._wrap(jnp.asarray(P.T)), Tensor._wrap(jnp.asarray(L)),
            Tensor._wrap(jnp.asarray(U)))


def solve(x, y, name=None):
    return apply_op("solve", jnp.linalg.solve, _t(x), _t(y))


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    def fn(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)
    return apply_op("triangular_solve", fn, _t(x), _t(y))


def lstsq(x, y, rcond=None, driver=None, name=None):
    sol, res, rank, sv = jnp.linalg.lstsq(_t(x)._data, _t(y)._data, rcond=rcond)
    return (Tensor._wrap(sol), Tensor._wrap(res), Tensor._wrap(rank),
            Tensor._wrap(sv))


def multi_dot(x, name=None):
    xs = [_t(v) for v in x]
    return apply_op("multi_dot", lambda *vs: jnp.linalg.multi_dot(vs), *xs)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return apply_op("cov",
                    lambda v: jnp.cov(v, rowvar=rowvar,
                                      ddof=1 if ddof else 0), _t(x))


def corrcoef(x, rowvar=True, name=None):
    return apply_op("corrcoef", lambda v: jnp.corrcoef(v, rowvar=rowvar), _t(x))


def cond(x, p=None, name=None):
    return Tensor._wrap(jnp.linalg.cond(_t(x)._data, p))


def householder_product(x, tau, name=None):
    def fn(a, t):
        m, n = a.shape[-2], a.shape[-1]
        q = jnp.eye(m, dtype=a.dtype)
        for i in range(t.shape[-1]):
            v = jnp.zeros((m,), a.dtype).at[i].set(1.0).at[i + 1:].set(a[i + 1:, i])
            q = q @ (jnp.eye(m, dtype=a.dtype) - t[i] * jnp.outer(v, v))
        return q[:, :n]
    return apply_op("householder_product", fn, _t(x), _t(tau))


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    d = _t(x)._data
    if q is None:
        q = min(6, d.shape[-2], d.shape[-1])
    if center:
        d = d - d.mean(axis=-2, keepdims=True)
    u, s, vt = jnp.linalg.svd(d, full_matrices=False)
    return (Tensor._wrap(u[..., :q]), Tensor._wrap(s[..., :q]),
            Tensor._wrap(jnp.swapaxes(vt, -1, -2)[..., :q]))


def matrix_exp(x, name=None):
    return apply_op("matrix_exp", jax.scipy.linalg.expm, _t(x))


def ormqr(x, tau, y, left=True, transpose=False, name=None):
    """Multiply y by Q from the Householder factors (x, tau) of a QR
    (reference: tensor/linalg.py ormqr -> LAPACK ?ormqr).  TPU-native:
    jax.lax.linalg.householder_product materialises Q (one XLA op), then
    one MXU matmul — the two-step form XLA fuses anyway."""
    import jax

    from .math import matmul

    def fn(xd, td, yd):
        import jax.numpy as jnp
        # householder_product has no JAX differentiation rule; the QR
        # factors are produced by a non-differentiable factorisation anyway
        # (matching the reference, which registers no ormqr_grad), so
        # gradients flow through y only
        xd = jax.lax.stop_gradient(xd)
        td = jax.lax.stop_gradient(td)
        m, n = xd.shape[-2], xd.shape[-1]
        if m > n:
            # LAPACK's Q is m x m; pad the reflector block with zero
            # columns (zero tau = identity reflector) to get the full Q
            xd = jnp.concatenate(
                [xd, jnp.zeros(xd.shape[:-1] + (m - n,), xd.dtype)], -1)
            td = jnp.concatenate(
                [td, jnp.zeros(td.shape[:-1] + (m - td.shape[-1],),
                               td.dtype)], -1)
        q = jax.lax.linalg.householder_product(xd, td)
        if transpose:
            q = jnp.swapaxes(q, -1, -2)
        return jnp.matmul(q, yd) if left else jnp.matmul(yd, q)
    from ..core.dispatch import apply_op
    from ..ops._runtime import _t
    return apply_op("ormqr", fn, _t(x), _t(tau), _t(y))


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    """Randomized low-rank SVD (Halko et al.; reference:
    tensor/linalg.py svd_lowrank).  q: rank of the approximation;
    niter: power iterations sharpening the spectrum — all dense
    MXU matmuls plus one tiny exact SVD."""
    import jax.numpy as jnp

    from ..core.dispatch import apply_op
    from ..ops._runtime import _t
    from .random import _next_key

    def fn(a, *rest):
        import jax
        key = _next_key()  # inside fn: static-program replay stays fresh
        av = a - rest[0] if rest else a
        m, n = av.shape[-2], av.shape[-1]
        r = min(q, m, n)
        omega = jax.random.normal(key, av.shape[:-2] + (n, r), av.dtype)
        ys = av @ omega
        for _ in range(niter):
            ys = av @ (jnp.swapaxes(av, -1, -2) @ ys)
        qm, _ = jnp.linalg.qr(ys)
        b = jnp.swapaxes(qm, -1, -2) @ av
        u, s, vh = jnp.linalg.svd(b, full_matrices=False)
        return qm @ u, s, jnp.swapaxes(vh, -1, -2)

    args = [_t(x)] + ([_t(M)] if M is not None else [])
    return apply_op("svd_lowrank", fn, *args, nout=3)
