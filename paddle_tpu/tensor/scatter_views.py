"""Scatter-view and windowing ops (reference: python/paddle/tensor/
manipulation.py diagonal_scatter/select_scatter/slice_scatter/unfold/
masked_scatter — there thin wrappers over set_value/strided kernels; here
each is one jnp ``.at[...]`` functional update or gather, which XLA lowers
to an in-place scatter when the input buffer is dead)."""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..core.dispatch import apply_op
from ..core.tensor import Tensor
from ..ops._runtime import _t


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    """Write ``y`` onto the (offset) diagonal of x over (axis1, axis2)."""
    def fn(v, s):
        m = jnp.moveaxis(v, (axis1, axis2), (-2, -1))
        h, w = m.shape[-2], m.shape[-1]
        n = s.shape[-1]
        r = jnp.arange(n) + (-offset if offset < 0 else 0)
        c = jnp.arange(n) + (offset if offset > 0 else 0)
        m = m.at[..., r, c].set(jnp.moveaxis(s, -1, -1))
        return jnp.moveaxis(m, (-2, -1), (axis1, axis2))
    return apply_op("diagonal_scatter", fn, _t(x), _t(y))


def select_scatter(x, values, axis, index, name=None):
    """Write ``values`` into slice ``index`` along ``axis``."""
    def fn(v, s):
        sl = (slice(None),) * (axis % v.ndim) + (index,)
        return v.at[sl].set(s)
    return apply_op("select_scatter", fn, _t(x), _t(values))


def slice_scatter(x, value, axes=(), starts=(), ends=(), strides=(),
                  name=None):
    """Write ``value`` into the strided slice of x described by
    axes/starts/ends/strides."""
    def fn(v, s):
        sl = [slice(None)] * v.ndim
        for ax, st, en, sr in zip(axes, starts, ends, strides):
            sl[ax] = slice(int(st), int(en), int(sr))
        return v.at[tuple(sl)].set(s)
    return apply_op("slice_scatter", fn, _t(x), _t(value))


def unfold(x, axis, size, step, name=None):
    """Sliding windows of ``size`` every ``step`` along ``axis``; windows
    land in a new trailing dim (torch/paddle unfold contract)."""
    x = _t(x)
    length = int(x.shape[axis])
    n_win = (length - size) // step + 1
    if n_win <= 0:
        raise ValueError(f"unfold: size {size} > dim {length}")
    idx = (np.arange(n_win)[:, None] * step
           + np.arange(size)[None, :])            # [n_win, size]

    def fn(v):
        g = jnp.take(v, jnp.asarray(idx.reshape(-1)), axis=axis)
        g = jnp.moveaxis(g, axis, -1)
        g = g.reshape(g.shape[:-1] + (n_win, size))
        return jnp.moveaxis(g, -2, axis)
    return apply_op("unfold", fn, x)


def masked_scatter(x, mask, value, name=None):
    """Fill x's True-masked positions with consecutive elements of
    ``value`` (row-major)."""
    def fn(v, m, s):
        m = jnp.broadcast_to(m, v.shape)
        pos = jnp.cumsum(m.reshape(-1)) - 1       # k-th True -> value[k]
        picked = jnp.take(s.reshape(-1),
                          jnp.clip(pos, 0, s.size - 1)).reshape(v.shape)
        return jnp.where(m, picked.astype(v.dtype), v)
    return apply_op("masked_scatter", fn, _t(x), _t(mask), _t(value))


def masked_scatter_(x, mask, value, name=None):
    return x._inplace_assign(masked_scatter(x, mask, value))


def combinations(x, r=2, with_replacement=False, name=None):
    """All r-combinations of a 1-D tensor's elements (itertools semantics;
    index set is static, the gather is traceable)."""
    import itertools

    x = _t(x)
    n = int(x.shape[0])
    it = (itertools.combinations_with_replacement(range(n), r)
          if with_replacement else itertools.combinations(range(n), r))
    idx = np.asarray(list(it), np.int32).reshape(-1, r)

    def fn(v):
        return v[jnp.asarray(idx)]
    return apply_op("combinations", fn, x)
