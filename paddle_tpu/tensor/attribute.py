"""Tensor attribute helpers (reference: python/paddle/tensor/attribute.py)."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor


def shape(input):
    return Tensor._wrap(jnp.asarray(input._data.shape, dtype=jnp.int64))


def rank(input):
    return Tensor._wrap(jnp.asarray(input._data.ndim, dtype=jnp.int64))


def is_complex(x):
    return jnp.issubdtype(x.dtype, jnp.complexfloating)


def is_integer(x):
    return jnp.issubdtype(x.dtype, jnp.integer)


def is_floating_point(x):
    return jnp.issubdtype(x.dtype, jnp.floating)


def real(x, name=None):
    from .math import real as _r
    return _r(x)


def imag(x, name=None):
    from .math import imag as _i
    return _i(x)
