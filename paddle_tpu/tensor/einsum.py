"""einsum (reference: python/paddle/tensor/einsum.py — 1k LoC of manual
planning; on TPU ``jnp.einsum`` lowers straight to dot_general on the MXU)."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import apply_op, matmul_precision
from ..core.tensor import Tensor


def einsum(equation, *operands, name=None):
    ops = [o if isinstance(o, Tensor) else Tensor(o) for o in operands]
    return apply_op(
        "einsum",
        lambda *xs: jnp.einsum(equation, *xs, precision=matmul_precision()),
        *ops)
