"""Math ops (reference: python/paddle/tensor/math.py — each wrapper there
branches eager/static and calls ``_C_ops.*``; here each op is one traceable
jnp/lax function dispatched through apply_op)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtypes
from ..core.dispatch import apply_op
from ..core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def _unary(name, fn):
    def op(x, name=None):
        return apply_op(name_, fn, _t(x))
    name_ = name
    op.__name__ = name
    return op


def _binary(name, fn):
    def op(x, y, name=None):
        y = y if isinstance(y, (int, float)) else _t(y)
        return apply_op(name_, fn, _t(x), y)
    name_ = name
    op.__name__ = name
    return op


# -- elementwise unary -------------------------------------------------------
exp = _unary("exp", jnp.exp)
expm1 = _unary("expm1", jnp.expm1)
log = _unary("log", jnp.log)
log2 = _unary("log2", jnp.log2)
log10 = _unary("log10", jnp.log10)
log1p = _unary("log1p", jnp.log1p)
sqrt = _unary("sqrt", jnp.sqrt)
rsqrt = _unary("rsqrt", jax.lax.rsqrt)
square = _unary("square", jnp.square)
abs = _unary("abs", jnp.abs)
sign = _unary("sign", jnp.sign)
ceil = _unary("ceil", jnp.ceil)
floor = _unary("floor", jnp.floor)
round = _unary("round", jnp.round)
trunc = _unary("trunc", jnp.trunc)
frac = _unary("frac", lambda x: x - jnp.trunc(x))
sin = _unary("sin", jnp.sin)
cos = _unary("cos", jnp.cos)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
acos = _unary("acos", jnp.arccos)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
cosh = _unary("cosh", jnp.cosh)
tanh = _unary("tanh", jnp.tanh)
asinh = _unary("asinh", jnp.arcsinh)
acosh = _unary("acosh", jnp.arccosh)
atanh = _unary("atanh", jnp.arctanh)
erf = _unary("erf", jax.lax.erf)
erfinv = _unary("erfinv", jax.lax.erf_inv)
sigmoid = _unary("sigmoid", jax.nn.sigmoid)
reciprocal = _unary("reciprocal", lambda x: 1.0 / x)
neg = _unary("neg", jnp.negative)
negative = neg
conj = _unary("conj", jnp.conj)
angle = _unary("angle", jnp.angle)
real = _unary("real", jnp.real)
imag = _unary("imag", jnp.imag)
digamma = _unary("digamma", jax.scipy.special.digamma)
lgamma = _unary("lgamma", jax.scipy.special.gammaln)
gamma = _unary("gamma", lambda x: jnp.exp(jax.scipy.special.gammaln(x)))
i0 = _unary("i0", jax.scipy.special.i0)
i0e = _unary("i0e", jax.scipy.special.i0e)
i1 = _unary("i1", jax.scipy.special.i1)
i1e = _unary("i1e", jax.scipy.special.i1e)
isnan = _unary("isnan", jnp.isnan)
isinf = _unary("isinf", jnp.isinf)
isfinite = _unary("isfinite", jnp.isfinite)
logit = _unary("logit", jax.scipy.special.logit)
nan_to_num = _unary("nan_to_num", jnp.nan_to_num)


def deg2rad(x, name=None):
    return apply_op("deg2rad", jnp.deg2rad, _t(x))


def rad2deg(x, name=None):
    return apply_op("rad2deg", jnp.rad2deg, _t(x))


def exponent(x):
    return apply_op("exponent", lambda v: jnp.floor(jnp.log2(jnp.abs(v))), _t(x))


# -- elementwise binary ------------------------------------------------------
add = _binary("add", jnp.add)
subtract = _binary("subtract", jnp.subtract)
multiply = _binary("multiply", jnp.multiply)
divide = _binary("divide", jnp.true_divide)
floor_divide = _binary("floor_divide", jnp.floor_divide)
remainder = _binary("remainder", jnp.remainder)
mod = remainder
floor_mod = remainder
pow = _binary("pow", jnp.power)
maximum = _binary("maximum", jnp.maximum)
minimum = _binary("minimum", jnp.minimum)
fmax = _binary("fmax", jnp.fmax)
fmin = _binary("fmin", jnp.fmin)
atan2 = _binary("atan2", jnp.arctan2)
hypot = _binary("hypot", jnp.hypot)
logaddexp = _binary("logaddexp", jnp.logaddexp)
nextafter = _binary("nextafter", jnp.nextafter)
copysign = _binary("copysign", jnp.copysign)
heaviside = _binary("heaviside", jnp.heaviside)
gcd = _binary("gcd", jnp.gcd)
lcm = _binary("lcm", jnp.lcm)
ldexp = _binary("ldexp", jnp.ldexp)


def divide_no_nan(x, y):
    return apply_op("divide_no_nan",
                    lambda a, b: jnp.where(b == 0, 0.0, a / jnp.where(b == 0, 1.0, b)),
                    _t(x), _t(y))


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    s = scale.item() if isinstance(scale, Tensor) else scale
    if bias_after_scale:
        out = apply_op("scale", lambda v: v * s + bias, _t(x))
    else:
        out = apply_op("scale", lambda v: (v + bias) * s, _t(x))
    return out


def multiplex(inputs, index, name=None):
    stacked = [i._data for i in inputs]
    return apply_op(
        "multiplex",
        lambda idx, *xs: jnp.stack(xs, 0)[idx.reshape(-1),
                                          jnp.arange(xs[0].shape[0])],
        index, *inputs)


def clip(x, min=None, max=None, name=None):
    mn = min.item() if isinstance(min, Tensor) else min
    mx = max.item() if isinstance(max, Tensor) else max
    return apply_op("clip", lambda v: jnp.clip(v, mn, mx), _t(x))


def lerp(x, y, weight, name=None):
    if isinstance(weight, Tensor):
        return apply_op("lerp", lambda a, b, w: a + w * (b - a), _t(x), _t(y),
                        weight)
    return apply_op("lerp", lambda a, b: a + weight * (b - a), _t(x), _t(y))


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply_op("stanh", lambda v: scale_b * jnp.tanh(scale_a * v), _t(x))


# -- reductions --------------------------------------------------------------
def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        a = axis.numpy()
        return tuple(int(v) for v in np.atleast_1d(a))
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    ax, dt = _axis(axis), dtypes.convert_dtype(dtype)
    x = _t(x)
    if dt is None and dtypes.is_integer(x.dtype) or x.dtype == jnp.bool_:
        dt = np.dtype(np.int64)
    return apply_op("sum", lambda v: jnp.sum(v, axis=ax, dtype=dt,
                                             keepdims=keepdim), x)


def mean(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return apply_op("mean", lambda v: jnp.mean(v, axis=ax, keepdims=keepdim),
                    _t(x))


def max(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return apply_op("max", lambda v: jnp.max(v, axis=ax, keepdims=keepdim), _t(x))


def min(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return apply_op("min", lambda v: jnp.min(v, axis=ax, keepdims=keepdim), _t(x))


def amax(x, axis=None, keepdim=False, name=None):
    return max(x, axis, keepdim)


def amin(x, axis=None, keepdim=False, name=None):
    return min(x, axis, keepdim)


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    ax = _axis(axis)
    dt = dtypes.convert_dtype(dtype)
    return apply_op("prod", lambda v: jnp.prod(v, axis=ax, dtype=dt,
                                               keepdims=keepdim), _t(x))


def logsumexp(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return apply_op("logsumexp",
                    lambda v: jax.scipy.special.logsumexp(v, axis=ax,
                                                          keepdims=keepdim),
                    _t(x))


def log_normalize(x, axis=-1):
    return apply_op("log_normalize",
                    lambda v: v - jax.scipy.special.logsumexp(
                        v, axis=axis, keepdims=True), _t(x))


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    ax = _axis(axis)
    return apply_op("nansum", lambda v: jnp.nansum(v, axis=ax, keepdims=keepdim),
                    _t(x))


def nanmean(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return apply_op("nanmean", lambda v: jnp.nanmean(v, axis=ax, keepdims=keepdim),
                    _t(x))


def count_nonzero(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return Tensor._wrap(jnp.count_nonzero(_t(x)._data, axis=ax, keepdims=keepdim))


def all(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return Tensor._wrap(jnp.all(_t(x)._data, axis=ax, keepdims=keepdim))


def any(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return Tensor._wrap(jnp.any(_t(x)._data, axis=ax, keepdims=keepdim))


# -- cumulative --------------------------------------------------------------
def cumsum(x, axis=None, dtype=None, name=None):
    x = _t(x)
    if axis is None:
        return apply_op("cumsum", lambda v: jnp.cumsum(v.reshape(-1)), x)
    return apply_op("cumsum", lambda v: jnp.cumsum(v, axis=int(axis)), x)


def cumprod(x, dim=None, dtype=None, name=None):
    return apply_op("cumprod", lambda v: jnp.cumprod(v, axis=int(dim)), _t(x))


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    """n-th forward difference along `axis` (reference: tensor/math.py diff)."""
    x = _t(x)
    pre = None if prepend is None else _t(prepend)._data
    app = None if append is None else _t(append)._data

    def fn(v, *extras):
        it = iter(extras)
        p = next(it) if pre is not None else None
        a = next(it) if app is not None else None
        return jnp.diff(v, n=n, axis=int(axis),
                        **({"prepend": p} if p is not None else {}),
                        **({"append": a} if a is not None else {}))

    extras = [e for e in (pre, app) if e is not None]
    return apply_op("diff", fn, x, *[Tensor._wrap(e) for e in extras])


def cummax(x, axis=None, dtype="int64", name=None):
    x = _t(x)
    ax = -1 if axis is None else int(axis)
    v = jax.lax.cummax(x._data, axis=ax if ax >= 0 else x.ndim + ax)
    idx = jnp.argmax(jnp.cumsum((x._data == v).astype(jnp.int32), axis=ax), axis=ax)
    out = apply_op("cummax", lambda t: jax.lax.cummax(t, axis=ax if ax >= 0 else t.ndim + ax), x)
    return out, Tensor._wrap(idx)


def cummin(x, axis=None, dtype="int64", name=None):
    x = _t(x)
    ax = -1 if axis is None else int(axis)
    out = apply_op("cummin", lambda t: jax.lax.cummin(t, axis=ax if ax >= 0 else t.ndim + ax), x)
    idx = jnp.argmax((x._data == out._data).astype(jnp.int32), axis=ax)
    return out, Tensor._wrap(idx)


def logcumsumexp(x, axis=None, name=None):
    x = _t(x)
    ax = 0 if axis is None else int(axis)
    if axis is None:
        return apply_op("logcumsumexp",
                        lambda v: jax.lax.cumlogsumexp(v.reshape(-1)), x)
    return apply_op("logcumsumexp", lambda v: jax.lax.cumlogsumexp(v, axis=ax), x)


# -- matmul family -----------------------------------------------------------
def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    from ..core.dispatch import matmul_precision

    def fn(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b, precision=matmul_precision())
    return apply_op("matmul", fn, _t(x), _t(y))


def mm(x, y, name=None):
    return matmul(x, y)


def bmm(x, y, name=None):
    return matmul(x, y)


def dot(x, y, name=None):
    return apply_op("dot", lambda a, b: jnp.sum(a * b, axis=-1), _t(x), _t(y))


def inner(x, y, name=None):
    return apply_op("inner", jnp.inner, _t(x), _t(y))


def outer(x, y, name=None):
    return apply_op("outer", jnp.outer, _t(x), _t(y))


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply_op("addmm",
                    lambda i, a, b: beta * i + alpha * jnp.matmul(a, b),
                    _t(input), _t(x), _t(y))


def kron(x, y, name=None):
    return apply_op("kron", jnp.kron, _t(x), _t(y))


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op("trace", lambda v: jnp.trace(v, offset, axis1, axis2), _t(x))


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op("diagonal",
                    lambda v: jnp.diagonal(v, offset, axis1, axis2), _t(x))


def diag_embed(x, offset=0, dim1=-2, dim2=-1):
    def fn(v):
        n = v.shape[-1] + (offset if offset >= 0 else -offset)
        pad = jnp.zeros(v.shape[:-1] + (n, n), v.dtype)
        idx = jnp.arange(v.shape[-1])
        r = idx + (0 if offset >= 0 else -offset)
        c = idx + (offset if offset >= 0 else 0)
        pad = pad.at[..., r, c].set(v)
        if (dim1, dim2) != (-2, -1):
            pad = jnp.moveaxis(pad, -2, dim1 if dim1 >= 0 else pad.ndim + dim1)
            pad = jnp.moveaxis(pad, -1, dim2 if dim2 >= 0 else pad.ndim + dim2)
        return pad
    return apply_op("diag_embed", fn, _t(x))


# -- misc --------------------------------------------------------------------
def increment(x, value=1.0, name=None):
    out = apply_op("increment", lambda v: v + value, x)
    return x._inplace_assign(out)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return Tensor._wrap(jnp.isclose(_t(x)._data, _t(y)._data, rtol, atol,
                                    equal_nan))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return Tensor._wrap(jnp.allclose(_t(x)._data, _t(y)._data, rtol, atol,
                                     equal_nan))


def equal_all(x, y, name=None):
    return Tensor._wrap(jnp.array_equal(_t(x)._data, _t(y)._data))


def inverse(x, name=None):
    return apply_op("inverse", jnp.linalg.inv, _t(x))


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply_op("rot90", lambda v: jnp.rot90(v, k, axes), _t(x))


def histogram(input, bins=100, min=0, max=0, name=None):
    d = input._data
    lo, hi = (min, max) if (min != 0 or max != 0) else (d.min(), d.max())
    h, _ = jnp.histogram(d, bins=bins, range=(lo, hi))
    return Tensor._wrap(h.astype(jnp.int64))


def bincount(x, weights=None, minlength=0, name=None):
    w = weights._data if weights is not None else None
    return Tensor._wrap(jnp.bincount(x._data, w, minlength=minlength))


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def take(x, index, mode="raise", name=None):
    return apply_op("take", lambda v, i: jnp.take(v.reshape(-1), i,
                                                  mode="clip" if mode == "clip" else "wrap"),
                    _t(x), index)


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    if x is not None:
        return apply_op("trapezoid",
                        lambda yy, xx: jax.scipy.integrate.trapezoid(yy, xx, axis=axis),
                        _t(y), _t(x))
    return apply_op("trapezoid",
                    lambda yy: jax.scipy.integrate.trapezoid(
                        yy, dx=1.0 if dx is None else dx, axis=axis), _t(y))


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    def fn(yy, xx=None):
        d = (jnp.diff(xx, axis=axis) if xx is not None
             else (1.0 if dx is None else dx))
        s1 = [slice(None)] * yy.ndim
        s2 = [slice(None)] * yy.ndim
        s1[axis] = slice(1, None)
        s2[axis] = slice(None, -1)
        avg = (yy[tuple(s1)] + yy[tuple(s2)]) / 2.0
        return jnp.cumsum(avg * d, axis=axis)
    if x is not None:
        return apply_op("cumulative_trapezoid", fn, _t(y), _t(x))
    return apply_op("cumulative_trapezoid", fn, _t(y))
