"""Math ops (reference: python/paddle/tensor/math.py — each wrapper there
branches eager/static and calls ``_C_ops.*``; here each op is one traceable
jnp/lax function dispatched through apply_op)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtypes
from ..core.dispatch import apply_op
from ..core.tensor import Tensor
from ..ops._runtime import _axis, _t  # noqa: F401  (re-exported for stat.py)


def _unary(name, fn):
    def op(x, name=None):
        return apply_op(name_, fn, _t(x))
    name_ = name
    op.__name__ = name
    return op


# -- elementwise unary -------------------------------------------------------
# Elementwise unary/binary + reductions are YAML-generated (ops/ops.yaml ->
# ops/_generated.py via scripts/gen_ops.py, the L3 single-source pipeline);
# re-exported here so the public namespace is unchanged.
from ..ops._generated import (  # noqa: F401
    abs, acos, acosh, add, asin, asinh, atan, atan2, atanh, ceil, clip,
    copysign, cos, cosh, digamma, divide, divide_no_nan, erf, erfinv, exp,
    expm1, floor, floor_divide, fmax, fmin, frac, gamma, gcd, heaviside,
    deg2rad, exponent, gammainc, gammaincc, gammaln, hypot, i0, i0e, i1,
    i1e, isfinite, isinf, isnan, isneginf, isposinf, isreal, lcm, ldexp,
    lgamma, log, log1p, log2, log10, logaddexp, logit, maximum, minimum,
    multigammaln, multiply, nan_to_num, neg, negative, nextafter,
    polygamma, pow, rad2deg, reciprocal, remainder, round, rsqrt, scale,
    sigmoid, sign, signbit, sin, sinc, sinh, sqrt, square, stanh, subtract,
    tan, tanh, trunc,
)
from ..ops._generated import (  # noqa: F401
    all, amax, amin, any, count_nonzero, logsumexp, max, mean, min, nanmean,
    nansum, prod, sum,
)

mod = remainder
floor_mod = remainder

# complex-valued ops stay hand-written (no AMP/bf16 parity legs apply)
conj = _unary("conj", jnp.conj)
angle = _unary("angle", jnp.angle)
real = _unary("real", jnp.real)
imag = _unary("imag", jnp.imag)


def multiplex(inputs, index, name=None):
    stacked = [i._data for i in inputs]
    return apply_op(
        "multiplex",
        lambda idx, *xs: jnp.stack(xs, 0)[idx.reshape(-1),
                                          jnp.arange(xs[0].shape[0])],
        index, *inputs)


def lerp(x, y, weight, name=None):
    if isinstance(weight, Tensor):
        return apply_op("lerp", lambda a, b, w: a + w * (b - a), _t(x), _t(y),
                        weight)
    return apply_op("lerp", lambda a, b: a + weight * (b - a), _t(x), _t(y))


# -- reductions --------------------------------------------------------------
def log_normalize(x, axis=-1):
    return apply_op("log_normalize",
                    lambda v: v - jax.scipy.special.logsumexp(
                        v, axis=axis, keepdims=True), _t(x))


# -- cumulative --------------------------------------------------------------
def cumsum(x, axis=None, dtype=None, name=None):
    x = _t(x)
    if axis is None:
        return apply_op("cumsum", lambda v: jnp.cumsum(v.reshape(-1)), x)
    return apply_op("cumsum", lambda v: jnp.cumsum(v, axis=int(axis)), x)


def cumprod(x, dim=None, dtype=None, name=None):
    return apply_op("cumprod", lambda v: jnp.cumprod(v, axis=int(dim)), _t(x))


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    """n-th forward difference along `axis` (reference: tensor/math.py diff)."""
    x = _t(x)
    pre = None if prepend is None else _t(prepend)._data
    app = None if append is None else _t(append)._data

    def fn(v, *extras):
        it = iter(extras)
        p = next(it) if pre is not None else None
        a = next(it) if app is not None else None
        return jnp.diff(v, n=n, axis=int(axis),
                        **({"prepend": p} if p is not None else {}),
                        **({"append": a} if a is not None else {}))

    extras = [e for e in (pre, app) if e is not None]
    return apply_op("diff", fn, x, *[Tensor._wrap(e) for e in extras])


def cummax(x, axis=None, dtype="int64", name=None):
    x = _t(x)
    ax = -1 if axis is None else int(axis)
    v = jax.lax.cummax(x._data, axis=ax if ax >= 0 else x.ndim + ax)
    idx = jnp.argmax(jnp.cumsum((x._data == v).astype(jnp.int32), axis=ax), axis=ax)
    out = apply_op("cummax", lambda t: jax.lax.cummax(t, axis=ax if ax >= 0 else t.ndim + ax), x)
    return out, Tensor._wrap(idx)


def cummin(x, axis=None, dtype="int64", name=None):
    x = _t(x)
    ax = -1 if axis is None else int(axis)
    out = apply_op("cummin", lambda t: jax.lax.cummin(t, axis=ax if ax >= 0 else t.ndim + ax), x)
    idx = jnp.argmax((x._data == out._data).astype(jnp.int32), axis=ax)
    return out, Tensor._wrap(idx)


def logcumsumexp(x, axis=None, name=None):
    x = _t(x)
    ax = 0 if axis is None else int(axis)
    if axis is None:
        return apply_op("logcumsumexp",
                        lambda v: jax.lax.cumlogsumexp(v.reshape(-1)), x)
    return apply_op("logcumsumexp", lambda v: jax.lax.cumlogsumexp(v, axis=ax), x)


# -- matmul family -----------------------------------------------------------
def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    from ..core.dispatch import matmul_precision

    def fn(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b, precision=matmul_precision())
    return apply_op("matmul", fn, _t(x), _t(y))


def mm(x, y, name=None):
    return matmul(x, y)


def bmm(x, y, name=None):
    return matmul(x, y)


def dot(x, y, name=None):
    return apply_op("dot", lambda a, b: jnp.sum(a * b, axis=-1), _t(x), _t(y))


def inner(x, y, name=None):
    return apply_op("inner", jnp.inner, _t(x), _t(y))


def outer(x, y, name=None):
    return apply_op("outer", jnp.outer, _t(x), _t(y))


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply_op("addmm",
                    lambda i, a, b: beta * i + alpha * jnp.matmul(a, b),
                    _t(input), _t(x), _t(y))


def kron(x, y, name=None):
    return apply_op("kron", jnp.kron, _t(x), _t(y))


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op("trace", lambda v: jnp.trace(v, offset, axis1, axis2), _t(x))


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op("diagonal",
                    lambda v: jnp.diagonal(v, offset, axis1, axis2), _t(x))


def diag_embed(x, offset=0, dim1=-2, dim2=-1):
    def fn(v):
        n = v.shape[-1] + (offset if offset >= 0 else -offset)
        pad = jnp.zeros(v.shape[:-1] + (n, n), v.dtype)
        idx = jnp.arange(v.shape[-1])
        r = idx + (0 if offset >= 0 else -offset)
        c = idx + (offset if offset >= 0 else 0)
        pad = pad.at[..., r, c].set(v)
        if (dim1, dim2) != (-2, -1):
            pad = jnp.moveaxis(pad, -2, dim1 if dim1 >= 0 else pad.ndim + dim1)
            pad = jnp.moveaxis(pad, -1, dim2 if dim2 >= 0 else pad.ndim + dim2)
        return pad
    return apply_op("diag_embed", fn, _t(x))


# -- misc --------------------------------------------------------------------
def increment(x, value=1.0, name=None):
    out = apply_op("increment", lambda v: v + value, x)
    return x._inplace_assign(out)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return Tensor._wrap(jnp.isclose(_t(x)._data, _t(y)._data, rtol, atol,
                                    equal_nan))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return Tensor._wrap(jnp.allclose(_t(x)._data, _t(y)._data, rtol, atol,
                                     equal_nan))


def equal_all(x, y, name=None):
    return Tensor._wrap(jnp.array_equal(_t(x)._data, _t(y)._data))


def inverse(x, name=None):
    return apply_op("inverse", jnp.linalg.inv, _t(x))


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply_op("rot90", lambda v: jnp.rot90(v, k, axes), _t(x))


def histogram(input, bins=100, min=0, max=0, name=None):
    d = input._data
    lo, hi = (min, max) if (min != 0 or max != 0) else (d.min(), d.max())
    h, _ = jnp.histogram(d, bins=bins, range=(lo, hi))
    return Tensor._wrap(h.astype(jnp.int64))


def bincount(x, weights=None, minlength=0, name=None):
    w = weights._data if weights is not None else None
    return Tensor._wrap(jnp.bincount(x._data, w, minlength=minlength))


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def take(x, index, mode="raise", name=None):
    return apply_op("take", lambda v, i: jnp.take(v.reshape(-1), i,
                                                  mode="clip" if mode == "clip" else "wrap"),
                    _t(x), index)


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    if x is not None:
        return apply_op("trapezoid",
                        lambda yy, xx: jax.scipy.integrate.trapezoid(yy, xx, axis=axis),
                        _t(y), _t(x))
    return apply_op("trapezoid",
                    lambda yy: jax.scipy.integrate.trapezoid(
                        yy, dx=1.0 if dx is None else dx, axis=axis), _t(y))


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    def fn(yy, xx=None):
        d = (jnp.diff(xx, axis=axis) if xx is not None
             else (1.0 if dx is None else dx))
        s1 = [slice(None)] * yy.ndim
        s2 = [slice(None)] * yy.ndim
        s1[axis] = slice(1, None)
        s2[axis] = slice(None, -1)
        avg = (yy[tuple(s1)] + yy[tuple(s2)]) / 2.0
        return jnp.cumsum(avg * d, axis=axis)
    if x is not None:
        return apply_op("cumulative_trapezoid", fn, _t(y), _t(x))
    return apply_op("cumulative_trapezoid", fn, _t(y))
