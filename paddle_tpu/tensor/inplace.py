"""Inplace (trailing-underscore) op variants.

Reference analogue: the ``inplace:`` annotations in phi/ops/yaml/ops.yaml
generate ``op_``(x) twins sharing x's buffer.  TPU-native: XLA arrays are
immutable, so ``op_`` computes functionally and rebinds the tensor's buffer
via ``Tensor._inplace_assign`` — when the old buffer is dead XLA reuses it,
which is the same memory behavior the reference's inplace pass buys, without
aliasing hazards under autograd (assign raises if x needs grad and the op
would invalidate the tape, matching dygraph's inplace check).
"""

from __future__ import annotations

from ..ops import _generated as _g
from . import extras as _extras
from . import logic as _logic


def _mk(name, fn, n_tensor_args=1):
    def op(x, *args, **kwargs):
        return x._inplace_assign(fn(x, *args, **kwargs))
    op.__name__ = name
    return op


_UNARY = [
    "abs", "acos", "acosh", "asin", "asinh", "atan", "atanh", "ceil", "cos", "cosh", "digamma", "erf",
    "exp", "expm1", "floor", "frac", "i0", "lgamma", "log", "log10",
    "log1p", "log2", "logit", "nan_to_num", "neg", "reciprocal", "round",
    "rsqrt", "sigmoid", "sign", "sin", "sinc", "sinh", "sqrt", "square",
    "tan", "tanh", "trunc", "gammaln",
]
_BINARY = [
    "add", "subtract", "multiply", "divide", "remainder", "floor_divide",
    "pow", "copysign", "hypot", "ldexp", "fmax", "fmin", "maximum",
    "minimum", "gcd", "lcm", "heaviside", "nextafter", "atan2",
    "logaddexp", "gammainc", "gammaincc",
]
_LOGIC = [
    "logical_and", "logical_or", "logical_xor", "logical_not",
    "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
    "bitwise_left_shift", "bitwise_right_shift", "equal", "not_equal",
    "less_than", "less_equal", "greater_than", "greater_equal",
]

__all__ = []
for _n in _UNARY + _BINARY:
    globals()[_n + "_"] = _mk(_n + "_", getattr(_g, _n))
    __all__.append(_n + "_")
for _n in _LOGIC:
    globals()[_n + "_"] = _mk(_n + "_", getattr(_logic, _n))
    __all__.append(_n + "_")

# aliases and non-YAML members
mod_ = remainder_  # noqa: F821
floor_mod_ = remainder_  # noqa: F821
__all__ += ["mod_", "floor_mod_"]


def cast_(x, dtype):
    return x._inplace_assign(_extras.cast(x, dtype))


def erfinv_(x, name=None):
    return x._inplace_assign(_g.erfinv(x))


def cumsum_(x, axis=None, dtype=None, name=None):
    from .math import cumsum
    return x._inplace_assign(cumsum(x, axis, dtype))


def cumprod_(x, dim=None, dtype=None, name=None):
    from .math import cumprod
    return x._inplace_assign(cumprod(x, dim, dtype))


def clip_(x, min=None, max=None, name=None):
    return x._inplace_assign(_g.clip(x, min, max))


def scale_(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None,
           name=None):
    return x._inplace_assign(_g.scale(x, scale, bias, bias_after_scale))


def addmm_(input, x, y, beta=1.0, alpha=1.0, name=None):
    from .math import addmm
    return input._inplace_assign(addmm(input, x, y, beta, alpha))


def tril_(x, diagonal=0, name=None):
    from .creation import tril
    return x._inplace_assign(tril(x, diagonal))


def triu_(x, diagonal=0, name=None):
    from .creation import triu
    return x._inplace_assign(triu(x, diagonal))


def t_(x, name=None):
    from .linalg import t
    return x._inplace_assign(t(x))


def where_(condition, x=None, y=None, name=None):
    """In-place where: x <- where(condition, x, y).  Method binding puts
    self on `condition` (reference math_op_patch attaches it plainly, so
    cond.where_(x, y) mutates x)."""
    if x is None or y is None:
        raise ValueError("where_ requires both x and y")
    from .search import where
    return x._inplace_assign(where(condition, x, y))


def divide_no_nan_(x, y, name=None):
    return x._inplace_assign(_g.divide_no_nan(x, y))


def polygamma_(x, n=1, name=None):
    return x._inplace_assign(_g.polygamma(x, n))


def multigammaln_(x, p=1, name=None):
    return x._inplace_assign(_g.multigammaln(x, p))


__all__ += ["polygamma_", "multigammaln_", "cast_", "erfinv_", "cumsum_", "cumprod_", "clip_", "scale_",
            "addmm_", "tril_", "triu_", "t_", "where_", "divide_no_nan_"]


def lerp_(x, y, weight, name=None):
    from .math import lerp
    return x._inplace_assign(lerp(x, y, weight))


def index_fill_(x, index, axis, value, name=None):
    from .manipulation import index_fill
    return x._inplace_assign(index_fill(x, index, axis, value))


def index_put_(x, indices, value, accumulate=False, name=None):
    from .manipulation import index_put
    return x._inplace_assign(index_put(x, indices, value, accumulate))


def put_along_axis_(x, indices, values, axis, reduce="assign", name=None):
    from .manipulation import put_along_axis
    return x._inplace_assign(put_along_axis(x, indices, values, axis,
                                            reduce))


__all__ += ["lerp_", "index_fill_", "index_put_", "put_along_axis_"]
