"""Random ops over a global stateful PRNG.

TPU-native design: the reference's per-device ``phi::Generator``
(/root/reference/paddle/phi/core/generator.h) becomes a process-global JAX PRNG
key chain — stateful at the Python level (paddle API compat) but every sample
is a pure function of a split key, so the same ops remain usable under jit
(the nn.functional dropout path threads keys explicitly; see
paddle_tpu/nn/functional/common.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtypes
from ..core.tensor import Tensor


class Generator:
    """Key-chain generator (reference: phi::Generator)."""

    def __init__(self, seed=0):
        self._key = jax.random.key(seed)
        self._seed = seed

    def manual_seed(self, seed):
        self._key = jax.random.key(seed)
        self._seed = seed
        return self

    def initial_seed(self):
        return self._seed

    def next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def get_state(self):
        return jax.random.key_data(self._key)

    def set_state(self, state):
        self._key = jax.random.wrap_key_data(state._data if isinstance(state, Tensor) else state)


_DEFAULT_GEN = Generator(np.random.randint(0, 2**31 - 1))


def default_generator():
    return _DEFAULT_GEN


def seed(value):
    _DEFAULT_GEN.manual_seed(int(value))
    return _DEFAULT_GEN


def get_rng_state():
    return [Tensor._wrap(_DEFAULT_GEN.get_state())]


def set_rng_state(state):
    _DEFAULT_GEN.set_state(state[0] if isinstance(state, (list, tuple)) else state)


class _TraceKeyChain:
    """Functional key chain used while tracing a compiled train step: the
    root key is a traced input, so every compiled step gets fresh randomness
    (the analogue of the reference's RNG-state offset threading,
    fleet/layers/mpu/random.py RNGStatesTracker)."""

    def __init__(self, key):
        self.key = key

    def next(self):
        self.key, sub = jax.random.split(self.key)
        return sub


_TRACE_CHAIN = [None]


def _next_key(recording_ok=False):
    """Draw the next PRNG key.

    ``recording_ok=True`` marks callers that thread the key INTO the op as an
    argument (e.g. functional dropout), so a recorded static Program replays
    them with fresh per-run keys.  All other callers sample at dispatch time:
    under ``program_guard`` that sample is frozen into the Program and every
    ``Executor.run`` replays the identical values — warn so the silent
    determinism is at least visible."""
    if _TRACE_CHAIN[0] is not None:
        return _TRACE_CHAIN[0].next()
    if not recording_ok:
        from ..core.state import STATE
        if STATE.recording_program is not None:
            import warnings
            warnings.warn(
                "dispatch-time randomness recorded under program_guard: the "
                "sampled values are frozen into the Program and will replay "
                "identically on every Executor.run (only key-threaded ops "
                "like nn.functional.dropout re-randomize per run)",
                RuntimeWarning, stacklevel=3)
    return _DEFAULT_GEN.next_key()


def _dt(dtype, default=jnp.float32):
    d = dtypes.convert_dtype(dtype)
    return default if d is None else d


def _shape(shape):
    from .creation import _shape as s
    return s(shape)


def rand(shape, dtype=None, name=None):
    return Tensor._wrap(jax.random.uniform(_next_key(), _shape(shape),
                                           _dt(dtype)))


def randn(shape, dtype=None, name=None):
    return Tensor._wrap(jax.random.normal(_next_key(), _shape(shape),
                                          _dt(dtype)))


def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype)


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._data if isinstance(mean, Tensor) else mean
        s = std._data if isinstance(std, Tensor) else std
        shp = np.broadcast_shapes(np.shape(m), np.shape(s))
        return Tensor._wrap(m + s * jax.random.normal(_next_key(), shp))
    shp = _shape(shape) if shape is not None else ()
    return Tensor._wrap(mean + std * jax.random.normal(_next_key(), shp))


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype=None, name=None):
    return Tensor._wrap(mean + std * jax.random.normal(_next_key(),
                                                       _shape(shape),
                                                       _dt(dtype)))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    return Tensor._wrap(jax.random.uniform(_next_key(), _shape(shape),
                                           _dt(dtype), minval=min, maxval=max))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    x._data = jax.random.uniform(_next_key(), x._data.shape, x._data.dtype,
                                 minval=min, maxval=max)
    return x


def randint(low=0, high=None, shape=[1], dtype=None, name=None):
    if high is None:
        low, high = 0, low
    return Tensor._wrap(jax.random.randint(_next_key(), _shape(shape), low,
                                           high, _dt(dtype, jnp.int64)))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    if high is None:
        low, high = 0, low
    return Tensor._wrap(jax.random.randint(_next_key(), x._data.shape, low,
                                           high,
                                           _dt(dtype, x.dtype)))


def randperm(n, dtype="int64", name=None):
    return Tensor._wrap(jax.random.permutation(_next_key(), n).astype(
        _dt(dtype, jnp.int64)))


def shuffle(x, name=None):
    perm = jax.random.permutation(_next_key(), x._data.shape[0])
    return Tensor._wrap(x._data[perm])


def multinomial(x, num_samples=1, replacement=False, name=None):
    d = x._data
    logits = jnp.log(jnp.maximum(d, 1e-30))
    if replacement:
        out = jax.random.categorical(_next_key(), logits,
                                     shape=d.shape[:-1] + (num_samples,))
    else:
        g = jax.random.gumbel(_next_key(), d.shape)
        _, out = jax.lax.top_k(logits + g, num_samples)
    return Tensor._wrap(out.astype(jnp.int64))


def bernoulli(x, name=None):
    return Tensor._wrap(
        jax.random.bernoulli(_next_key(), x._data).astype(x.dtype))


def bernoulli_(x, p=0.5, name=None):
    x._data = jax.random.bernoulli(_next_key(), p, x._data.shape).astype(x.dtype)
    return x


def poisson(x, name=None):
    return Tensor._wrap(jax.random.poisson(_next_key(), x._data).astype(x.dtype))


def binomial(count, prob, name=None):
    c = count._data if isinstance(count, Tensor) else count
    p = prob._data if isinstance(prob, Tensor) else prob
    return Tensor._wrap(jax.random.binomial(_next_key(), c, p).astype(jnp.int64))


def log_normal(mean=1.0, std=2.0, shape=None, name=None):
    shp = _shape(shape) if shape is not None else ()
    return Tensor._wrap(jnp.exp(mean + std * jax.random.normal(_next_key(), shp)))


def normal_(x, mean=0.0, std=1.0, name=None):
    x._data = (mean + std * jax.random.normal(_next_key(), x._data.shape)
               ).astype(x.dtype)
    return x


def exponential_(x, lam=1.0, name=None):
    x._data = (jax.random.exponential(_next_key(), x._data.shape) / lam).astype(
        x.dtype)
    return x


def cauchy_(x, loc=0, scale=1, name=None):
    """In-place Cauchy fill (reference: tensor/random.py cauchy_ ->
    inverse-CDF over uniform)."""
    import jax

    u = jax.random.uniform(_next_key(), tuple(x.shape),
                           minval=1e-7, maxval=1.0 - 1e-7)
    vals = loc + scale * jnp.tan(jnp.pi * (u - 0.5))
    x._data = vals.astype(x._data.dtype)
    return x


def geometric_(x, probs, name=None):
    """In-place Geometric(probs) fill (number of Bernoulli trials until
    first success; reference: tensor/random.py geometric_)."""
    import jax

    p = probs._data if hasattr(probs, "_data") else probs
    u = jax.random.uniform(_next_key(), tuple(x.shape),
                           minval=1e-7, maxval=1.0 - 1e-7)
    vals = jnp.ceil(jnp.log(u) / jnp.log1p(-p))
    x._data = vals.astype(x._data.dtype)
    return x
