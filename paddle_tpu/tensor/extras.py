"""Breadth ops rounding out the paddle.* namespace (reference:
python/paddle/tensor/math.py + linalg.py entries not covered by the YAML
corpus — cast/sgn/frexp/renorm/reduce_as/mv/tensordot/vander/cdist/pdist/
standard_gamma)."""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core.dispatch import apply_op, matmul_precision
from ..core.tensor import Tensor
from ..ops._runtime import _t


def cast(x, dtype):
    """paddle.cast (reference: tensor/manipulation.py cast -> cast kernel;
    AMP-exempt so explicit casts are never overridden)."""
    dt = dtypes.convert_dtype(dtype)
    return apply_op("cast", lambda v: v.astype(dt), _t(x), amp=False)


def sgn(x, name=None):
    """sign for real dtypes; x/|x| (0 -> 0) for complex."""
    x = _t(x)
    if jnp.issubdtype(x._data.dtype, jnp.complexfloating):
        def fn(v):
            a = jnp.abs(v)
            return jnp.where(a == 0, 0.0 + 0.0j, v / jnp.where(a == 0, 1.0,
                                                               a))
        return apply_op("sgn", fn, x)
    return apply_op("sgn", jnp.sign, x)


def frexp(x, name=None):
    """(mantissa, exponent) with x = mantissa * 2**exponent,
    |mantissa| in [0.5, 1)."""
    m, e = jnp.frexp(_t(x)._data)
    return Tensor._wrap(m), Tensor._wrap(e.astype(jnp.int32))


def mv(x, vec, name=None):
    return apply_op("mv",
                    lambda a, b: jnp.matmul(a, b,
                                            precision=matmul_precision()),
                    _t(x), _t(vec))


def tensordot(x, y, axes=2, name=None):
    if isinstance(axes, Tensor):
        axes = axes.tolist()
    return apply_op("tensordot",
                    lambda a, b: jnp.tensordot(
                        a, b, axes=axes, precision=matmul_precision()),
                    _t(x), _t(y))


def vander(x, n=None, increasing=False, name=None):
    return apply_op("vander",
                    lambda v: jnp.vander(v, N=n, increasing=increasing),
                    _t(x))


def renorm(x, p, axis, max_norm, name=None):
    """Scale each sub-tensor along ``axis`` whose p-norm exceeds max_norm
    down to max_norm (reference: renorm kernel)."""
    def fn(v):
        m = jnp.moveaxis(v, axis, 0).reshape(v.shape[axis], -1)
        norms = jnp.sum(jnp.abs(m) ** p, axis=1) ** (1.0 / p)
        scale = jnp.where(norms > max_norm,
                          max_norm / jnp.maximum(norms, 1e-12), 1.0)
        out = m * scale[:, None]
        return jnp.moveaxis(out.reshape(jnp.moveaxis(v, axis, 0).shape), 0,
                            axis)
    return apply_op("renorm", fn, _t(x))


def renorm_(x, p, axis, max_norm, name=None):
    return x._inplace_assign(renorm(x, p, axis, max_norm))


def reduce_as(x, target, name=None):
    """Sum x down to target's shape (the broadcast adjoint; reference:
    reduce_as op)."""
    tshape = tuple(int(s) for s in (target.shape if hasattr(target, "shape")
                                    else target))

    def fn(v):
        extra = v.ndim - len(tshape)
        if extra:
            v = v.sum(axis=tuple(range(extra)))
        keep = tuple(i for i, (a, b) in enumerate(zip(v.shape, tshape))
                     if a != b)
        return v.sum(axis=keep, keepdims=True) if keep else v
    return apply_op("reduce_as", fn, _t(x))


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    """Pairwise p-distance between row batches [..., n, d] x [..., m, d]."""
    def fn(a, b):
        diff = jnp.abs(a[..., :, None, :] - b[..., None, :, :])
        if p == 0:
            return (diff != 0).sum(-1).astype(a.dtype)
        if jnp.isinf(p):
            return diff.max(-1)
        return (diff ** p).sum(-1) ** (1.0 / p)
    return apply_op("cdist", fn, _t(x), _t(y))


def pdist(x, p=2.0, name=None):
    """Condensed pairwise distance of rows [n, d] -> [n*(n-1)/2]."""
    n = int(x.shape[0])
    iu = np.triu_indices(n, k=1)

    def fn(a):
        d = jnp.abs(a[:, None, :] - a[None, :, :])
        full = (d.max(-1) if jnp.isinf(p)
                else (d ** p).sum(-1) ** (1.0 / p))
        return full[iu]
    return apply_op("pdist", fn, _t(x))


def standard_gamma(x, name=None):
    """Sample Gamma(alpha=x, scale=1) elementwise (reference:
    standard_gamma op over the Marsaglia-Tsang sampler; here
    jax.random.gamma)."""
    from .random import _next_key
    return Tensor._wrap(jax.random.gamma(_next_key(), _t(x)._data))


def as_complex(x, name=None):
    """[..., 2] float -> [...] complex (reference: as_complex kernel)."""
    return apply_op("as_complex",
                    lambda v: jax.lax.complex(v[..., 0], v[..., 1]), _t(x))


def as_real(x, name=None):
    """[...] complex -> [..., 2] float."""
    return apply_op("as_real",
                    lambda v: jnp.stack([jnp.real(v), jnp.imag(v)], -1),
                    _t(x))


def tolist(x):
    return _t(x).tolist()


def check_shape(shape):
    """Validate a shape argument (reference: utils checker) — ints or a
    1-D int tensor; -1 allowed once."""
    vals = shape.tolist() if isinstance(shape, Tensor) else list(shape)
    if sum(1 for v in vals if int(v) == -1) > 1:
        raise ValueError(f"shape {vals} has more than one -1")
    for v in vals:
        if int(v) < -1:
            raise ValueError(f"shape {vals}: dims must be >= -1")
    return vals
