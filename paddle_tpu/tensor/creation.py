"""Creation ops (reference: python/paddle/tensor/creation.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtypes
from ..core.dispatch import apply_op
from ..core.tensor import Tensor, to_tensor  # noqa: F401


def _dt(dtype, default=jnp.float32):
    d = dtypes.convert_dtype(dtype)
    return default if d is None else d


def zeros(shape, dtype=None, name=None):
    return Tensor._wrap(jnp.zeros(_shape(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor._wrap(jnp.ones(_shape(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    d = dtypes.convert_dtype(dtype)
    if d is None:
        d = jnp.float32 if isinstance(fill_value, float) else None
    return Tensor._wrap(jnp.full(_shape(shape), fill_value, d))


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy())
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s.item()) if isinstance(s, Tensor) else int(s)
                 for s in shape)


def zeros_like(x, dtype=None, name=None):
    return Tensor._wrap(jnp.zeros_like(x._data, dtype=dtypes.convert_dtype(dtype)))


def ones_like(x, dtype=None, name=None):
    return Tensor._wrap(jnp.ones_like(x._data, dtype=dtypes.convert_dtype(dtype)))


def full_like(x, fill_value, dtype=None, name=None):
    return Tensor._wrap(jnp.full_like(x._data, fill_value,
                                      dtype=dtypes.convert_dtype(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x
    start, end, step = _v(start), _v(end), _v(step)
    if end is None:
        start, end = 0, start
    d = dtypes.convert_dtype(dtype)
    if d is None:
        d = (np.dtype(np.float32)
             if any(isinstance(v, float) for v in (start, end, step))
             else np.dtype(np.int64))
    return Tensor._wrap(jnp.arange(start, end, step, dtype=d))


def linspace(start, stop, num, dtype=None, name=None):
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x
    return Tensor._wrap(jnp.linspace(_v(start), _v(stop), int(_v(num)),
                                     dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor._wrap(jnp.logspace(start, stop, int(num), base=base,
                                     dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor._wrap(jnp.eye(int(num_rows),
                                None if num_columns is None else int(num_columns),
                                dtype=_dt(dtype)))


def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = args[0]
    outs = jnp.meshgrid(*[a._data for a in args], indexing="ij")
    return [Tensor._wrap(o) for o in outs]


def diag(x, offset=0, padding_value=0, name=None):
    if padding_value != 0 and x.ndim == 1:
        n = x.shape[0] + abs(offset)
        base = jnp.full((n, n), padding_value, x.dtype)
        return apply_op("diag", lambda v: base * (1 - jnp.eye(n, dtype=base.dtype))
                        + jnp.diag(v, offset), x)
    return apply_op("diag", lambda v: jnp.diag(v, offset), x)


def diagflat(x, offset=0, name=None):
    return apply_op("diagflat", lambda v: jnp.diagflat(v, offset), x)


def tril(x, diagonal=0, name=None):
    return apply_op("tril", lambda v: jnp.tril(v, diagonal), x)


def triu(x, diagonal=0, name=None):
    return apply_op("triu", lambda v: jnp.triu(v, diagonal), x)


def tril_indices(row, col, offset=0, dtype="int64"):
    r, c = np.tril_indices(row, offset, col)
    return Tensor._wrap(jnp.asarray(np.stack([r, c]), dtype=_dt(dtype, jnp.int64)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    r, c = np.triu_indices(row, offset, col if col is not None else row)
    return Tensor._wrap(jnp.asarray(np.stack([r, c]), dtype=_dt(dtype, jnp.int64)))


def assign(x, output=None):
    x = x if isinstance(x, Tensor) else Tensor(x)
    out = apply_op("assign", jnp.copy, x)
    if output is not None:
        output._inplace_assign(out)
        return output
    return out


def clone(x, name=None):
    return apply_op("assign", jnp.copy, x)


def complex(real, imag, name=None):
    return apply_op("complex", jax.lax.complex, real, imag)


def polar(abs_t, angle, name=None):
    return apply_op("polar",
                    lambda a, th: a * jnp.exp(1j * th.astype(jnp.complex64)),
                    abs_t, angle)


def create_tensor(dtype="float32", name=None, persistable=False):
    """reference: tensor/creation.py create_tensor — an empty typed holder
    (static-mode legacy); here a 0-size tensor of the dtype."""
    return Tensor._wrap(jnp.zeros((0,), _dt(dtype)))
