"""Search/sort ops (reference: python/paddle/tensor/search.py)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply_op
from ..core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    from ..core import dtype as dtypes
    d = _t(x)._data
    r = jnp.argmax(d if axis is not None else d.reshape(-1),
                   axis=axis, keepdims=keepdim if axis is not None else False)
    return Tensor._wrap(r.astype(dtypes.convert_dtype(dtype)))


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    from ..core import dtype as dtypes
    d = _t(x)._data
    r = jnp.argmin(d if axis is not None else d.reshape(-1),
                   axis=axis, keepdims=keepdim if axis is not None else False)
    return Tensor._wrap(r.astype(dtypes.convert_dtype(dtype)))


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    d = _t(x)._data
    r = jnp.argsort(-d if descending else d, axis=axis, stable=stable)
    return Tensor._wrap(r.astype(jnp.int64))


def sort(x, axis=-1, descending=False, stable=False, name=None):
    def fn(v):
        s = jnp.sort(v, axis=axis, stable=stable)
        return jnp.flip(s, axis=axis) if descending else s
    return apply_op("sort", fn, _t(x))


def _topk_impl(vv, k, largest):
    import jax
    if largest:
        return jax.lax.top_k(vv, k)
    nv, ni = jax.lax.top_k(-vv, k)
    return -nv, ni


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    x = _t(x)
    k = int(k.item()) if isinstance(k, Tensor) else int(k)
    ax = -1 if axis is None else axis
    last = ax in (-1, x.ndim - 1)

    def fn(v):
        vv = v if last else jnp.moveaxis(v, ax, -1)
        vals, _ = _topk_impl(vv, k, largest)
        return vals if last else jnp.moveaxis(vals, -1, ax)

    d = x._data
    vv = d if last else jnp.moveaxis(d, ax, -1)
    _, idx = _topk_impl(vv, k, largest)
    if not last:
        idx = jnp.moveaxis(idx, -1, ax)
    vals = apply_op("topk", fn, x)
    return vals, Tensor._wrap(idx.astype(jnp.int64))


def where(condition, x=None, y=None, name=None):
    from .manipulation import where as _w
    return _w(condition, x, y, name)


def nonzero(x, as_tuple=False):
    from .manipulation import nonzero as _nz
    return _nz(x, as_tuple)


def index_select(x, index, axis=0, name=None):
    from .manipulation import index_select as _is
    return _is(x, index, axis)


def masked_select(x, mask, name=None):
    from .manipulation import masked_select as _ms
    return _ms(x, mask)


def searchsorted(sorted_sequence, values, out_int32=False, right=False,
                 name=None):
    r = jnp.searchsorted(sorted_sequence._data, _t(values)._data,
                         side="right" if right else "left")
    return Tensor._wrap(r.astype(jnp.int32 if out_int32 else jnp.int64))


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32, right)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    x = _t(x)

    def fn(v):
        s = jnp.sort(v, axis=axis)
        out = jnp.take(s, k - 1, axis=axis)
        return jnp.expand_dims(out, axis) if keepdim else out
    vals = apply_op("kthvalue", fn, x)
    idx = jnp.take(jnp.argsort(x._data, axis=axis), k - 1, axis=axis)
    if keepdim:
        idx = jnp.expand_dims(idx, axis)
    return vals, Tensor._wrap(idx.astype(jnp.int64))


def mode(x, axis=-1, keepdim=False, name=None):
    d = np.asarray(_t(x)._data)
    d2 = np.moveaxis(d, axis, -1)
    flat = d2.reshape(-1, d2.shape[-1])
    vals, idxs = [], []
    for row in flat:
        u, c = np.unique(row, return_counts=True)
        v = u[np.argmax(c)]
        vals.append(v)
        idxs.append(np.where(row == v)[0][-1])
    shp = d2.shape[:-1]
    v = np.asarray(vals).reshape(shp)
    i = np.asarray(idxs).reshape(shp)
    if keepdim:
        v = np.expand_dims(v, axis)
        i = np.expand_dims(i, axis)
    return Tensor._wrap(jnp.asarray(v)), Tensor._wrap(jnp.asarray(i, np.int64))


import jax  # noqa: E402  (used inside topk impl)


def top_p_sampling(x, ps, threshold=None, seed=None, name=None):
    """Nucleus sampling (reference: tensor/search.py top_p_sampling ->
    top_p_sampling kernel): per row, sample from the smallest
    probability-sorted prefix whose mass exceeds ps.
    x: [B, V] probabilities; ps: [B, 1] (or [B]) cumulative thresholds;
    threshold: tokens with probability below it leave the nucleus.
    Returns (values [B, 1], ids [B, 1])."""
    import jax
    import jax.numpy as jnp

    from .random import _next_key

    def fn(probs, p):
        key = _next_key()  # inside fn: static-program replay stays fresh
        p = p.reshape(-1, 1)
        order = jnp.argsort(-probs, axis=-1)
        sorted_p = jnp.take_along_axis(probs, order, axis=-1)
        cum = jnp.cumsum(sorted_p, axis=-1)
        # keep the first token whose inclusion crosses p, drop the rest
        keep = (cum - sorted_p) < p
        if threshold is not None:
            keep = keep & (sorted_p >= threshold)
        filt = jnp.where(keep, sorted_p, 0.0)
        filt = filt / jnp.maximum(filt.sum(-1, keepdims=True), 1e-9)
        idx_in_sorted = jax.random.categorical(key, jnp.log(
            jnp.maximum(filt, 1e-30)), axis=-1)
        ids = jnp.take_along_axis(order, idx_in_sorted[:, None], axis=-1)
        vals = jnp.take_along_axis(probs, ids, axis=-1)
        return vals, ids.astype(jnp.int64)

    return apply_op("top_p_sampling", fn, _t(x), _t(ps), nout=2)
