"""paddle_tpu.tensor — the op surface, and Tensor method attachment.

Mirrors python/paddle/tensor/__init__.py which patches ~300 methods onto the
Tensor type at import time (reference: tensor/__init__.py `tensor_method_func`
list)."""

from __future__ import annotations

from ..core.tensor import Parameter, Tensor, to_tensor  # noqa: F401
from . import (attribute, creation, einsum as einsum_mod, extras, inplace,
               linalg, logic, manipulation, math, random, scatter_views,
               search, stat)
from .attribute import *  # noqa: F401,F403
from .creation import *  # noqa: F401,F403
from .einsum import einsum  # noqa: F401
from .linalg import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .array import (array_length, array_read, array_write,  # noqa: F401
                    create_array)
from .math import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .stat import *  # noqa: F401,F403

_METHOD_SOURCES = [math, manipulation, linalg, logic, search, stat,
                   attribute, extras, inplace, scatter_views]

# non-op helpers defined inside op modules (so the __module__ filter below
# cannot catch them)
_SKIP = {"check_shape", "builtins_sum", "builtins_slice"}


def _attach_methods():
    import types
    for mod in _METHOD_SOURCES:
        for name in dir(mod):
            if name.startswith("_") or name in _SKIP:
                continue
            fn = getattr(mod, name)
            if not isinstance(fn, types.FunctionType):
                continue
            m = getattr(fn, "__module__", "") or ""
            # only op functions become methods: infra helpers a module
            # merely imports (core.dispatch.apply_op/matmul_precision,
            # core.tensor.to_tensor, numpy/jax callables) must not leak
            # onto the Tensor API
            if not m.startswith("paddle_tpu.") or m.startswith(
                    "paddle_tpu.core."):
                continue
            if not hasattr(Tensor, name):
                setattr(Tensor, name, fn)
    # a few renames / extras
    Tensor.add_n = staticmethod(add_n) if "add_n" in globals() else None
    Tensor.mod = math.remainder
    Tensor.floor_mod = math.remainder
    Tensor.reshape = manipulation.reshape
    Tensor.reshape_ = manipulation.reshape_
    Tensor.unbind = manipulation.unbind
    Tensor.split = manipulation.split
    Tensor.chunk = manipulation.chunk
    Tensor.topk = search.topk
    Tensor.einsum = lambda self, eq, *others: einsum(eq, self, *others)
    # names the reference attaches from modules outside _METHOD_SOURCES
    # (creation/signal/random/framework; reference tensor_method_func list)
    from ..signal import istft as _istft, stft as _stft
    from ..framework import create_parameter as _create_parameter
    from .creation import diag, diagflat, tril, triu
    Tensor.tril = tril
    Tensor.triu = triu
    Tensor.diag = diag
    Tensor.diagflat = diagflat
    Tensor.stft = _stft
    Tensor.istft = _istft
    Tensor.multinomial = random.multinomial
    Tensor.reverse = manipulation.flip
    Tensor.create_parameter = staticmethod(_create_parameter)
    Tensor.create_tensor = staticmethod(create_tensor)
    from .creation import polar as _polar
    Tensor.polar = _polar
    Tensor.cauchy_ = random.cauchy_
    Tensor.geometric_ = random.geometric_

    def _add_(self, y, alpha=1):
        return self._inplace_assign(self + (y * alpha if alpha != 1 else y))

    def _subtract_(self, y):
        return self._inplace_assign(self - y)

    def _multiply_(self, y):
        return self._inplace_assign(self * y)

    def _divide_(self, y):
        return self._inplace_assign(self / y)

    def _scale_(self, scale=1.0, bias=0.0, bias_after_scale=True, act=None,
                name=None):
        return self._inplace_assign(math.scale(self, scale, bias,
                                               bias_after_scale))

    def _clip_(self, min=None, max=None, name=None):
        return self._inplace_assign(math.clip(self, min, max))

    def _exp_(self):
        return self._inplace_assign(math.exp(self))

    def _fill_(self, value):
        return manipulation.fill_(self, value)

    def _zero_(self):
        return manipulation.zero__(self)

    Tensor.add_ = _add_
    Tensor.subtract_ = _subtract_
    Tensor.multiply_ = _multiply_
    Tensor.divide_ = _divide_
    Tensor.scale_ = _scale_
    Tensor.clip_ = _clip_
    Tensor.exp_ = _exp_
    Tensor.fill_ = _fill_
    Tensor.zero_ = _zero_
    Tensor.uniform_ = random.uniform_
    Tensor.normal_ = random.normal_
    Tensor.exponential_ = random.exponential_
    Tensor.bernoulli_ = random.bernoulli_


def add_n(inputs, name=None):
    """paddle.add_n — sum a list of tensors."""
    import functools
    from ..core.dispatch import apply_op
    if isinstance(inputs, Tensor):
        return inputs
    return apply_op("add_n",
                    lambda *xs: functools.reduce(lambda a, b: a + b, xs),
                    *inputs)


_attach_methods()
