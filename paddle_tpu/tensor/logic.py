"""Logical / comparison ops (reference: python/paddle/tensor/logic.py)."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import apply_op
from ..core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def _logic(name, fn):
    def op(x, y=None, out=None, name=None):
        if y is None:
            r = Tensor._wrap(fn(_t(x)._data))
        else:
            yd = y if isinstance(y, (int, float, bool)) else _t(y)._data
            r = Tensor._wrap(fn(_t(x)._data, yd))
        if out is not None:
            out._data = r._data
            return out
        return r
    op.__name__ = name
    return op


logical_and = _logic("logical_and", jnp.logical_and)
logical_or = _logic("logical_or", jnp.logical_or)
logical_xor = _logic("logical_xor", jnp.logical_xor)
logical_not = _logic("logical_not", jnp.logical_not)
equal = _logic("equal", jnp.equal)
not_equal = _logic("not_equal", jnp.not_equal)
less_than = _logic("less_than", jnp.less)
less_equal = _logic("less_equal", jnp.less_equal)
greater_than = _logic("greater_than", jnp.greater)
greater_equal = _logic("greater_equal", jnp.greater_equal)
bitwise_and = _logic("bitwise_and", jnp.bitwise_and)
bitwise_or = _logic("bitwise_or", jnp.bitwise_or)
bitwise_xor = _logic("bitwise_xor", jnp.bitwise_xor)
bitwise_not = _logic("bitwise_not", jnp.invert)
bitwise_left_shift = _logic("bitwise_left_shift", jnp.left_shift)
bitwise_right_shift = _logic("bitwise_right_shift", jnp.right_shift)


def is_tensor(x):
    return isinstance(x, Tensor)


def is_empty(x, name=None):
    return Tensor._wrap(jnp.asarray(x.size == 0))


def is_complex(x):
    return jnp.issubdtype(_t(x).dtype, jnp.complexfloating)


def is_floating_point(x):
    return jnp.issubdtype(_t(x).dtype, jnp.floating)


def is_integer(x):
    return jnp.issubdtype(_t(x).dtype, jnp.integer)
