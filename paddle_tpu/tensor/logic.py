"""Logical / comparison ops (reference: python/paddle/tensor/logic.py)."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


# Comparison/bitwise ops are YAML-generated (ops/ops.yaml -> ops/_generated.py
# via scripts/gen_ops.py); re-exported so the public namespace is unchanged.
from ..ops._generated import (  # noqa: F401
    bitwise_and, bitwise_left_shift, bitwise_not, bitwise_or,
    bitwise_right_shift, bitwise_xor, equal, greater_equal, greater_than,
    less_equal, less_than, logical_and, logical_not, logical_or, logical_xor,
    not_equal,
)


def is_tensor(x):
    return isinstance(x, Tensor)


def is_empty(x, name=None):
    return Tensor._wrap(jnp.asarray(x.size == 0))


def is_complex(x):
    return jnp.issubdtype(_t(x).dtype, jnp.complexfloating)


def is_floating_point(x):
    return jnp.issubdtype(_t(x).dtype, jnp.floating)


def is_integer(x):
    return jnp.issubdtype(_t(x).dtype, jnp.integer)
