"""TensorArray API (reference: python/paddle/tensor/array.py —
array_read:25, array_length:95, array_write:164, create_array:261).

TPU-native: the reference's TensorArray is a variable-length list variable
for static-graph loops; in the jit-tracing world a python list of arrays
serves the same role (appends happen at trace time, and `lax.scan` is the
compiled-loop form).  This module keeps the four-function API for ported
user code."""

from __future__ import annotations

from ..core.tensor import Tensor


def create_array(dtype="float32", initialized_list=None):
    """reference: array.py:261 — returns the (python-list) TensorArray."""
    arr = []
    if initialized_list is not None:
        for t in initialized_list:
            if not isinstance(t, Tensor):
                raise TypeError(
                    f"initialized_list entries must be Tensors, got "
                    f"{type(t).__name__}")
            arr.append(t)
    return arr


def array_write(x, i, array=None):
    """Write x at index i, growing the array if i == len (reference
    array.py:164 semantics)."""
    if not isinstance(x, Tensor):
        raise TypeError("x must be a Tensor")
    idx = int(i) if not isinstance(i, Tensor) else int(i.numpy())
    if array is None:
        array = []
    if idx > len(array):
        raise IndexError(
            f"array_write index {idx} > array length {len(array)}")
    if idx == len(array):
        array.append(x)
    else:
        array[idx] = x
    return array


def array_read(array, i):
    """reference: array.py:25."""
    idx = int(i) if not isinstance(i, Tensor) else int(i.numpy())
    if not 0 <= idx < len(array):
        raise IndexError(f"array_read index {idx} out of range "
                         f"[0, {len(array)})")
    return array[idx]


def array_length(array):
    """reference: array.py:95."""
    return len(array)
