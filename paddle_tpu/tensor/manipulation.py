"""Shape/layout manipulation ops
(reference: python/paddle/tensor/manipulation.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply_op
from ..core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def _static_shape(shape):
    out = []
    for s in (shape if isinstance(shape, (list, tuple)) else [shape]):
        if isinstance(s, Tensor):
            out.append(int(s.item()))
        else:
            out.append(int(s))
    return tuple(out)


def reshape(x, shape, name=None):
    shp = _static_shape(shape)
    return apply_op("reshape", lambda v: jnp.reshape(v, shp), _t(x))


def reshape_(x, shape, name=None):
    return x._inplace_assign(reshape(x, shape))


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return x.astype(shape_or_dtype)


view_as = lambda x, other, name=None: reshape(x, other.shape)  # noqa: E731


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    x = _t(x)
    nd = x.ndim
    s = start_axis if start_axis >= 0 else start_axis + nd
    e = stop_axis if stop_axis >= 0 else stop_axis + nd
    shp = x.shape[:s] + [int(np.prod(x.shape[s:e + 1] or [1]))] + x.shape[e + 1:]
    return reshape(x, shp)


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    return x._inplace_assign(flatten(x, start_axis, stop_axis))


def transpose(x, perm, name=None):
    perm = [int(p) for p in perm]
    return apply_op("transpose", lambda v: jnp.transpose(v, perm), _t(x))


def moveaxis(x, source, destination, name=None):
    return apply_op("moveaxis", lambda v: jnp.moveaxis(v, source, destination),
                    _t(x))


def swapaxes(x, axis0, axis1, name=None):
    return apply_op("swapaxes", lambda v: jnp.swapaxes(v, axis0, axis1), _t(x))


transpose_ = lambda x, perm, name=None: x._inplace_assign(transpose(x, perm))  # noqa: E731


def unsqueeze(x, axis, name=None):
    ax = axis
    if isinstance(ax, Tensor):
        ax = [int(v) for v in np.atleast_1d(ax.numpy())]
    if isinstance(ax, (list, tuple)):
        ax = tuple(int(a) for a in ax)
    return apply_op("unsqueeze", lambda v: jnp.expand_dims(v, ax), _t(x))


def unsqueeze_(x, axis, name=None):
    return x._inplace_assign(unsqueeze(x, axis))


def squeeze(x, axis=None, name=None):
    x = _t(x)

    def fn(v):
        if axis is None:
            return jnp.squeeze(v)
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        axes = tuple(a if a >= 0 else a + v.ndim for a in axes)
        axes = tuple(a for a in axes if v.shape[a] == 1)
        return jnp.squeeze(v, axes) if axes else v
    return apply_op("squeeze", fn, x)


def squeeze_(x, axis=None, name=None):
    return x._inplace_assign(squeeze(x, axis))


def concat(x, axis=0, name=None):
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    xs = [_t(v) for v in x]
    return apply_op("concat", lambda *vs: jnp.concatenate(vs, axis=ax), *xs)


def stack(x, axis=0, name=None):
    xs = [_t(v) for v in x]
    return apply_op("stack", lambda *vs: jnp.stack(vs, axis=axis), *xs)


def unstack(x, axis=0, num=None, name=None):
    x = _t(x)
    n = x.shape[axis] if num is None else num
    outs = apply_op("unstack",
                    lambda v: tuple(jnp.squeeze(s, axis) for s in
                                    jnp.split(v, n, axis)), x, nout=n)
    return list(outs)


def split(x, num_or_sections, axis=0, name=None):
    x = _t(x)
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    if isinstance(num_or_sections, int):
        n = num_or_sections
        outs = apply_op("split", lambda v: tuple(jnp.split(v, n, ax)), x, nout=n)
        return list(outs)
    sections = [int(s) for s in num_or_sections]
    total = x.shape[ax]
    if any(s == -1 for s in sections):
        known = builtins_sum(s for s in sections if s != -1)
        sections = [total - known if s == -1 else s for s in sections]
    idx = np.cumsum(sections)[:-1].tolist()
    outs = apply_op("split", lambda v: tuple(jnp.split(v, idx, ax)), x,
                    nout=len(sections))
    return list(outs)


def builtins_sum(it):
    import builtins
    return builtins.sum(it)


def tensor_split(x, num_or_indices, axis=0, name=None):
    x = _t(x)
    outs = jnp.array_split(x._data, num_or_indices, axis) \
        if isinstance(num_or_indices, int) else \
        jnp.split(x._data, [int(i) for i in num_or_indices], axis)
    n = len(outs)
    if isinstance(num_or_indices, int):
        return list(apply_op("tensor_split",
                             lambda v: tuple(jnp.array_split(v, num_or_indices, axis)),
                             x, nout=n))
    idx = [int(i) for i in num_or_indices]
    return list(apply_op("tensor_split",
                         lambda v: tuple(jnp.split(v, idx, axis)), x, nout=n))


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def tile(x, repeat_times, name=None):
    reps = _static_shape(repeat_times)
    return apply_op("tile", lambda v: jnp.tile(v, reps), _t(x))


def expand(x, shape, name=None):
    shp = _static_shape(shape)
    x = _t(x)

    def fn(v):
        tgt = list(shp)
        for i in range(len(tgt)):
            if tgt[i] == -1:
                tgt[i] = v.shape[i - len(tgt) + v.ndim]
        return jnp.broadcast_to(v, tgt)
    return apply_op("expand", fn, x)


def expand_as(x, y, name=None):
    return expand(x, y.shape)


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def broadcast_tensors(inputs, name=None):
    datas = [i._data for i in inputs]
    shp = np.broadcast_shapes(*[d.shape for d in datas])
    return [apply_op("broadcast_to", lambda v: jnp.broadcast_to(v, shp), i)
            for i in inputs]


def flip(x, axis, name=None):
    ax = axis if isinstance(axis, (list, tuple)) else [axis]
    return apply_op("flip", lambda v: jnp.flip(v, tuple(ax)), _t(x))


def roll(x, shifts, axis=None, name=None):
    return apply_op("roll", lambda v: jnp.roll(v, shifts, axis), _t(x))


def gather(x, index, axis=0, name=None):
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    return apply_op("gather",
                    lambda v, i: jnp.take(v, i.reshape(-1) if i.ndim > 1 else i,
                                          axis=ax), _t(x), index)


def gather_nd(x, index, name=None):
    def fn(v, i):
        idx = tuple(jnp.moveaxis(i, -1, 0))
        return v[idx]
    return apply_op("gather_nd", fn, _t(x), index)


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    return apply_op("take_along_axis",
                    lambda v, i: jnp.take_along_axis(v, i, axis=axis),
                    _t(arr), indices)


def put_along_axis(arr, indices, values, axis, reduce="assign",
                   include_self=True, broadcast=True, name=None):
    values = values if isinstance(values, Tensor) else Tensor(values)

    def fn(v, i, val):
        val = jnp.broadcast_to(val.astype(v.dtype), i.shape)
        if reduce == "assign":
            return jnp.put_along_axis(v, i, val, axis=axis, inplace=False)
        dn = jnp.zeros_like(v)
        cnt = jnp.zeros_like(v)
        dims = list(range(v.ndim))
        # scatter-add via .at
        idx = [jnp.broadcast_to(
            jnp.arange(i.shape[d]).reshape([-1 if k == d else 1
                                            for k in range(i.ndim)]), i.shape)
            for d in dims]
        idx[axis] = i
        if reduce in ("add", "sum"):
            return v.at[tuple(idx)].add(val)
        if reduce in ("mul", "multiply"):
            return v.at[tuple(idx)].multiply(val)
        if reduce == "amax":
            return v.at[tuple(idx)].max(val)
        if reduce == "amin":
            return v.at[tuple(idx)].min(val)
        if reduce == "mean":
            summed = v.at[tuple(idx)].add(val)
            counts = jnp.ones_like(v).at[tuple(idx)].add(jnp.ones_like(val))
            return summed / counts
        raise ValueError(f"unknown reduce {reduce}")
    return apply_op("put_along_axis", fn, _t(arr), indices, values)


def scatter(x, index, updates, overwrite=True, name=None):
    def fn(v, i, u):
        i = i.reshape(-1)
        if overwrite:
            return v.at[i].set(u.astype(v.dtype))
        return v.at[i].set(jnp.zeros_like(u, dtype=v.dtype)).at[i].add(
            u.astype(v.dtype))
    return apply_op("scatter", fn, _t(x), index, _t(updates))


def scatter_(x, index, updates, overwrite=True, name=None):
    return x._inplace_assign(scatter(x, index, updates, overwrite))


def scatter_nd_add(x, index, updates, name=None):
    def fn(v, i, u):
        idx = tuple(jnp.moveaxis(i, -1, 0))
        return v.at[idx].add(u.astype(v.dtype))
    return apply_op("scatter_nd_add", fn, _t(x), index, _t(updates))


def scatter_nd(index, updates, shape, name=None):
    zero = Tensor._wrap(jnp.zeros(_static_shape(shape), updates.dtype))
    return scatter_nd_add(zero, index, updates)


def index_select(x, index, axis=0, name=None):
    return apply_op("index_select",
                    lambda v, i: jnp.take(v, i, axis=axis), _t(x), index)


def index_sample(x, index):
    def fn(v, i):
        return jnp.take_along_axis(v, i, axis=1)
    return apply_op("index_sample", fn, _t(x), index)


def index_add(x, index, axis, value, name=None):
    def fn(v, i, val):
        sl = [builtins_slice(None)] * v.ndim
        idx = [jnp.broadcast_to(
            jnp.arange(val.shape[d]).reshape([-1 if k == d else 1
                                              for k in range(val.ndim)]),
            val.shape) for d in range(val.ndim)]
        idx[axis] = jnp.broadcast_to(
            i.reshape([-1 if k == axis else 1 for k in range(val.ndim)]),
            val.shape)
        return v.at[tuple(idx)].add(val.astype(v.dtype))
    return apply_op("index_add", fn, _t(x), index, _t(value))


def index_put(x, indices, value, accumulate=False, name=None):
    idx = tuple(i._data if isinstance(i, Tensor) else i for i in indices)

    def fn(v, val):
        if accumulate:
            return v.at[idx].add(val.astype(v.dtype))
        return v.at[idx].set(val.astype(v.dtype))
    return apply_op("index_put", fn, _t(x), _t(value))


def index_fill(x, index, axis, value, name=None):
    def fn(v, i):
        # NB: module-level `slice` is the paddle op — use the builtin
        sl = [builtins_slice(None)] * v.ndim
        sl[axis] = i
        return v.at[tuple(sl)].set(value)
    return apply_op("index_fill", fn, _t(x), index)


def repeat_interleave(x, repeats, axis=None, name=None):
    r = repeats._data if isinstance(repeats, Tensor) else repeats
    x = _t(x)
    if axis is None:
        x = flatten(x)
        ax = 0
    else:
        ax = axis
    if isinstance(r, int):
        return apply_op("repeat_interleave",
                        lambda v: jnp.repeat(v, r, axis=ax), x)
    total = int(np.asarray(r).sum())
    return apply_op("repeat_interleave",
                    lambda v, rr: jnp.repeat(v, rr, axis=ax,
                                             total_repeat_length=total), x,
                    Tensor._wrap(jnp.asarray(r)))


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    d = _t(x)._data
    res = jnp.unique(d, return_index=return_index, return_inverse=return_inverse,
                     return_counts=return_counts, axis=axis)
    if not (return_index or return_inverse or return_counts):
        return Tensor._wrap(res)
    return tuple(Tensor._wrap(r) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    d = np.asarray(_t(x)._data)
    if axis is None:
        d = d.reshape(-1)
        keep = np.concatenate([[True], d[1:] != d[:-1]])
    else:
        raise NotImplementedError("unique_consecutive with axis")
    out = d[keep]
    rets = [Tensor._wrap(jnp.asarray(out))]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        rets.append(Tensor._wrap(jnp.asarray(inv)))
    if return_counts:
        idx = np.flatnonzero(keep)
        cnt = np.diff(np.append(idx, d.size))
        rets.append(Tensor._wrap(jnp.asarray(cnt)))
    return rets[0] if len(rets) == 1 else tuple(rets)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    x = _t(x)
    if isinstance(pad, Tensor):
        pad = [int(p) for p in pad.numpy()]
    pad = [int(p) for p in pad]
    nd = x.ndim
    if len(pad) == 2 * nd:
        width = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # paddle semantics: pad applies to last len(pad)//2 spatial dims,
        # ordered from last dim backwards (like torch.nn.functional.pad)
        k = len(pad) // 2
        width = [(0, 0)] * (nd - k) + [
            (pad[2 * i], pad[2 * i + 1]) for i in range(k)][::-1]
    jmode = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}[mode]
    if jmode == "constant":
        return apply_op("pad", lambda v: jnp.pad(v, width, jmode,
                                                 constant_values=value), x)
    return apply_op("pad", lambda v: jnp.pad(v, width, jmode), x)


def as_strided(x, shape, stride, offset=0, name=None):
    def fn(v):
        flat = v.reshape(-1)
        idx = np.zeros(tuple(shape), dtype=np.int64) + offset
        for d, (s, st) in enumerate(zip(shape, stride)):
            idx += np.arange(s).reshape([-1 if k == d else 1
                                         for k in range(len(shape))]) * st
        return flat[idx]
    return apply_op("as_strided", fn, _t(x))


def strided_slice(x, axes, starts, ends, strides, name=None):
    def fn(v):
        sl = [builtins_slice(None)] * v.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            sl[ax] = builtins_slice(s, e, st)
        return v[tuple(sl)]
    return apply_op("strided_slice", fn, _t(x))


def slice(x, axes, starts, ends, name=None):
    def _v(s):
        return int(s.item()) if isinstance(s, Tensor) else int(s)

    def fn(v):
        sl = [builtins_slice(None)] * v.ndim
        for ax, s, e in zip(axes, starts, ends):
            sl[ax] = builtins_slice(_v(s), _v(e))
        return v[tuple(sl)]
    return apply_op("slice_op", fn, _t(x))


def builtins_slice(*a):
    import builtins
    return builtins.slice(*a)


def crop(x, shape=None, offsets=None, name=None):
    x = _t(x)
    shape = _static_shape(shape)
    offsets = [0] * x.ndim if offsets is None else [
        int(o.item()) if isinstance(o, Tensor) else int(o) for o in offsets]

    def fn(v):
        sl = tuple(builtins_slice(o, o + (s if s != -1 else v.shape[d] - o))
                   for d, (o, s) in enumerate(zip(offsets, shape)))
        return v[sl]
    return apply_op("crop", fn, x)


def masked_select(x, mask, name=None):
    d = _t(x)._data
    m = mask._data if isinstance(mask, Tensor) else mask
    return Tensor._wrap(d[m])


def masked_fill(x, mask, value, name=None):
    v = value.item() if isinstance(value, Tensor) else value
    return apply_op("masked_fill",
                    lambda d, m: jnp.where(m, jnp.asarray(v, d.dtype), d),
                    _t(x), mask)


def masked_fill_(x, mask, value, name=None):
    return x._inplace_assign(masked_fill(x, mask, value))


def masked_scatter(x, mask, value, name=None):
    d = np.asarray(_t(x)._data).copy()
    m = np.asarray(mask._data, dtype=bool)
    vals = np.asarray(value._data).reshape(-1)
    d[m] = vals[: int(m.sum())]
    return Tensor._wrap(jnp.asarray(d))


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return apply_op("where",
                    lambda c, a, b: jnp.where(c, a, b),
                    condition, _t(x), _t(y))


def nonzero(x, as_tuple=False):
    d = np.asarray(_t(x)._data)
    nz = np.nonzero(d)
    if as_tuple:
        return tuple(Tensor._wrap(jnp.asarray(n)) for n in nz)
    return Tensor._wrap(jnp.asarray(np.stack(nz, axis=1)))


def rotate90(x, k=1, axes=(0, 1)):
    return apply_op("rot90", lambda v: jnp.rot90(v, k, axes), _t(x))


def fill_(x, value):
    x._data = jnp.full_like(x._data, value)
    return x


def zero__(x):
    x._data = jnp.zeros_like(x._data)
    return x


def fill_diagonal_(x, value, offset=0, wrap=False, name=None):
    n = min(x.shape[-2], x.shape[-1])
    idx = jnp.arange(n - (offset if offset >= 0 else -offset))
    x._data = x._data.at[..., idx + (0 if offset >= 0 else -offset),
                         idx + (offset if offset >= 0 else 0)].set(value)
    return x


def atleast_1d(*inputs, name=None):
    outs = [Tensor._wrap(jnp.atleast_1d(_t(i)._data)) for i in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [Tensor._wrap(jnp.atleast_2d(_t(i)._data)) for i in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [Tensor._wrap(jnp.atleast_3d(_t(i)._data)) for i in inputs]
    return outs[0] if len(outs) == 1 else outs


def hsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=1 if x.ndim > 1 else 0)


def vsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=0)


def dsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=2)


def hstack(x, name=None):
    xs = [_t(v) for v in x]
    return apply_op("hstack", lambda *vs: jnp.hstack(vs), *xs)


def vstack(x, name=None):
    xs = [_t(v) for v in x]
    return apply_op("vstack", lambda *vs: jnp.vstack(vs), *xs)


def dstack(x, name=None):
    xs = [_t(v) for v in x]
    return apply_op("dstack", lambda *vs: jnp.dstack(vs), *xs)


def column_stack(x, name=None):
    xs = [_t(v) for v in x]
    return apply_op("column_stack", lambda *vs: jnp.column_stack(vs), *xs)


def row_stack(x, name=None):
    return vstack(x)


def unflatten(x, axis, shape, name=None):
    x = _t(x)
    ax = axis if axis >= 0 else axis + x.ndim
    shp = list(_static_shape(shape))
    if -1 in shp:
        known = int(np.prod([s for s in shp if s != -1]))
        shp[shp.index(-1)] = x.shape[ax] // known
    new_shape = x.shape[:ax] + shp + x.shape[ax + 1:]
    return reshape(x, new_shape)


def unbind(input, axis=0):
    return unstack(input, axis)


def numel(x, name=None):
    return Tensor._wrap(jnp.asarray(int(np.prod(x._data.shape)), jnp.int64))


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    shard_size = (index_num + nshards - 1) // nshards

    def fn(v):
        in_shard = (v // shard_size) == shard_id
        return jnp.where(in_shard, v % shard_size, ignore_value)
    return apply_op("shard_index", fn, _t(input))
