"""paddle.autograd equivalent (reference: python/paddle/autograd/)."""

from __future__ import annotations

from contextlib import contextmanager

import jax

from ..core.autograd import run_backward
from ..core.dispatch import apply_op
from ..core.state import STATE, enable_grad_guard, no_grad_guard
from ..core.tensor import Tensor


class no_grad:
    """Context manager AND decorator (paddle.no_grad)."""

    def __enter__(self):
        self._prev = STATE.grad_enabled
        STATE.grad_enabled = False
        return self

    def __exit__(self, *exc):
        STATE.grad_enabled = self._prev
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*a, **k):
            with no_grad():
                return fn(*a, **k)
        return wrapper


class enable_grad:
    def __enter__(self):
        self._prev = STATE.grad_enabled
        STATE.grad_enabled = True
        return self

    def __exit__(self, *exc):
        STATE.grad_enabled = self._prev
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*a, **k):
            with enable_grad():
                return fn(*a, **k)
        return wrapper


@contextmanager
def set_grad_enabled(mode):
    prev = STATE.grad_enabled
    STATE.grad_enabled = bool(mode)
    try:
        yield
    finally:
        STATE.grad_enabled = prev


def is_grad_enabled():
    return STATE.grad_enabled


def backward(tensors, grad_tensors=None, retain_graph=False):
    """paddle.autograd.backward (reference: python/paddle/autograd/autograd.py)."""
    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is not None and isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]
    run_backward(tensors, grad_tensors, retain_graph=retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None, name=None):
    """paddle.grad — returns grads of outputs w.r.t. inputs without touching
    ``.grad`` (reference: python/paddle/base/dygraph/base.py grad)."""
    outputs = [outputs] if isinstance(outputs, Tensor) else list(outputs)
    inputs = [inputs] if isinstance(inputs, Tensor) else list(inputs)
    if grad_outputs is not None and isinstance(grad_outputs, Tensor):
        grad_outputs = [grad_outputs]
    if retain_graph is None:
        retain_graph = create_graph
    res = run_backward(outputs, grad_outputs, retain_graph=retain_graph,
                       accumulate_into_grad=False, inputs=inputs)
    if not allow_unused:
        for r, i in zip(res, inputs):
            if r is None:
                raise RuntimeError(
                    f"input tensor {i.name} is unused in the graph; pass "
                    "allow_unused=True to get None instead")
    return res


class PyLayerContext:
    """ctx object handed to PyLayer.forward/backward
    (reference: python/paddle/autograd/py_layer.py)."""

    def __init__(self):
        self._saved = []
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self._saved = list(tensors)

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved

    def mark_not_inplace(self, *args):
        self.not_inplace_tensors = args

    def set_materialize_grads(self, value):
        pass


class PyLayerMeta(type):
    def __call__(cls, *args, **kwargs):
        raise RuntimeError("PyLayer is not instantiable; call .apply()")


class PyLayer(metaclass=PyLayerMeta):
    """Custom autograd op with user forward/backward
    (reference: python/paddle/autograd/py_layer.py PyLayer).

    TPU design note: forward runs eagerly (or traced); backward is spliced
    into the tape as a GradNode whose vjp calls the user's backward.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..core.autograd import GradNode

        ctx = PyLayerContext()
        with no_grad():
            outs = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(outs, (tuple, list))
        outs_t = [outs] if single else list(outs)

        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        diff_inputs = [t for t in tensor_inputs if not t.stop_gradient]
        if STATE.grad_enabled and diff_inputs:
            def vjp_fn(cotangents):
                gouts = [Tensor._wrap(c) for c in cotangents]
                with no_grad():
                    gins = cls.backward(ctx, *gouts)
                gins = [gins] if isinstance(gins, Tensor) else list(gins)
                # align with diff_inputs: user returns grads for every tensor
                # input in order; pick the diff ones
                out = []
                k = 0
                for t in tensor_inputs:
                    g = gins[k] if k < len(gins) else None
                    k += 1
                    if t.stop_gradient:
                        continue
                    out.append(None if g is None else
                               (g._data if isinstance(g, Tensor) else g))
                return out

            node = GradNode(cls.__name__, vjp_fn, diff_inputs,
                            [(o._data.shape, o._data.dtype) for o in outs_t])
            for i, o in enumerate(outs_t):
                o.stop_gradient = False
                o._node = node
                o._out_idx = i
                node.set_output(i, o)
        return outs_t[0] if single else tuple(outs_t)


# -- functional API over pure functions (reference: autograd/autograd.py) ----
def _functional(fn):
    def unwrapped(*xs):
        outs = fn(*[Tensor._wrap(x) for x in xs])
        if isinstance(outs, (tuple, list)):
            return tuple(o._data for o in outs)
        return outs._data
    return unwrapped


def vjp(func, xs, v=None):
    xs_list = xs if isinstance(xs, (tuple, list)) else [xs]
    out, vjp_fn = jax.vjp(_functional(func), *[x._data for x in xs_list])
    if v is None:
        import jax.numpy as jnp
        v = jnp.ones_like(out) if not isinstance(out, tuple) else tuple(
            jnp.ones_like(o) for o in out)
    else:
        v = v._data if isinstance(v, Tensor) else tuple(
            t._data for t in v) if isinstance(v, (tuple, list)) else v
    grads = vjp_fn(v)
    wrap = lambda g: Tensor._wrap(g)  # noqa: E731
    out_w = (Tensor._wrap(out) if not isinstance(out, tuple)
             else tuple(map(wrap, out)))
    g_w = tuple(map(wrap, grads))
    return out_w, g_w[0] if len(g_w) == 1 and not isinstance(xs, (tuple, list)) else g_w


def jvp(func, xs, v=None):
    xs_list = xs if isinstance(xs, (tuple, list)) else [xs]
    import jax.numpy as jnp
    if v is None:
        v = tuple(jnp.ones_like(x._data) for x in xs_list)
    else:
        v = tuple(t._data for t in (v if isinstance(v, (tuple, list)) else [v]))
    out, tang = jax.jvp(_functional(func), tuple(x._data for x in xs_list), v)
    wrap = lambda g: Tensor._wrap(g)  # noqa: E731
    out_w = Tensor._wrap(out) if not isinstance(out, tuple) else tuple(map(wrap, out))
    t_w = Tensor._wrap(tang) if not isinstance(tang, tuple) else tuple(map(wrap, tang))
    return out_w, t_w


class Jacobian:
    def __init__(self, data):
        self._d = data

    def __getitem__(self, idx):
        return Tensor._wrap(self._d[idx])

    def __repr__(self):
        return f"Jacobian({self._d.shape})"

    @property
    def shape(self):
        return list(self._d.shape)

    def numpy(self):
        import numpy as np
        return np.asarray(self._d)


def jacobian(ys_fn_or_ys, xs, batch_axis=None):
    """paddle.autograd.jacobian over a function (functional form)."""
    if callable(ys_fn_or_ys):
        fn = _functional(ys_fn_or_ys)
        xs_list = xs if isinstance(xs, (tuple, list)) else [xs]
        jac = jax.jacrev(fn, argnums=tuple(range(len(xs_list))))(
            *[x._data for x in xs_list])
        if len(xs_list) == 1 and not isinstance(xs, (tuple, list)):
            return Jacobian(jac[0] if isinstance(jac, tuple) else jac)
        return tuple(Jacobian(j) for j in jac)
    raise TypeError("jacobian expects a callable first argument")


def hessian(fn, xs, batch_axis=None):
    f = _functional(fn)
    xs_list = xs if isinstance(xs, (tuple, list)) else [xs]
    hes = jax.hessian(f, argnums=tuple(range(len(xs_list))))(
        *[x._data for x in xs_list])
    if len(xs_list) == 1 and not isinstance(xs, (tuple, list)):
        h = hes[0][0] if isinstance(hes, tuple) else hes
        return Jacobian(h)
    return hes


def saved_tensors_hooks(pack_hook, unpack_hook):
    import contextlib
    return contextlib.nullcontext()
