"""paddle.static compat layer (reference: python/paddle/static/).

TPU-native: there is no second graph IR — "static graph" IS jax.jit tracing
(see paddle_tpu.jit).  This module keeps the Program/Executor API shape for
user code portability: a Program records a python callable; Executor.run jits
and runs it."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor


class InputSpec:
    def __init__(self, shape=None, dtype="float32", name=None,
                 stop_gradient=False):
        self.shape = shape
        self.dtype = dtype
        self.name = name

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype})"

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, str(tensor.dtype), name)


class Program:
    def __init__(self):
        self._fn = None

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self


def default_main_program():
    return Program()


def default_startup_program():
    return Program()


class program_guard:
    def __init__(self, main_program=None, startup_program=None):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


class Executor:
    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kwargs):
        if callable(program):
            out = program(**(feed or {}))
            return out if isinstance(out, (list, tuple)) else [out]
        if fetch_list:
            return [f.numpy() if isinstance(f, Tensor) else f
                    for f in fetch_list]
        return []


def data(name, shape, dtype="float32", lod_level=0):
    return InputSpec(shape, dtype, name)


class nn:
    @staticmethod
    def fc(x, size, **kwargs):
        raise NotImplementedError("use paddle_tpu.nn.Linear")


def save(program, path):
    pass


def load(program, path):
    pass


class amp:
    pass
