"""paddle.static: the static-graph twin (Program / Executor / program_guard).

Reference analogue: python/paddle/static/ over the PIR program +
StandaloneExecutor (/root/reference/paddle/fluid/framework/new_executor/
standalone_executor.h:34): user code under ``program_guard`` appends one op
per API call into the current Block; ``Executor.run`` feeds placeholders,
executes the program, fetches results.

TPU-native redesign: there is no second IR to maintain — the "program" is a
recorded list of the very same traceable kernels eager mode dispatches
(core/dispatch.py appends each op while a Program is under guard), and
``Executor.run`` replays that list inside ONE ``jax.jit`` so XLA sees the
whole program and fuses it exactly like the jit path (compiled per
feed-shape signature, like the reference's shape-specialised kernels).
``gradients``/``append_backward`` differentiate the replay with ``jax.grad``
instead of building reverse ops into the program.
"""

from __future__ import annotations

import itertools

import jax
import jax.numpy as jnp
import numpy as np

from ..core import flags as _flags
from ..core.state import STATE
from ..core.tensor import Tensor
from ..profiler import counters as _counters
from ..profiler import host_tracer as _trace


class InputSpec:
    def __init__(self, shape=None, dtype="float32", name=None,
                 stop_gradient=False):
        self.shape = shape
        self.dtype = dtype
        self.name = name

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype})"

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, str(tensor.dtype), name)


class _Node:
    __slots__ = ("name", "fn", "treedef", "leaf_keys", "kwargs", "out_keys")

    def __init__(self, name, fn, treedef, leaf_keys, kwargs, out_keys):
        self.name = name
        self.fn = fn
        self.treedef = treedef
        self.leaf_keys = leaf_keys   # ('var', vid) | ('const', value)
        self.kwargs = kwargs
        self.out_keys = out_keys


class Program:
    """Recorded op list + variable environment (the Block/ProgramDesc
    analogue; one implicit global block)."""

    def __init__(self):
        self._nodes: list[_Node] = []
        self._externals: dict[int, Tensor] = {}  # params/captured tensors
        self._feeds: dict[str, int] = {}         # data() name -> vid
        self._feed_shapes: dict[str, tuple] = {}
        self._next_vid = itertools.count()
        self._compile_cache: dict = {}
        self._keepalive: list = []               # layers created via nn.fc
        self._origin = self   # shared vid namespace across clone()s

    # -- recording (called from core.dispatch._maybe_record) ---------------
    def _vid_of(self, t, create_external=True):
        ref = getattr(t, "_prog_ref", None)
        if ref is not None and ref[0]._origin is self._origin:
            return ref[1]
        if not create_external:
            return None
        vid = next(self._next_vid)
        t._prog_ref = (self, vid)
        self._externals[vid] = t  # parameter/constant input: resolved live
        return vid

    @staticmethod
    def _is_rng_key(t):
        try:
            return jax.dtypes.issubdtype(t._data.dtype, jax.dtypes.prng_key)
        except (AttributeError, TypeError):
            return False

    def _record(self, name, fn, treedef, leaves, kwargs, outputs):
        leaf_keys = []
        for leaf in leaves:
            if isinstance(leaf, Tensor):
                if self._is_rng_key(leaf):
                    # PRNG key argument (e.g. functional dropout): recorded
                    # as a per-run rng leaf — replay folds the run's root key
                    # with this slot id, so every Executor.run re-randomizes
                    # instead of replaying the dispatch-time sample
                    leaf_keys.append(("rng", next(self._next_vid)))
                else:
                    leaf_keys.append(("var", self._vid_of(leaf)))
            else:
                leaf_keys.append(("const", leaf))
        outs = outputs if isinstance(outputs, tuple) else (outputs,)
        out_keys = []
        for t in outs:
            vid = next(self._next_vid)
            t._prog_ref = (self, vid)
            out_keys.append(vid)
        self._nodes.append(_Node(name, fn, treedef, leaf_keys, dict(kwargs),
                                 out_keys))
        self._compile_cache.clear()

    def _add_feed(self, name, shape, dtype):
        placeholder_shape = tuple(1 if (s is None or s < 0) else int(s)
                                  for s in (shape or ()))
        t = Tensor._wrap(jnp.zeros(placeholder_shape, dtype))
        vid = next(self._next_vid)
        t._prog_ref = (self, vid)
        self._feeds[name] = vid
        self._feed_shapes[name] = tuple(shape or ())
        return t

    # -- replay -------------------------------------------------------------
    def _run_nodes(self, env, overrides=None, rng_root=None):
        """Replay the op list.  ``overrides`` maps vids to values that take
        the place of their producer's output (and of their env0 entry) —
        which is what differentiating w.r.t. those variables means:
        downstream consumers see the overrides, the producers' values are
        discarded.  ``rng_root`` is the per-run PRNG root: each ("rng", n)
        leaf resolves to fold_in(rng_root, n)."""
        if overrides:
            env.update(overrides)
        for node in self._nodes:
            datas = []
            for kind, k in node.leaf_keys:
                if kind == "var":
                    datas.append(env[k])
                elif kind == "rng":
                    root = rng_root if rng_root is not None \
                        else jax.random.key(0)
                    datas.append(jax.random.fold_in(root, k))
                else:
                    datas.append(k)
            rebuilt = jax.tree_util.tree_unflatten(node.treedef, datas)
            out = node.fn(*rebuilt, **node.kwargs)
            outs = out if isinstance(out, tuple) else (out,)
            for vid, o in zip(node.out_keys, outs):
                if not overrides or vid not in overrides:
                    env[vid] = o
        return env

    # -- introspection ------------------------------------------------------
    @property
    def ops(self):
        return [n.name for n in self._nodes]

    def global_block(self):
        return self

    def block(self, i=0):
        return self

    def clone(self, for_test=False):
        p = Program()
        p._nodes = list(self._nodes)
        p._externals = dict(self._externals)
        p._feeds = dict(self._feeds)
        p._feed_shapes = dict(self._feed_shapes)
        # clones share the origin's vid namespace, so variables recorded in
        # either remain fetchable from both and new vids never collide
        p._origin = self._origin
        p._next_vid = self._origin._next_vid
        return p

    def to_string(self):
        from ..ops import SPMD_RULES
        lines = []
        feed_names = {v: k for k, v in self._feeds.items()}
        for vid, name in sorted(feed_names.items()):
            lines.append(f"%{vid} = feed[{name!r}] "
                         f"shape={self._feed_shapes[name]}")
        for vid, t in sorted(self._externals.items()):
            lines.append(f"%{vid} = param shape={tuple(t.shape)} "
                         f"dtype={t.dtype}")
        for n in self._nodes:
            ins = ", ".join(f"%{k}" if kind == "var" else repr(k)
                            for kind, k in n.leaf_keys)
            outs = ", ".join(f"%{k}" for k in n.out_keys)
            attrs = f" {n.kwargs}" if n.kwargs else ""
            rule = SPMD_RULES.get(n.name)
            spmd = f"  [spmd: {rule}]" if rule else ""
            lines.append(f"{outs} = {n.name}({ins}){attrs}{spmd}")
        return "\n".join(lines)

    __str__ = to_string
    __repr__ = to_string


class _GradVar:
    """Marker returned by gradients()/append_backward(): fetchable handle
    for d(sum of targets)/d(wrt)."""

    def __init__(self, program, target_vids, wrt_vid, name):
        self.program = program
        self.target_vids = tuple(target_vids)
        self.wrt_vid = wrt_vid
        self.name = name


_DEFAULT_MAIN = Program()
_DEFAULT_STARTUP = Program()


def default_main_program():
    return _DEFAULT_MAIN


def default_startup_program():
    return _DEFAULT_STARTUP


class program_guard:
    """Route op recording into ``main_program`` (reference:
    python/paddle/base/framework.py program_guard)."""

    def __init__(self, main_program=None, startup_program=None):
        self.main = main_program if main_program is not None else Program()
        self.startup = startup_program

    def __enter__(self):
        self._prev = STATE.recording_program
        STATE.recording_program = self.main
        return self

    def __exit__(self, *a):
        STATE.recording_program = self._prev
        return False


def data(name, shape, dtype="float32", lod_level=0):
    """Declare a feed placeholder in the current program (reference:
    python/paddle/static/input.py data)."""
    prog = STATE.recording_program
    if prog is None:
        return InputSpec(shape, dtype, name)
    return prog._add_feed(name, shape, dtype)


def gradients(targets, inputs, target_gradients=None):
    """d(sum over all targets)/d(inputs) as fetchable handles (reference:
    python/paddle/base/backward.py gradients)."""
    if target_gradients is not None:
        raise NotImplementedError(
            "gradients(target_gradients=...) custom cotangents are not "
            "supported; compose the weighting into the target expression")
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    prog = targets[0]._prog_ref[0]
    t_vids = []
    for t in targets:
        ref = getattr(t, "_prog_ref", None)
        if ref is None or ref[0]._origin is not prog._origin:
            raise ValueError("gradients(): targets belong to different "
                             "programs")
        t_vids.append(ref[1])
    out = []
    for w in inputs:
        ref = getattr(w, "_prog_ref", None)
        if ref is None or ref[0]._origin is not prog._origin:
            raise ValueError("gradients(): input is not a variable of the "
                             "target's program")
        out.append(_GradVar(prog, t_vids, ref[1], f"grad_{ref[1]}"))
    return out


def append_backward(loss, parameter_list=None):
    """Classic static API: returns [(param, grad_handle)] (reference:
    python/paddle/base/backward.py append_backward)."""
    prog = loss._prog_ref[0]
    if parameter_list is None:
        parameter_list = [t for t in prog._externals.values()
                          if not t.stop_gradient]
    grads = gradients(loss, list(parameter_list))
    return list(zip(parameter_list, grads))


class Executor:
    """Compile-and-run the recorded program (reference:
    StandaloneExecutor; here the whole program replays inside one jax.jit
    per feed-shape signature)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kwargs):
        with _trace.span("static.executor_run"):
            return self._run_impl(program, feed, fetch_list, **kwargs)

    def _run_impl(self, program=None, feed=None, fetch_list=None, **kwargs):
        _counters.inc("static.runs")
        feed = feed or {}
        # legacy convenience: Executor.run(callable)
        if callable(program) and not isinstance(program, Program):
            out = program(**feed)
            return out if isinstance(out, (list, tuple)) else [out]
        if program is None:
            program = default_main_program()
        if not program._nodes:  # startup program: params already initialized
            return []
        if not fetch_list:
            return []
        fetch_list = (fetch_list if isinstance(fetch_list, (list, tuple))
                      else [fetch_list])

        missing = sorted(set(program._feeds) - set(feed))
        if missing:
            raise KeyError(f"missing feed(s) {missing}: every data() "
                           f"placeholder of the program must be fed")
        feed_vids = []
        feed_vals = []
        for name, val in sorted(feed.items()):
            if name not in program._feeds:
                raise KeyError(f"feed '{name}' is not a data() placeholder "
                               f"of this program (have "
                               f"{sorted(program._feeds)})")
            feed_vids.append(program._feeds[name])
            feed_vals.append(jnp.asarray(val))

        fetch_spec = []
        for f in fetch_list:
            if isinstance(f, _GradVar):
                fetch_spec.append(("grad", f.target_vids, f.wrt_vid))
            else:
                ref = getattr(f, "_prog_ref", None)
                if ref is None or ref[0]._origin is not program._origin:
                    raise ValueError("fetch target is not a variable of "
                                     "this program")
                fetch_spec.append(("val", ref[1], None))

        ext_vids = sorted(program._externals)
        ext_vals = [program._externals[v]._data for v in ext_vids]

        key = (tuple(feed_vids),
               tuple((v.shape, str(v.dtype)) for v in feed_vals),
               tuple(fetch_spec))
        compiled = program._compile_cache.get(key)
        if compiled is None:
            # group grad fetches by target set: ONE jax.grad over a tuple of
            # wrt values per group, so P requested grads cost 1 + G forward
            # traces (G = distinct target sets, usually 1) instead of 1 + P
            grad_groups = {}
            for i, (kind, a, b) in enumerate(fetch_spec):
                if kind == "grad":
                    grad_groups.setdefault(a, []).append((i, b))

            def replay(feeds, exts, rng_root):
                _counters.inc("static.traces")  # python body runs per trace
                env0 = dict(zip(feed_vids, feeds))
                env0.update(zip(ext_vids, exts))
                env = program._run_nodes(dict(env0), rng_root=rng_root)
                results = [None] * len(fetch_spec)
                for i, (kind, a, b) in enumerate(fetch_spec):
                    if kind == "val":
                        results[i] = env[a]
                for t_vids, wrts in grad_groups.items():
                    uniq = list(dict.fromkeys(b for _, b in wrts))

                    def scalar_target(wvals, _ts=t_vids, _uniq=tuple(uniq)):
                        e = program._run_nodes(
                            dict(env0), overrides=dict(zip(_uniq, wvals)),
                            rng_root=rng_root)
                        return sum(jnp.sum(e[t]) for t in _ts)
                    # differentiate at the variables' actual values — for
                    # feeds/externals that's env0, for intermediates the
                    # forward pass's produced value
                    ats = tuple(env0.get(b, env.get(b)) for b in uniq)
                    grads = jax.grad(scalar_target)(ats)
                    gmap = dict(zip(uniq, grads))
                    for i, b in wrts:
                        results[i] = gmap[b]
                return results

            compiled = jax.jit(replay)
            program._compile_cache[key] = compiled
            _counters.inc("static.compiles")
        from ..tensor.random import _DEFAULT_GEN
        with _trace.span("static.dispatch"):
            outs = compiled(feed_vals, ext_vals, _DEFAULT_GEN.next_key())
            results = [np.asarray(o) for o in outs]
        if _flags.flag("FLAGS_check_nan_inf"):
            bad = [i for i, r in enumerate(results)
                   if np.issubdtype(r.dtype, np.floating)
                   and not np.isfinite(r).all()]
            if bad:
                stack = _trace.current_stack()
                ctx = (f" [active spans: {' > '.join(stack)}]" if stack
                       else "")
                raise FloatingPointError(
                    f"FLAGS_check_nan_inf: non-finite values in Executor.run "
                    f"fetch indices {bad}{ctx}")
        return results

    def close(self):
        pass


class CompiledProgram:
    """Compat alias: programs always compile via jax.jit on first run."""

    def __init__(self, program, build_strategy=None):
        self._program = program

    def __getattr__(self, item):
        return getattr(self._program, item)


class nn:
    """Static-mode layer helpers (reference: python/paddle/static/nn/)."""

    @staticmethod
    def fc(x, size, num_flatten_dims=1, activation=None, name=None):
        import paddle_tpu as paddle
        # dims [num_flatten_dims:] flatten into the weight's input dim
        # (reference: static/nn/common.py fc)
        nfd = num_flatten_dims if num_flatten_dims >= 0 else len(x.shape) - 1
        in_feats = int(np.prod([int(s) for s in x.shape[nfd:]]))
        layer = paddle.nn.Linear(in_feats, size)
        prog = STATE.recording_program
        if prog is not None:
            prog._keepalive.append(layer)
        if nfd != len(x.shape) - 1:
            # -1 on the batch dim keeps the program feed-shape-polymorphic
            lead = [-1] + [int(s) for s in x.shape[1:nfd]]
            x = paddle.reshape(x, lead + [in_feats])
        out = layer(x)
        if activation == "relu":
            out = paddle.nn.functional.relu(out)
        elif activation == "tanh":
            out = paddle.tanh(out)
        elif activation == "softmax":
            out = paddle.nn.functional.softmax(out)
        elif activation is not None:
            raise ValueError(f"unsupported fc activation '{activation}'")
        return out

    @staticmethod
    def sparse_embedding(input, size, **kwargs):
        from ..distributed.ps import SparseEmbedding
        emb = SparseEmbedding(kwargs.get("name", "sparse_emb"),
                              size[0], size[1])
        return emb(input)


def save(program, path):
    """Persist the program's parameters (the program structure itself lives
    in python; for a deployable artifact use paddle_tpu.jit.save →
    StableHLO)."""
    arrs = {str(vid): np.asarray(t._data)
            for vid, t in program._externals.items()}
    np.savez(path if path.endswith(".npz") else path + ".npz", **arrs)


def load(program, path):
    data_ = np.load(path if path.endswith(".npz") else path + ".npz")
    for vid_s, arr in data_.items():
        t = program._externals.get(int(vid_s))
        if t is not None:
            t._data = jnp.asarray(arr)


def global_scope():
    return _DEFAULT_MAIN


class scope_guard:
    def __init__(self, scope):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


class amp:
    pass
