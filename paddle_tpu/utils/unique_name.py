"""Unique name generator (reference: python/paddle/utils/unique_name.py)."""

import itertools

_counters = {}


def generate(key):
    c = _counters.setdefault(key, itertools.count())
    return f"{key}_{next(c)}"


def guard(new_generator=None):
    import contextlib
    return contextlib.nullcontext()


def switch(new_generator=None):
    pass
