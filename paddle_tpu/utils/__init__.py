"""Utilities (reference: python/paddle/utils/)."""

from . import dlpack, unique_name  # noqa: F401
from .flops import flops  # noqa: F401


def try_import(module_name, err_msg=None):
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(err_msg or f"module {module_name} not found")


def run_check():
    """paddle.utils.run_check (reference: utils/install_check.py)."""
    import jax
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    from ..nn import Linear
    from ..optimizer import SGD
    x = Tensor(jnp.ones((4, 8)), stop_gradient=False)
    lin = Linear(8, 2)
    opt = SGD(0.1, parameters=lin.parameters())
    y = lin(x)
    loss = (y * y).mean()
    loss.backward()
    opt.step()
    dev = jax.devices()[0].platform
    print(f"paddle_tpu is installed successfully! device={dev}, "
          f"n_devices={jax.device_count()}")
    return True


def deprecated(update_to="", since="", reason="", level=0):
    def deco(fn):
        return fn
    return deco


class cpp_extension:
    """Custom-op build surface (reference: utils/cpp_extension/). On TPU,
    custom device ops are Pallas kernels — point users there."""

    @staticmethod
    def load(**kwargs):
        raise RuntimeError(
            "C++/CUDA custom ops do not exist on TPU; write a Pallas kernel "
            "(see paddle_tpu/kernels/) or a jnp composite op instead")

    CppExtension = CUDAExtension = staticmethod(load)
