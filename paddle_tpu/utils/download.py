"""Download helper (reference: python/paddle/utils/download.py). Zero-egress
environment: only local cache hits succeed."""

import os


WEIGHTS_HOME = os.path.expanduser("~/.cache/paddle_tpu/weights")


def get_weights_path_from_url(url, md5sum=None):
    fname = os.path.join(WEIGHTS_HOME, url.split("/")[-1])
    if os.path.exists(fname):
        return fname
    raise RuntimeError(
        f"network access disabled; place the file at {fname} manually")
