"""DLPack interop (reference: paddle/fluid/framework/dlpack_tensor.cc,
python/paddle/utils/dlpack.py)."""

from __future__ import annotations

import jax

from ..core.tensor import Tensor


def to_dlpack(x):
    return x._data.__dlpack__()


def from_dlpack(capsule):
    import jax.numpy as jnp
    return Tensor._wrap(jnp.from_dlpack(capsule))
