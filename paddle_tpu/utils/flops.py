"""Model FLOPs counter (reference: python/paddle/utils/flops.py)."""

from __future__ import annotations

import numpy as np


def flops(net, input_size, custom_ops=None, print_detail=False):
    from ..core.tensor import Tensor
    from ..nn import Conv2D, Linear
    total = [0]
    hooks = []

    def count_linear(layer, inp, out):
        total[0] += 2 * int(np.prod(inp[0].shape)) * layer.weight.shape[1]

    def count_conv(layer, inp, out):
        oshape = out.shape if not isinstance(out, (tuple, list)) else out[0].shape
        kh, kw = layer._kernel_size
        cin = layer._in_channels // layer._groups
        total[0] += 2 * int(np.prod(oshape)) * cin * kh * kw

    for lay in net.sublayers(include_self=True):
        if isinstance(lay, Linear):
            hooks.append(lay.register_forward_post_hook(count_linear))
        elif isinstance(lay, Conv2D):
            hooks.append(lay.register_forward_post_hook(count_conv))
    import jax.numpy as jnp
    x = Tensor(jnp.zeros(input_size, jnp.float32))
    net.eval()
    net(x)
    for h in hooks:
        h.remove()
    return total[0]
