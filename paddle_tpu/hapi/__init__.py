"""High-level API (reference: python/paddle/hapi/model.py:1052 paddle.Model
fit/evaluate/predict + callbacks)."""

from __future__ import annotations

import time

import numpy as np

from ..core.tensor import Tensor


class Callback:
    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.t0 = time.time()

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            items = ", ".join(f"{k}: {v:.4f}" if isinstance(v, float) else
                              f"{k}: {v}" for k, v in (logs or {}).items())
            print(f"epoch {self.epoch} step {step}: {items}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            self.model.save(f"{self.save_dir}/{epoch}")


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        self.monitor = monitor
        self.patience = patience
        self.best = None
        self.wait = 0
        self.stopped = False
        self.mode = "min" if mode in ("auto", "min") else "max"

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        better = (self.best is None
                  or (cur < self.best if self.mode == "min" else cur > self.best))
        if better:
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stopped = True
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        self.by_step = by_step
        self.by_epoch = by_epoch

    def on_train_batch_end(self, step, logs=None):
        from ..optimizer.lr import LRScheduler as Sched
        opt = self.model._optimizer
        if self.by_step and isinstance(opt._learning_rate, Sched):
            opt._learning_rate.step()

    def on_epoch_end(self, epoch, logs=None):
        from ..optimizer.lr import LRScheduler as Sched
        opt = self.model._optimizer
        if self.by_epoch and isinstance(opt._learning_rate, Sched):
            opt._learning_rate.step()


class Model:
    """paddle.Model (reference: hapi/model.py:1052)."""

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self.stop_training = False

    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = metrics if isinstance(metrics, (list, tuple)) else \
            ([metrics] if metrics else [])

    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        outs = self.network(*inputs)
        losses = []
        if labels is not None:
            labels = labels if isinstance(labels, (list, tuple)) else [labels]
            loss = self._loss(outs, *labels) if not isinstance(
                outs, (list, tuple)) else self._loss(*outs, *labels)
            losses.append(loss)
            loss.backward()
            if update:
                self._optimizer.step()
                self._optimizer.clear_grad()
        return [l.numpy() for l in losses]

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        outs = self.network(*inputs)
        metrics = []
        if labels is not None and self._loss is not None:
            labels = labels if isinstance(labels, (list, tuple)) else [labels]
            loss = self._loss(outs, *labels)
            metrics.append(loss.numpy())
        return metrics

    def predict_batch(self, inputs):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        outs = self.network(*inputs)
        return [o.numpy() for o in (outs if isinstance(outs, (list, tuple))
                                    else [outs])]

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        from ..io import DataLoader, Dataset
        if isinstance(train_data, Dataset):
            train_loader = DataLoader(train_data, batch_size=batch_size,
                                      shuffle=shuffle, drop_last=drop_last,
                                      num_workers=num_workers)
        else:
            train_loader = train_data
        cbs = [ProgBarLogger(log_freq, verbose)] + (callbacks or [])
        for cb in cbs:
            cb.set_model(self)
        for cb in cbs:
            cb.on_train_begin()
        it = 0
        for epoch in range(epochs):
            for cb in cbs:
                cb.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            for step, batch in enumerate(train_loader):
                data, label = (batch[:-1], batch[-1]) if isinstance(
                    batch, (list, tuple)) and len(batch) > 1 else (batch, None)
                self.network.train()
                data_list = list(data) if isinstance(data, (list, tuple)) \
                    else [data]
                outs = self.network(*data_list)
                loss = self._loss(outs, label)
                loss.backward()
                self._optimizer.step()
                self._optimizer.clear_grad()
                logs = {"loss": float(loss.numpy())}
                for m in self._metrics:
                    corr = m.compute(outs, label)
                    res = m.update(corr)
                    logs[m.name()[0] if isinstance(m.name(), list)
                         else m.name()] = res
                for cb in cbs:
                    cb.on_train_batch_end(step, logs)
                it += 1
                if num_iters is not None and it >= num_iters:
                    break
            for cb in cbs:
                cb.on_epoch_end(epoch, {})
            if eval_data is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_data, batch_size=batch_size,
                              num_workers=num_workers, verbose=verbose)
            if self.stop_training:
                break
        for cb in cbs:
            cb.on_train_end()

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        from ..io import DataLoader, Dataset
        if isinstance(eval_data, Dataset):
            loader = DataLoader(eval_data, batch_size=batch_size,
                                num_workers=num_workers)
        else:
            loader = eval_data
        self.network.eval()
        for m in self._metrics:
            m.reset()
        losses = []
        for batch in loader:
            data, label = (batch[:-1], batch[-1]) if isinstance(
                batch, (list, tuple)) and len(batch) > 1 else (batch, None)
            data_list = list(data) if isinstance(data, (list, tuple)) else [data]
            outs = self.network(*data_list)
            if self._loss is not None and label is not None:
                losses.append(float(self._loss(outs, label).numpy()))
            for m in self._metrics:
                m.update(m.compute(outs, label))
        res = {"loss": [float(np.mean(losses))] if losses else []}
        for m in self._metrics:
            name = m.name()[0] if isinstance(m.name(), list) else m.name()
            res[name] = m.accumulate()
        if verbose:
            print("Eval:", res)
        return res

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        from ..io import DataLoader, Dataset
        if isinstance(test_data, Dataset):
            loader = DataLoader(test_data, batch_size=batch_size,
                                num_workers=num_workers)
        else:
            loader = test_data
        self.network.eval()
        outputs = []
        for batch in loader:
            data = batch[0] if isinstance(batch, (list, tuple)) else batch
            outputs.append(self.predict_batch([data]))
        return outputs

    def save(self, path, training=True):
        from ..framework import save as psave
        psave(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            psave(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework import load as pload
        state = pload(path + ".pdparams")
        self.network.set_state_dict(state)

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        return summary(self.network, input_size, dtype)


def summary(net, input_size=None, dtypes=None, input=None):
    """paddle.summary (reference: hapi/model_summary.py)."""
    lines = []
    total_params = 0
    trainable = 0
    for name, p in net.named_parameters():
        n = int(np.prod(p.shape)) if p.shape else 1
        total_params += n
        if p.trainable:
            trainable += n
        lines.append(f"  {name:60s} {str(p.shape):20s} {n:>12,d}")
    header = f"{'Layer (param name)':62s} {'Shape':20s} {'Param #':>12s}"
    sep = "-" * len(header)
    print("\n".join([sep, header, sep] + lines + [sep]))
    print(f"Total params: {total_params:,}")
    print(f"Trainable params: {trainable:,}")
    print(sep)
    return {"total_params": total_params, "trainable_params": trainable}


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Model FLOPs for one forward pass (reference: hapi/dynamic_flops.py —
    per-layer hook estimates).

    TPU-native: instead of per-layer formulas, trace the forward under jit
    and read XLA's own cost analysis — exact for whatever the compiler
    will actually run (fusions included)."""
    import jax
    import jax.numpy as jnp
    from ..jit import (bind_layer_state, eval_mode, functional_forward,
                       layer_state)

    if custom_ops:
        import warnings
        warnings.warn(
            "paddle.flops: custom_ops is ignored — counts come from XLA's "
            "cost analysis of the traced forward, not per-layer hooks",
            RuntimeWarning, stacklevel=2)
    shape = tuple(int(s) for s in input_size)
    params, buffers = layer_state(net)
    fwd = functional_forward(net)
    with eval_mode(net):
        try:
            x = jnp.zeros(shape, jnp.float32)
            compiled = jax.jit(fwd).lower(params, buffers, x).compile()
            cost = compiled.cost_analysis() or {}
        finally:
            bind_layer_state(net, params, buffers)
    if "flops" not in cost:
        raise RuntimeError(
            "XLA cost analysis returned no 'flops' entry on this backend; "
            f"keys: {sorted(cost)}")
    total = int(cost["flops"])
    if print_detail:
        print(f"FLOPs (XLA cost analysis, input {shape}): {total:,}")
        for k in ("bytes accessed", "transcendentals"):
            if k in cost:
                print(f"  {k}: {int(cost[k]):,}")
    return total
