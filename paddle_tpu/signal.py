"""paddle.signal — frame / overlap_add / stft / istft.

Reference: /root/reference/python/paddle/signal.py (frame:30, overlap_add
:131, stft:193, istft:368 — thin wrappers over fft + framing kernels).
TPU-native: pure jnp gather/scatter + jnp.fft; XLA fuses the framing with
the FFT's data movement.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .core.dispatch import apply_op
from .core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice overlapping frames (reference layouts, signal.py:30):
    axis=-1: [..., N]  -> [..., frame_length, n_frames]
    axis=0:  [N, ...]  -> [n_frames, frame_length, ...]"""
    if frame_length <= 0 or hop_length <= 0:
        raise ValueError("frame_length and hop_length must be positive")
    if axis not in (0, -1):
        raise ValueError("axis must be 0 or -1 (reference contract)")

    def fn(v):
        ax = 0 if axis == 0 else v.ndim - 1
        n = v.shape[ax]
        if frame_length > n:
            raise ValueError(
                f"frame_length {frame_length} > signal length {n}")
        n_frames = 1 + (n - frame_length) // hop_length
        starts = jnp.arange(n_frames) * hop_length
        if axis == 0:
            idx = starts[:, None] + jnp.arange(frame_length)[None, :]
            out = jnp.take(v, idx.reshape(-1), axis=0)
            return out.reshape((n_frames, frame_length) + v.shape[1:])
        idx = starts[None, :] + jnp.arange(frame_length)[:, None]
        out = jnp.take(v, idx.reshape(-1), axis=ax)
        return out.reshape(v.shape[:-1] + (frame_length, n_frames))

    return apply_op("frame", fn, _t(x))


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of frame (reference layouts, signal.py:131):
    axis=-1: [..., frame_length, n_frames] -> [..., N]
    axis=0:  [n_frames, frame_length, ...] -> [N, ...]"""
    if hop_length <= 0:
        raise ValueError("hop_length must be positive")
    if axis not in (0, -1):
        raise ValueError("axis must be 0 or -1 (reference contract)")

    def fn(v):
        if axis == 0:
            # [nf, fl, ...] -> [..., fl, nf]
            v2 = jnp.moveaxis(v, (0, 1), (-1, -2))
        else:
            v2 = v
        fl, nf = v2.shape[-2], v2.shape[-1]
        n = (nf - 1) * hop_length + fl
        lead = v2.shape[:-2]
        flat = v2.reshape(-1, fl, nf)
        idx = (jnp.arange(nf)[None, :] * hop_length
               + jnp.arange(fl)[:, None])           # [fl, nf]

        def one(sig):
            return jnp.zeros((n,), v.dtype).at[idx.reshape(-1)].add(
                sig.reshape(-1))

        out = jax.vmap(one)(flat).reshape(*lead, n)
        if axis == 0:
            out = jnp.moveaxis(out, -1, 0)
        return out

    return apply_op("overlap_add", fn, _t(x))


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """Short-time Fourier transform (reference: signal.py:193).

    x: [..., N] real (or complex with onesided=False).
    Returns [..., n_fft//2+1 (or n_fft), n_frames] complex."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is not None:
        window = _t(window)

    def fn(v, *w):
        if jnp.iscomplexobj(v) and onesided:
            raise ValueError(
                "stft: onesided must be False for complex input "
                "(reference signal.py contract)")
        win = w[0] if w else jnp.ones((win_length,), jnp.float32)
        if win_length < n_fft:  # center-pad the window to n_fft
            lp = (n_fft - win_length) // 2
            win = jnp.pad(win, (lp, n_fft - win_length - lp))
        if center:
            pad = [(0, 0)] * (v.ndim - 1) + [(n_fft // 2, n_fft // 2)]
            v = jnp.pad(v, pad, mode=pad_mode)
        n = v.shape[-1]
        if n < n_fft:
            raise ValueError(
                f"stft: signal length {n} < n_fft {n_fft} "
                f"(center={center}); pad the input or enable center")
        n_frames = 1 + (n - n_fft) // hop_length
        starts = jnp.arange(n_frames) * hop_length
        idx = starts[:, None] + jnp.arange(n_fft)[None, :]   # [nf, n_fft]
        frames = v[..., idx] * win                           # [..., nf, n_fft]
        if onesided and not jnp.iscomplexobj(v):
            spec = jnp.fft.rfft(frames, n=n_fft, axis=-1)
        else:
            spec = jnp.fft.fft(frames, n=n_fft, axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        return jnp.swapaxes(spec, -1, -2)    # [..., freq, n_frames]

    args = [_t(x)] + ([window] if window is not None else [])
    return apply_op("stft", fn, *args)


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """Inverse STFT with NOLA window-envelope normalization
    (reference: signal.py:368)."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is not None:
        window = _t(window)

    def fn(v, *w):
        expect = n_fft // 2 + 1 if onesided else n_fft
        if v.shape[-2] != expect:
            raise ValueError(
                f"istft: spectrum has {v.shape[-2]} frequency bins, "
                f"expected {expect} for n_fft={n_fft} onesided={onesided}")
        if onesided and return_complex:
            raise ValueError(
                "istft: return_complex=True requires onesided=False "
                "(a onesided inverse is real by construction)")
        win = w[0] if w else jnp.ones((win_length,), jnp.float32)
        if win_length < n_fft:
            lp = (n_fft - win_length) // 2
            win = jnp.pad(win, (lp, n_fft - win_length - lp))
        spec = jnp.swapaxes(v, -1, -2)       # [..., n_frames, freq]
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        if onesided:
            frames = jnp.fft.irfft(spec, n=n_fft, axis=-1)
        else:
            frames = jnp.fft.ifft(spec, n=n_fft, axis=-1)
            if not return_complex:
                frames = frames.real
        frames = frames * win
        nf = frames.shape[-2]
        n = (nf - 1) * hop_length + n_fft
        lead = frames.shape[:-2]
        flat = frames.reshape(-1, nf, n_fft)

        idx = (jnp.arange(nf)[:, None] * hop_length
               + jnp.arange(n_fft)[None, :])

        def one(fr):
            return jnp.zeros((n,), fr.dtype).at[idx.reshape(-1)].add(
                fr.reshape(-1))

        out = jax.vmap(one)(flat)
        env = jnp.zeros((n,), jnp.float32).at[idx.reshape(-1)].add(
            jnp.tile((win.astype(jnp.float32) ** 2)[None], (nf, 1))
            .reshape(-1))
        out = out / jnp.maximum(env, 1e-11)
        out = out.reshape(*lead, n)
        if center:
            out = out[..., n_fft // 2: n - n_fft // 2]
        if length is not None:
            out = out[..., :length]
        return out

    args = [_t(x)] + ([window] if window is not None else [])
    return apply_op("istft", fn, *args)
