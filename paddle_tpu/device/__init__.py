"""Device management (reference: python/paddle/device/ and
phi::Place, /root/reference/paddle/phi/common/place.h:57).

On TPU the device runtime (streams, events, allocators) is owned by
XLA/PJRT — the C++ analogue of the reference's DeviceContext stack ships
inside libtpu. This module provides the paddle-style identity layer: Places,
set_device/get_device, and synchronization."""

from __future__ import annotations

import jax

_CURRENT = None


class Place:
    def __init__(self, kind, device_id=0):
        self._kind = kind
        self._id = device_id

    def __repr__(self):
        return f"Place({self._kind}:{self._id})"

    def __eq__(self, other):
        return (isinstance(other, Place) and self._kind == other._kind
                and self._id == other._id)

    def is_cpu_place(self):
        return self._kind == "cpu"

    def is_tpu_place(self):
        return self._kind == "tpu"

    # compat: treat TPU as "the accelerator"
    def is_gpu_place(self):
        return self._kind == "tpu"


class CPUPlace(Place):
    def __init__(self):
        super().__init__("cpu", 0)


class TPUPlace(Place):
    def __init__(self, device_id=0):
        super().__init__("tpu", device_id)


# compat alias: code written against CUDAPlace runs on TPU
CUDAPlace = TPUPlace


class CUDAPinnedPlace(Place):
    """Pinned-host-memory place (reference: CUDAPinnedPlace). TPU analogue:
    plain host memory — jax device_put from numpy already uses pinned
    staging buffers internally."""

    def __init__(self):
        super().__init__("cpu_pinned", 0)

XPUPlace = TPUPlace
CustomPlace = TPUPlace


def _platform():
    try:
        return jax.devices()[0].platform
    except RuntimeError:
        return "cpu"


def set_device(device):
    """paddle.device.set_device('tpu'|'cpu'|'tpu:0')."""
    global _CURRENT
    name = device.split(":")[0]
    if name in ("gpu", "cuda", "xpu"):
        name = "tpu" if _platform() != "cpu" else "cpu"
    _CURRENT = name
    return TPUPlace() if name == "tpu" else CPUPlace()


def get_device():
    return _current_place()


def _current_place():
    if _CURRENT is not None:
        return f"{_CURRENT}:0"
    return f"{_platform()}:0"


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()})


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return []


def device_count():
    return jax.device_count()


def is_compiled_with_cuda():
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_tpu():
    return any(d.platform in ("tpu", "axon") for d in jax.devices())


def is_compiled_with_distribute():
    return True


def is_compiled_with_cinn():
    # XLA plays CINN's role and is always on
    return True


def synchronize(device=None):
    """Block until all launched work completes (reference:
    paddle.device.synchronize)."""
    for d in jax.live_arrays():
        d.block_until_ready()


class Event:
    """Host-visible completion marker (reference: paddle.device.Event).
    XLA's async dispatch has no user streams; record/synchronize map to
    array readiness."""

    def __init__(self, device=None, enable_timing=False):
        self._arrays = []
        import time
        self._time = None
        self._enable_timing = enable_timing

    def record(self, stream=None):
        import time
        self._arrays = list(jax.live_arrays())
        self._time = time.perf_counter()

    def synchronize(self):
        for a in self._arrays:
            a.block_until_ready()

    def query(self):
        return True

    def elapsed_time(self, end_event):
        return (end_event._time - self._time) * 1000.0


class Stream:
    """Compat shim: XLA:TPU exposes a single ordered execution stream."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        event.synchronize()

    def wait_stream(self, stream):
        synchronize()

    def record_event(self, event=None):
        e = event or Event()
        e.record()
        return e


def current_stream(device=None):
    return Stream(device)


def set_stream(stream):
    return stream


def stream_guard(stream):
    import contextlib
    return contextlib.nullcontext()


class cuda:
    """paddle.device.cuda compat namespace (maps onto the TPU runtime)."""
    Event = Event
    Stream = Stream

    @staticmethod
    def device_count():
        return jax.device_count()

    @staticmethod
    def synchronize(device=None):
        synchronize()

    @staticmethod
    def current_stream(device=None):
        return Stream(device)

    @staticmethod
    def max_memory_allocated(device=None):
        stats = jax.devices()[0].memory_stats() or {}
        return stats.get("peak_bytes_in_use", 0)

    @staticmethod
    def memory_allocated(device=None):
        stats = jax.devices()[0].memory_stats() or {}
        return stats.get("bytes_in_use", 0)

    @staticmethod
    def max_memory_reserved(device=None):
        stats = jax.devices()[0].memory_stats() or {}
        return stats.get("peak_bytes_in_use", 0)

    @staticmethod
    def memory_reserved(device=None):
        stats = jax.devices()[0].memory_stats() or {}
        return stats.get("bytes_limit", 0)

    @staticmethod
    def empty_cache():
        pass
