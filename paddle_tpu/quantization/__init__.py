"""Quantization (reference: python/paddle/quantization/ — QAT qat.py:23,
PTQ ptq.py:24, QuantConfig config.py:60).

TPU-native: fake-quant ops in bf16/int8 with straight-through estimators;
int8/fp8 matmuls lower onto the MXU natively."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import apply_op
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer


def fake_quant(x, scale, bits=8):
    qmax = 2 ** (bits - 1) - 1

    def fn(v, s):
        q = jnp.clip(jnp.round(v / s * qmax), -qmax, qmax)
        dq = q * s / qmax
        # straight-through estimator
        return v + jax.lax.stop_gradient(dq - v)
    return apply_op("fake_quant", fn, x, scale)


class BaseQuanter(Layer):
    def scales(self):
        raise NotImplementedError


class AbsmaxObserver(BaseQuanter):
    def __init__(self, quant_bits=8):
        super().__init__()
        self.bits = quant_bits
        self.register_buffer("_scale", Tensor(jnp.ones((), jnp.float32)))

    def forward(self, x):
        m = jnp.max(jnp.abs(x._data.astype(jnp.float32)))
        self._scale._data = jnp.maximum(self._scale._data, m)
        return fake_quant(x, Tensor._wrap(self._scale._data), self.bits)

    def scales(self):
        return self._scale


class FakeQuanterWithAbsMaxObserver(AbsmaxObserver):
    def __init__(self, moving_rate=0.9, bit_length=8, dtype="float32",
                 name=None):
        super().__init__(bit_length)
        self.moving_rate = moving_rate

    def forward(self, x):
        m = jnp.max(jnp.abs(x._data.astype(jnp.float32)))
        self._scale._data = (self.moving_rate * self._scale._data
                             + (1 - self.moving_rate) * m)
        return fake_quant(x, Tensor._wrap(self._scale._data), self.bits)


QuanterFactory = FakeQuanterWithAbsMaxObserver


class QuantConfig:
    """reference: quantization/config.py:60."""

    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight
        self._layer_configs = {}
        self._type_configs = {}

    def add_layer_config(self, layer, activation=None, weight=None):
        for l in (layer if isinstance(layer, list) else [layer]):
            self._layer_configs[id(l)] = (activation, weight)

    def add_type_config(self, layer_type, activation=None, weight=None):
        for t in (layer_type if isinstance(layer_type, list) else [layer_type]):
            self._type_configs[t] = (activation, weight)

    def _config_for(self, layer):
        if id(layer) in self._layer_configs:
            return self._layer_configs[id(layer)]
        for t, cfg in self._type_configs.items():
            if isinstance(layer, t):
                return cfg
        return (self.activation, self.weight)


class QuantedLinear(Layer):
    def __init__(self, linear, act_quanter, w_quanter):
        super().__init__()
        self.inner = linear
        self.act_quanter = act_quanter() if callable(act_quanter) else act_quanter
        self.w_quanter = w_quanter() if callable(w_quanter) else w_quanter

    def forward(self, x):
        from ..nn import functional as F
        if self.act_quanter is not None:
            x = self.act_quanter(x)
        w = self.inner.weight
        if self.w_quanter is not None:
            w = self.w_quanter(Tensor._wrap(w._data))
        return F.linear(x, w, self.inner.bias)


class QAT:
    """Quantization-aware training (reference: quantization/qat.py:23)."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model, inplace=False):
        from ..nn import Linear
        target = model
        for name, sub in list(target.named_sublayers()):
            if isinstance(sub, Linear):
                act_q, w_q = self.config._config_for(sub)
                if act_q is None and w_q is None:
                    continue
                parts = name.split(".")
                parent = target
                for p in parts[:-1]:
                    parent = getattr(parent, p)
                parent.add_sublayer(parts[-1],
                                    QuantedLinear(sub, act_q, w_q))
        return target

    def convert(self, model, inplace=False):
        return model


class PercentileObserver(BaseQuanter):
    """Clip-to-percentile observer (reference: the PTQ observers under
    quantization/observers/): the running scale tracks the
    ``percentile``-th percentile of |x| instead of the absolute max, so a
    handful of outlier activations can't blow up the quantization grid.
    """

    def __init__(self, quant_bits=8, percentile=99.99):
        super().__init__()
        if not 0.0 < percentile <= 100.0:
            raise ValueError(f"percentile must be in (0, 100], "
                             f"got {percentile}")
        self.bits = quant_bits
        self.percentile = float(percentile)
        self.register_buffer("_scale", Tensor(jnp.ones((), jnp.float32)))

    def forward(self, x):
        m = jnp.percentile(jnp.abs(x._data.astype(jnp.float32)),
                           self.percentile)
        self._scale._data = jnp.maximum(self._scale._data, m)
        return fake_quant(x, Tensor._wrap(self._scale._data), self.bits)

    def scales(self):
        return self._scale


class PTQ(QAT):
    """Post-training quantization (reference: quantization/ptq.py:24)."""
    pass


# ---------------------------------------------------------------------------
# int8 weight-only PTQ over GPT decode-state pytrees
# ---------------------------------------------------------------------------
#: stacked [L, in, out] layer weights eligible for weight-only PTQ; MoE
#: expert weights ([L, E, in, out]) and biases/norms stay full precision.
PTQ_WEIGHTS = ("qkv_w", "proj_w", "fc1_w", "fc2_w")


def channel_scales(w, observer="absmax", percentile=99.99, qmax=127.0):
    """Per-output-channel symmetric scales for a stacked weight
    ``w [L, in, out]``: one fp32 scale per (layer, out) channel, shaped
    ``[L, 1, out]`` so it broadcasts over the contraction result.
    ``observer="absmax"`` uses the channel max; ``"percentile"`` clips to
    the given percentile of |w| per channel (outlier-robust)."""
    wf = jnp.abs(w.astype(jnp.float32))
    if observer == "absmax":
        amax = jnp.max(wf, axis=-2)                        # [L, out]
    elif observer == "percentile":
        amax = jnp.percentile(wf, percentile, axis=-2)
    else:
        raise ValueError(f"observer must be 'absmax' or 'percentile', "
                         f"got {observer!r}")
    return (jnp.maximum(amax, 1e-8) / qmax)[:, None, :]    # [L, 1, out]


def quantize_weight_int8(w, observer="absmax", percentile=99.99):
    """``(q_int8 [L, in, out], scale [L, 1, out] fp32)`` such that
    ``q * scale ~= w`` (symmetric, per-output-channel; values beyond a
    percentile clip saturate at +-127)."""
    scale = channel_scales(w, observer, percentile)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127)
    return q.astype(jnp.int8), scale


def ptq_int8_decode_state(model, observer="absmax", percentile=99.99):
    """Int8 weight-only PTQ of a GPT serving weight pytree: the
    ``decode_state()`` dict with every stacked matmul weight in
    :data:`PTQ_WEIGHTS` replaced by its int8 tensor plus a
    ``<name>__scale`` fp32 per-output-channel companion.  The serving
    programs (``models.gpt._mm``) spot the scale key and fold dequant
    into the matmul epilogue — per-output-channel scales commute with the
    contraction, so logits match fp32 up to the int8 rounding of the
    weights.  Embeddings, the LM head, biases, and norms stay full
    precision; MoE expert stacks (ndim != 3) are skipped."""
    w = model.decode_state()
    lws = dict(w["lws"])
    for name in PTQ_WEIGHTS:
        v = lws.get(name)
        if v is None or v.ndim != 3:
            continue
        q, scale = quantize_weight_int8(v, observer, percentile)
        lws[name] = q
        lws[name + "__scale"] = scale
    w["lws"] = lws
    return w
