"""Data pipeline (reference: python/paddle/io/ — Dataset/DataLoader,
dataloader_iter.py multiprocess workers + LoDTensorBlockingQueue async
staging).

TPU-native: the host pipeline produces numpy batches on background threads
(prefetch queue = the BlockingQueue analogue); device transfer happens once
per step (jnp.asarray) and overlaps with compute thanks to XLA async dispatch.
``DevicePrefetcher`` closes the remaining gap: it issues ``jax.device_put``
for batch N+1 while step N is still executing (depth-2 double buffer), so the
host->device copy never sits on the step critical path.
"""

from __future__ import annotations

import itertools

import threading
import time as _time
import weakref

import numpy as np

from ..core.tensor import Tensor
from ..profiler import counters as _counters
from ..profiler import host_tracer as _trace
from ..profiler import metrics as _metrics


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        return itertools.chain(*self.datasets)


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cum[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        di = int(np.searchsorted(self.cum, idx, side="right"))
        prev = 0 if di == 0 else self.cum[di - 1]
        return self.datasets[di][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    lengths = list(lengths)
    if all(isinstance(l, float) for l in lengths) and abs(sum(lengths) - 1) < 1e-6:
        n = len(dataset)
        lengths = [int(np.floor(n * f)) for f in lengths]
        lengths[-1] = n - sum(lengths[:-1])
    if sum(lengths) != len(dataset):
        raise ValueError("sum of lengths != dataset size")
    perm = np.random.permutation(len(dataset)).tolist()
    out, ofs = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[ofs:ofs + l]))
        ofs += l
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num = num_samples

    @property
    def num_samples(self):
        return self._num if self._num is not None else len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(
            weights.numpy() if isinstance(weights, Tensor) else weights,
            dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(p), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards indices across data-parallel ranks (reference:
    io/dataloader/batch_sampler.py DistributedBatchSampler)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..distributed import get_rank, get_world_size
        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else \
            get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        indices = np.concatenate(
            [indices, indices[: self.total_size - n]])
        indices = indices[self.local_rank::self.nranks]
        batch = []
        for idx in indices:
            batch.append(int(idx))
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        from .native import collate_stack
        return Tensor(collate_stack(batch))
    if isinstance(sample, Tensor):
        from ..tensor.manipulation import stack
        return stack(batch, 0)
    if isinstance(sample, (int, np.integer)):
        return Tensor(np.asarray(batch, np.int64))
    if isinstance(sample, (float, np.floating)):
        return Tensor(np.asarray(batch, np.float32))
    if isinstance(sample, (list, tuple)):
        return tuple(default_collate_fn([b[i] for b in batch])
                     for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    return batch


class _PrefetchIter:
    """Background-thread prefetcher — the BlockingQueue analogue
    (reference: io/dataloader/dataloader_iter.py:365 multiprocess loop)."""

    def __init__(self, loader, index_iter):
        self._loader = loader
        self._index_iter = index_iter
        self._index_lock = threading.Lock()
        self._stop = threading.Event()
        self._seq = itertools.count()
        self._results = {}
        self._cv = threading.Condition()
        self._next_emit = 0
        n = max(1, loader.num_workers)
        self._max_pending = max(2, loader.prefetch_factor) * n
        self._threads = []
        # Start workers only after ALL state above exists — they touch
        # _cv/_results immediately (round-1 deadlock: workers raced a
        # partially-constructed self, died on AttributeError, and the
        # consumer waited forever).  Workers hold only a weakref to self so
        # an abandoned iterator is collectable and its workers exit.
        wref = weakref.ref(self)
        for _ in range(n):
            t = threading.Thread(target=_PrefetchIter._worker_main,
                                 args=(wref,), daemon=True)
            t.start()
            self._threads.append(t)

    @staticmethod
    def _worker_main(wref):
        strong = wref()
        if strong is None:
            return
        # long-lived primitives; none of these keep the iterator alive
        cv = strong._cv
        stop = strong._stop
        index_lock = strong._index_lock
        index_iter = strong._index_iter
        seq_counter = strong._seq
        del strong
        try:
            while not stop.is_set():
                sampler_err = None
                with index_lock:
                    try:
                        indices = next(index_iter)
                    except StopIteration:
                        break
                    except Exception as e:  # broken batch_sampler: deliver,
                        sampler_err = e     # don't silently truncate the epoch
                    seq = next(seq_counter)

                # backpressure: at most _max_pending undelivered batches.
                # Predicate re-resolves the weakref so a blocked worker never
                # pins an abandoned iterator.
                def _ready():
                    st = wref()
                    return (st is None or stop.is_set()
                            or seq - st._next_emit < st._max_pending)

                with cv:
                    while not cv.wait_for(_ready, timeout=0.5):
                        pass
                s = wref()
                if s is None or stop.is_set():
                    return
                if sampler_err is not None:
                    batch = sampler_err
                else:
                    try:
                        batch = s._fetch(indices)
                    except Exception as e:  # propagate to the consumer
                        batch = e
                with cv:
                    s._results[seq] = batch
                    cv.notify_all()
                if isinstance(batch, Exception):
                    break
                del s
        finally:
            # unconditional: a worker dying for ANY reason must never leave
            # the consumer blocked
            s = wref()
            if s is not None:
                with cv:
                    s._results.setdefault("done", None)
                    cv.notify_all()

    def _fetch(self, indices):
        with _trace.span("io.reader"):
            data = [self._loader.dataset[i] for i in indices]
            cf = self._loader.collate_fn or default_collate_fn
            return cf(data)

    def __next__(self):
        t0 = _time.perf_counter_ns()
        with self._cv:
            while True:
                if self._next_emit in self._results:
                    batch = self._results.pop(self._next_emit)
                    self._next_emit += 1
                    self._cv.notify_all()  # wake backpressured workers
                    # time this consumer spent blocked on the worker queue
                    _metrics.observe("io.queue_wait_ns",
                                     _time.perf_counter_ns() - t0,
                                     unit="ns", sum_counter=True)
                    if isinstance(batch, Exception):
                        raise batch
                    return batch
                if "done" in self._results and not any(
                        isinstance(k, int) and k >= self._next_emit
                        for k in self._results):
                    alive = any(t.is_alive() for t in self._threads)
                    if not alive:
                        raise StopIteration
                self._cv.wait(timeout=0.05)

    def __iter__(self):
        return self

    def __del__(self):
        self._stop.set()
        with self._cv:
            self._cv.notify_all()  # wake backpressured workers to exit


class DataLoader:
    """reference: python/paddle/io/reader.py:216 DataLoader."""

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self._is_iterable = isinstance(dataset, IterableDataset)
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = getattr(batch_sampler, "batch_size", batch_size)
        elif self._is_iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)
            self.batch_size = batch_size

    def __len__(self):
        if self._is_iterable:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    def _iter_iterable(self):
        cf = self.collate_fn or default_collate_fn
        batch = []
        for item in self.dataset:
            batch.append(item)
            if len(batch) == self.batch_size:
                yield cf(batch)
                batch = []
        if batch and not getattr(self, "drop_last", False):
            yield cf(batch)

    def __iter__(self):
        if self._is_iterable:
            return self._iter_iterable()
        index_iter = iter(self.batch_sampler)
        if self.num_workers == 0:
            def gen():
                cf = self.collate_fn or default_collate_fn
                for indices in index_iter:
                    with _trace.span("io.reader"):
                        batch = cf([self.dataset[i] for i in indices])
                    yield batch
            return gen()
        return _PrefetchIter(self, index_iter)


class DevicePrefetcher:
    """Depth-``depth`` device double buffer over any batch iterable.

    Wrap a DataLoader (or any iterable yielding Tensors / nested
    tuples/lists/dicts of Tensors or numpy arrays) and iterate the wrapper
    instead: each incoming host batch is pushed through ``jax.device_put``
    the moment the loader produces it, and handed to the consumer
    ``depth - 1`` batches later.  Because jax dispatch is async, the
    transfer for batch N+1 is in flight while the train step for batch N is
    still executing — the copy never blocks the step critical path.  Batch
    values are bit-identical to the plain loader's; only placement/timing
    changes.

        loader = paddle_tpu.io.DataLoader(ds, batch_size=64)
        for x, y in paddle_tpu.io.DevicePrefetcher(loader, depth=2):
            loss = compiled_step(x, y)

    Resume cursor (``resilience.CheckpointManager``): ``consumed`` counts
    batches *delivered to the consumer* (buffered-but-undelivered batches
    don't count — they were never trained on), so it is the exact
    data-iterator offset to checkpoint.  Passing it back as
    ``start_offset`` on a fresh prefetcher over a deterministic loader
    replays the epoch to that position: skipped batches are pulled from the
    loader but neither staged on device nor delivered, and are counted
    under ``io.skipped_batches``.

    Multi-chip: pass ``sharding`` (a ``jax.sharding.NamedSharding``,
    typically ``NamedSharding(mesh, P("dp"))``) instead of ``device`` and
    each batch leaf is placed data-parallel across the mesh in ONE sharded
    ``jax.device_put`` — no per-shard host loop.  Leaves whose batch dim
    does not divide the data axes (or whose rank is below the spec) degrade
    to replicated-on-mesh so the device set stays uniform.  Sharded bytes
    are tallied under ``dist.device_put_sharded_bytes``.
    """

    def __init__(self, loader, depth=2, device=None, start_offset=0,
                 sharding=None):
        self.loader = loader
        self.depth = max(1, int(depth))
        self.device = device
        self.sharding = sharding
        self.start_offset = max(0, int(start_offset))
        self.consumed = self.start_offset

    def __len__(self):
        return max(0, len(self.loader) - self.start_offset)

    def _target(self, shape):
        """Placement target for one batch leaf: the configured sharding
        (spec degraded to replicated when it doesn't fit the leaf), else
        the configured device."""
        if self.sharding is None:
            return self.device, False
        spec = getattr(self.sharding, "spec", None)
        mesh = getattr(self.sharding, "mesh", None)
        if spec is None or mesh is None:
            return self.sharding, True
        from jax.sharding import NamedSharding
        from ..distributed.sharding_utils import validate_spec
        return NamedSharding(mesh, validate_spec(spec, shape, mesh,
                                                 quiet=True)), True

    def _put(self, arr):
        import jax
        target, sharded = self._target(arr.shape)
        _counters.inc("io.device_put_calls")
        _counters.inc("io.device_put_bytes", int(arr.nbytes))
        out = jax.device_put(arr, target)
        if sharded:
            _counters.inc("dist.device_put_sharded_bytes", int(arr.nbytes))
        return out

    def _stage(self, batch):
        if isinstance(batch, Tensor):
            return Tensor._wrap(self._put(batch._data))
        if isinstance(batch, (np.ndarray, np.generic)):
            return Tensor._wrap(self._put(np.asarray(batch)))
        if isinstance(batch, (list, tuple)):
            return type(batch)(self._stage(b) for b in batch)
        if isinstance(batch, dict):
            return {k: self._stage(v) for k, v in batch.items()}
        return batch

    def __iter__(self):
        from collections import deque
        buf = deque()
        it = iter(self.loader)
        self.consumed = self.start_offset
        if self.start_offset:
            # replay-to-offset: drain skipped batches host-side only — no
            # device_put, no staging, just advancing the loader cursor
            with _trace.span("io.skip_replay"):
                skipped = 0
                for _ in range(self.start_offset):
                    try:
                        next(it)
                    except StopIteration:
                        break
                    skipped += 1
                _counters.inc("io.skipped_batches", skipped)
        while True:
            with _trace.span("io.prefetcher"):
                t0 = _time.perf_counter_ns()
                try:
                    batch = next(it)
                except StopIteration:
                    break
                wait = _time.perf_counter_ns() - t0
                # reader wait is a true stall only when the device buffer is
                # dry — otherwise the transfer already in flight hides it
                _counters.inc("io.reader_ns", wait)
                if not buf:
                    _metrics.observe("io.prefetch_stall_ns", wait,
                                     unit="ns", sum_counter=True)
                with _trace.span("io.device_put"):
                    staged = self._stage(batch)
                buf.append(staged)
            if len(buf) >= self.depth:
                self.consumed += 1
                yield buf.popleft()
        while buf:
            self.consumed += 1
            yield buf.popleft()


class Window(tuple):
    """A window of ``k`` training batches stacked along a new leading axis,
    ready for fused multi-step dispatch (``jit.CompiledTrainStep`` with
    ``fused_steps=k``).

    A ``Window`` IS the tuple of stacked step-arguments (``step(*w)``
    unpacks them), carrying the window length as ``.k`` so partial tail
    windows (loader length not a multiple of k) stay self-describing —
    the compiled step falls back to single-step dispatch for them instead
    of dropping or padding batches.
    """

    def __new__(cls, args, k):
        self = tuple.__new__(cls, tuple(args))
        self.k = int(k)
        return self


class StackingPrefetcher:
    """Window feeder for fused multi-step dispatch: stages the next ``k``
    batches on device (through a ``DevicePrefetcher``) and stacks them into
    one ``Window`` while the current window is still executing.

    The stack itself (``jnp.stack`` over already-staged device arrays) is
    async XLA work, so neither the host->device copies nor the stacking sit
    on the step critical path.  Batch values are bit-identical to the plain
    loader's; only placement/grouping changes.

        loader = paddle_tpu.io.DataLoader(ds, batch_size=64)
        step = jit.CompiledTrainStep(model, loss_fn, opt, fused_steps=4)
        for w in paddle_tpu.io.StackingPrefetcher(loader, k=4):
            losses = step(*w)      # ONE XLA launch for 4 steps

    Drain edge: when the loader length is not a multiple of ``k`` (or a
    trailing batch changes shape, e.g. a drop_last=False remainder batch),
    the leftover batches are emitted as a partial ``Window`` (``w.k < k``)
    — never dropped, never padded; the compiled step runs them as single
    steps.

    Multi-chip: pass ``sharding`` (the per-batch data-parallel
    ``NamedSharding``, e.g. ``NamedSharding(mesh, P("dp"))``) and batches
    stage sharded (see ``DevicePrefetcher``); the stacked window is then
    re-pinned to ``P(None, dp...)`` — window axis replicated, batch axis
    sharded — which is exactly the xs layout the mesh-native fused step
    slices per scan iteration.
    """

    def __init__(self, loader, k, depth=None, device=None, start_offset=0,
                 sharding=None):
        self.loader = loader
        self.k = max(1, int(k))
        # double-buffer in window units: the next window's batches stage
        # while the current window runs
        depth = 2 * self.k if depth is None else max(1, int(depth))
        self.start_offset = max(0, int(start_offset))
        self.sharding = sharding
        self._pref = DevicePrefetcher(loader, depth=depth, device=device,
                                      start_offset=self.start_offset,
                                      sharding=sharding)
        # resume cursor in UNDERLYING batches (k per full window), counted
        # when a window is delivered — matches DevicePrefetcher.consumed
        self.consumed = self.start_offset

    def __len__(self):
        n = max(0, len(self.loader) - self.start_offset)
        return (n + self.k - 1) // self.k

    @staticmethod
    def _spec(batch):
        if isinstance(batch, Tensor):
            return ("t", tuple(batch._data.shape), str(batch._data.dtype))
        if isinstance(batch, (list, tuple)):
            return tuple(StackingPrefetcher._spec(b) for b in batch)
        if isinstance(batch, dict):
            return {k: StackingPrefetcher._spec(v)
                    for k, v in sorted(batch.items())}
        return ("py", type(batch).__name__)

    def _restage(self, arr):
        """Pin a K-stacked window leaf to the window version of the batch
        sharding (batch spec shifted right past the new leading window
        axis): ``jnp.stack`` over sharded inputs lets the compiler pick an
        arbitrary output layout, and the fused step needs the stable
        ``P(None, dp...)`` one."""
        if self.sharding is None:
            return arr
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        spec = getattr(self.sharding, "spec", None)
        mesh = getattr(self.sharding, "mesh", None)
        if spec is None or mesh is None:
            return jax.device_put(arr, self.sharding)
        from ..distributed.sharding_utils import validate_spec
        wspec = validate_spec(PartitionSpec(None, *spec), arr.shape, mesh,
                              quiet=True)
        out = jax.device_put(arr, NamedSharding(mesh, wspec))
        _counters.inc("dist.device_put_sharded_bytes", int(arr.nbytes))
        return out

    def _stack(self, items):
        import jax.numpy as jnp
        first = items[0]
        if isinstance(first, Tensor):
            return Tensor._wrap(self._restage(
                jnp.stack([t._data for t in items])))
        if isinstance(first, (list, tuple)):
            return type(first)(self._stack([b[i] for b in items])
                               for i in range(len(first)))
        if isinstance(first, dict):
            return {k: self._stack([b[k] for b in items])
                    for k in first}
        return Tensor._wrap(self._restage(
            jnp.stack([jnp.asarray(x) for x in items])))

    def _emit(self, batches):
        with _trace.span("io.stack_window"):
            _counters.inc("io.stack_windows")
            _counters.inc("io.stack_batches", len(batches))
            stacked = self._stack(batches)
            args = stacked if isinstance(stacked, tuple) else (stacked,)
            self.consumed += len(batches)
            return Window(args, len(batches))

    def __iter__(self):
        pending = []
        spec0 = None
        self.consumed = self.start_offset
        for staged in self._pref:
            s = self._spec(staged)
            if pending and s != spec0:
                # shape/structure break (e.g. a drop_last=False remainder
                # batch): flush what accumulated as a partial window
                yield self._emit(pending)
                pending = []
            if not pending:
                spec0 = s
            pending.append(staged)
            if len(pending) == self.k:
                yield self._emit(pending)
                pending = []
        if pending:
            # loader length not a multiple of k: partial tail window
            yield self._emit(pending)


def get_worker_info():
    return None


class SubsetRandomSampler(Sampler):
    """Sample a fixed index subset in random order (reference:
    io/sampler.py SubsetRandomSampler)."""

    def __init__(self, indices):
        self.indices = list(indices)

    def __iter__(self):
        import numpy as np
        order = np.random.permutation(len(self.indices))
        return iter([self.indices[i] for i in order])

    def __len__(self):
        return len(self.indices)
