// Native batch-collation engine.
//
// Reference analogue: the C++ DataLoader internals
// (/root/reference/paddle/fluid/operators/reader/ buffered_reader.cc and
// the blocking-queue feed pipeline) — batch assembly runs in native code
// off the Python hot path.
//
// TPU-native role: the feed path's job is to keep the host step ahead of
// the device; stacking B sample buffers into one contiguous [B, ...]
// batch is a pure memcpy fan-out, so it parallelizes across std::threads
// with the GIL released (ctypes releases it around the call).  For the
// multi-GB-per-step batches of large-model training this turns the
// collate from a single-core numpy loop into memory-bandwidth-bound
// copies.
//
// Build: g++ -O3 -shared -fPIC -std=c++17 -pthread collate.cc -o
//        libptpu_collate.so   (done lazily by paddle_tpu/io/native.py)

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// Copy n buffers of `bytes` each into dst (contiguous [n, bytes]).
void ptpu_collate(const void** srcs, int64_t n, int64_t bytes, void* dst,
                  int nthreads) {
  if (n <= 0 || bytes <= 0) return;
  char* out = static_cast<char*>(dst);
  if (nthreads <= 1 || n == 1 || n * bytes < (int64_t)1 << 20) {
    for (int64_t i = 0; i < n; ++i)
      std::memcpy(out + i * bytes, srcs[i], bytes);
    return;
  }
  if (nthreads > n) nthreads = static_cast<int>(n);
  std::vector<std::thread> pool;
  pool.reserve(nthreads);
  const int64_t per = (n + nthreads - 1) / nthreads;
  for (int t = 0; t < nthreads; ++t) {
    const int64_t lo = t * per;
    const int64_t hi = lo + per < n ? lo + per : n;
    if (lo >= hi) break;
    pool.emplace_back([=]() {
      for (int64_t i = lo; i < hi; ++i)
        std::memcpy(out + i * bytes, srcs[i], bytes);
    });
  }
  for (auto& th : pool) th.join();
}

// Gather rows: dst[i] = src[idx[i]] for row size `bytes` — the shuffle/
// sampler fast path (one pass instead of python fancy-indexing per item).
void ptpu_gather_rows(const void* src, const int64_t* idx, int64_t n,
                      int64_t bytes, void* dst, int nthreads) {
  const char* in = static_cast<const char*>(src);
  char* out = static_cast<char*>(dst);
  if (nthreads <= 1 || n * bytes < (int64_t)1 << 20) {
    for (int64_t i = 0; i < n; ++i)
      std::memcpy(out + i * bytes, in + idx[i] * bytes, bytes);
    return;
  }
  if (nthreads > n) nthreads = static_cast<int>(n);
  std::vector<std::thread> pool;
  const int64_t per = (n + nthreads - 1) / nthreads;
  for (int t = 0; t < nthreads; ++t) {
    const int64_t lo = t * per;
    const int64_t hi = lo + per < n ? lo + per : n;
    if (lo >= hi) break;
    pool.emplace_back([=]() {
      for (int64_t i = lo; i < hi; ++i)
        std::memcpy(out + i * bytes, in + idx[i] * bytes, bytes);
    });
  }
  for (auto& th : pool) th.join();
}

}  // extern "C"
