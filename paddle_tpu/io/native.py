"""ctypes bindings for the native collation engine (io/_native/collate.cc).

Reference analogue: the C++ reader/feed internals (buffered_reader.cc).
The library builds lazily with g++ on first use and caches next to the
source; every entry point falls back to numpy when the toolchain or the
input layout doesn't qualify.  On a single-core host the copies are
memory-bandwidth-bound either way (numpy parity); the threaded fan-out
pays off on real multi-core TPU-VM hosts where the feed pipeline
competes with the training step for the Python thread.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_native")
_SRC = os.path.join(_DIR, "collate.cc")
_LIB = os.path.join(_DIR, "libptpu_collate.so")
_lock = threading.Lock()
_lib = [None]   # ctypes.CDLL | False (build failed) | None (not tried)


def _load():
    if _lib[0] is not None:
        return _lib[0]
    with _lock:
        if _lib[0] is not None:
            return _lib[0]
        try:
            if (not os.path.exists(_LIB)
                    or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
                tmp = f"{_LIB}.{os.getpid()}.tmp"  # unique: parallel
                # first-use builds from sibling processes must not clobber
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                     "-pthread", _SRC, "-o", tmp],
                    check=True, capture_output=True, timeout=120)
                os.replace(tmp, _LIB)
            lib = ctypes.CDLL(_LIB)
            lib.ptpu_collate.argtypes = [
                ctypes.POINTER(ctypes.c_void_p), ctypes.c_int64,
                ctypes.c_int64, ctypes.c_void_p, ctypes.c_int]
            lib.ptpu_gather_rows.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p,
                ctypes.c_int]
            _lib[0] = lib
        except Exception:
            _lib[0] = False
        return _lib[0]


def native_available():
    return bool(_load())


_NT = min(8, os.cpu_count() or 1)


def collate_stack(arrays):
    """np.stack(arrays) via the native engine; numpy fallback when the
    items aren't large same-shape contiguous buffers."""
    lib = _load()
    n = len(arrays)
    first = arrays[0]
    if (not lib or n < 2 or first.nbytes * n < (1 << 20)
            or first.dtype.hasobject  # PyObject* must be refcounted
            or any(a.shape != first.shape or a.dtype != first.dtype
                   or not a.flags.c_contiguous for a in arrays)):
        return np.stack(arrays)
    out = np.empty((n,) + first.shape, first.dtype)
    ptrs = (ctypes.c_void_p * n)(
        *[a.ctypes.data_as(ctypes.c_void_p).value for a in arrays])
    lib.ptpu_collate(ptrs, n, first.nbytes,
                     out.ctypes.data_as(ctypes.c_void_p), _NT)
    return out


def gather_rows(src, idx):
    """src[idx] along dim 0 via the native engine (the sampler fast path);
    numpy fallback for small or non-contiguous inputs."""
    lib = _load()
    idx = np.ascontiguousarray(idx, np.int64)
    nrows = src.shape[0]
    # numpy index semantics BEFORE the raw-pointer path: wrap negatives,
    # reject out-of-bounds (memcpy would silently read garbage)
    idx = np.where(idx < 0, idx + nrows, idx)
    if idx.size and (int(idx.min()) < 0 or int(idx.max()) >= nrows):
        raise IndexError(
            f"gather_rows: index out of bounds for axis 0 of size {nrows}")
    row_bytes = src.nbytes // max(nrows, 1)
    if (not lib or not src.flags.c_contiguous or src.dtype.hasobject
            or idx.size * row_bytes < (1 << 20)):
        return src[idx]
    out = np.empty((idx.size,) + src.shape[1:], src.dtype)
    lib.ptpu_gather_rows(
        src.ctypes.data_as(ctypes.c_void_p),
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        idx.size, row_bytes, out.ctypes.data_as(ctypes.c_void_p), _NT)
    # numpy fancy-index shape semantics: out shape = idx.shape + row shape
    return out.reshape(idx.shape + src.shape[1:])
