"""paddle.signal: frame/overlap_add/stft/istft (reference:
python/paddle/signal.py; parity vs scipy-style numpy references)."""

import numpy as np
import pytest

import paddle_tpu as paddle


class TestFrame:
    def test_frame_and_inverse(self):
        x = np.arange(16, dtype=np.float32)
        f = paddle.signal.frame(paddle.to_tensor(x), 4, 2)
        fn = np.asarray(f.numpy())
        assert fn.shape == (4, 7)
        for j in range(7):
            assert np.array_equal(fn[:, j], x[j * 2: j * 2 + 4])
        # overlap_add of ones-framed == windowed-count * x pattern
        back = paddle.signal.overlap_add(f, 2)
        exp = np.zeros(16, np.float32)
        for j in range(7):
            exp[j * 2: j * 2 + 4] += x[j * 2: j * 2 + 4]
        assert np.allclose(np.asarray(back.numpy()), exp)

    def test_batched(self):
        x = np.random.RandomState(0).randn(3, 20).astype(np.float32)
        f = paddle.signal.frame(paddle.to_tensor(x), 5, 3)
        assert list(f.shape) == [3, 5, 6]

    def test_axis0_reference_layout(self):
        """axis=0: [N, ...] -> [n_frames, frame_length, ...] (the reference
        docstring example, signal.py:30)."""
        x = np.arange(8, dtype=np.float32)
        f = np.asarray(paddle.signal.frame(
            paddle.to_tensor(x), 4, 2, axis=0).numpy())
        assert f.shape == (3, 4)
        assert np.array_equal(f, [[0, 1, 2, 3], [2, 3, 4, 5], [4, 5, 6, 7]])
        back = paddle.signal.overlap_add(
            paddle.to_tensor(f), 2, axis=0)
        exp = np.zeros(8, np.float32)
        for j in range(3):
            exp[j * 2: j * 2 + 4] += f[j]
        assert np.allclose(np.asarray(back.numpy()), exp)

    def test_overlap_add_axis0_batched(self):
        """axis=0 with trailing dims: [nf, fl, d1, d2] -> [N, d1, d2]
        (the reference overlap_add docstring example shape)."""
        x = np.arange(32, dtype=np.float32).reshape(2, 8, 1, 2)
        out = np.asarray(paddle.signal.overlap_add(
            paddle.to_tensor(x), 2, axis=0).numpy())
        assert out.shape == (10, 1, 2), out.shape
        exp = np.zeros((10, 1, 2), np.float32)
        for j in range(2):
            exp[j * 2: j * 2 + 8] += x[j]
        assert np.allclose(out, exp)


class TestStft:
    @pytest.mark.parametrize("center", [True, False])
    def test_stft_matches_numpy(self, center):
        rng = np.random.RandomState(1)
        x = rng.randn(2, 64).astype(np.float32)
        n_fft, hop = 16, 4
        win = np.hanning(n_fft).astype(np.float32)
        out = paddle.signal.stft(paddle.to_tensor(x), n_fft, hop,
                                 window=paddle.to_tensor(win),
                                 center=center)
        got = np.asarray(out.numpy())
        xr = x
        if center:
            xr = np.pad(x, [(0, 0), (n_fft // 2, n_fft // 2)],
                        mode="reflect")
        n_frames = 1 + (xr.shape[-1] - n_fft) // hop
        assert got.shape == (2, n_fft // 2 + 1, n_frames)
        for b in range(2):
            for j in range(n_frames):
                seg = xr[b, j * hop: j * hop + n_fft] * win
                ref = np.fft.rfft(seg)
                assert np.allclose(got[b, :, j], ref, atol=1e-4), (b, j)

    def test_istft_roundtrip(self):
        rng = np.random.RandomState(2)
        x = rng.randn(64).astype(np.float32)
        n_fft, hop = 16, 4
        win = np.hanning(n_fft).astype(np.float32)
        spec = paddle.signal.stft(paddle.to_tensor(x), n_fft, hop,
                                  window=paddle.to_tensor(win))
        back = paddle.signal.istft(spec, n_fft, hop,
                                   window=paddle.to_tensor(win),
                                   length=64)
        assert np.allclose(np.asarray(back.numpy()), x, atol=1e-4), \
            np.abs(np.asarray(back.numpy()) - x).max()


class TestHermitianFFT:
    """hfft2/ihfft2/hfftn/ihfftn via the irfftn(conj)/conj(rfftn) identities
    (reference: python/paddle/fft.py); torch.fft is the oracle."""

    @pytest.mark.parametrize("norm", ["backward", "forward", "ortho"])
    def test_matches_torch(self, norm):
        import torch

        from paddle_tpu import fft as pfft
        rng = np.random.RandomState(0)
        x = (rng.rand(4, 6) + 1j * rng.rand(4, 6)).astype(np.complex64)
        xr = rng.rand(4, 6).astype(np.float32)
        np.testing.assert_allclose(
            pfft.hfft2(paddle.to_tensor(x), norm=norm).numpy(),
            torch.fft.hfft2(torch.from_numpy(x), norm=norm).numpy(),
            atol=1e-4)
        np.testing.assert_allclose(
            pfft.ihfft2(paddle.to_tensor(xr), norm=norm).numpy(),
            torch.fft.ihfft2(torch.from_numpy(xr), norm=norm).numpy(),
            atol=1e-5)
        np.testing.assert_allclose(
            pfft.hfftn(paddle.to_tensor(x), norm=norm).numpy(),
            torch.fft.hfftn(torch.from_numpy(x), norm=norm).numpy(),
            atol=1e-4)
        np.testing.assert_allclose(
            pfft.ihfftn(paddle.to_tensor(xr), norm=norm).numpy(),
            torch.fft.ihfftn(torch.from_numpy(xr), norm=norm).numpy(),
            atol=1e-5)
