"""fp16 dynamic loss scaling in the compiled step + ZeRO stage semantics.

Reference patterns: amp/grad_scaler.py:619 (scale update / skipped step) and
dygraph_sharding_optimizer.py:44,550 (stage-1 state sharding vs stage-3 param
sharding), exercised the TPU way: everything inside one jitted program on the
8-device virtual CPU mesh."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def np_t(x):
    return np.asarray(x.numpy())


class TestTraceableScaler:
    def test_good_step_updates_and_grows_scale(self):
        paddle.seed(0)
        net = nn.Linear(4, 4)
        opt = paddle.optimizer.AdamW(0.01, parameters=net.parameters())
        scaler = paddle.amp.GradScaler(init_loss_scaling=1024.0,
                                       incr_every_n_steps=2)
        from paddle_tpu.jit import CompiledTrainStep
        step = CompiledTrainStep(net, lambda m, x: (m(x) ** 2).mean(), opt,
                                 scaler=scaler)
        x = paddle.randn([2, 4])
        w0 = np_t(net.weight).copy()
        l0 = float(step(x).numpy())
        assert np.isfinite(l0)
        assert not np.allclose(np_t(net.weight), w0)
        assert int(scaler._good_steps) == 1
        assert float(scaler._scale) == 1024.0
        step(x)
        step.sync()  # state is device-resident between steps
        # second good step hits incr_every_n_steps=2 -> scale doubles
        assert float(scaler._scale) == 2048.0
        assert int(scaler._good_steps) == 0

    def test_overflow_skips_update_and_halves_scale(self):
        paddle.seed(0)
        net = nn.Linear(4, 4)
        opt = paddle.optimizer.AdamW(0.01, parameters=net.parameters())
        scaler = paddle.amp.GradScaler(init_loss_scaling=1024.0)
        from paddle_tpu.jit import CompiledTrainStep
        step = CompiledTrainStep(net, lambda m, x: (m(x) ** 2).mean(), opt,
                                 scaler=scaler)
        x = paddle.randn([2, 4])
        step(x)  # create accumulators with a good step
        w_before = np_t(net.weight).copy()
        m_before = {k: np.asarray(v) for k, v in
                    opt._accumulators.get("moment1", {}).items()}
        xinf = paddle.to_tensor(np.full((2, 4), np.inf, np.float32))
        step(xinf)
        step.sync()  # state is device-resident between steps
        # update skipped: params and moments unchanged, scale halved
        assert np.allclose(np_t(net.weight), w_before)
        for k, v in opt._accumulators.get("moment1", {}).items():
            assert np.allclose(np.asarray(v), m_before[k])
        assert float(scaler._scale) == 512.0
        assert int(scaler._bad_steps) == 0  # reset after decrease
        # recovery: a finite batch trains again
        l = float(step(x).numpy())
        assert np.isfinite(l)
        step.sync()
        assert not np.allclose(np_t(net.weight), w_before)


class TestZeROStages:
    def setup_method(self, _):
        from paddle_tpu.distributed import fleet
        fleet._reset()

    def teardown_method(self, _):
        from paddle_tpu.distributed import fleet
        fleet._reset()

    def _mesh(self, dp, sharding):
        import jax
        if jax.device_count() < dp * sharding:
            pytest.skip("needs %d devices" % (dp * sharding))
        from paddle_tpu.distributed import fleet
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": dp,
                                   "sharding_degree": sharding}
        fleet.init(is_collective=True, strategy=strategy)

    def test_stage1_vs_stage3_specs(self):
        from jax.sharding import PartitionSpec as P
        from paddle_tpu.distributed.fleet.parallel_apply import (
            apply_fsdp_annotations)
        self._mesh(dp=4, sharding=2)
        net1 = nn.Linear(64, 64)
        apply_fsdp_annotations(net1, stage=1)
        # stage 1: params replicated, optimizer-state spec sharded
        assert net1.weight.placements in (None, P())
        assert "sharding" in str(net1.weight._opt_state_spec)
        net3 = nn.Linear(64, 64)
        apply_fsdp_annotations(net3, stage=3)
        assert "sharding" in str(net3.weight.placements)
        assert getattr(net3.weight, "_opt_state_spec", None) is None

    def test_stage2_fp16_amp_compiled(self):
        """BASELINE config #1 shape: DP + sharding stage-2 + fp16 AMP with
        dynamic loss scaling, one compiled program."""
        from paddle_tpu.distributed import DistributedTrainStep
        from paddle_tpu.distributed.fleet.parallel_apply import (
            apply_fsdp_annotations)
        self._mesh(dp=4, sharding=2)
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(32, 64), nn.GELU(), nn.Linear(64, 32))
        apply_fsdp_annotations(net, stage=2, min_size=64)
        opt = paddle.optimizer.AdamW(1e-2, parameters=net.parameters(),
                                     multi_precision=True)
        scaler = paddle.amp.GradScaler(init_loss_scaling=256.0)
        x = paddle.randn([8, 32])
        y = paddle.randn([8, 32])
        step = DistributedTrainStep(
            net, lambda m, a, b: ((m(a) - b) ** 2).mean(), opt, scaler=scaler)
        l0 = float(step(x, y).numpy())
        for _ in range(3):
            l = float(step(x, y).numpy())
        assert np.isfinite(l) and l < l0
        # optimizer accumulators actually sharded over the 'sharding' axis
        sharded = False
        for store in opt._accumulators.values():
            for v in store.values():
                spec = getattr(getattr(v, "sharding", None), "spec", None)
                if spec is not None and "sharding" in str(spec):
                    sharded = True
        assert sharded
