"""Fused multi-step dispatch: CompiledTrainStep(fused_steps=K) contract.

The fused path scans K training steps inside ONE donated XLA program
(one ``jax.lax.scan`` over the shared step body).  The contract it must
keep:

  * bit-identity — a K=4 fused run produces the exact bits of a K=1 run:
    losses, parameters, optimizer state, GradScaler trajectory (including
    an inf-grad skip-step landing INSIDE a fused window), and an lr
    schedule advancing across the window;
  * dispatch economics — a steady-state window is exactly one
    ``jit.host.dispatches`` with zero retraces / rehydrates; the
    first-ever window and partial tail windows fall back to single-step
    dispatch (counter ``jit.fused_fallback_steps``), never drop batches;
  * satellites — ``LRScheduler.peek(k)`` previews without mutating, and
    ``io.StackingPrefetcher`` stacks loader batches into ``io.Window``s
    bit-identically, flushing partial windows on tail/shape breaks.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.jit as pjit
import paddle_tpu.nn as nn
from paddle_tpu.core import flags as cflags
from paddle_tpu.io import StackingPrefetcher, Window
from paddle_tpu.optimizer import lr as lrsched
from paddle_tpu.profiler import counters

K = 4


def _mse(m, x, y):
    return ((m(x) - y) ** 2).mean()


def _make(fused_steps, lr=1e-2, scaler=None, dtype=None, opt_cls=None,
          seed=0):
    paddle.seed(seed)
    net = nn.Linear(8, 4)
    if dtype is not None:
        net.to(dtype=dtype)
    opt_cls = opt_cls or paddle.optimizer.Adam
    opt = opt_cls(parameters=net.parameters(), learning_rate=lr)
    step = pjit.CompiledTrainStep(net, _mse, opt, scaler=scaler,
                                  fused_steps=fused_steps)
    return net, opt, step


def _batches(n, seed=1, dtype="float32", poison=None):
    rng = np.random.RandomState(seed)
    xs = [rng.randn(16, 8).astype(dtype) for _ in range(n)]
    ys = [rng.randn(16, 4).astype(dtype) for _ in range(n)]
    if poison is not None:
        xs[poison] = (np.full((16, 8), 60000.0)
                      if dtype == "float16" else np.full((16, 8), np.inf)
                      ).astype(dtype)
    return xs, ys


def _window(xs, ys, lo, hi):
    return Window((paddle.to_tensor(np.stack(xs[lo:hi])),
                   paddle.to_tensor(np.stack(ys[lo:hi]))), hi - lo)


def _run_single(step, xs, ys, scheduler=None):
    losses = []
    for x, y in zip(xs, ys):
        losses.append(float(step(paddle.to_tensor(x),
                                 paddle.to_tensor(y)).numpy()))
        if scheduler is not None:
            scheduler.step()
    step.sync()
    return np.array(losses, np.float32)


def _run_windows(step, xs, ys, k=K, scheduler=None):
    losses = []
    for lo in range(0, len(xs), k):
        w = _window(xs, ys, lo, min(lo + k, len(xs)))
        losses.extend(np.asarray(step(w).numpy()).tolist())
        if scheduler is not None:
            for _ in range(w.k):
                scheduler.step()
    step.sync()
    return np.array(losses, np.float32)


class TestFusedBitIdentity:
    def test_k4_matches_k1_exactly(self):
        xs, ys = _batches(2 * K)
        n1, o1, s1 = _make(fused_steps=1)
        l1 = _run_single(s1, xs, ys)
        n4, o4, s4 = _make(fused_steps=K)
        l4 = _run_windows(s4, xs, ys)
        assert np.array_equal(l1, l4)
        assert np.array_equal(np.asarray(n1.weight._data),
                              np.asarray(n4.weight._data))
        assert np.array_equal(np.asarray(n1.bias._data),
                              np.asarray(n4.bias._data))
        assert o1._step_count == o4._step_count == 2 * K

    def test_scaler_skip_step_inside_fused_window(self):
        # overflow batch at global step 6 == index 1 of fused window 2:
        # the skip + scale shrink must happen INSIDE the scanned program
        # and leave the exact same scaler/param trajectory as K=1
        xs, ys = _batches(2 * K, dtype="float16", poison=5)

        def mk(k):
            paddle.seed(0)
            net = nn.Linear(8, 4)
            net.to(dtype="float16")
            scaler = paddle.amp.GradScaler(init_loss_scaling=2.0 ** 15,
                                           incr_every_n_steps=2)
            opt = paddle.optimizer.Adam(parameters=net.parameters(),
                                        learning_rate=1e-2)
            step = pjit.CompiledTrainStep(net, _mse, opt, scaler=scaler,
                                          fused_steps=k)
            return net, scaler, step

        n1, sc1, s1 = mk(1)
        l1 = _run_single(s1, xs, ys)
        n4, sc4, s4 = mk(K)
        l4 = _run_windows(s4, xs, ys)
        # the overflow step's loss is inf in both runs, at the same index
        assert np.array_equal(np.isfinite(l1), np.isfinite(l4))
        assert np.array_equal(l1[np.isfinite(l1)], l4[np.isfinite(l4)])
        assert np.array_equal(np.asarray(n1.weight._data, np.float32),
                              np.asarray(n4.weight._data, np.float32))
        assert float(sc1._scale) == float(sc4._scale)
        assert (sc1._good_steps, sc1._bad_steps) == \
               (sc4._good_steps, sc4._bad_steps)

    def test_lr_schedule_advances_inside_window(self):
        # decay boundary at step 3 lands inside the first fused window's
        # successor: the scan's lr xs-vector must track what a K=1 run
        # stepping the scheduler after every step would use
        xs, ys = _batches(2 * K)

        def mk(k):
            sched = lrsched.StepDecay(learning_rate=0.1, step_size=3,
                                      gamma=0.5)
            net, opt, step = _make(fused_steps=k, lr=sched)
            return net, opt, step, sched

        n1, _, s1, sched1 = mk(1)
        l1 = _run_single(s1, xs, ys, scheduler=sched1)
        n4, _, s4, sched4 = mk(K)
        l4 = _run_windows(s4, xs, ys, scheduler=sched4)
        assert np.array_equal(l1, l4)
        assert np.array_equal(np.asarray(n1.weight._data),
                              np.asarray(n4.weight._data))
        assert sched1.last_lr == sched4.last_lr

    def test_window_on_unfused_step_runs_as_singles(self):
        # a Window handed to a fused_steps=1 step is serviced (fallback
        # loop), bit-identical to calling the step per batch
        xs, ys = _batches(K)
        _, _, s1 = _make(fused_steps=1)
        ref = _run_single(s1, xs, ys)
        _, _, sw = _make(fused_steps=1)
        got = np.asarray(sw(_window(xs, ys, 0, K)).numpy())
        assert got.shape == (K,)
        assert np.array_equal(ref, got.astype(np.float32))


class TestFusedDispatchEconomics:
    def test_priming_window_falls_back_to_singles(self):
        xs, ys = _batches(K)
        _, _, step = _make(fused_steps=K)
        before = counters.snapshot()
        step(_window(xs, ys, 0, K))
        d = counters.delta(before)
        assert d.get("jit.fused_fallback_steps") == K
        assert d.get("jit.host.dispatches") == K
        assert d.get("jit.steps") == K
        assert not d.get("jit.fused_windows")

    def test_steady_window_is_one_dispatch_zero_retrace(self):
        xs, ys = _batches(3 * K, seed=3)
        _, _, step = _make(fused_steps=K)
        step(_window(xs, ys, 0, K))            # priming (fallback singles)
        step(_window(xs, ys, K, 2 * K)).numpy()  # scan compile
        before = counters.snapshot()
        step(_window(xs, ys, 2 * K, 3 * K)).numpy()  # steady state
        d = counters.delta(before)
        assert d.get("jit.host.dispatches") == 1
        assert d.get("jit.steps") == K
        assert d.get("jit.fused_windows") == 1
        assert d.get("jit.cache_hits") == 1
        assert not d.get("jit.traces")
        assert not d.get("jit.hydrates")
        assert not d.get("jit.cache_misses")
        assert not d.get("jit.host.param_binds")

    def test_partial_tail_window_single_step_fallback(self):
        n = 2 * K + 3  # tail of 3 < K
        xs, ys = _batches(n, seed=4)
        _, _, step = _make(fused_steps=K)
        step(_window(xs, ys, 0, K))
        step(_window(xs, ys, K, 2 * K))
        before = counters.snapshot()
        tail = step(_window(xs, ys, 2 * K, n))
        d = counters.delta(before)
        assert np.asarray(tail.numpy()).shape == (3,)
        assert d.get("jit.fused_fallback_steps") == 3
        assert d.get("jit.host.dispatches") == 3
        assert d.get("jit.steps") == 3

    def test_raw_stacked_args_infer_window_length(self):
        # fused mode accepts bare K-stacked tensors (no Window wrapper)
        xs, ys = _batches(K, seed=5)
        _, _, step = _make(fused_steps=K)
        out = step(paddle.to_tensor(np.stack(xs)),
                   paddle.to_tensor(np.stack(ys)))
        assert np.asarray(out.numpy()).shape == (K,)

    def test_check_nan_inf_names_step_inside_window(self):
        xs, ys = _batches(2 * K, seed=6)
        xs[K + 2] = np.full((16, 8), np.inf, np.float32)  # window 2, idx 2
        _, _, step = _make(fused_steps=K,
                           opt_cls=paddle.optimizer.SGD)
        step(_window(xs, ys, 0, K))  # prime (clean)
        cflags.set_flags({"FLAGS_check_nan_inf": 1})
        try:
            with pytest.raises(FloatingPointError,
                               match=r"FLAGS_check_nan_inf: non-finite "
                                     r".*train step 7 \(step 2 of a "
                                     r"4-step fused window\)"):
                step(_window(xs, ys, K, 2 * K))
        finally:
            cflags.set_flags({"FLAGS_check_nan_inf": 0})


class TestLRSchedulerPeek:
    def test_peek_matches_stepping(self):
        sched = lrsched.StepDecay(learning_rate=0.1, step_size=2, gamma=0.5)
        preview = sched.peek(6)
        vals = [float(sched.last_lr)]
        for _ in range(5):
            sched.step()
            vals.append(float(sched.last_lr))
        assert preview == vals
        assert preview == [0.1, 0.1, 0.05, 0.05, 0.025, 0.025]

    def test_peek_does_not_mutate(self):
        sched = lrsched.StepDecay(learning_rate=0.1, step_size=2, gamma=0.5)
        sched.step()
        before = (sched.last_epoch, sched.last_lr)
        first = sched.peek(5)
        assert (sched.last_epoch, sched.last_lr) == before
        assert sched.peek(5) == first  # idempotent

    def test_peek_linear_warmup_nested_scheduler_untouched(self):
        # LinearWarmup.get_lr steps its WRAPPED scheduler — the deepcopy
        # probe must keep both layers of state untouched
        inner = lrsched.StepDecay(learning_rate=0.1, step_size=1, gamma=0.5)
        sched = lrsched.LinearWarmup(learning_rate=inner, warmup_steps=3,
                                     start_lr=0.0, end_lr=0.1)
        inner_before = (inner.last_epoch, inner.last_lr)
        preview = sched.peek(6)
        assert (inner.last_epoch, inner.last_lr) == inner_before
        vals = [float(sched.last_lr)]
        for _ in range(5):
            sched.step()
            vals.append(float(sched.last_lr))
        assert preview == pytest.approx(vals)

    def test_peek_validates_k(self):
        sched = lrsched.StepDecay(learning_rate=0.1, step_size=2)
        with pytest.raises(ValueError):
            sched.peek(0)

    def test_optimizer_peek_constant_lr_broadcasts(self):
        paddle.seed(0)
        net = nn.Linear(4, 2)
        opt = paddle.optimizer.SGD(parameters=net.parameters(),
                                   learning_rate=0.25)
        assert opt._peek_lrs(3) == [0.25, 0.25, 0.25]


class TestStackingPrefetcher:
    def _loader(self, n, batch=8, seed=7, last_batch=None):
        rng = np.random.RandomState(seed)
        batches = [(rng.randn(batch, 8).astype("float32"),
                    rng.randn(batch, 4).astype("float32"))
                   for _ in range(n)]
        if last_batch is not None:
            batches.append(last_batch)
        return [(paddle.to_tensor(x), paddle.to_tensor(y))
                for x, y in batches]

    def test_full_windows_bit_identical(self):
        data = self._loader(2 * K)
        wins = list(StackingPrefetcher(data, k=K))
        assert [w.k for w in wins] == [K, K]
        assert len(StackingPrefetcher(data, k=K)) == 2
        for wi, w in enumerate(wins):
            assert isinstance(w, Window) and len(w) == 2
            xs = np.stack([np.asarray(b[0].numpy())
                           for b in data[wi * K:(wi + 1) * K]])
            assert np.array_equal(np.asarray(w[0].numpy()), xs)

    def test_partial_tail_window_not_dropped(self):
        data = self._loader(K + 2)
        wins = list(StackingPrefetcher(data, k=K))
        assert [w.k for w in wins] == [K, 2]
        tail = np.stack([np.asarray(b[0].numpy()) for b in data[K:]])
        assert np.array_equal(np.asarray(wins[1][0].numpy()), tail)
        assert len(StackingPrefetcher(data, k=K)) == 2

    def test_shape_break_flushes_partial_window(self):
        # a drop_last=False remainder batch (smaller leading dim) cannot
        # stack with its window-mates: flush, then window it alone
        rng = np.random.RandomState(8)
        small = (rng.randn(3, 8).astype("float32"),
                 rng.randn(3, 4).astype("float32"))
        data = self._loader(K + 1, last_batch=small)
        wins = list(StackingPrefetcher(data, k=K))
        assert [w.k for w in wins] == [K, 1, 1]
        assert np.asarray(wins[2][0].numpy()).shape == (1, 3, 8)

    def test_counters(self):
        data = self._loader(K + 1)
        before = counters.snapshot()
        list(StackingPrefetcher(data, k=K))
        d = counters.delta(before)
        assert d.get("io.stack_windows") == 2
        assert d.get("io.stack_batches") == K + 1

    def test_feeds_fused_step_bit_identically(self):
        data = self._loader(2 * K, seed=9)
        _, _, s1 = _make(fused_steps=1)
        ref = []
        for x, y in data:
            ref.append(float(s1(x, y).numpy()))
        _, _, s4 = _make(fused_steps=K)
        got = []
        for w in StackingPrefetcher(data, k=K):
            got.extend(np.asarray(s4(*w).numpy()).tolist())
        assert np.array_equal(np.array(ref, np.float32),
                              np.array(got, np.float32))


class TestFlagDefault:
    def test_fused_steps_flag_seeds_constructor(self):
        cflags.set_flags({"FLAGS_fused_steps": 3})
        try:
            _, _, step = _make(fused_steps=None)
            assert step.fused_steps == 3
        finally:
            cflags.set_flags({"FLAGS_fused_steps": 1})
        _, _, step = _make(fused_steps=None)
        assert step.fused_steps == 1
