"""Paged KV-cache subsystem (paddle_tpu.serving.kvcache / .paged).

The load-bearing contracts: (1) the paged engine is TOKEN-IDENTICAL to
the legacy slot arena and to sequential GPT.generate — block tables,
prefix sharing, copy-on-write, and chunked prefill must be invisible in
the tokens; (2) block accounting never tears — all-or-nothing
reservation, refcounted sharing, LRU eviction only of unreferenced
blocks; (3) exhaustion (real or injected) defers admission and surfaces
as backpressure, never a crash.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.profiler import counters
from paddle_tpu.resilience import faultinject
from paddle_tpu.serving.kvcache import (TRASH_BLOCK, BlockPool,
                                        BlockPoolExhausted, HostKVTier,
                                        PrefixCache, blocks_for_tokens)

_MODEL = None


def _model():
    global _MODEL
    if _MODEL is None:
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=4, max_seq_len=32,
                        use_flash_attention=False)
        paddle.seed(31)
        _MODEL = GPTForCausalLM(cfg)
        _MODEL.eval()
    return _MODEL


def _paged(m, **kw):
    from paddle_tpu.serving import LLMEngine
    kw.setdefault("max_slots", 3)
    kw.setdefault("max_seq_len", 32)
    kw.setdefault("min_bucket", 4)
    kw.setdefault("block_size", 4)
    kw.setdefault("prefill_chunk", 8)
    return LLMEngine(m, kv_layout="paged", **kw)


def _ref_generate(m, prompt, max_new, **kw):
    out = np.asarray(m.generate(paddle.to_tensor(np.asarray([prompt])),
                                max_new_tokens=max_new, **kw).numpy())[0]
    return out[len(prompt):].tolist()


def _run(eng, handles, limit=300):
    n = 0
    while not all(h.is_finished for h in handles):
        eng.step()
        n += 1
        assert n < limit, "engine did not converge"
    return n


class TestBlockPool:
    def test_alloc_free_refcount(self):
        pool = BlockPool(5, 4)
        assert pool.capacity == 4 and pool.free_blocks == 4
        a = pool.alloc()
        assert a != TRASH_BLOCK and pool.ref(a) == 1
        pool.retain(a)
        assert pool.ref(a) == 2
        assert pool.release(a) is False       # still held
        assert pool.release(a) is True        # freed
        assert pool.free_blocks == 4

    def test_alloc_n_all_or_nothing(self):
        pool = BlockPool(5, 4)
        got = pool.alloc_n(3)
        assert len(got) == 3 and pool.free_blocks == 1
        with pytest.raises(BlockPoolExhausted) as ei:
            pool.alloc_n(2)
        assert ei.value.needed == 2 and ei.value.free == 1
        assert pool.free_blocks == 1          # nothing torn off

    def test_trash_block_reserved(self):
        pool = BlockPool(3, 4)
        blocks = pool.alloc_n(2)
        assert TRASH_BLOCK not in blocks
        with pytest.raises(BlockPoolExhausted):
            pool.alloc()
        with pytest.raises(ValueError):
            pool.retain(TRASH_BLOCK)

    def test_release_free_block_raises(self):
        pool = BlockPool(3, 4)
        with pytest.raises(ValueError):
            pool.release(1)

    def test_blocks_for_tokens(self):
        assert blocks_for_tokens(1, 4) == 1
        assert blocks_for_tokens(4, 4) == 1
        assert blocks_for_tokens(5, 4) == 2
        assert blocks_for_tokens(16, 4) == 4


class TestPrefixCache:
    def test_match_full_and_partial(self):
        pool = BlockPool(9, 4)
        cache = PrefixCache(pool)
        seq = list(range(10))                       # 2 full blocks + 2 rest
        blocks = pool.alloc_n(3)
        assert cache.insert(seq, blocks) == 3
        for b in blocks:
            pool.release(b)                         # donor refs dropped
        assert all(pool.ref(b) == 1 for b in blocks)

        # full-block hit: first 8 tokens shared, partial [8,9] usable
        got, cached, pn, p = cache.match(seq + [42], limit=10)
        assert got == blocks[:2] and cached == 8
        assert pn is not None and pn.block == blocks[2] and p == 2
        assert pool.ref(blocks[0]) == 2             # retained for caller
        assert pool.ref(pn.block) == 2              # partial retained too
        for b in got:
            pool.release(b)
        pool.release(pn.block)

        # limit clips the partial
        got, cached, pn, p = cache.match(seq, limit=9)
        assert cached == 8 and p == 1
        for b in got:
            pool.release(b)
        pool.release(pn.block)

        # divergent second block: only the first is shared
        div = seq[:4] + [63, 62, 61, 60]
        got, cached, pn, p = cache.match(div, limit=8)
        assert got == blocks[:1] and cached == 4 and pn is None
        for b in got:
            pool.release(b)

    def test_partial_survives_repeated_cow_matches(self):
        """Regression: ``match`` retains the partial block for the
        caller, so the COW-side release (``_reserve`` drops it after the
        copy) does NOT strip the tree's own retain.  Without the
        caller-side retain the first COW adoption freed the partial's
        block under a live tree node — the next sharer matched a
        dangling node over a freed (or reused) block and the release
        blew up with "release of free block"."""
        pool = BlockPool(9, 4)
        cache = PrefixCache(pool)
        seq = list(range(6))                        # 1 full block + 2 rest
        blocks = pool.alloc_n(2)
        cache.insert(seq, blocks)
        for b in blocks:
            pool.release(b)
        for _ in range(3):                          # every sharer COWs
            got, cached, pn, p = cache.match(seq, limit=5)
            assert cached == 4 and pn is not None and p == 1
            for b in got:
                pool.release(b)                     # admission bookkeeping
            pool.release(pn.block)                  # post-COW release
            assert pool.ref(pn.block) == 1          # tree retain intact
        cache.clear()
        assert pool.free_blocks == pool.capacity

    def test_peek_is_read_only(self):
        pool = BlockPool(9, 4)
        cache = PrefixCache(pool)
        seq = list(range(10))
        blocks = pool.alloc_n(3)
        cache.insert(seq, blocks)
        for b in blocks:
            pool.release(b)
        assert cache.peek(seq, limit=10) == 10
        assert cache.peek(seq, limit=9) == 9
        assert cache.peek([59] * 10, limit=10) == 0
        assert all(pool.ref(b) == 1 for b in blocks)   # no refs taken

    def test_evict_lru_unreferenced_only(self):
        pool = BlockPool(9, 4)
        cache = PrefixCache(pool)
        s1, s2 = [1] * 4, [2] * 4
        b1 = pool.alloc_n(1)
        cache.insert(s1, b1)
        pool.release(b1[0])
        b2 = pool.alloc_n(1)
        cache.insert(s2, b2)
        pool.release(b2[0])
        # touch s1 so s2 is LRU
        got, *_ = cache.match(s1 + [0], limit=5)
        assert cache.evict(1) == 1                  # evicts s2, not held s1
        assert pool.ref(b2[0]) == 0
        assert cache.peek(s2, limit=4) == 0
        assert cache.peek(s1 + [0], limit=5) == 4   # s1 survives (referenced)
        for b in got:
            pool.release(b)

    def test_evict_parent_after_child(self):
        pool = BlockPool(9, 4)
        cache = PrefixCache(pool)
        seq = list(range(8))                        # chain of 2 full blocks
        blocks = pool.alloc_n(2)
        cache.insert(seq, blocks)
        for b in blocks:
            pool.release(b)
        assert cache.evict(2) == 2                  # leaf first, then parent
        assert cache.nodes == 0
        assert pool.free_blocks == pool.capacity

    def test_clear_releases_everything(self):
        pool = BlockPool(9, 4)
        cache = PrefixCache(pool)
        blocks = pool.alloc_n(3)
        cache.insert(list(range(10)), blocks)
        for b in blocks:
            pool.release(b)
        cache.clear()
        assert pool.free_blocks == pool.capacity and cache.nodes == 0


class TestPagedIdentity:
    def test_greedy_vs_generate_and_slot_engine(self):
        m = _model()
        from paddle_tpu.serving import LLMEngine
        rng = np.random.default_rng(0)
        prompts = [rng.integers(0, 64, size=n).tolist()
                   for n in (5, 3, 9, 6, 11)]
        refs = [_ref_generate(m, p, 6) for p in prompts]
        slot = LLMEngine(m, max_slots=3, max_seq_len=32, min_bucket=4)
        hs = [slot.add_request(p, max_new_tokens=6, seed=i)
              for i, p in enumerate(prompts)]
        _run(slot, hs)
        paged = _paged(m)
        hp = [paged.add_request(p, max_new_tokens=6, seed=i)
              for i, p in enumerate(prompts)]
        _run(paged, hp)
        for h, hq, r in zip(hs, hp, refs):
            assert h.tokens == r
            assert hq.tokens == r

    def test_sampled_identity(self):
        m = _model()
        rng = np.random.default_rng(3)
        prompts = [rng.integers(0, 64, size=n).tolist() for n in (7, 4, 10)]
        kw = dict(do_sample=True, temperature=0.8, top_k=8, top_p=0.9)
        refs = [_ref_generate(m, p, 6, seed=100 + i, **kw)
                for i, p in enumerate(prompts)]
        eng = _paged(m)
        hs = [eng.add_request(p, max_new_tokens=6, seed=100 + i, **kw)
              for i, p in enumerate(prompts)]
        _run(eng, hs)
        for h, r in zip(hs, refs):
            assert h.tokens == r

    def test_chunked_prefill_identity(self):
        m = _model()
        rng = np.random.default_rng(4)
        long_p = rng.integers(0, 64, size=26).tolist()
        eng = _paged(m, prefill_chunk=8)            # 26 tokens -> 4 chunks
        before = counters.snapshot().get("serving.kv.prefill_chunks", 0)
        h = eng.add_request(long_p, max_new_tokens=5, seed=9)
        _run(eng, [h])
        chunks = counters.snapshot().get("serving.kv.prefill_chunks",
                                         0) - before
        assert chunks == 4
        assert h.tokens == _ref_generate(m, long_p, 5)

    def test_shared_prefix_hit_identity(self):
        m = _model()
        rng = np.random.default_rng(5)
        sys_p = rng.integers(0, 64, size=12).tolist()
        eng = _paged(m)
        tails = [rng.integers(0, 64, size=4).tolist() for _ in range(3)]
        first = eng.add_request(sys_p + tails[0], max_new_tokens=4, seed=0)
        _run(eng, [first])
        assert first.tokens == _ref_generate(m, sys_p + tails[0], 4)
        st0 = eng.stats()
        hs = [eng.add_request(sys_p + t, max_new_tokens=4, seed=1 + i)
              for i, t in enumerate(tails[1:])]
        _run(eng, hs)
        st = eng.stats()
        assert st["prefix_hits"] - st0["prefix_hits"] == 2
        assert st["prefix_hit_tokens"] > st0["prefix_hit_tokens"]
        for h, t in zip(hs, tails[1:]):
            assert h.tokens == _ref_generate(m, sys_p + t, 4)

    def test_cow_partial_block_identity(self):
        m = _model()
        rng = np.random.default_rng(6)
        p1 = rng.integers(0, 64, size=10).tolist()
        eng = _paged(m)
        h1 = eng.add_request(p1, max_new_tokens=6, seed=2)
        _run(eng, [h1])
        # the finished sequence cached 15 KV positions: 3 full blocks + a
        # 3-token partial; extending past it forces a copy-on-write
        seq1 = p1 + h1.tokens
        p2 = seq1[:15] + rng.integers(0, 64, size=4).tolist()
        h2 = eng.add_request(p2, max_new_tokens=5, seed=3)
        _run(eng, [h2])
        st = eng.stats()
        assert st["cow_copies"] >= 1
        assert h2.tokens == _ref_generate(m, p2, 5)

    def test_repeated_cow_adoptions_of_one_partial(self):
        """Regression (engine level): several requests COW-adopting the
        SAME cached partial, one after another.  Each adoption must
        leave the tree's partial node alive over a still-referenced
        block; pre-fix the first COW freed it and the next admission
        crashed the engine on "release of free block"."""
        m = _model()
        rng = np.random.default_rng(8)
        eng = _paged(m)
        p1 = rng.integers(0, 64, size=10).tolist()
        h1 = eng.add_request(p1, max_new_tokens=6, seed=2)
        _run(eng, [h1])
        seq1 = p1 + h1.tokens
        for i in range(3):
            p2 = seq1[:15] + rng.integers(0, 64, size=4).tolist()
            h2 = eng.add_request(p2, max_new_tokens=4, seed=10 + i)
            _run(eng, [h2])
            assert h2.tokens == _ref_generate(m, p2, 4)
        assert eng.stats()["cow_copies"] >= 3
        pool = eng.pool
        live = sum(1 for b in range(1, len(pool._ref))
                   if pool._ref[b] > 0)
        assert len(pool._free) + live == pool.capacity


class TestChunkedPrefillInterleaving:
    def test_decode_not_starved_by_long_prefill(self):
        m = _model()
        rng = np.random.default_rng(7)
        eng = _paged(m, prefill_chunk=8, prefix_cache=False)
        short = rng.integers(0, 64, size=4).tolist()
        long_p = rng.integers(0, 64, size=24).tolist()
        h_short = eng.add_request(short, max_new_tokens=10, seed=1)
        eng.step()                                   # short is now decoding
        h_long = eng.add_request(long_p, max_new_tokens=3, seed=2)
        # while the long prompt prefills chunk by chunk, the short request
        # must receive one token per step — chunked prefill never starves
        # inter-token latency
        while h_long.state != "running" and not h_long.is_finished:
            before = len(h_short.tokens)
            eng.step()
            if not h_short.is_finished:
                assert len(h_short.tokens) == before + 1
        _run(eng, [h_short, h_long])
        assert h_short.tokens == _ref_generate(m, short, 10)
        assert h_long.tokens == _ref_generate(m, long_p, 3)


class TestDeadlineAndRelease:
    def test_deadline_expiry_mid_chunked_prefill(self):
        m = _model()
        rng = np.random.default_rng(8)
        eng = _paged(m, prefill_chunk=8)
        long_p = rng.integers(0, 64, size=24).tolist()
        h = eng.add_request(long_p, max_new_tokens=4, seed=1,
                            deadline_s=0.0)
        eng.step()                                   # sweep reaps it
        assert h.is_finished and h.finish_reason == "deadline"
        st = eng.stats()
        assert st["blocks_used"] == 0                # every block released
        assert st["blocks_free"] == st["blocks_total"]

    def test_cancel_mid_prefill_releases_blocks(self):
        m = _model()
        rng = np.random.default_rng(9)
        eng = _paged(m, prefill_chunk=8, prefix_cache=False)
        h = eng.add_request(rng.integers(0, 64, size=24).tolist(),
                            max_new_tokens=4, seed=1)
        eng.step()                                   # admitted, 1 chunk in
        assert h.state == "prefilling"
        h.cancel()
        eng.step()
        assert h.finish_reason == "cancelled"
        assert eng.stats()["blocks_used"] == 0


class TestExhaustionBackpressure:
    def test_impossible_request_rejected(self):
        m = _model()
        eng = _paged(m, n_blocks=3)                  # 2 usable blocks
        with pytest.raises(ValueError):
            eng.add_request(list(range(12)), max_new_tokens=4)

    def test_real_exhaustion_defers_and_recovers(self):
        m = _model()
        # pool fits ~1 request at a time: 6 usable blocks of 4 tokens
        eng = _paged(m, n_blocks=7, max_slots=2, prefix_cache=False)
        p = list(range(10))
        h1 = eng.add_request(p, max_new_tokens=6, seed=0)      # 4 blocks
        h2 = eng.add_request(p[::-1], max_new_tokens=6, seed=1)
        _run(eng, [h1, h2])
        st = eng.stats()
        assert st["pool_exhausted"] >= 1             # h2 had to wait
        assert h1.tokens == _ref_generate(m, p, 6)
        assert h2.tokens == _ref_generate(m, p[::-1], 6)

    def test_injected_exhaustion_is_deterministic(self):
        m = _model()
        eng = _paged(m)
        h0 = eng.add_request([1, 2, 3], max_new_tokens=3, seed=0)
        rid = h0.rid + 1
        with faultinject.fault_schedule(f"kv_pool_exhausted@{rid}"):
            h1 = eng.add_request([4, 5, 6], max_new_tokens=3, seed=1)
            _run(eng, [h0, h1])
            assert ("kv_pool_exhausted", rid) in faultinject.fired
        assert h1.finish_reason == "length"          # deferred, not dropped
        assert h1.tokens == _ref_generate(m, [4, 5, 6], 3)
        assert eng.stats()["pool_exhausted"] == 1

    def test_backpressure_surfaces_when_queue_fills(self):
        from paddle_tpu.serving import EngineBackpressure
        m = _model()
        eng = _paged(m, max_slots=1, queue_size=1, n_blocks=9,
                     prefix_cache=False)
        h1 = eng.add_request(list(range(10)), max_new_tokens=6, seed=0)
        eng.step()                                   # h1 occupies the pool
        h2 = eng.add_request(list(range(8)), max_new_tokens=6, seed=1,
                             block=False)            # queued
        with pytest.raises(EngineBackpressure):
            eng.add_request(list(range(6)), max_new_tokens=4, seed=2,
                            block=False)             # queue full
        _run(eng, [h1, h2])
        assert h1.finish_reason == "length"
        assert h2.finish_reason == "length"


class TestRouterPrefixAware:
    def test_pick_prefers_warm_prefix(self):
        m = _model()
        from paddle_tpu.serving import Replica, Router
        rng = np.random.default_rng(10)
        sys_p = rng.integers(0, 64, size=12).tolist()
        warm = _paged(m)
        cold = _paged(m)
        h = warm.add_request(sys_p + [1, 2], max_new_tokens=4, seed=0)
        _run(warm, [h])
        reps = [Replica(0, cold), Replica(1, warm)]
        before = counters.snapshot().get("serving.fleet.prefix_routed", 0)
        picked = Router().pick(reps, est_tokens=16, prompt=sys_p + [3, 4])
        assert picked.engine is warm                 # despite higher idx
        got = counters.snapshot().get("serving.fleet.prefix_routed", 0)
        assert got == before + 1
        # without a prompt the tie breaks to the lowest index
        assert Router().pick(reps, est_tokens=16).engine is cold


class TestFleetPagedChaos:
    def test_fleet_kv_stats_and_injected_exhaustion(self):
        m = _model()
        from paddle_tpu.serving import ServingFleet
        rng = np.random.default_rng(11)
        sys_p = rng.integers(0, 64, size=8).tolist()
        with ServingFleet(m, replicas=2, max_slots=2, max_seq_len=32,
                          min_bucket=4, threaded=False, kv_layout="paged",
                          block_size=4, prefill_chunk=8) as fleet:
            reqs = [fleet.submit(sys_p + rng.integers(0, 64, size=3).tolist(),
                                 max_new_tokens=4, seed=i)
                    for i in range(4)]
            # chaos leg: exhaust the pool at a specific engine-level
            # admission — the request must still finish
            victim = fleet.submit(sys_p + [7, 8, 9], max_new_tokens=4,
                                  seed=99)
            erid = victim._er.rid
            with faultinject.fault_schedule(f"kv_pool_exhausted@{erid}"):
                n = 0
                while any(not r.is_finished for r in reqs + [victim]):
                    fleet.pump()
                    n += 1
                    assert n < 500
                assert ("kv_pool_exhausted", erid) in faultinject.fired
            st = fleet.stats()
            assert st["kv"]["prefix_hits"] > 0
            assert st["kv"]["pool_exhausted"] >= 1
            assert st["kv"]["blocks_total"] > 0
            for r in reqs + [victim]:
                assert r.finish_reason in ("length", "eos")
                ref = _ref_generate(m, list(r.prompt), 4)
                assert r.tokens == ref


def _pool_reconciles(eng):
    pool = eng.pool
    live = sum(1 for b in range(1, len(pool._ref)) if pool._ref[b] > 0)
    return len(pool._free) + live == pool.capacity


class TestHostKVTierUnit:
    SPEC = (((2, 4, 2, 8), np.dtype(np.float32)),
            ((2, 4, 2, 8), np.dtype(np.float32)))

    def test_acquire_reuse_and_arena_gauge(self):
        before = counters.snapshot()
        tier = HostKVTier(4)
        bufs = tier.acquire(self.SPEC)
        assert len(bufs) == 2 and all(b.shape == (2, 4, 2, 8)
                                      for b in bufs)
        nbytes = sum(b.nbytes for b in bufs)
        assert tier.arena_bytes == nbytes
        # recycle via pop, then re-acquire: pool hit, no new bytes
        tier.put("a", bufs)
        assert tier.pop("a") is True
        again = tier.acquire(self.SPEC)
        assert tier.arena_bytes == nbytes                # flat once warm
        d = counters.delta(before)
        assert d.get("serving.kv.host_buf_reuse", 0) == 2
        # last-write-wins gauge: this tier published its arena total
        # (delta vs `before` would see other engines' tiers)
        assert counters.get("serving.kv.host_arena_bytes") == nbytes
        assert {id(b) for b in again} == {id(b) for b in bufs}

    def test_put_lru_overflow_returns_dropped_keys(self):
        tier = HostKVTier(2)
        for key in ("a", "b"):
            assert tier.put(key, tier.acquire(self.SPEC)) == []
        # touching "a" makes "b" the LRU victim of the next overflow
        assert tier.get("a") is not None
        dropped = tier.put("c", tier.acquire(self.SPEC))
        assert dropped == ["b"]
        assert tier.resident == 2
        assert tier.get("b") is None
        # the dropped entry's buffers were recycled, not leaked
        tier.put("d", tier.acquire(self.SPEC))
        bytes_before = tier.arena_bytes
        assert tier.arena_bytes == bytes_before

    def test_pop_is_tolerant_of_absent_keys(self):
        tier = HostKVTier(1)
        assert tier.pop("nope") is False
        with pytest.raises(ValueError):
            HostKVTier(0)


def _tiered(m, **kw):
    kw.setdefault("n_blocks", 10)
    kw.setdefault("host_kv_blocks", 32)
    kw.setdefault("max_slots", 2)
    return _paged(m, **kw)


class TestKVTiering:
    """Tentpole: cold KV spills to pinned host RAM and pages back on
    demand — token identity is preserved across the round-trip, the
    host reuse pool keeps steady-state traffic allocation-free, and a
    dropped host copy degrades to a deterministic cache-miss replay."""

    def test_oversubscribed_identity_greedy(self):
        m = _model()
        rng = np.random.default_rng(20)
        prompts = [rng.integers(0, 64, size=9).tolist() for _ in range(6)]
        refs = [_ref_generate(m, p, 4) for p in prompts]
        before = counters.snapshot()
        eng = _tiered(m)                 # 9 usable blocks, far too few
        for two_pass in range(2):        # pass 2 restores what 1 spilled
            for i, p in enumerate(prompts):
                h = eng.add_request(p, max_new_tokens=4, seed=i)
                _run(eng, [h])
                assert h.tokens == refs[i], \
                    f"pass {two_pass} prompt {i} diverged"
        d = counters.delta(before)
        assert d.get("serving.kv.tier.spilled_blocks", 0) > 0
        assert d.get("serving.kv.tier.restored_blocks", 0) > 0
        assert d.get("serving.kv.host_buf_reuse", 0) > 0
        assert _pool_reconciles(eng)
        eng.prefix.clear()
        assert eng.pool.free_blocks == eng.pool.capacity
        assert eng._host_tier.resident == 0

    def test_oversubscribed_identity_sampled(self):
        m = _model()
        rng = np.random.default_rng(21)
        prompts = [rng.integers(0, 64, size=9).tolist() for _ in range(5)]
        kw = dict(do_sample=True, temperature=0.8, top_k=8, top_p=0.9)
        ample = _paged(m, n_blocks=64, max_slots=2)
        refs = []
        for i, p in enumerate(prompts):
            h = ample.add_request(p, max_new_tokens=4, seed=50 + i, **kw)
            _run(ample, [h])
            refs.append(h.tokens)
        before = counters.snapshot()
        eng = _tiered(m)
        for _ in range(2):
            for i, p in enumerate(prompts):
                h = eng.add_request(p, max_new_tokens=4, seed=50 + i,
                                    **kw)
                _run(eng, [h])
                assert h.tokens == refs[i]
        d = counters.delta(before)
        assert d.get("serving.kv.tier.spilled_blocks", 0) > 0
        assert d.get("serving.kv.tier.restored_blocks", 0) > 0
        assert _pool_reconciles(eng)

    def test_steady_state_spill_restore_compiles_nothing(self):
        """After one warm cycle compiled the one-block gather/scatter
        programs, further spill/restore churn traces nothing and the
        host arena stays flat (the reuse pool covers every buffer)."""
        m = _model()
        rng = np.random.default_rng(22)
        prompts = [rng.integers(0, 64, size=9).tolist() for _ in range(6)]
        eng = _tiered(m)
        for p in prompts:                          # warm: compiles + fills
            _run(eng, [eng.add_request(p, max_new_tokens=4, seed=3)])
        before = counters.snapshot()
        for p in prompts:                          # measured churn
            _run(eng, [eng.add_request(p, max_new_tokens=4, seed=3)])
        d = counters.delta(before)
        assert d.get("serving.kv.tier.spilled_blocks", 0) > 0
        assert d.get("serving.kv.tier.restored_blocks", 0) > 0
        assert d.get("serving.retraces", 0) == 0
        assert d.get("serving.kv.host_arena_bytes", 0) == 0
        assert d.get("serving.kv.host_buf_reuse", 0) > 0

    def test_kv_spill_drop_degrades_to_cache_miss(self):
        """Chaos: the host copy vanishes mid-restore — the chain is
        dropped, admission proceeds as a plain prefix miss, and the
        replayed prefill is token-identical."""
        m = _model()
        rng = np.random.default_rng(23)
        p = rng.integers(0, 64, size=9).tolist()   # 9 + 4 - 1 = 3 blocks
        eng = _tiered(m)
        h1 = eng.add_request(p, max_new_tokens=4, seed=0)
        _run(eng, [h1])
        with eng._cond:
            assert eng._spill_cold(3) == 3         # whole chain to host
        assert eng._host_tier.resident == 3
        before = counters.snapshot()
        h2 = eng.add_request(p, max_new_tokens=4, seed=0)
        with faultinject.fault_schedule(f"kv_spill_drop@{h2.rid}"):
            _run(eng, [h2])
            assert ("kv_spill_drop", h2.rid) in faultinject.fired
        assert h2.tokens == h1.tokens == _ref_generate(m, p, 4)
        d = counters.delta(before)
        assert d.get("serving.kv.tier.spill_drops", 0) == 3
        assert d.get("serving.kv.tier.restored_blocks", 0) == 0
        assert d.get("resilience.faults_injected.kv_spill_drop", 0) == 1
        assert d.get("serving.kv.prefix_misses", 0) >= 1
        assert eng._host_tier.resident == 0
        assert _pool_reconciles(eng)

    def test_readoption_flips_host_node_back_for_free(self):
        """A donor inserting over a host-resident node re-adopts it to
        device residency without any host->device copy: the donor's
        live block simply replaces the spilled one."""
        m = _model()
        rng = np.random.default_rng(24)
        p = rng.integers(0, 64, size=9).tolist()
        eng = _tiered(m)
        h1 = eng.add_request(p, max_new_tokens=4, seed=0)
        _run(eng, [h1])
        with eng._cond:
            eng._spill_cold(3)
        before = counters.snapshot()
        # admission pages back only the first 2 blocks (the match limit
        # is prompt-1 = 8 tokens); the third host node is re-adopted at
        # donation time — the finishing request carries a live device
        # copy of the same tokens, so residency flips back for free
        h2 = eng.add_request(p, max_new_tokens=4, seed=0)
        _run(eng, [h2])
        d = counters.delta(before)
        assert d.get("serving.kv.tier.restored_blocks", 0) == 2
        assert d.get("serving.kv.tier.readopted", 0) == 1
        assert h2.tokens == h1.tokens
        assert eng._host_tier.resident == 0
        assert _pool_reconciles(eng)


class TestHostTierRouting:
    def test_probe_reports_host_tokens_and_router_prices_restore(self):
        m = _model()
        from paddle_tpu.serving import Replica, Router
        rng = np.random.default_rng(25)
        sys_p = rng.integers(0, 64, size=8).tolist()
        warm = _tiered(m)
        cold = _paged(m)
        h = warm.add_request(sys_p + [1, 2], max_new_tokens=3, seed=0)
        _run(warm, [h])                  # KV = 12 tokens = 3 full blocks
        with warm._cond:
            assert warm._spill_cold(3) == 3
        probe_p = np.asarray(sys_p + [9, 9], np.int32)
        dev, host = warm.prefix_probe(probe_p)
        assert dev == 0 and host == 8    # whole prefix is host-resident
        assert cold.prefix_probe(probe_p) == (0, 0)
        reps = [Replica(0, cold), Replica(1, warm)]
        before = counters.snapshot()
        picked = Router().pick(reps, est_tokens=16, prompt=probe_p)
        assert picked.engine is warm     # host tokens still win routing
        d = counters.delta(before)
        assert d.get("serving.fleet.prefix_routed", 0) == 1
        # restore_cost=1.0 prices paging at a full re-prefill: the
        # host-resident prefix carries no edge and the tie breaks cold
        router = Router(restore_cost=1.0)
        assert router.pick(reps, est_tokens=16,
                           prompt=probe_p).engine is cold

    def test_digest_short_circuits_cold_probes(self):
        pool = BlockPool(9, 4)
        cache = PrefixCache(pool)
        seq = list(range(8))
        blocks = pool.alloc_n(2)
        cache.insert(seq, blocks)
        for b in blocks:
            pool.release(b)
        assert cache.digest() == frozenset({hash(tuple(seq[:4]))})
        # digest miss: a full-block probe of unseen tokens never walks
        assert cache.probe([40] * 8, limit=8) == (0, 0)
        assert cache.probe(seq, limit=8) == (8, 0)
        cache.clear()
        assert cache.digest() == frozenset()
