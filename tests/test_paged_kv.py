"""Paged KV-cache subsystem (paddle_tpu.serving.kvcache / .paged).

The load-bearing contracts: (1) the paged engine is TOKEN-IDENTICAL to
the legacy slot arena and to sequential GPT.generate — block tables,
prefix sharing, copy-on-write, and chunked prefill must be invisible in
the tokens; (2) block accounting never tears — all-or-nothing
reservation, refcounted sharing, LRU eviction only of unreferenced
blocks; (3) exhaustion (real or injected) defers admission and surfaces
as backpressure, never a crash.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.profiler import counters
from paddle_tpu.resilience import faultinject
from paddle_tpu.serving.kvcache import (TRASH_BLOCK, BlockPool,
                                        BlockPoolExhausted, PrefixCache,
                                        blocks_for_tokens)

_MODEL = None


def _model():
    global _MODEL
    if _MODEL is None:
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=4, max_seq_len=32,
                        use_flash_attention=False)
        paddle.seed(31)
        _MODEL = GPTForCausalLM(cfg)
        _MODEL.eval()
    return _MODEL


def _paged(m, **kw):
    from paddle_tpu.serving import LLMEngine
    kw.setdefault("max_slots", 3)
    kw.setdefault("max_seq_len", 32)
    kw.setdefault("min_bucket", 4)
    kw.setdefault("block_size", 4)
    kw.setdefault("prefill_chunk", 8)
    return LLMEngine(m, kv_layout="paged", **kw)


def _ref_generate(m, prompt, max_new, **kw):
    out = np.asarray(m.generate(paddle.to_tensor(np.asarray([prompt])),
                                max_new_tokens=max_new, **kw).numpy())[0]
    return out[len(prompt):].tolist()


def _run(eng, handles, limit=300):
    n = 0
    while not all(h.is_finished for h in handles):
        eng.step()
        n += 1
        assert n < limit, "engine did not converge"
    return n


class TestBlockPool:
    def test_alloc_free_refcount(self):
        pool = BlockPool(5, 4)
        assert pool.capacity == 4 and pool.free_blocks == 4
        a = pool.alloc()
        assert a != TRASH_BLOCK and pool.ref(a) == 1
        pool.retain(a)
        assert pool.ref(a) == 2
        assert pool.release(a) is False       # still held
        assert pool.release(a) is True        # freed
        assert pool.free_blocks == 4

    def test_alloc_n_all_or_nothing(self):
        pool = BlockPool(5, 4)
        got = pool.alloc_n(3)
        assert len(got) == 3 and pool.free_blocks == 1
        with pytest.raises(BlockPoolExhausted) as ei:
            pool.alloc_n(2)
        assert ei.value.needed == 2 and ei.value.free == 1
        assert pool.free_blocks == 1          # nothing torn off

    def test_trash_block_reserved(self):
        pool = BlockPool(3, 4)
        blocks = pool.alloc_n(2)
        assert TRASH_BLOCK not in blocks
        with pytest.raises(BlockPoolExhausted):
            pool.alloc()
        with pytest.raises(ValueError):
            pool.retain(TRASH_BLOCK)

    def test_release_free_block_raises(self):
        pool = BlockPool(3, 4)
        with pytest.raises(ValueError):
            pool.release(1)

    def test_blocks_for_tokens(self):
        assert blocks_for_tokens(1, 4) == 1
        assert blocks_for_tokens(4, 4) == 1
        assert blocks_for_tokens(5, 4) == 2
        assert blocks_for_tokens(16, 4) == 4


class TestPrefixCache:
    def test_match_full_and_partial(self):
        pool = BlockPool(9, 4)
        cache = PrefixCache(pool)
        seq = list(range(10))                       # 2 full blocks + 2 rest
        blocks = pool.alloc_n(3)
        assert cache.insert(seq, blocks) == 3
        for b in blocks:
            pool.release(b)                         # donor refs dropped
        assert all(pool.ref(b) == 1 for b in blocks)

        # full-block hit: first 8 tokens shared, partial [8,9] usable
        got, cached, pn, p = cache.match(seq + [42], limit=10)
        assert got == blocks[:2] and cached == 8
        assert pn is not None and pn.block == blocks[2] and p == 2
        assert pool.ref(blocks[0]) == 2             # retained for caller
        assert pool.ref(pn.block) == 2              # partial retained too
        for b in got:
            pool.release(b)
        pool.release(pn.block)

        # limit clips the partial
        got, cached, pn, p = cache.match(seq, limit=9)
        assert cached == 8 and p == 1
        for b in got:
            pool.release(b)
        pool.release(pn.block)

        # divergent second block: only the first is shared
        div = seq[:4] + [63, 62, 61, 60]
        got, cached, pn, p = cache.match(div, limit=8)
        assert got == blocks[:1] and cached == 4 and pn is None
        for b in got:
            pool.release(b)

    def test_partial_survives_repeated_cow_matches(self):
        """Regression: ``match`` retains the partial block for the
        caller, so the COW-side release (``_reserve`` drops it after the
        copy) does NOT strip the tree's own retain.  Without the
        caller-side retain the first COW adoption freed the partial's
        block under a live tree node — the next sharer matched a
        dangling node over a freed (or reused) block and the release
        blew up with "release of free block"."""
        pool = BlockPool(9, 4)
        cache = PrefixCache(pool)
        seq = list(range(6))                        # 1 full block + 2 rest
        blocks = pool.alloc_n(2)
        cache.insert(seq, blocks)
        for b in blocks:
            pool.release(b)
        for _ in range(3):                          # every sharer COWs
            got, cached, pn, p = cache.match(seq, limit=5)
            assert cached == 4 and pn is not None and p == 1
            for b in got:
                pool.release(b)                     # admission bookkeeping
            pool.release(pn.block)                  # post-COW release
            assert pool.ref(pn.block) == 1          # tree retain intact
        cache.clear()
        assert pool.free_blocks == pool.capacity

    def test_peek_is_read_only(self):
        pool = BlockPool(9, 4)
        cache = PrefixCache(pool)
        seq = list(range(10))
        blocks = pool.alloc_n(3)
        cache.insert(seq, blocks)
        for b in blocks:
            pool.release(b)
        assert cache.peek(seq, limit=10) == 10
        assert cache.peek(seq, limit=9) == 9
        assert cache.peek([59] * 10, limit=10) == 0
        assert all(pool.ref(b) == 1 for b in blocks)   # no refs taken

    def test_evict_lru_unreferenced_only(self):
        pool = BlockPool(9, 4)
        cache = PrefixCache(pool)
        s1, s2 = [1] * 4, [2] * 4
        b1 = pool.alloc_n(1)
        cache.insert(s1, b1)
        pool.release(b1[0])
        b2 = pool.alloc_n(1)
        cache.insert(s2, b2)
        pool.release(b2[0])
        # touch s1 so s2 is LRU
        got, *_ = cache.match(s1 + [0], limit=5)
        assert cache.evict(1) == 1                  # evicts s2, not held s1
        assert pool.ref(b2[0]) == 0
        assert cache.peek(s2, limit=4) == 0
        assert cache.peek(s1 + [0], limit=5) == 4   # s1 survives (referenced)
        for b in got:
            pool.release(b)

    def test_evict_parent_after_child(self):
        pool = BlockPool(9, 4)
        cache = PrefixCache(pool)
        seq = list(range(8))                        # chain of 2 full blocks
        blocks = pool.alloc_n(2)
        cache.insert(seq, blocks)
        for b in blocks:
            pool.release(b)
        assert cache.evict(2) == 2                  # leaf first, then parent
        assert cache.nodes == 0
        assert pool.free_blocks == pool.capacity

    def test_clear_releases_everything(self):
        pool = BlockPool(9, 4)
        cache = PrefixCache(pool)
        blocks = pool.alloc_n(3)
        cache.insert(list(range(10)), blocks)
        for b in blocks:
            pool.release(b)
        cache.clear()
        assert pool.free_blocks == pool.capacity and cache.nodes == 0


class TestPagedIdentity:
    def test_greedy_vs_generate_and_slot_engine(self):
        m = _model()
        from paddle_tpu.serving import LLMEngine
        rng = np.random.default_rng(0)
        prompts = [rng.integers(0, 64, size=n).tolist()
                   for n in (5, 3, 9, 6, 11)]
        refs = [_ref_generate(m, p, 6) for p in prompts]
        slot = LLMEngine(m, max_slots=3, max_seq_len=32, min_bucket=4)
        hs = [slot.add_request(p, max_new_tokens=6, seed=i)
              for i, p in enumerate(prompts)]
        _run(slot, hs)
        paged = _paged(m)
        hp = [paged.add_request(p, max_new_tokens=6, seed=i)
              for i, p in enumerate(prompts)]
        _run(paged, hp)
        for h, hq, r in zip(hs, hp, refs):
            assert h.tokens == r
            assert hq.tokens == r

    def test_sampled_identity(self):
        m = _model()
        rng = np.random.default_rng(3)
        prompts = [rng.integers(0, 64, size=n).tolist() for n in (7, 4, 10)]
        kw = dict(do_sample=True, temperature=0.8, top_k=8, top_p=0.9)
        refs = [_ref_generate(m, p, 6, seed=100 + i, **kw)
                for i, p in enumerate(prompts)]
        eng = _paged(m)
        hs = [eng.add_request(p, max_new_tokens=6, seed=100 + i, **kw)
              for i, p in enumerate(prompts)]
        _run(eng, hs)
        for h, r in zip(hs, refs):
            assert h.tokens == r

    def test_chunked_prefill_identity(self):
        m = _model()
        rng = np.random.default_rng(4)
        long_p = rng.integers(0, 64, size=26).tolist()
        eng = _paged(m, prefill_chunk=8)            # 26 tokens -> 4 chunks
        before = counters.snapshot().get("serving.kv.prefill_chunks", 0)
        h = eng.add_request(long_p, max_new_tokens=5, seed=9)
        _run(eng, [h])
        chunks = counters.snapshot().get("serving.kv.prefill_chunks",
                                         0) - before
        assert chunks == 4
        assert h.tokens == _ref_generate(m, long_p, 5)

    def test_shared_prefix_hit_identity(self):
        m = _model()
        rng = np.random.default_rng(5)
        sys_p = rng.integers(0, 64, size=12).tolist()
        eng = _paged(m)
        tails = [rng.integers(0, 64, size=4).tolist() for _ in range(3)]
        first = eng.add_request(sys_p + tails[0], max_new_tokens=4, seed=0)
        _run(eng, [first])
        assert first.tokens == _ref_generate(m, sys_p + tails[0], 4)
        st0 = eng.stats()
        hs = [eng.add_request(sys_p + t, max_new_tokens=4, seed=1 + i)
              for i, t in enumerate(tails[1:])]
        _run(eng, hs)
        st = eng.stats()
        assert st["prefix_hits"] - st0["prefix_hits"] == 2
        assert st["prefix_hit_tokens"] > st0["prefix_hit_tokens"]
        for h, t in zip(hs, tails[1:]):
            assert h.tokens == _ref_generate(m, sys_p + t, 4)

    def test_cow_partial_block_identity(self):
        m = _model()
        rng = np.random.default_rng(6)
        p1 = rng.integers(0, 64, size=10).tolist()
        eng = _paged(m)
        h1 = eng.add_request(p1, max_new_tokens=6, seed=2)
        _run(eng, [h1])
        # the finished sequence cached 15 KV positions: 3 full blocks + a
        # 3-token partial; extending past it forces a copy-on-write
        seq1 = p1 + h1.tokens
        p2 = seq1[:15] + rng.integers(0, 64, size=4).tolist()
        h2 = eng.add_request(p2, max_new_tokens=5, seed=3)
        _run(eng, [h2])
        st = eng.stats()
        assert st["cow_copies"] >= 1
        assert h2.tokens == _ref_generate(m, p2, 5)

    def test_repeated_cow_adoptions_of_one_partial(self):
        """Regression (engine level): several requests COW-adopting the
        SAME cached partial, one after another.  Each adoption must
        leave the tree's partial node alive over a still-referenced
        block; pre-fix the first COW freed it and the next admission
        crashed the engine on "release of free block"."""
        m = _model()
        rng = np.random.default_rng(8)
        eng = _paged(m)
        p1 = rng.integers(0, 64, size=10).tolist()
        h1 = eng.add_request(p1, max_new_tokens=6, seed=2)
        _run(eng, [h1])
        seq1 = p1 + h1.tokens
        for i in range(3):
            p2 = seq1[:15] + rng.integers(0, 64, size=4).tolist()
            h2 = eng.add_request(p2, max_new_tokens=4, seed=10 + i)
            _run(eng, [h2])
            assert h2.tokens == _ref_generate(m, p2, 4)
        assert eng.stats()["cow_copies"] >= 3
        pool = eng.pool
        live = sum(1 for b in range(1, len(pool._ref))
                   if pool._ref[b] > 0)
        assert len(pool._free) + live == pool.capacity


class TestChunkedPrefillInterleaving:
    def test_decode_not_starved_by_long_prefill(self):
        m = _model()
        rng = np.random.default_rng(7)
        eng = _paged(m, prefill_chunk=8, prefix_cache=False)
        short = rng.integers(0, 64, size=4).tolist()
        long_p = rng.integers(0, 64, size=24).tolist()
        h_short = eng.add_request(short, max_new_tokens=10, seed=1)
        eng.step()                                   # short is now decoding
        h_long = eng.add_request(long_p, max_new_tokens=3, seed=2)
        # while the long prompt prefills chunk by chunk, the short request
        # must receive one token per step — chunked prefill never starves
        # inter-token latency
        while h_long.state != "running" and not h_long.is_finished:
            before = len(h_short.tokens)
            eng.step()
            if not h_short.is_finished:
                assert len(h_short.tokens) == before + 1
        _run(eng, [h_short, h_long])
        assert h_short.tokens == _ref_generate(m, short, 10)
        assert h_long.tokens == _ref_generate(m, long_p, 3)


class TestDeadlineAndRelease:
    def test_deadline_expiry_mid_chunked_prefill(self):
        m = _model()
        rng = np.random.default_rng(8)
        eng = _paged(m, prefill_chunk=8)
        long_p = rng.integers(0, 64, size=24).tolist()
        h = eng.add_request(long_p, max_new_tokens=4, seed=1,
                            deadline_s=0.0)
        eng.step()                                   # sweep reaps it
        assert h.is_finished and h.finish_reason == "deadline"
        st = eng.stats()
        assert st["blocks_used"] == 0                # every block released
        assert st["blocks_free"] == st["blocks_total"]

    def test_cancel_mid_prefill_releases_blocks(self):
        m = _model()
        rng = np.random.default_rng(9)
        eng = _paged(m, prefill_chunk=8, prefix_cache=False)
        h = eng.add_request(rng.integers(0, 64, size=24).tolist(),
                            max_new_tokens=4, seed=1)
        eng.step()                                   # admitted, 1 chunk in
        assert h.state == "prefilling"
        h.cancel()
        eng.step()
        assert h.finish_reason == "cancelled"
        assert eng.stats()["blocks_used"] == 0


class TestExhaustionBackpressure:
    def test_impossible_request_rejected(self):
        m = _model()
        eng = _paged(m, n_blocks=3)                  # 2 usable blocks
        with pytest.raises(ValueError):
            eng.add_request(list(range(12)), max_new_tokens=4)

    def test_real_exhaustion_defers_and_recovers(self):
        m = _model()
        # pool fits ~1 request at a time: 6 usable blocks of 4 tokens
        eng = _paged(m, n_blocks=7, max_slots=2, prefix_cache=False)
        p = list(range(10))
        h1 = eng.add_request(p, max_new_tokens=6, seed=0)      # 4 blocks
        h2 = eng.add_request(p[::-1], max_new_tokens=6, seed=1)
        _run(eng, [h1, h2])
        st = eng.stats()
        assert st["pool_exhausted"] >= 1             # h2 had to wait
        assert h1.tokens == _ref_generate(m, p, 6)
        assert h2.tokens == _ref_generate(m, p[::-1], 6)

    def test_injected_exhaustion_is_deterministic(self):
        m = _model()
        eng = _paged(m)
        h0 = eng.add_request([1, 2, 3], max_new_tokens=3, seed=0)
        rid = h0.rid + 1
        with faultinject.fault_schedule(f"kv_pool_exhausted@{rid}"):
            h1 = eng.add_request([4, 5, 6], max_new_tokens=3, seed=1)
            _run(eng, [h0, h1])
            assert ("kv_pool_exhausted", rid) in faultinject.fired
        assert h1.finish_reason == "length"          # deferred, not dropped
        assert h1.tokens == _ref_generate(m, [4, 5, 6], 3)
        assert eng.stats()["pool_exhausted"] == 1

    def test_backpressure_surfaces_when_queue_fills(self):
        from paddle_tpu.serving import EngineBackpressure
        m = _model()
        eng = _paged(m, max_slots=1, queue_size=1, n_blocks=9,
                     prefix_cache=False)
        h1 = eng.add_request(list(range(10)), max_new_tokens=6, seed=0)
        eng.step()                                   # h1 occupies the pool
        h2 = eng.add_request(list(range(8)), max_new_tokens=6, seed=1,
                             block=False)            # queued
        with pytest.raises(EngineBackpressure):
            eng.add_request(list(range(6)), max_new_tokens=4, seed=2,
                            block=False)             # queue full
        _run(eng, [h1, h2])
        assert h1.finish_reason == "length"
        assert h2.finish_reason == "length"


class TestRouterPrefixAware:
    def test_pick_prefers_warm_prefix(self):
        m = _model()
        from paddle_tpu.serving import Replica, Router
        rng = np.random.default_rng(10)
        sys_p = rng.integers(0, 64, size=12).tolist()
        warm = _paged(m)
        cold = _paged(m)
        h = warm.add_request(sys_p + [1, 2], max_new_tokens=4, seed=0)
        _run(warm, [h])
        reps = [Replica(0, cold), Replica(1, warm)]
        before = counters.snapshot().get("serving.fleet.prefix_routed", 0)
        picked = Router().pick(reps, est_tokens=16, prompt=sys_p + [3, 4])
        assert picked.engine is warm                 # despite higher idx
        got = counters.snapshot().get("serving.fleet.prefix_routed", 0)
        assert got == before + 1
        # without a prompt the tie breaks to the lowest index
        assert Router().pick(reps, est_tokens=16).engine is cold


class TestFleetPagedChaos:
    def test_fleet_kv_stats_and_injected_exhaustion(self):
        m = _model()
        from paddle_tpu.serving import ServingFleet
        rng = np.random.default_rng(11)
        sys_p = rng.integers(0, 64, size=8).tolist()
        with ServingFleet(m, replicas=2, max_slots=2, max_seq_len=32,
                          min_bucket=4, threaded=False, kv_layout="paged",
                          block_size=4, prefill_chunk=8) as fleet:
            reqs = [fleet.submit(sys_p + rng.integers(0, 64, size=3).tolist(),
                                 max_new_tokens=4, seed=i)
                    for i in range(4)]
            # chaos leg: exhaust the pool at a specific engine-level
            # admission — the request must still finish
            victim = fleet.submit(sys_p + [7, 8, 9], max_new_tokens=4,
                                  seed=99)
            erid = victim._er.rid
            with faultinject.fault_schedule(f"kv_pool_exhausted@{erid}"):
                n = 0
                while any(not r.is_finished for r in reqs + [victim]):
                    fleet.pump()
                    n += 1
                    assert n < 500
                assert ("kv_pool_exhausted", erid) in faultinject.fired
            st = fleet.stats()
            assert st["kv"]["prefix_hits"] > 0
            assert st["kv"]["pool_exhausted"] >= 1
            assert st["kv"]["blocks_total"] > 0
            for r in reqs + [victim]:
                assert r.finish_reason in ("length", "eos")
                ref = _ref_generate(m, list(r.prompt), 4)
                assert r.tokens == ref
