"""Device-resident train state: CompiledTrainStep perf/semantics contract.

Covers the three guarantees of the device-resident redesign:
  * steady-state steps keep params/buffers/opt-state on device — zero
    per-parameter host dict rebuilds/rebinds (counter-asserted, and the
    Parameter objects are provably NOT rebound between steps);
  * full buffer donation under GradScaler does not corrupt the
    skipped-update semantics on synthetic inf gradients;
  * the io.DevicePrefetcher yields batches identical to the plain loader.
Plus the host<->device coherence contract: sync()/state_dict()/mutation
barrier.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.jit as pjit
import paddle_tpu.nn as nn


def _mse(m, x, y):
    return ((m(x) - y) ** 2).mean()


def _make(lr=1e-2, scaler=None, donate=True, dtype=None):
    paddle.seed(0)
    net = nn.Linear(8, 4)
    if dtype is not None:
        net.to(dtype=dtype)
    opt = paddle.optimizer.Adam(parameters=net.parameters(),
                                learning_rate=lr)
    step = pjit.CompiledTrainStep(net, _mse, opt, scaler=scaler,
                                  donate=donate)
    return net, opt, step


class TestDeviceResidentState:
    def test_steady_state_zero_host_syncs(self):
        net, opt, step = _make()
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(16, 8).astype("float32"))
        y = paddle.to_tensor(rng.randn(16, 4).astype("float32"))
        step(x, y)  # hydrate + compile
        before = pjit.host_sync_counts()
        step(x, y)  # retrace (acc structure) but no host work
        step(x, y)  # fully cached
        after = pjit.host_sync_counts()
        assert before == after, {k: after[k] - before[k] for k in after}

    def test_state_fed_back_without_rebind(self):
        net, opt, step = _make()
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(4, 8).astype("float32"))
        y = paddle.to_tensor(rng.randn(4, 4).astype("float32"))
        step(x, y)
        held = net.weight._data  # synced after the hydration call
        held_np = np.asarray(held).copy()  # donation deletes it next step
        out_state = step._state
        fed_w = out_state[0]["weight"]
        step(x, y)  # steady state
        # the python Parameter was NOT rebound (state stayed on device) ...
        assert net.weight._data is held
        # ... the held output pytree was fed back and replaced wholesale ...
        assert step._state is not out_state
        assert step._state[0]["weight"] is not fed_w
        # ... and sync() re-binds the fresh arrays into the Parameter
        step.sync()
        assert net.weight._data is step._state[0]["weight"]
        assert not np.allclose(np.asarray(net.weight._data), held_np)

    def test_losses_decrease_and_match_nondonating(self):
        losses = {}
        for donate in (True, False):
            net, opt, step = _make(donate=donate)
            rng = np.random.RandomState(1)
            x = paddle.to_tensor(rng.randn(16, 8).astype("float32"))
            y = paddle.to_tensor(rng.randn(16, 4).astype("float32"))
            losses[donate] = [float(step(x, y).numpy()) for _ in range(4)]
        assert np.allclose(losses[True], losses[False])
        assert losses[True][-1] < losses[True][0]

    def test_mutation_barrier_set_value(self):
        net, opt, step = _make()
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(4, 8).astype("float32"))
        y = paddle.to_tensor(rng.randn(4, 4).astype("float32"))
        for _ in range(3):
            step(x, y)
        # official mutation API flushes device state, then lands the write;
        # the next call re-hydrates so the mutation takes effect
        net.weight.set_value(np.zeros((8, 4), "float32"))
        assert np.allclose(np.asarray(net.weight._data), 0.0)
        before = float(_mse(net, x, y).numpy())
        after = float(step(x, y).numpy())
        assert np.isclose(before, after, rtol=1e-5)

    def test_state_dict_auto_syncs(self):
        net, opt, step = _make()
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(4, 8).astype("float32"))
        y = paddle.to_tensor(rng.randn(4, 4).astype("float32"))
        step(x, y)
        w1 = np.asarray(net.state_dict()["weight"]._data).copy()
        step(x, y)  # device-resident: python object now stale ...
        w2 = np.asarray(net.state_dict()["weight"]._data)  # ... until here
        assert not np.allclose(w1, w2)
        # optimizer state_dict also syncs (accumulators advanced twice)
        osd = opt.state_dict()
        assert osd["step"] == 2

    def test_invalidate_rehydrates_raw_surgery(self):
        net, opt, step = _make()
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(4, 8).astype("float32"))
        y = paddle.to_tensor(rng.randn(4, 4).astype("float32"))
        step(x, y)
        import jax.numpy as jnp
        net.weight._data = jnp.zeros((8, 4), jnp.float32)  # untracked poke
        step.invalidate()
        before = float(_mse(net, x, y).numpy())
        after = float(step(x, y).numpy())
        assert np.isclose(before, after, rtol=1e-5)


class TestDonationUnderScaler:
    @pytest.mark.filterwarnings("ignore::UserWarning")
    def test_inf_grads_skip_update_donating_vs_not(self):
        results = {}
        for donate in (True, False):
            scaler = paddle.amp.GradScaler(init_loss_scaling=2.0 ** 15,
                                           incr_every_n_steps=2)
            net, opt, step = _make(scaler=scaler, donate=donate,
                                   dtype="float16")
            rng = np.random.RandomState(2)
            x = paddle.to_tensor(rng.randn(16, 8).astype("float16"))
            y = paddle.to_tensor(rng.randn(16, 4).astype("float16"))
            losses = [float(step(x, y).numpy()) for _ in range(3)]
            # overflow batch: fp16 forward produces inf -> inf grads
            xbad = paddle.to_tensor(
                (np.ones((16, 8)) * 60000).astype("float16"))
            step(xbad, y)
            step.sync()
            results[donate] = (
                losses,
                np.asarray(net.weight._data, dtype=np.float32),
                float(scaler._scale), int(scaler._good_steps),
                int(scaler._bad_steps))
        ld, wd, sd, gd_, bd = results[True]
        ln, wn, sn, gn, bn = results[False]
        assert np.allclose(ld, ln), "donation changed the loss trajectory"
        assert np.allclose(wd, wn), "donation changed the weights"
        assert np.isfinite(wd).all(), "inf grads leaked into weights"
        assert (sd, gd_, bd) == (sn, gn, bn)
        assert sd == 2.0 ** 14  # halved by the overflow step


class TestDevicePrefetcher:
    def test_identical_batches_tuple(self):
        from paddle_tpu.io import DataLoader, DevicePrefetcher, TensorDataset
        xs = paddle.to_tensor(np.arange(40, dtype="float32").reshape(10, 4))
        ys = paddle.to_tensor(np.arange(10, dtype="float32"))
        ds = TensorDataset([xs, ys])
        loader = DataLoader(ds, batch_size=3)
        plain = list(loader)
        pref = list(DevicePrefetcher(DataLoader(ds, batch_size=3), depth=2))
        assert len(plain) == len(pref) == len(loader)
        for (px, py), (qx, qy) in zip(plain, pref):
            assert np.array_equal(np.asarray(px._data), np.asarray(qx._data))
            assert np.array_equal(np.asarray(py._data), np.asarray(qy._data))

    def test_identical_batches_dict_and_depth(self):
        from paddle_tpu.io import DataLoader, Dataset, DevicePrefetcher

        class D(Dataset):
            def __len__(self):
                return 7

            def __getitem__(self, i):
                return {"a": np.full((2,), i, "float32"), "b": float(i)}

        for depth in (1, 2, 4):
            plain = list(DataLoader(D(), batch_size=2))
            pref = list(DevicePrefetcher(DataLoader(D(), batch_size=2),
                                         depth=depth))
            assert len(plain) == len(pref)
            for p, q in zip(plain, pref):
                assert np.array_equal(np.asarray(p["a"]._data),
                                      np.asarray(q["a"]._data))
                assert np.array_equal(np.asarray(p["b"]._data),
                                      np.asarray(q["b"]._data))


class TestBenchSmoke:
    # ~40s of serial phase compiles; scripts/check_counters.py gates the
    # same counter contracts (and more) outside the tier-1 time budget.
    @pytest.mark.slow
    def test_bench_smoke_counter_contract(self):
        import importlib.util
        import pathlib
        path = (pathlib.Path(__file__).resolve().parent.parent / "scripts"
                / "bench_smoke.py")
        spec = importlib.util.spec_from_file_location("bench_smoke", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        result = mod.run()
        assert result["value"] == 0
