"""Top-level namespace parity vs the reference's paddle.__all__.

The reference's python/paddle/__init__.py exports 410 public names; every
one must resolve on paddle_tpu (the "switch frameworks and find everything"
criterion).  Plus behavior checks for the names added to close the gap
(inplace variants, scatter views, distance ops, framework utilities).
"""

import re

import numpy as np
import pytest

import paddle_tpu as paddle

REF_INIT = "/root/reference/python/paddle/__init__.py"


def test_reference_all_fully_covered():
    src = open(REF_INIT).read()
    block = re.search(r"__all__ = \[(.*?)\]", src, re.S).group(1)
    ref_names = set(re.findall(r"'([^']+)'", block))
    assert len(ref_names) > 350  # sanity: parsed the real list
    missing = sorted(n for n in ref_names if not hasattr(paddle, n))
    assert missing == [], f"missing from paddle_tpu: {missing}"


class TestInplaceVariants:
    def test_unary_inplace_rebinds(self):
        x = paddle.to_tensor(np.array([1.0, 4.0], np.float32))
        out = paddle.sqrt_(x)
        assert out is x
        np.testing.assert_allclose(x.numpy(), [1.0, 2.0])

    def test_binary_inplace(self):
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        paddle.add_(x, paddle.to_tensor(np.array([10.0, 20.0], np.float32)))
        np.testing.assert_allclose(x.numpy(), [11.0, 22.0])

    def test_cast_(self):
        x = paddle.to_tensor(np.array([1.5], np.float32))
        paddle.cast_(x, "int32")
        assert "int32" in str(x.numpy().dtype)

    def test_where_(self):
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        cond = paddle.to_tensor(np.array([True, False]))
        paddle.where_(cond, x, paddle.to_tensor(np.array([9.0, 9.0],
                                                         np.float32)))
        np.testing.assert_allclose(x.numpy(), [1.0, 9.0])


class TestScatterViews:
    def test_select_scatter(self):
        x = paddle.to_tensor(np.zeros((3, 2), np.float32))
        out = paddle.select_scatter(
            x, paddle.to_tensor(np.ones(2, np.float32)), axis=0, index=1)
        np.testing.assert_allclose(out.numpy()[1], 1.0)
        np.testing.assert_allclose(out.numpy()[[0, 2]], 0.0)

    def test_slice_scatter(self):
        x = paddle.to_tensor(np.zeros((4, 4), np.float32))
        v = paddle.to_tensor(np.ones((2, 4), np.float32))
        out = paddle.slice_scatter(x, v, axes=[0], starts=[1], ends=[3],
                                   strides=[1])
        np.testing.assert_allclose(out.numpy()[1:3], 1.0)

    def test_diagonal_scatter_matches_numpy(self):
        x = paddle.to_tensor(np.zeros((3, 3), np.float32))
        out = paddle.diagonal_scatter(
            x, paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32)))
        np.testing.assert_allclose(np.diag(out.numpy()), [1, 2, 3])

    def test_unfold(self):
        x = paddle.to_tensor(np.arange(6, dtype=np.float32))
        out = paddle.unfold(x, axis=0, size=3, step=2)
        np.testing.assert_allclose(out.numpy(), [[0, 1, 2], [2, 3, 4]])

    def test_masked_scatter(self):
        x = paddle.to_tensor(np.zeros(4, np.float32))
        mask = paddle.to_tensor(np.array([True, False, True, False]))
        out = paddle.masked_scatter(
            x, mask, paddle.to_tensor(np.array([7.0, 8.0], np.float32)))
        np.testing.assert_allclose(out.numpy(), [7, 0, 8, 0])

    def test_combinations(self):
        x = paddle.to_tensor(np.array([10.0, 20.0, 30.0], np.float32))
        out = paddle.combinations(x, r=2).numpy()
        np.testing.assert_allclose(out, [[10, 20], [10, 30], [20, 30]])


class TestExtras:
    def test_cdist_pdist(self):
        from scipy.spatial.distance import cdist as sc_cdist
        from scipy.spatial.distance import pdist as sc_pdist
        rng = np.random.RandomState(0)
        a = rng.rand(4, 3).astype(np.float32)
        b = rng.rand(5, 3).astype(np.float32)
        np.testing.assert_allclose(
            paddle.cdist(paddle.to_tensor(a), paddle.to_tensor(b)).numpy(),
            sc_cdist(a, b), atol=1e-5)
        np.testing.assert_allclose(
            paddle.pdist(paddle.to_tensor(a)).numpy(), sc_pdist(a),
            atol=1e-5)

    def test_frexp_roundtrip(self):
        x = np.array([0.75, 6.0, -3.0], np.float32)
        m, e = paddle.frexp(paddle.to_tensor(x))
        np.testing.assert_allclose(m.numpy() * 2.0 ** e.numpy(), x,
                                   rtol=1e-6)

    def test_tensordot_matches_numpy(self):
        rng = np.random.RandomState(1)
        a = rng.rand(2, 3, 4).astype(np.float32)
        b = rng.rand(4, 3, 5).astype(np.float32)
        got = paddle.tensordot(paddle.to_tensor(a), paddle.to_tensor(b),
                               axes=[[2], [0]]).numpy()
        np.testing.assert_allclose(got, np.tensordot(a, b, axes=([2], [0])),
                                   atol=1e-5)

    def test_renorm_caps_norms(self):
        x = paddle.to_tensor(np.array([[3.0, 4.0], [0.3, 0.4]], np.float32))
        out = paddle.renorm(x, p=2.0, axis=0, max_norm=1.0).numpy()
        np.testing.assert_allclose(np.linalg.norm(out[0]), 1.0, rtol=1e-5)
        np.testing.assert_allclose(out[1], [0.3, 0.4], rtol=1e-5)  # untouched

    def test_reduce_as(self):
        x = paddle.to_tensor(np.ones((2, 3, 4), np.float32))
        t = paddle.to_tensor(np.ones((3, 1), np.float32))
        out = paddle.reduce_as(x, t)
        assert list(out.shape) == [3, 1]
        np.testing.assert_allclose(out.numpy(), 8.0)

    def test_as_complex_real_roundtrip(self):
        x = np.random.RandomState(0).rand(3, 2).astype(np.float32)
        c = paddle.as_complex(paddle.to_tensor(x))
        back = paddle.as_real(c).numpy()
        np.testing.assert_allclose(back, x, atol=1e-6)

    def test_sgn_complex(self):
        z = np.array([3 + 4j, 0 + 0j], np.complex64)
        out = paddle.sgn(paddle.to_tensor(z)).numpy()
        np.testing.assert_allclose(out[0], 0.6 + 0.8j, atol=1e-6)
        np.testing.assert_allclose(out[1], 0.0, atol=1e-7)

    def test_vander(self):
        x = np.array([1.0, 2.0, 3.0], np.float32)
        np.testing.assert_allclose(
            paddle.vander(paddle.to_tensor(x)).numpy(), np.vander(x))

    def test_standard_gamma_positive(self):
        alpha = paddle.to_tensor(np.full((100,), 2.0, np.float32))
        s = paddle.standard_gamma(alpha).numpy()
        assert (s > 0).all() and 1.0 < s.mean() < 3.5  # E[Gamma(2,1)] = 2


class TestFrameworkUtils:
    def test_finfo_iinfo(self):
        fi = paddle.finfo(paddle.bfloat16)
        assert fi.bits == 16 and fi.max > 3e38
        ii = paddle.iinfo(paddle.int32)
        assert ii.max == 2**31 - 1

    def test_create_parameter(self):
        p = paddle.create_parameter([2, 3], "float32")
        assert not p.stop_gradient and list(p.shape) == [2, 3]

    def test_batch(self):
        r = paddle.batch(lambda: iter(range(5)), batch_size=2)
        assert list(r()) == [[0, 1], [2, 3], [4]]
        r = paddle.batch(lambda: iter(range(5)), batch_size=2,
                         drop_last=True)
        assert list(r()) == [[0, 1], [2, 3]]

    def test_check_shape(self):
        assert paddle.check_shape([2, -1, 3]) == [2, -1, 3]
        with pytest.raises(ValueError):
            paddle.check_shape([-1, -1])

    def test_lazy_guard_and_misc(self):
        with paddle.LazyGuard():
            lin = paddle.nn.Linear(2, 2)
        assert lin.parameters()
        paddle.disable_signal_handler()
        st = paddle.get_cuda_rng_state()
        paddle.set_cuda_rng_state(st)


def test_set_printoptions_scoped_to_tensor_repr():
    import numpy as np
    before = np.get_printoptions()
    paddle.set_printoptions(precision=2, sci_mode=False)
    try:
        t = paddle.to_tensor(np.array([1.23456789e-5], np.float32))
        assert "1.23456789" not in repr(t)
        # the user's numpy formatting is untouched
        assert np.get_printoptions() == before
    finally:
        paddle.set_printoptions()  # reset


def test_output_size_and_output_padding_mutually_exclusive():
    import paddle_tpu.nn.functional as F
    x = paddle.to_tensor(np.zeros((1, 3, 8, 8), np.float32))
    w = paddle.to_tensor(np.zeros((3, 4, 3, 3), np.float32))
    with pytest.raises(ValueError, match="mutually exclusive"):
        F.conv2d_transpose(x, w, stride=2, output_padding=1,
                           output_size=[17, 17])


def test_where_method_binds_condition_like_reference():
    """reference math_op_patch attaches where_ plainly, so
    cond.where_(x, y) == where(cond, x, y) written in-place into x."""
    cond = paddle.to_tensor(np.array([True, False]))
    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    y = paddle.to_tensor(np.array([9.0, 9.0], np.float32))
    out = cond.where_(x, y)
    assert out is x
    np.testing.assert_allclose(x.numpy(), [1.0, 9.0])
    with pytest.raises(ValueError, match="both"):
        cond.where_(x)


def test_no_infra_helpers_leak_onto_tensor():
    from paddle_tpu.core.tensor import Tensor
    for bad in ("matmul_precision", "apply_op", "to_tensor",
                "check_shape"):
        assert not hasattr(Tensor, bad), bad
    # op methods from every source module still attach
    for good in ("exp", "cdist", "unfold", "sqrt_", "masked_scatter"):
        assert hasattr(Tensor, good), good


def test_reference_tensor_method_func_fully_covered():
    """Every name in the reference's tensor_method_func list (372 methods
    patched onto Tensor) must exist on our Tensor."""
    from paddle_tpu.core.tensor import Tensor
    src = open("/root/reference/python/paddle/tensor/__init__.py").read()
    block = re.search(r"tensor_method_func = \[(.*?)\]", src, re.S).group(1)
    names = set(re.findall(r"'([^']+)'", block))
    assert len(names) > 300
    missing = sorted(n for n in names if not hasattr(Tensor, n))
    assert missing == [], f"missing Tensor methods: {missing}"


class TestLateMethodAdditions:
    def test_ormqr_reproduces_full_q(self):
        import scipy.linalg as sl
        a = np.random.RandomState(0).rand(6, 4).astype(np.float32)
        (h, tau), _ = sl.qr(a, mode="raw")
        got = paddle.ormqr(paddle.to_tensor(np.asarray(h, np.float32)),
                           paddle.to_tensor(np.asarray(tau, np.float32)),
                           paddle.to_tensor(np.eye(6, dtype=np.float32)))
        np.testing.assert_allclose(got.numpy(), sl.qr(a)[0], atol=5e-3)

    def test_svd_lowrank_approximates_top_singular_values(self):
        a = np.random.RandomState(1).rand(20, 8).astype(np.float32)
        u, s, v = paddle.svd_lowrank(paddle.to_tensor(a), q=4, niter=3)
        ref = np.linalg.svd(a, compute_uv=False)[:4]
        np.testing.assert_allclose(s.numpy(), ref, rtol=0.05)
        # and the rank-4 reconstruction is close
        rec = u.numpy() @ np.diag(s.numpy()) @ v.numpy().T
        assert np.abs(rec - a).max() < np.abs(a).max()

    def test_top_p_sampling_stays_in_nucleus(self):
        probs = paddle.to_tensor(
            np.tile(np.array([[0.5, 0.3, 0.15, 0.05]], np.float32),
                    (8, 1)))
        vals, ids = paddle.top_p_sampling(
            probs, paddle.to_tensor(np.full((8, 1), 0.6, np.float32)))
        assert set(ids.numpy().reshape(-1).tolist()) <= {0, 1}

    def test_cauchy_and_geometric_fills(self):
        x = paddle.to_tensor(np.zeros(4000, np.float32))
        x.cauchy_(loc=1.0, scale=0.5)
        assert abs(float(np.median(x.numpy())) - 1.0) < 0.1
        g = paddle.to_tensor(np.zeros(4000, np.float32))
        g.geometric_(0.5)
        assert (g.numpy() >= 1).all() and 1.8 < g.numpy().mean() < 2.2

    def test_inplace_index_ops(self):
        x = paddle.to_tensor(np.zeros((3, 2), np.float32))
        x.index_fill_(paddle.to_tensor(np.array([1], np.int64)), 0, 7.0)
        np.testing.assert_allclose(x.numpy()[1], 7.0)
        y = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        y.lerp_(paddle.to_tensor(np.array([3.0, 4.0], np.float32)), 0.5)
        np.testing.assert_allclose(y.numpy(), [2.0, 3.0])

    def test_attached_late_methods(self):
        x = paddle.to_tensor(np.eye(3, dtype=np.float32))
        assert list(x.tril().shape) == [3, 3]
        assert list(x.diag().shape) == [3]
        v = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        assert list(v.reverse([0]).numpy()) == [2.0, 1.0]
        assert paddle.create_tensor("float32").shape == [0]


def test_slice_shadow_victims():
    """index_fill / strided_slice previously crashed because the paddle
    `slice` op shadows the builtin inside manipulation.py."""
    x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
    out = paddle.index_fill(x, paddle.to_tensor(np.array([0], np.int64)),
                            0, -1.0)
    np.testing.assert_allclose(out.numpy()[0], -1.0)
    np.testing.assert_allclose(out.numpy()[1:], x.numpy()[1:])
    s = paddle.strided_slice(x, axes=[1], starts=[0], ends=[4], strides=[2])
    np.testing.assert_allclose(s.numpy(), x.numpy()[:, ::2])


def test_builtins_helpers_not_tensor_methods():
    from paddle_tpu.core.tensor import Tensor
    assert not hasattr(Tensor, "builtins_slice")
    assert not hasattr(Tensor, "builtins_sum")


def test_ormqr_forward_works_under_autograd():
    """Q-building has no JAX grad rule; forward must still run in a
    grad-enabled context (grads flow through y only, like the reference
    which registers no ormqr_grad)."""
    import scipy.linalg as sl
    a = np.random.RandomState(0).rand(5, 3).astype(np.float32)
    (h, tau), _ = sl.qr(a, mode="raw")
    x = paddle.to_tensor(np.asarray(h, np.float32))
    x.stop_gradient = False
    y = paddle.to_tensor(np.eye(5, dtype=np.float32))
    y.stop_gradient = False
    out = paddle.ormqr(x, tau=paddle.to_tensor(np.asarray(tau, np.float32)),
                       y=y)
    out.sum().backward()
    assert y.grad is not None


def test_top_p_threshold_excludes_low_prob_tokens():
    probs = paddle.to_tensor(
        np.tile(np.array([[0.4, 0.35, 0.2, 0.05]], np.float32), (16, 1)))
    # ps=0.99 would admit everything; threshold kicks token 3 (p=0.05) out
    vals, ids = paddle.top_p_sampling(
        probs, paddle.to_tensor(np.full((16, 1), 0.99, np.float32)),
        threshold=0.1)
    assert 3 not in set(ids.numpy().reshape(-1).tolist())


def test_geometric_accepts_tensor_probs():
    g = paddle.to_tensor(np.zeros(100, np.float32))
    g.geometric_(paddle.to_tensor(np.full(100, 0.5, np.float32)))
    assert (g.numpy() >= 1).all()


@pytest.mark.parametrize("ref_path,mod_name", [
    ("/root/reference/python/paddle/nn/__init__.py", "nn"),
    ("/root/reference/python/paddle/nn/functional/__init__.py",
     "nn.functional"),
    ("/root/reference/python/paddle/fft.py", "fft"),
    ("/root/reference/python/paddle/signal.py", "signal"),
    ("/root/reference/python/paddle/io/__init__.py", "io"),
    ("/root/reference/python/paddle/distribution/__init__.py",
     "distribution"),
    ("/root/reference/python/paddle/sparse/__init__.py", "sparse"),
    ("/root/reference/python/paddle/vision/__init__.py", "vision"),
    ("/root/reference/python/paddle/optimizer/__init__.py", "optimizer"),
    ("/root/reference/python/paddle/amp/__init__.py", "amp"),
    ("/root/reference/python/paddle/metric/__init__.py", "metric"),
    ("/root/reference/python/paddle/jit/__init__.py", "jit"),
    ("/root/reference/python/paddle/distributed/__init__.py",
     "distributed"),
    ("/root/reference/python/paddle/incubate/__init__.py", "incubate"),
    ("/root/reference/python/paddle/incubate/nn/__init__.py",
     "incubate.nn"),
    ("/root/reference/python/paddle/vision/transforms/__init__.py",
     "vision.transforms"),
    ("/root/reference/python/paddle/vision/ops.py", "vision.ops"),
    ("/root/reference/python/paddle/vision/models/__init__.py",
     "vision.models"),
    ("/root/reference/python/paddle/text/__init__.py", "text"),
    ("/root/reference/python/paddle/audio/__init__.py", "audio"),
    ("/root/reference/python/paddle/distributed/fleet/__init__.py",
     "distributed.fleet"),
])
def test_nn_namespaces_fully_covered(ref_path, mod_name):
    src = open(ref_path).read()
    block = re.search(r"__all__ = \[(.*?)\]", src, re.S).group(1)
    names = set(re.findall(r"'([^']+)'", block))
    mod = paddle
    for part in mod_name.split("."):
        mod = getattr(mod, part)
    missing = sorted(n for n in names if not hasattr(mod, n))
    assert missing == [], f"{mod_name} missing: {missing}"


class TestNamespaceGapFills:
    def test_subset_random_sampler(self):
        s = paddle.io.SubsetRandomSampler([3, 5, 9])
        assert sorted(iter(s)) == [3, 5, 9] and len(s) == 3

    def test_register_kl_overrides_builtin(self):
        from paddle_tpu.distribution import Normal, register_kl
        from paddle_tpu.distribution.distributions import _KL_REGISTRY

        class MyNormal(Normal):
            pass

        @register_kl(MyNormal, MyNormal)
        def _kl(p, q):
            return paddle.to_tensor(np.float32(7.0))
        try:
            got = paddle.distribution.kl_divergence(MyNormal(0.0, 1.0),
                                                    MyNormal(1.0, 1.0))
            assert float(got.numpy()) == 7.0
        finally:
            _KL_REGISTRY.pop((MyNormal, MyNormal), None)

    def test_exponential_family_entropy_bregman(self):
        """Normal as an exponential family: Bregman entropy must equal the
        closed form 0.5*log(2*pi*e*sigma^2)."""
        import jax.numpy as jnp

        from paddle_tpu.distribution import ExponentialFamily

        class NormalEF(ExponentialFamily):
            def __init__(self, loc, scale):
                self.loc, self.scale = loc, scale

            @property
            def _natural_parameters(self):
                import numpy as np
                return (np.float32(self.loc / self.scale ** 2),
                        np.float32(-0.5 / self.scale ** 2))

            def _log_normalizer(self, n1, n2):
                return -n1 ** 2 / (4 * n2) - 0.5 * jnp.log(-2 * n2)

            @property
            def _mean_carrier_measure(self):
                # E[log h(x)] with h(x) = 1/sqrt(2*pi)
                return -0.5 * np.log(2 * np.pi)

        ent = NormalEF(0.3, 2.0).entropy()
        want = 0.5 * np.log(2 * np.pi * np.e * 4.0)  # closed form
        np.testing.assert_allclose(float(ent.numpy()), want, rtol=1e-5)

    def test_sparse_slice_addmm_pca(self):
        coo = paddle.sparse.to_sparse_coo(
            paddle.to_tensor(np.eye(4, dtype=np.float32)))
        assert list(paddle.sparse.slice(coo, [0], [1], [3])
                    .to_dense().shape) == [2, 4]
        out = paddle.sparse.addmm(
            paddle.to_tensor(np.ones((4, 4), np.float32)), coo, coo,
            beta=2.0)
        np.testing.assert_allclose(out.numpy(),
                                   2.0 + np.eye(4, dtype=np.float32))
        u, s, v = paddle.sparse.pca_lowrank(coo, q=2)
        assert list(s.shape) == [2]

    def test_jit_logging_knobs(self):
        paddle.jit.set_code_level(50)
        paddle.jit.set_verbosity(3)


class TestBreadthBatch:
    def test_audio_io_roundtrip(self, tmp_path):
        sr = 16000
        sig = np.sin(np.linspace(0, 100, 1600)).astype(np.float32)[None]
        p = str(tmp_path / "t.wav")
        paddle.audio.save(p, paddle.to_tensor(sig), sr)
        back, sr2 = paddle.audio.load(p)
        assert sr2 == sr
        np.testing.assert_allclose(back.numpy(), sig, atol=1e-3)
        assert paddle.audio.info(p).sample_rate == sr

    def test_transforms_rotate_matches_rot90(self):
        img = (np.random.RandomState(0).rand(8, 8, 3) * 255).astype(
            np.uint8)
        T = paddle.vision.transforms
        np.testing.assert_allclose(
            T.rotate(img, 90).astype(float),
            np.rot90(img, 1, (0, 1)).astype(float), atol=1.0)
        np.testing.assert_allclose(
            T.affine(img, 90, (0, 0), 1.0, 0.0).astype(float),
            T.rotate(img, 90).astype(float), atol=1.0)

    def test_transforms_hue_saturation_identity(self):
        img = (np.random.RandomState(1).rand(6, 6, 3) * 255).astype(
            np.uint8)
        T = paddle.vision.transforms
        np.testing.assert_allclose(
            T.adjust_hue(img, 0.0).astype(float), img.astype(float),
            atol=2.0)
        np.testing.assert_allclose(
            T.adjust_saturation(img, 1.0).astype(float), img.astype(float),
            atol=1.0)

    def test_matrix_nms_decays_overlaps(self):
        # box 1 overlaps box 0 (iou ~0.67): its score decays but survives;
        # box 2 is disjoint and keeps its score
        boxes = np.array([[[0, 0, 10, 10], [2, 0, 12, 10],
                           [20, 20, 30, 30]]], np.float32)
        scores = np.zeros((1, 2, 3), np.float32)   # [N, C, M]; class 0 = bg
        scores[0, 1] = [0.9, 0.8, 0.95]
        out, num = paddle.vision.ops.matrix_nms(
            paddle.to_tensor(boxes), paddle.to_tensor(scores),
            score_threshold=0.1, post_threshold=0.0, background_label=0)
        o = out.numpy()
        assert int(num.numpy()[0]) == 3
        decayed = sorted(o[:, 1])[0]
        assert decayed < 0.5                        # 0.8 * (1 - 0.67)
        assert sorted(o[:, 1])[-1] == np.float32(0.95)  # disjoint untouched

    def test_prior_box_shapes_and_range(self):
        feat = paddle.to_tensor(np.zeros((1, 8, 4, 4), np.float32))
        img = paddle.to_tensor(np.zeros((1, 3, 32, 32), np.float32))
        boxes, var = paddle.vision.ops.prior_box(
            feat, img, min_sizes=[8.0], aspect_ratios=[2.0], clip=True)
        assert list(boxes.shape) == [4, 4, 2, 4]
        b = boxes.numpy()
        assert (b >= 0).all() and (b <= 1).all()

    def test_incubate_lookahead_and_model_average(self):
        lin = paddle.nn.Linear(3, 1)
        opt = paddle.incubate.LookAhead(
            paddle.optimizer.SGD(0.1, parameters=lin.parameters()), k=2)
        x = paddle.to_tensor(np.ones((4, 3), np.float32))
        for _ in range(4):
            loss = (lin(x) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
        ma = paddle.incubate.ModelAverage(parameters=lin.parameters())
        w_now = lin.parameters()[0].numpy().copy()
        ma.step()
        ma.apply()
        np.testing.assert_allclose(lin.parameters()[0].numpy(), w_now,
                                   atol=1e-6)
        ma.restore()

    def test_softmax_mask_fuse_upper_triangle_is_causal(self):
        x = paddle.to_tensor(np.random.RandomState(0)
                             .rand(1, 1, 4, 4).astype(np.float32))
        p = paddle.incubate.softmax_mask_fuse_upper_triangle(x).numpy()
        assert np.allclose(np.triu(p[0, 0], k=1), 0.0, atol=1e-6)
        np.testing.assert_allclose(p[0, 0].sum(-1), 1.0, rtol=1e-5)

    def test_distributed_compat_objects(self):
        from paddle_tpu import distributed as dist
        assert dist.ParallelMode.DATA_PARALLEL == 0
        assert dist.is_available()
        e = dist.ProbabilityEntry(0.5)
        assert "probability_entry" in e._to_attr()
        with pytest.raises(ValueError):
            dist.ProbabilityEntry(2.0)
        s = dist.Strategy()
        assert s.pipeline["schedule_mode"] == "1F1B"
        ds = dist.InMemoryDataset()
        ds.init(batch_size=2)

    def test_dist_model_trains(self):
        from paddle_tpu import distributed as dist
        lin = paddle.nn.Linear(4, 2)
        opt = paddle.optimizer.SGD(0.1, parameters=lin.parameters())
        dm = dist.DistModel(lin, loss=paddle.nn.MSELoss(), optimizer=opt)
        x = paddle.to_tensor(np.random.rand(8, 4).astype(np.float32))
        y = paddle.to_tensor(np.random.rand(8, 2).astype(np.float32))
        l0 = float(dm(x, y).numpy())
        for _ in range(5):
            l1 = float(dm(x, y).numpy())
        assert l1 < l0
        dm.eval()
        assert list(dm(x).shape) == [8, 2]

    def test_fleet_util_and_data_generator(self):
        from paddle_tpu.distributed import fleet

        class Gen(fleet.MultiSlotDataGenerator):
            def generate_sample(self, line):
                def g():
                    yield [("ids", [1, 2]), ("label", [0])]
                return g
        out = Gen().run_from_memory(["x"])
        assert out == ["2 1 2 1 0\n"]
        u = fleet.UtilBase()
        assert u.get_file_shard(["a", "b", "c"]) == ["a", "b", "c"]


class TestBreadthReviewFixes:
    def test_rotate_grayscale_2d(self):
        img = (np.random.RandomState(0).rand(8, 8) * 255).astype(np.uint8)
        T = paddle.vision.transforms
        r = T.rotate(img, 90)
        np.testing.assert_allclose(r.astype(float),
                                   np.rot90(img).astype(float), atol=1.0)

    def test_float_255_range_stays_float(self):
        T = paddle.vision.transforms
        img = (np.random.RandomState(0).rand(4, 4, 3) * 255).astype(
            np.float32)
        out = T.adjust_brightness(img, 1.1)
        assert out.dtype == np.float32
        assert out.max() <= 255.0 + 1e-3

    def test_matrix_nms_decay_never_boosts(self):
        # iou(C,A)=big, iou(C,B)=small, iou(B,A)=big: the per-predecessor
        # min must keep decay <= 1 (a global-max compensation boosts it)
        boxes = np.array([[[0, 0, 10, 10], [4, 0, 14, 10],
                           [5, 0, 15, 10]]], np.float32)
        scores = np.zeros((1, 2, 3), np.float32)
        scores[0, 1] = [0.9, 0.85, 0.8]
        out, num = paddle.vision.ops.matrix_nms(
            paddle.to_tensor(boxes), paddle.to_tensor(scores),
            score_threshold=0.1, post_threshold=0.0, background_label=0)
        o = out.numpy()
        orig = {0.9, 0.85, 0.8}
        for row in o:
            assert row[1] <= max(orig) + 1e-6
        # every decayed score <= its original
        assert sorted(o[:, 1])[-1] == np.float32(0.9)

    def test_random_affine_scalar_shear_applies(self):
        T = paddle.vision.transforms
        img = (np.random.RandomState(0).rand(8, 8, 3) * 255).astype(
            np.uint8)
        t = T.RandomAffine(degrees=0, shear=30)
        outs = {t(img).tobytes() for _ in range(8)}
        assert len(outs) > 1  # shear actually samples

    def test_strategy_config_merges_sections(self):
        from paddle_tpu import distributed as dist
        s = dist.Strategy({"sharding": {"enable": True}})
        assert s.sharding.enable is True
        assert s.sharding["degree"] == 1  # merged, not replaced

    def test_fleet_all_reduce_mode_validated(self):
        from paddle_tpu.distributed import fleet
        with pytest.raises(ValueError):
            fleet.UtilBase().all_reduce(np.ones(2), mode="bogus")
