"""Per-op parity suite over the OpTest-style harness (tests/op_harness.py).

Reference: /root/reference/test/legacy_test/op_test.py + the per-op
test_*_op.py files under test/legacy_test/ — each case here plays the role
of one OpTest subclass: numpy reference vs eager vs jit vs dp-sharded,
fp32/bf16/fp16, plus numeric-vs-analytic gradients.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

from op_harness import OpCase, run_case

rng = np.random.RandomState(0)


def A(*shape):
    return rng.uniform(-1.0, 1.0, size=shape).astype(np.float32)


def POS(*shape):
    return rng.uniform(0.1, 2.0, size=shape).astype(np.float32)


X = A(8, 4)
Y = A(8, 4)
XP = POS(8, 4)
M1 = A(8, 4)
M2 = A(4, 8)
V = A(8)
IDX = rng.randint(0, 4, size=(8,)).astype(np.int64)
LOGITS = A(8, 5)
LABELS = rng.randint(0, 5, size=(8,)).astype(np.int64)


def _sm(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


def _conv2d_ref(x, w, stride, pad):
    """Direct NCHW convolution (cross-correlation) reference."""
    if pad:
        x = np.pad(x, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    B, C, H, W = x.shape
    O, _, kh, kw = w.shape
    oh = (H - kh) // stride + 1
    ow = (W - kw) // stride + 1
    out = np.zeros((B, O, oh, ow), np.float32)
    for i in range(oh):
        for j in range(ow):
            patch = x[:, :, i * stride:i * stride + kh,
                      j * stride:j * stride + kw]
            out[:, :, i, j] = np.einsum("bchw,ochw->bo", patch, w)
    return out


CASES = [
    # ---- elementwise binary -------------------------------------------------
    OpCase("add", paddle.add, lambda a, b: a + b, [X, Y]),
    OpCase("subtract", paddle.subtract, lambda a, b: a - b, [X, Y]),
    OpCase("multiply", paddle.multiply, lambda a, b: a * b, [X, Y]),
    OpCase("divide", paddle.divide, lambda a, b: a / b, [X, XP]),
    OpCase("pow", paddle.pow, lambda a, b: a ** b, [XP, Y]),
    OpCase("maximum", paddle.maximum, np.maximum, [X, Y]),
    OpCase("minimum", paddle.minimum, np.minimum, [X, Y]),
    OpCase("fmax", paddle.fmax, np.fmax, [X, Y]),
    OpCase("fmin", paddle.fmin, np.fmin, [X, Y]),
    OpCase("atan2", paddle.atan2, np.arctan2, [X, XP]),
    OpCase("lerp", paddle.lerp, lambda a, b, w: a + w * (b - a),
           [X, Y, POS(8, 4)]),
    # ---- elementwise unary --------------------------------------------------
    OpCase("exp", paddle.exp, np.exp, [X]),
    OpCase("expm1", paddle.expm1, np.expm1, [X]),
    OpCase("log", paddle.log, np.log, [XP]),
    OpCase("log1p", paddle.log1p, np.log1p, [XP]),
    OpCase("log2", paddle.log2, np.log2, [XP]),
    OpCase("sqrt", paddle.sqrt, np.sqrt, [XP]),
    OpCase("rsqrt", paddle.rsqrt, lambda x: 1 / np.sqrt(x), [XP]),
    OpCase("abs", paddle.abs, np.abs, [X]),
    OpCase("neg", paddle.neg, np.negative, [X]),
    OpCase("sin", paddle.sin, np.sin, [X]),
    OpCase("cos", paddle.cos, np.cos, [X]),
    OpCase("tan", paddle.tan, np.tan, [X]),
    OpCase("asin", paddle.asin, np.arcsin, [X]),
    OpCase("atan", paddle.atan, np.arctan, [X]),
    OpCase("sinh", paddle.sinh, np.sinh, [X]),
    OpCase("cosh", paddle.cosh, np.cosh, [X]),
    OpCase("tanh", paddle.tanh, np.tanh, [X]),
    OpCase("erf", paddle.erf, lambda x: np.vectorize(__import__(
        "math").erf)(x).astype(np.float32), [X]),
    OpCase("floor", paddle.floor, np.floor, [X], grad=False),
    OpCase("ceil", paddle.ceil, np.ceil, [X], grad=False),
    OpCase("round", paddle.round, np.round, [X], grad=False),
    OpCase("trunc", paddle.trunc, np.trunc, [X], grad=False),
    OpCase("sign", paddle.sign, np.sign, [X], grad=False),
    OpCase("reciprocal", paddle.reciprocal, lambda x: 1.0 / x, [XP]),
    OpCase("square", paddle.square, np.square, [X]),
    OpCase("logit", paddle.logit,
           lambda x: np.log(x / (1 - x)), [POS(8, 4) * 0.4],
           tol={"bfloat16": (5e-2, 5e-2)}),
    # ---- activations --------------------------------------------------------
    OpCase("relu", F.relu, lambda x: np.maximum(x, 0), [X]),
    OpCase("sigmoid", F.sigmoid, lambda x: 1 / (1 + np.exp(-x)), [X]),
    OpCase("gelu", F.gelu,
           lambda x: x * 0.5 * (1 + np.vectorize(__import__("math").erf)(
               x / np.sqrt(2)).astype(np.float32)), [X]),
    OpCase("silu", F.silu, lambda x: x / (1 + np.exp(-x)), [X]),
    OpCase("softplus", F.softplus, lambda x: np.log1p(np.exp(x)), [X]),
    OpCase("elu", F.elu, lambda x: np.where(x > 0, x, np.exp(x) - 1), [X]),
    OpCase("leaky_relu", F.leaky_relu,
           lambda x: np.where(x > 0, x, 0.01 * x), [X]),
    OpCase("hardswish", F.hardswish,
           lambda x: x * np.clip(x + 3, 0, 6) / 6, [X],
           max_relative_error=0.1),
    OpCase("mish", F.mish, lambda x: x * np.tanh(np.log1p(np.exp(x))), [X]),
    OpCase("softmax", F.softmax, _sm, [X]),
    OpCase("log_softmax", F.log_softmax,
           lambda x: np.log(_sm(x)), [X]),
    # ---- reductions ---------------------------------------------------------
    OpCase("sum", paddle.sum, np.sum, [X]),
    OpCase("sum_axis", lambda t: paddle.sum(t, axis=1),
           lambda x: x.sum(1), [X]),
    OpCase("mean", paddle.mean, np.mean, [X]),
    OpCase("max", paddle.max, np.max, [X]),
    OpCase("min", paddle.min, np.min, [X]),
    OpCase("prod", paddle.prod, np.prod, [A(2, 3) * 0.5 + 1.0],
           sharded=False),
    OpCase("logsumexp", paddle.logsumexp,
           lambda x: np.log(np.exp(x).sum()), [X]),
    OpCase("argmax", lambda t: paddle.argmax(t, axis=1),
           lambda x: x.argmax(1), [X], grad=False, dtypes=("float32",)),
    OpCase("argmin", lambda t: paddle.argmin(t, axis=1),
           lambda x: x.argmin(1), [X], grad=False, dtypes=("float32",)),
    OpCase("cumsum", lambda t: paddle.cumsum(t, axis=1),
           lambda x: x.cumsum(1), [X]),
    OpCase("cumprod", lambda t: paddle.cumprod(t, dim=1),
           lambda x: np.cumprod(x, 1), [XP]),
    OpCase("std", paddle.std, lambda x: x.std(ddof=1), [X],
           max_relative_error=0.08),
    OpCase("var", paddle.var, lambda x: x.var(ddof=1), [X]),
    OpCase("median", paddle.median, np.median, [A(8, 5)], grad=False,
           dtypes=("float32",)),
    OpCase("nanmean", paddle.nanmean, np.nanmean, [X], grad=False),
    # ---- linear algebra -----------------------------------------------------
    OpCase("matmul", paddle.matmul, lambda a, b: a @ b, [M1, M2],
           tol={"bfloat16": (3e-2, 3e-2), "float16": (4e-3, 4e-3)}),
    OpCase("bmm", paddle.bmm, lambda a, b: a @ b,
           [A(8, 3, 4), A(8, 4, 5)],
           tol={"bfloat16": (3e-2, 3e-2), "float16": (4e-3, 4e-3)}),
    OpCase("dot", paddle.dot, lambda a, b: (a * b).sum(-1), [V, A(8)]),
    OpCase("t", paddle.t, np.transpose, [M1], sharded=False),
    OpCase("norm_fro", lambda t: paddle.linalg.norm(t),
           lambda x: np.linalg.norm(x), [X]),
    OpCase("outer", paddle.outer, np.outer, [V, A(4)], sharded=False),
    OpCase("diag", paddle.diag, np.diag, [V], sharded=False),
    OpCase("tril", paddle.tril, np.tril, [M1]),
    OpCase("triu", paddle.triu, np.triu, [M1]),
    OpCase("kron", paddle.kron, np.kron, [A(2, 3), A(3, 2)],
           sharded=False),
    # ---- manipulation -------------------------------------------------------
    OpCase("reshape", lambda t: paddle.reshape(t, [4, 8]),
           lambda x: x.reshape(4, 8), [X], sharded=False),
    OpCase("transpose", lambda t: paddle.transpose(t, [1, 0]),
           lambda x: x.T, [X], sharded=False),
    OpCase("concat", lambda a, b: paddle.concat([a, b], axis=1),
           lambda a, b: np.concatenate([a, b], 1), [X, Y]),
    OpCase("stack", lambda a, b: paddle.stack([a, b], axis=0),
           lambda a, b: np.stack([a, b]), [X, Y], sharded=False),
    OpCase("split", lambda t: paddle.split(t, 2, axis=1),
           lambda x: np.split(x, 2, 1), [X]),
    OpCase("squeeze", lambda t: paddle.squeeze(t, axis=1),
           lambda x: x.squeeze(1), [A(8, 1, 4)]),
    OpCase("unsqueeze", lambda t: paddle.unsqueeze(t, axis=1),
           lambda x: x[:, None], [X]),
    OpCase("flatten", lambda t: paddle.flatten(t, start_axis=1),
           lambda x: x.reshape(8, -1), [A(8, 2, 2)]),
    OpCase("tile", lambda t: paddle.tile(t, [2, 3]),
           lambda x: np.tile(x, (2, 3)), [X], sharded=False),
    OpCase("expand", lambda t: paddle.expand(t, [8, 4]),
           lambda x: np.broadcast_to(x, (8, 4)).copy(), [A(1, 4)],
           sharded=False),
    OpCase("roll", lambda t: paddle.roll(t, 2, axis=0),
           lambda x: np.roll(x, 2, 0), [X], sharded=False),
    OpCase("flip", lambda t: paddle.flip(t, axis=[0]),
           lambda x: x[::-1].copy(), [X], sharded=False),
    OpCase("clip", lambda t: paddle.clip(t, -0.5, 0.5),
           lambda x: np.clip(x, -0.5, 0.5), [X]),
    OpCase("gather", lambda t, i: paddle.gather(t, i, axis=0),
           lambda x, i: x[i], [X, IDX], integer_inputs=(1,)),
    OpCase("index_select", lambda t, i: paddle.index_select(t, i, axis=0),
           lambda x, i: x[i], [X, IDX], integer_inputs=(1,)),
    OpCase("where", paddle.where,
           lambda c, a, b: np.where(c, a, b),
           [X > 0, X, Y], integer_inputs=(0,)),
    OpCase("masked_select", paddle.masked_select,
           lambda x, m: x[m], [X, X > 0], integer_inputs=(1,),
           sharded=False, grad=False, jit=False),  # data-dependent shape
    OpCase("pad", lambda t: F.pad(t, [1, 1, 2, 2]),
           lambda x: np.pad(x, ((1, 1), (2, 2))), [X], sharded=False),
    OpCase("chunk", lambda t: paddle.chunk(t, 2, axis=0),
           lambda x: np.split(x, 2, 0), [X], sharded=False),
    OpCase("one_hot", lambda i: F.one_hot(i, num_classes=4),
           lambda i: np.eye(4, dtype=np.float32)[i],
           [IDX], integer_inputs=(0,), grad=False, dtypes=("float32",)),
    # ---- indexing / search --------------------------------------------------
    OpCase("topk", lambda t: paddle.topk(t, k=2, axis=1),
           lambda x: (np.sort(x, 1)[:, ::-1][:, :2].copy(),
                      np.argsort(-x, 1, kind="stable")[:, :2].copy()),
           [X], grad=False, dtypes=("float32",)),
    OpCase("sort", lambda t: paddle.sort(t, axis=1),
           lambda x: np.sort(x, 1), [X], grad=False),
    OpCase("argsort", lambda t: paddle.argsort(t, axis=1),
           lambda x: np.argsort(x, 1, kind="stable"), [X], grad=False,
           dtypes=("float32",)),
    OpCase("unique", paddle.unique, np.unique,
           [rng.randint(0, 5, (12,)).astype(np.int64)],
           integer_inputs=(0,), grad=False, sharded=False, jit=False,
           dtypes=("float32",)),
    # ---- comparison / logical ----------------------------------------------
    OpCase("equal", paddle.equal, lambda a, b: a == b,
           [IDX.astype(np.float32), IDX.astype(np.float32)], grad=False,
           dtypes=("float32",)),
    OpCase("greater_than", paddle.greater_than, lambda a, b: a > b,
           [X, Y], grad=False, dtypes=("float32",)),
    OpCase("less_equal", paddle.less_equal, lambda a, b: a <= b,
           [X, Y], grad=False, dtypes=("float32",)),
    OpCase("isnan", paddle.isnan, np.isnan,
           [np.where(X > 0.8, np.nan, X).astype(np.float32)], grad=False,
           dtypes=("float32",)),
    OpCase("isfinite", paddle.isfinite, np.isfinite, [X], grad=False,
           dtypes=("float32",)),
    OpCase("logical_and", paddle.logical_and, np.logical_and,
           [X > 0, Y > 0], integer_inputs=(0, 1), grad=False,
           dtypes=("float32",)),
    # ---- nn functional ------------------------------------------------------
    OpCase("linear", F.linear,
           lambda x, w, b: x @ w + b, [X, A(4, 6), A(6)],
           tol={"bfloat16": (3e-2, 3e-2), "float16": (4e-3, 4e-3)}),
    OpCase("embedding", lambda i, w: F.embedding(i, w),
           lambda i, w: w[i], [IDX, A(4, 6)], integer_inputs=(0,)),
    OpCase("layer_norm",
           lambda x, w, b: F.layer_norm(x, (4,), weight=w, bias=b),
           lambda x, w, b: ((x - x.mean(-1, keepdims=True)) /
                            np.sqrt(x.var(-1, keepdims=True) + 1e-5)
                            * w + b),
           [X, POS(4), A(4)], max_relative_error=0.08),
    OpCase("mse_loss", F.mse_loss,
           lambda a, b: ((a - b) ** 2).mean(), [X, Y]),
    OpCase("l1_loss", F.l1_loss,
           lambda a, b: np.abs(a - b).mean(), [X, Y]),
    OpCase("cross_entropy",
           lambda lo, la: F.cross_entropy(lo, la),
           lambda lo, la: -np.log(_sm(lo)[np.arange(8), la]).mean(),
           [LOGITS, LABELS], integer_inputs=(1,)),
    OpCase("nll_loss",
           lambda lo, la: F.nll_loss(lo, la),
           lambda lo, la: -lo[np.arange(8), la].mean(),
           [np.log(_sm(LOGITS)), LABELS], integer_inputs=(1,)),
    OpCase("binary_cross_entropy", F.binary_cross_entropy,
           lambda p, t: -(t * np.log(p) + (1 - t) * np.log(1 - p)).mean(),
           [POS(8, 4) * 0.4, (A(8, 4) > 0).astype(np.float32)],
           integer_inputs=(1,)),
    OpCase("cosine_similarity", F.cosine_similarity,
           lambda a, b: (a * b).sum(-1) /
           (np.linalg.norm(a, axis=-1) * np.linalg.norm(b, axis=-1)),
           [X, Y]),
    # ---- conv / pool / vision functional ------------------------------------
    OpCase("conv2d",
           lambda x, w: F.conv2d(x, w, stride=1, padding=1),
           lambda x, w: _conv2d_ref(x, w, 1, 1),
           [A(8, 2, 6, 6), A(3, 2, 3, 3)],
           tol={"bfloat16": (4e-2, 4e-2), "float16": (5e-3, 5e-3)},
           max_relative_error=0.2),  # fd noise over many accum terms
    OpCase("conv2d_stride2",
           lambda x, w: F.conv2d(x, w, stride=2, padding=0),
           lambda x, w: _conv2d_ref(x, w, 2, 0),
           [A(8, 2, 6, 6), A(3, 2, 3, 3)],
           tol={"bfloat16": (4e-2, 4e-2), "float16": (5e-3, 5e-3)},
           max_relative_error=0.2),
    OpCase("max_pool2d",
           lambda x: F.max_pool2d(x, kernel_size=2, stride=2),
           lambda x: x.reshape(8, 2, 3, 2, 3, 2).max(5).max(3),
           [A(8, 2, 6, 6)], grad=False),
    OpCase("avg_pool2d",
           lambda x: F.avg_pool2d(x, kernel_size=2, stride=2),
           lambda x: x.reshape(8, 2, 3, 2, 3, 2).mean(5).mean(3),
           [A(8, 2, 6, 6)]),
    OpCase("adaptive_avg_pool2d",
           lambda x: F.adaptive_avg_pool2d(x, 1),
           lambda x: x.mean((2, 3), keepdims=True), [A(8, 2, 6, 6)]),
    OpCase("interpolate_nearest",
           lambda x: F.interpolate(x, scale_factor=2, mode="nearest"),
           lambda x: x.repeat(2, axis=2).repeat(2, axis=3),
           [A(8, 2, 3, 3)], grad=False),
    OpCase("normalize",
           lambda x: F.normalize(x, axis=-1),
           lambda x: x / np.maximum(
               np.linalg.norm(x, axis=-1, keepdims=True), 1e-12), [X]),
    OpCase("pixel_shuffle",
           lambda x: F.pixel_shuffle(x, 2),
           lambda x: x.reshape(8, 1, 2, 2, 3, 3).transpose(
               0, 1, 4, 2, 5, 3).reshape(8, 1, 6, 6),
           [A(8, 4, 3, 3)], grad=False),
    # ---- misc ---------------------------------------------------------------
    OpCase("allclose", paddle.allclose, np.allclose, [X, X], grad=False,
           dtypes=("float32",), sharded=False),
    OpCase("diff", paddle.diff, lambda x: np.diff(x), [V], sharded=False),
    OpCase("histogram",
           lambda t: paddle.histogram(t, bins=4, min=-1, max=1),
           lambda x: np.histogram(x, bins=4, range=(-1, 1))[0],
           [X], grad=False, dtypes=("float32",), sharded=False),
    OpCase("bincount", paddle.bincount, np.bincount,
           [rng.randint(0, 5, (12,)).astype(np.int64)],
           integer_inputs=(0,), grad=False, sharded=False, jit=False,
           dtypes=("float32",)),
    OpCase("trace", paddle.trace, np.trace, [A(4, 4)], sharded=False),
]

_IDS = [c.name for c in CASES]
assert len(set(_IDS)) == len(_IDS), "duplicate case names"


@pytest.mark.parametrize("case", CASES, ids=_IDS)
def test_op_parity(case):
    run_case(case)


def test_case_count_at_least_50():
    """SURVEY §4 / round-5 verdict: >=50 highest-traffic ops through the
    multi-path harness."""
    assert len(CASES) >= 50, len(CASES)
