"""Disaggregated prefill/decode fleet (serving.fleet + serving.autoscale).

The load-bearing contracts:

  * block-granular KV migration — a request prefilled on a prefill
    replica continues decoding on a decode replica with TOKEN IDENTITY
    to the unified fleet (same id, same seed, same PRNG chain), and the
    hand-off copies exactly the blocks the request owns:
    ``blocks_copied == ceil(pos / block_size) - blocks_shared``, where
    prefix blocks already cached on the destination adopt by refcount
    transfer and are NEVER copied;
  * chaos — ``kv_migrate_drop`` severs the hand-off between export and
    adopt: both block pools reconcile (free + live == capacity), the
    request replays deterministically, zero lost requests; a replica
    killed mid-stream on a disaggregated fleet drains through the same
    zero-lost path;
  * backpressure — a migration that finds no decode slot is DEFERRED
    (the request stays held on its source, KV intact), not discarded
    into a replay;
  * router health actions — admission level ``degraded`` tightens the
    SLO shed margin, ``critical`` refuses new admissions
    (``serving.fleet.health_shed``) while ``shed=False`` replays pass;
  * autoscaler — ``itl_burn`` on a unified fleet triggers
    ``disaggregate`` (a replica flips to prefill), the alert resolves
    after the rebalance, and ``serving.autoscale.*`` counters prove the
    transition; with ``FLAGS_health`` off the autoscaler is inert.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import flags as core_flags
from paddle_tpu.profiler import counters
from paddle_tpu.resilience import faultinject
from paddle_tpu.serving import LLMEngine, RetryAfter, Router, ServingFleet
from paddle_tpu.serving.kvcache import (TRASH_BLOCK, BlockPoolExhausted,
                                        HostTierLost, blocks_for_tokens)


@pytest.fixture(scope="module")
def model():
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=64,
                    use_flash_attention=False)
    paddle.seed(31)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def draft_model():
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    cfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=1,
                    num_heads=2, max_seq_len=64,
                    use_flash_attention=False)
    paddle.seed(7)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


BS = 8


def _fleet(m, **kw):
    kw.setdefault("replicas", 2)
    kw.setdefault("threaded", False)
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("min_bucket", 4)
    kw.setdefault("queue_size", 16)
    kw.setdefault("heartbeat_timeout_s", 30.0)
    kw.setdefault("kv_layout", "paged")
    kw.setdefault("block_size", BS)
    kw.setdefault("n_blocks", 128)
    kw.setdefault("prefill_chunk", 16)
    return ServingFleet(m, **kw)


def _prompts(rng, sizes):
    return [rng.integers(1, 64, size=n).tolist() for n in sizes]


def _assert_pools_reconcile(fleet):
    """free + live-refcounted == capacity on every alive replica pool."""
    for rep in fleet._alive():
        pool = rep.engine.pool
        refs = list(pool._ref)
        live = sum(1 for b in range(1, len(refs)) if refs[b] > 0)
        assert len(pool._free) + live == pool.capacity, \
            f"replica {rep.idx}: pool leak"


# -- construction ------------------------------------------------------------
class TestConstruction:
    def test_requires_paged_layout(self, model):
        with pytest.raises(ValueError, match="paged"):
            ServingFleet(model, replicas=2, prefill_replicas=1,
                         threaded=False, kv_layout="slots",
                         max_seq_len=64)

    def test_requires_a_decode_replica(self, model):
        with pytest.raises(ValueError, match="decode"):
            _fleet(model, replicas=2, prefill_replicas=2)

    def test_roles_and_gauges(self, model):
        fleet = _fleet(model, replicas=3, prefill_replicas=1)
        st = fleet.stats()
        assert st["roles"] == {"prefill": 1, "decode": 2, "unified": 0}
        assert counters.get("serving.autoscale.prefill_replicas") == 1
        assert counters.get("serving.autoscale.decode_replicas") == 2
        fleet.drain()

    def test_unified_fleet_has_no_roles(self, model):
        fleet = _fleet(model)
        assert fleet.stats()["roles"] == \
            {"prefill": 0, "decode": 0, "unified": 2}
        fleet.drain()


# -- migration ---------------------------------------------------------------
class TestMigration:
    def test_token_identity_vs_unified_fleet(self, model):
        """The tentpole identity: disaggregated output is bitwise equal
        to the unified paged fleet's (itself gated against sequential
        generate), for greedy AND sampled requests."""
        rng = np.random.default_rng(0)
        prompts = _prompts(rng, (24, 9, 40, 17))
        seeds = list(range(4))
        uni = _fleet(model)
        ref = uni.generate(prompts, seeds=seeds, max_new_tokens=8,
                           do_sample=True)
        uni.drain()
        before = counters.snapshot()
        dis = _fleet(model, prefill_replicas=1)
        out = dis.generate(prompts, seeds=seeds, max_new_tokens=8,
                           do_sample=True)
        dis.drain()
        for i, (a, b) in enumerate(zip(ref, out)):
            assert np.array_equal(a, b), f"request {i} diverged"
        d = counters.delta(before)
        assert d.get("serving.fleet.migrate.requests", 0) == 4
        assert d.get("serving.fleet.lost", 0) == 0
        # every request decoded on the decode replica, so the source
        # finished each engine-attempt with reason "migrated"
        assert d.get("serving.evictions.migrated", 0) == 4

    def test_migrated_blocks_equal_owned_nonshared(self, model):
        """blocks_copied == ceil(pos/bs) for a cold destination: the
        request owns every data block and all of them move."""
        fleet = _fleet(model, prefill_replicas=1)
        prompt = _prompts(np.random.default_rng(1), (27,))[0]
        before = counters.snapshot()
        h = fleet.submit(prompt, seed=0, max_new_tokens=6)
        fleet.join([h])
        fleet.drain()
        d = counters.delta(before)
        # held at pos == len(prompt) with the first token emitted but
        # not yet inserted: KV covers exactly the prompt
        expect = blocks_for_tokens(len(prompt), BS)
        assert d.get("serving.fleet.migrate.blocks_copied", 0) == expect
        assert d.get("serving.fleet.migrate.blocks_shared", 0) == 0
        assert d.get("serving.fleet.migrate.tokens", 0) == len(prompt)

    def test_shared_prefix_blocks_never_copied_twice(self, model):
        """Two requests sharing a block-aligned prefix: the second
        migration adopts the prefix from the destination's radix tree
        (refcount transfer) and copies only its private tail."""
        rng = np.random.default_rng(2)
        shared = rng.integers(1, 64, size=2 * BS).tolist()
        p1 = shared + rng.integers(1, 64, size=8).tolist()
        p2 = shared + rng.integers(1, 64, size=9).tolist()
        fleet = _fleet(model, prefill_replicas=1)
        h1 = fleet.submit(p1, seed=1, max_new_tokens=4)
        fleet.join([h1])
        before = counters.snapshot()
        h2 = fleet.submit(p2, seed=2, max_new_tokens=4)
        fleet.join([h2])
        fleet.drain()
        d = counters.delta(before)
        n_data = blocks_for_tokens(len(p2), BS)
        assert d.get("serving.fleet.migrate.blocks_shared", 0) == 2
        assert d.get("serving.fleet.migrate.blocks_copied", 0) == \
            n_data - 2

    def test_decode_backpressure_defers_instead_of_replaying(self, model):
        """More prefilled requests than decode slots: the overflow
        hand-offs park on the source (KV intact) and complete when the
        decode side drains — no retry budget burned, nothing lost."""
        rng = np.random.default_rng(3)
        prompts = _prompts(rng, (24, 9, 40, 17, 12, 30))
        before = counters.snapshot()
        fleet = _fleet(model, prefill_replicas=1, max_slots=2)
        hs = [fleet.submit(p, seed=i, max_new_tokens=8)
              for i, p in enumerate(prompts)]
        fleet.join(hs)
        fleet.drain()
        d = counters.delta(before)
        assert all(h.finish_reason == "length" for h in hs)
        assert all(h.retries == 0 for h in hs)
        assert d.get("serving.fleet.migrate.requests", 0) == len(prompts)
        assert d.get("serving.fleet.migrate.deferred", 0) > 0
        assert d.get("serving.fleet.lost", 0) == 0

    def test_zero_steady_retraces_on_both_roles(self, model):
        """After one migration compiled the gather/scatter program, a
        steady stream of migrating requests compiles NOTHING on either
        role — the one-decode-program economics survive disaggregation."""
        rng = np.random.default_rng(4)
        fleet = _fleet(model, prefill_replicas=1,
                       warm_buckets=(16, 32, 48))
        warm = [fleet.submit(p, seed=9, max_new_tokens=4)
                for p in _prompts(rng, (24, 40))]
        fleet.join(warm)                       # compiles migrate program
        before = counters.snapshot()
        hs = [fleet.submit(p, seed=i, max_new_tokens=6)
              for i, p in enumerate(_prompts(rng, (24, 40, 9, 17)))]
        fleet.join(hs)
        d = counters.delta(before)
        assert d.get("serving.fleet.migrate.requests", 0) == 4
        assert d.get("serving.retraces", 0) == 0
        fleet.drain()


# -- engines with extra state on the decode side -----------------------------
class TestEngineVariants:
    def test_speculative_decode_replicas_token_identical(self, model,
                                                         draft_model):
        """Speculative engines on both roles: the draft namespace never
        migrates — the destination re-prefills its draft KV — and
        draft/verify acceptance stays distribution-preserving (token
        identity vs the unified speculative fleet)."""
        rng = np.random.default_rng(5)
        prompts = _prompts(rng, (24, 9, 40, 17))
        seeds = list(range(4))
        uni = _fleet(model, draft_model=draft_model, spec_k=3)
        ref = uni.generate(prompts, seeds=seeds, max_new_tokens=8,
                           do_sample=True)
        uni.drain()
        before = counters.snapshot()
        dis = _fleet(model, prefill_replicas=1,
                     draft_model=draft_model, spec_k=3)
        out = dis.generate(prompts, seeds=seeds, max_new_tokens=8,
                           do_sample=True)
        dis.drain()
        for i, (a, b) in enumerate(zip(ref, out)):
            assert np.array_equal(a, b), f"request {i} diverged"
        d = counters.delta(before)
        assert d.get("serving.fleet.migrate.requests", 0) == 4
        assert d.get("serving.spec.drafted", 0) > 0

    def test_quantized_kv_migration(self, model):
        """int8 KV arenas migrate scale rows along with the blocks; the
        stream completes with zero lost requests."""
        rng = np.random.default_rng(6)
        prompts = _prompts(rng, (24, 9, 40))
        before = counters.snapshot()
        fleet = _fleet(model, prefill_replicas=1, kv_dtype="int8")
        hs = [fleet.submit(p, seed=i, max_new_tokens=8)
              for i, p in enumerate(prompts)]
        fleet.join(hs)
        fleet.drain()
        d = counters.delta(before)
        assert all(h.finish_reason == "length" for h in hs)
        assert d.get("serving.fleet.migrate.requests", 0) == 3
        assert d.get("serving.fleet.lost", 0) == 0


# -- chaos -------------------------------------------------------------------
class TestMigrationChaos:
    def test_kv_migrate_drop_replays_with_identity(self, model):
        """The migration severed between export and adopt: refcounts on
        BOTH pools reconcile, the request replays (same id, same seed)
        and the delivered stream is identical to the unfaulted fleet."""
        rng = np.random.default_rng(7)
        prompts = _prompts(rng, (24, 9, 40, 17))
        seeds = list(range(4))
        uni = _fleet(model)
        ref = uni.generate(prompts, seeds=seeds, max_new_tokens=8,
                           do_sample=True)
        uni.drain()
        before = counters.snapshot()
        with faultinject.fault_schedule(
                "kv_migrate_drop@0,kv_migrate_drop@2"):
            dis = _fleet(model, prefill_replicas=1, max_retries=2)
            out = dis.generate(prompts, seeds=seeds, max_new_tokens=8,
                               do_sample=True)
            _assert_pools_reconcile(dis)
            dis.drain()
        for i, (a, b) in enumerate(zip(ref, out)):
            assert np.array_equal(a, b), f"request {i} diverged"
        d = counters.delta(before)
        assert d.get("serving.fleet.migrate.dropped", 0) == 2
        assert d.get("resilience.faults_injected.kv_migrate_drop", 0) == 2
        assert d.get("serving.fleet.retried", 0) == 2
        assert d.get("serving.fleet.lost", 0) == 0

    def test_replica_crash_on_disagg_fleet_loses_nothing(self, model):
        """A replica killed mid-stream on a disaggregated fleet drains
        through the normal death path: respawn inherits the role, every
        request reaches a terminal state, zero lost."""
        rng = np.random.default_rng(8)
        prompts = _prompts(rng, (24, 9, 40, 17))
        before = counters.snapshot()
        fleet = _fleet(model, prefill_replicas=1, max_retries=2)
        hs = [fleet.submit(p, seed=i, max_new_tokens=8)
              for i, p in enumerate(prompts)]
        with faultinject.fault_schedule(f"replica_crash@{hs[0].rid}"):
            fleet.join(hs)
        st = fleet.stats()
        fleet.drain()
        d = counters.delta(before)
        assert d.get("serving.fleet.replica_deaths", 0) == 1
        assert d.get("serving.fleet.lost", 0) == 0
        assert all(h.finish_reason is not None for h in hs)
        # the respawn preserved the role split
        assert st["roles"]["prefill"] == 1
        assert st["roles"]["decode"] == 1


# -- router acting on its health signal --------------------------------------
class _FakeHealth:
    def __init__(self, level):
        self.level = level

    def admission_level(self):
        return self.level


class _FakeEngine:
    queue_size = 16

    def stats(self):
        return {"closed": False, "queued": 0, "outstanding_tokens": 10,
                "decode_tps_ema": 1000.0}

    def prefix_peek(self, prompt):
        return 0


class _FakeReplica:
    def __init__(self, idx, role=None):
        self.idx = idx
        self.role = role
        self.engine = _FakeEngine()


@pytest.fixture
def health_on():
    core_flags.set_flags({"FLAGS_health": True,
                          "FLAGS_health_interval_s": 0.0})
    yield
    core_flags.set_flags({"FLAGS_health": False,
                          "FLAGS_health_interval_s": 1.0})


class TestRouterHealthActions:
    def test_critical_refuses_new_admissions(self, health_on):
        router = Router()
        router.health = _FakeHealth("critical")
        before = counters.snapshot()
        with pytest.raises(RetryAfter) as ei:
            router.pick([_FakeReplica(0)], est_tokens=4)
        assert ei.value.reason == "health"
        d = counters.delta(before)
        assert d.get("serving.fleet.health_shed", 0) == 1
        assert d.get("serving.fleet.shed", 0) == 1

    def test_critical_still_routes_replays(self, health_on):
        router = Router()
        router.health = _FakeHealth("critical")
        rep = _FakeReplica(0)
        assert router.pick([rep], est_tokens=4, shed=False) is rep

    def test_degraded_tightens_slo_margin(self, health_on):
        """deadline budget sits between the plain estimate and the
        degraded-factor estimate: ok-level admits, degraded sheds."""
        router = Router(slo_margin=1.0, degraded_factor=10.0)
        rep = _FakeReplica(0)
        # est_done = (10 + 10) / 1000 = 0.02s; budget 0.05s admits at
        # margin 1.0 but sheds at margin 10.0
        router.health = _FakeHealth("ok")
        assert router.pick([rep], est_tokens=10, deadline_s=0.05) is rep
        router.health = _FakeHealth("degraded")
        with pytest.raises(RetryAfter) as ei:
            router.pick([rep], est_tokens=10, deadline_s=0.05)
        assert ei.value.reason == "slo"

    def test_health_off_flag_disables_actions(self):
        """FLAGS_health off: a critical monitor changes nothing."""
        router = Router()
        router.health = _FakeHealth("critical")
        rep = _FakeReplica(0)
        assert router.pick([rep], est_tokens=4) is rep

    def test_role_filter_with_unified_fallback(self, health_on):
        router = Router()
        pre, dec = _FakeReplica(0, "prefill"), _FakeReplica(1, "decode")
        uni = _FakeReplica(2)
        assert router.pick([pre, dec], role="decode") is dec
        assert router.pick([pre, dec], role="prefill") is pre
        # no replica of the requested role → unified fallback
        assert router.pick([pre, uni], role="decode") is uni
        # nothing matching at all → degrade to the full list
        assert router.pick([pre], role="decode") is pre


# -- autoscaler --------------------------------------------------------------
class TestAutoscaler:
    def _burn_fleet(self, model, rules, **autoscale_kw):
        from paddle_tpu.profiler.health import SLO
        return _fleet(model, autoscale=True,
                      autoscale_kw=dict(cooldown_ticks=1, ok_streak=100,
                                        **autoscale_kw),
                      health_kw=dict(rules=rules, interval_s=0.0),
                      prefill_chunk=8)

    def test_disaggregate_on_itl_burn_then_resolve(self, model,
                                                   health_on):
        """The acceptance loop: mixed long/short traffic on a UNIFIED
        fleet fires itl_burn; the autoscaler flips the least-loaded
        replica to prefill (disaggregate); with prefill interference off
        the decode path, the burn alert resolves — all inside one test,
        with the serving.autoscale.* counters proving the transition."""
        import time
        from paddle_tpu.profiler.health import SLO
        rng = np.random.default_rng(9)
        rules = [SLO("itl_burn", ("hist_p95", "serving.itl_ns"), 2e6,
                     windows=((0.5, 1.0),), min_count=4)]
        before = counters.snapshot()
        fleet = self._burn_fleet(model, rules)

        def submit(n, mx):
            p = rng.integers(1, 64, size=n).tolist()
            while True:
                try:
                    return fleet.submit(p, seed=3, max_new_tokens=mx)
                except RetryAfter:
                    fleet.pump()

        hs, t0 = [], time.monotonic()
        while time.monotonic() - t0 < 60:
            hs.append(submit(48, 12))
            hs.append(submit(6, 12))
            for _ in range(4):
                fleet.pump()
            if counters.get("serving.autoscale.decisions.disaggregate") \
                    > before.get(
                        "serving.autoscale.decisions.disaggregate", 0):
                break
        d = counters.delta(before)
        assert d.get("health.alerts.fired.itl_burn", 0) >= 1
        assert d.get("serving.autoscale.decisions.disaggregate", 0) >= 1
        assert fleet.stats()["roles"]["prefill"] == 1
        t1 = time.monotonic()
        while time.monotonic() - t1 < 60:
            hs.append(submit(6, 12))
            for _ in range(6):
                fleet.pump()
            if counters.delta(before).get(
                    "health.alerts.resolved.itl_burn", 0):
                break
        fleet.join(hs)
        fleet.drain()
        d = counters.delta(before)
        assert d.get("health.alerts.resolved.itl_burn", 0) >= 1
        assert d.get("serving.autoscale.flips.to_prefill", 0) >= 1
        assert d.get("serving.fleet.migrate.requests", 0) > 0
        assert d.get("serving.fleet.lost", 0) == 0
        assert all(h.finish_reason == "length" for h in hs)

    def test_grow_prefill_spawns_then_retires(self, model, health_on):
        """ttft_burn on an already-disaggregated fleet grows the prefill
        pool (spawn: the single decode replica is at its floor); once
        the alert clears, the ok-streak retires the spawned replica."""
        import time
        from paddle_tpu.profiler.health import SLO
        rng = np.random.default_rng(10)
        rules = [SLO("ttft_burn", ("hist_p95", "serving.ttft_ns"), 1.0,
                     windows=((0.4, 1.0),), min_count=2)]
        before = counters.snapshot()
        fleet = _fleet(model, prefill_replicas=1, autoscale=True,
                       autoscale_kw=dict(cooldown_ticks=0, ok_streak=2,
                                         max_replicas=3),
                       health_kw=dict(rules=rules, interval_s=0.0))
        hs, t0 = [], time.monotonic()
        while time.monotonic() - t0 < 60:
            p = rng.integers(1, 64, size=24).tolist()
            try:
                hs.append(fleet.submit(p, seed=1, max_new_tokens=4))
            except RetryAfter:
                pass
            fleet.pump()
            if counters.delta(before).get(
                    "serving.autoscale.spawns", 0):
                break
        d = counters.delta(before)
        assert d.get("serving.autoscale.spawns", 0) >= 1
        assert d.get("serving.autoscale.decisions.grow_prefill", 0) >= 1
        assert fleet.stats()["roles"]["prefill"] == 2
        fleet.join(hs)
        # drain the burn: 1ns target can never resolve while samples
        # arrive, so stop traffic — the window empties, the rule
        # abstains, the alert resolves, and the ok-streak retires
        t1 = time.monotonic()
        while time.monotonic() - t1 < 60:
            fleet.pump()
            if counters.delta(before).get("serving.autoscale.retires", 0):
                break
        d = counters.delta(before)
        assert d.get("serving.autoscale.retires", 0) >= 1
        assert fleet.stats()["roles"]["prefill"] == 1
        fleet.drain()
        assert counters.delta(before).get("serving.fleet.lost", 0) == 0

    def test_kv_spill_burn_disaggregates_then_grows_decode(self, model):
        """Sustained spill-rate burn is a capacity signal: a unified
        fleet disaggregates (the split frees decode-side arena), an
        already-split fleet flips surplus prefill capacity to decode."""
        before = counters.snapshot()
        uni = _fleet(model, autoscale=True)
        uni.health.firing_names = lambda: {"kv_spill_burn"}
        assert uni.autoscaler._evaluate() == "disaggregate"
        assert uni.stats()["roles"]["prefill"] == 1
        uni.drain()
        dis = _fleet(model, replicas=3, prefill_replicas=2,
                     autoscale=True)
        dis.health.firing_names = lambda: {"kv_spill_burn"}
        assert dis.autoscaler._evaluate() == "grow_decode"
        assert dis.stats()["roles"] == \
            {"prefill": 1, "decode": 2, "unified": 0}
        dis.drain()
        d = counters.delta(before)
        assert d.get("serving.autoscale.decisions.disaggregate", 0) == 1
        assert d.get("serving.autoscale.decisions.grow_decode", 0) == 1
        assert d.get("serving.autoscale.flips.to_decode", 0) >= 2

    def test_inert_when_health_off(self, model):
        """FLAGS_health off: maybe_scale is a no-op and no autoscale
        counter moves (the zero-overhead-off gate)."""
        before = counters.snapshot()
        fleet = _fleet(model, autoscale=True)
        assert fleet.autoscaler.maybe_scale() is None
        hs = [fleet.submit([1, 2, 3], seed=0, max_new_tokens=4)]
        fleet.join(hs)
        fleet.drain()
        d = counters.delta(before)
        assert d.get("serving.autoscale.decisions", 0) == 0
        assert d.get("serving.autoscale.flips.to_prefill", 0) == 0


# -- host-RAM KV tier on the migration path ----------------------------------
def _engine(m, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("min_bucket", 4)
    kw.setdefault("block_size", BS)
    kw.setdefault("prefill_chunk", 16)
    return LLMEngine(m, kv_layout="paged", **kw)


def _engine_reconciles(eng):
    pool = eng.pool
    live = sum(1 for b in range(1, len(pool._ref)) if pool._ref[b] > 0)
    return len(pool._free) + live == pool.capacity


def _ref_tokens(m, prompt, seed, max_new):
    eng = _engine(m)
    h = eng.add_request(prompt, max_new_tokens=max_new, seed=seed)
    while not h.is_finished:
        eng.step()
    return h.tokens


class TestHeldRequestSpill:
    """A request parked ``"held"`` past ``spill_idle_steps`` demotes its
    KV to the host tier (freeing device blocks for live traffic); the
    export that finally migrates it pages everything back first — or
    raises ``HostTierLost`` when the host copy is gone, with both tiers
    reconciled and nothing torn."""

    def test_idle_spill_then_export_restores_and_migrates(self, model):
        rng = np.random.default_rng(30)
        prompt = rng.integers(1, 64, size=27).tolist()  # 3 full + partial
        ref = _ref_tokens(model, prompt, seed=5, max_new=6)
        before = counters.snapshot()
        src = _engine(model, host_kv_blocks=16, spill_idle_steps=2)
        dst = _engine(model)
        req = src.add_request(prompt, max_new_tokens=6, seed=5,
                              hold_after_prefill=True)
        for _ in range(8):
            src.step()
        assert req.state == "held"
        d = counters.delta(before)
        n_data = blocks_for_tokens(len(prompt), BS)
        assert d.get("serving.kv.tier.spilled_blocks", 0) == n_data
        table = src._slot_blocks[req.slot]
        assert all(b == TRASH_BLOCK for b in table[:n_data])
        assert src._host_tier.resident == n_data
        mig = src.export_request(req)        # pages the KV back in
        assert all(b != TRASH_BLOCK for b in mig["table"][:n_data])
        assert src._host_tier.resident == 0
        d = counters.delta(before)
        assert d.get("serving.kv.tier.restored_blocks", 0) == n_data
        assert d.get("serving.kv.host_buf_reuse", 0) >= 0
        new_req, info = dst.adopt_migration(mig, src)
        src.finish_migrated(req)
        while not new_req.is_finished:
            dst.step()
        assert new_req.tokens == ref
        assert info["blocks_copied"] == n_data
        assert _engine_reconciles(src) and _engine_reconciles(dst)

    def test_kv_spill_drop_on_export_raises_hosttierlost(self, model):
        """Chaos: the spilled copy is dropped before the export can
        restore it.  ``HostTierLost`` surfaces (the fleet's replay
        signal), the tier empties, no device block was allocated for
        the lost data, and the pool reconciles after teardown."""
        rng = np.random.default_rng(31)
        prompt = rng.integers(1, 64, size=27).tolist()
        src = _engine(model, host_kv_blocks=16, spill_idle_steps=2)
        req = src.add_request(prompt, max_new_tokens=6, seed=5,
                              hold_after_prefill=True)
        for _ in range(8):
            src.step()
        assert src._host_tier.resident > 0
        before = counters.snapshot()
        free_before = src.pool.free_blocks
        with faultinject.fault_schedule(f"kv_spill_drop@{req.rid}"):
            with pytest.raises(HostTierLost):
                src.export_request(req)
            assert ("kv_spill_drop", req.rid) in faultinject.fired
        assert src._host_tier.resident == 0
        assert src.pool.free_blocks == free_before
        d = counters.delta(before)
        assert d.get("serving.kv.tier.spill_drops", 0) == \
            blocks_for_tokens(len(prompt), BS)
        assert d.get("serving.kv.tier.restored_blocks", 0) == 0
        src._finish(req, "dropped", [])
        src.prefix.clear()
        assert src.pool.free_blocks == src.pool.capacity

    def test_adopt_reenters_prefix_into_destination_tree(self, model):
        """Tentpole contract: a migrated prefix is shareable on the
        destination IMMEDIATELY after adopt — the next same-prefix
        prompt (or migration) resolves it from the radix tree without
        waiting for the request to finish and donate."""
        rng = np.random.default_rng(32)
        prompt = rng.integers(1, 64, size=27).tolist()
        src = _engine(model)
        dst = _engine(model)
        req = src.add_request(prompt, max_new_tokens=6, seed=5,
                              hold_after_prefill=True)
        while req.state != "held":
            src.step()
        new_req, _ = dst.adopt_migration(src.export_request(req), src)
        src.finish_migrated(req)
        n_full_tokens = (len(prompt) // BS) * BS
        # still mid-decode on dst, yet the full prompt blocks are shared
        assert new_req.state == "running"
        assert dst.prefix_peek(np.asarray(prompt, np.int32)) == \
            n_full_tokens
        while not new_req.is_finished:
            dst.step()
        assert new_req.tokens == _ref_tokens(model, prompt, 5, 6)
        assert _engine_reconciles(dst)

    def test_destination_exhausted_mid_adopt_tears_nothing(self, model):
        """Satellite: adopt against a pool that cannot host the table
        raises ``BlockPoolExhausted`` with NOTHING allocated on the
        destination and the source intact — the same payload then
        adopts cleanly elsewhere."""
        rng = np.random.default_rng(33)
        prompt = rng.integers(1, 64, size=27).tolist()
        src = _engine(model)
        tiny = _engine(model, n_blocks=3, prefix_cache=False)
        req = src.add_request(prompt, max_new_tokens=6, seed=5,
                              hold_after_prefill=True)
        while req.state != "held":
            src.step()
        mig = src.export_request(req)
        before = counters.snapshot()
        free_before = tiny.pool.free_blocks
        with pytest.raises(BlockPoolExhausted):
            tiny.adopt_migration(mig, src)
        assert tiny.pool.free_blocks == free_before
        assert all(r is None for r in tiny._slots)
        assert counters.delta(before).get(
            "serving.kv.pool_exhausted", 0) == 1
        # the source never moved: the same export adopts cleanly
        dst = _engine(model)
        new_req, _ = dst.adopt_migration(mig, src)
        src.finish_migrated(req)
        while not new_req.is_finished:
            dst.step()
        assert new_req.tokens == _ref_tokens(model, prompt, 5, 6)

    def test_int8_partial_block_scale_rows_survive_tier_roundtrip(
            self, model):
        """Satellite: an int8 arena spills fp32 scale rows alongside
        the quantised tiles.  A held request whose last block is
        partial round-trips through the host tier, migrates, and the
        destination's scale rows match the source bit for bit."""
        rng = np.random.default_rng(34)
        prompt = rng.integers(1, 64, size=27).tolist()  # partial of 3
        ref_eng = _engine(model, kv_dtype="int8")
        hr = ref_eng.add_request(prompt, max_new_tokens=6, seed=5)
        while not hr.is_finished:
            ref_eng.step()
        src = _engine(model, kv_dtype="int8", host_kv_blocks=16,
                      spill_idle_steps=2)
        dst = _engine(model, kv_dtype="int8")
        req = src.add_request(prompt, max_new_tokens=6, seed=5,
                              hold_after_prefill=True)
        for _ in range(8):
            src.step()
        n_data = blocks_for_tokens(len(prompt), BS)
        assert src._host_tier.resident == n_data       # scales spilled too
        mig = src.export_request(req)
        new_req, _ = dst.adopt_migration(mig, src)
        sk_src = np.asarray(src._sk)
        sk_dst = np.asarray(dst._sk)
        dtable = dst._slot_blocks[new_req.slot]
        pos = int(mig["pos"])
        for i in range(n_data):
            valid = min(BS, pos - i * BS)              # partial last block
            assert np.array_equal(sk_src[:, mig["table"][i], :valid],
                                  sk_dst[:, dtable[i], :valid]), \
                f"scale rows of block {i} diverged"
        src.finish_migrated(req)
        while not new_req.is_finished:
            dst.step()
        assert new_req.tokens == hr.tokens
        assert _engine_reconciles(src) and _engine_reconciles(dst)

    def test_fleet_rolls_up_tier_stats(self, model):
        """Fleet stats aggregate the per-engine tier view; a tiered
        disaggregated stream completes with zero lost requests."""
        rng = np.random.default_rng(35)
        prompts = _prompts(rng, (24, 9, 40, 17))
        before = counters.snapshot()
        fleet = _fleet(model, prefill_replicas=1, host_kv_blocks=16)
        hs = [fleet.submit(p, seed=i, max_new_tokens=6)
              for i, p in enumerate(prompts)]
        fleet.join(hs)
        st = fleet.stats()["kv"]
        fleet.drain()
        assert st["host_tier_capacity"] == 16 * 2      # both replicas
        assert st["host_tier_blocks"] >= 0
        assert {"host_arena_bytes", "tier_spilled",
                "tier_restored"} <= set(st)
        d = counters.delta(before)
        assert d.get("serving.fleet.lost", 0) == 0
        assert all(h.finish_reason == "length" for h in hs)
