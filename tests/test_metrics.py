"""Telemetry subsystem (profiler.metrics / profiler.flight).

The four contracts from the observability tentpole:

  * histogram math — the shared log2-bucket layout gives exact
    count/sum/min/max, percentiles with bounded (<=2x) relative error,
    and element-wise mergeability (thread/replica histograms combine into
    the same numbers as one histogram fed everything);
  * zero-sync train metrics — ``CompiledTrainStep(metrics=...)``
    accumulates device scalars inside the donated carry and harvests them
    only at sync boundaries: metrics ON adds zero ``jit.syncs`` /
    ``jit.traces`` / extra dispatches to a steady loop (the same gate
    ``scripts/check_counters.py`` enforces end-to-end);
  * concurrency — counters, the global histogram registry and host-tracer
    spans stay exact under concurrent writer threads;
  * flight recorder — faults leave a postmortem bundle; a killed fleet
    replica's dump names its in-flight request ids (THE chaos hook).
"""

import json
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.jit as pjit
import paddle_tpu.nn as nn
from paddle_tpu.core import flags as core_flags
from paddle_tpu.profiler import counters, flight, host_tracer, metrics
from paddle_tpu.profiler.metrics import Histogram, MetricsLogger


@pytest.fixture(autouse=True)
def _restore_trace_flags():
    level = core_flags.flag("FLAGS_host_trace_level")
    yield
    core_flags.set_flags({"FLAGS_host_trace_level": level})
    if host_tracer.is_collecting():
        host_tracer.stop()


class TestHistogram:
    def test_exact_stats(self):
        h = Histogram("t", "ns")
        for v in (1.0, 2.0, 4.0, 8.0):
            h.record(v)
        assert h.count == 4
        assert h.sum == 15.0
        assert h.min == 1.0 and h.max == 8.0
        assert h.mean == pytest.approx(3.75)

    def test_single_value_percentiles_exact(self):
        for v in (1.0, 3.7, 1e6, 123456.0):
            h = Histogram()
            h.record(v)
            s = h.summary()
            assert s["p50"] == s["p95"] == s["p99"] == v

    def test_percentile_bounded_relative_error(self):
        import math
        rng = np.random.RandomState(0)
        vals = np.sort(rng.lognormal(mean=12.0, sigma=2.0, size=2000))
        h = Histogram()
        for v in vals:
            h.record(v)
        for q in (50, 95, 99):
            got = h.percentile(q)
            # nearest-rank reference: the exact order statistic the
            # bucket walk targets; log2 buckets bound the answer to the
            # bucket holding it, whose geometric midpoint is within
            # sqrt(2)x of any member
            true = float(vals[max(1, math.ceil(q / 100 * len(vals))) - 1])
            assert true / 2 <= got <= true * 2, (q, got, true)
        s = h.summary()
        assert s["p50"] <= s["p95"] <= s["p99"] <= s["max"]
        assert s["min"] <= s["p50"]

    def test_merge_matches_single_histogram(self):
        rng = np.random.RandomState(1)
        a, b = rng.uniform(1, 1e6, 50), rng.uniform(1e3, 1e9, 70)
        h1, h2, ref = Histogram("m"), Histogram("m"), Histogram("m")
        for v in a:
            h1.record(v)
            ref.record(v)
        for v in b:
            h2.record(v)
            ref.record(v)
        h1.merge(h2)
        assert h1.summary() == ref.summary()

    def test_empty_summary_is_zeros(self):
        s = Histogram().summary()
        assert s == {"count": 0, "sum": 0.0, "mean": 0.0, "min": 0.0,
                     "max": 0.0, "p50": 0.0, "p95": 0.0, "p99": 0.0}
        assert Histogram().percentile(99) == 0.0

    def test_zero_and_negative_absorbed_by_bucket_zero(self):
        h = Histogram()
        h.record(0.0)
        h.record(-5.0)
        assert h.count == 2
        assert h.min == -5.0 and h.max == 0.0
        # percentiles clamp to the observed range, never invent positives
        assert h.percentile(50) <= 0.0

    def test_to_dict_from_dict_roundtrip_and_merge(self):
        h = Histogram("serving.ttft_ns", "ns")
        for v in (10.0, 1e6, 3e6, 5e9):
            h.record(v)
        d = json.loads(json.dumps(h.to_dict()))  # wire-format safe
        back = Histogram.from_dict(d)
        assert back.name == h.name and back.unit == h.unit
        assert back.summary() == h.summary()
        # a deserialized histogram still merges element-wise
        ref = h.copy().merge(h)
        assert back.merge(h).summary() == ref.summary()

    def test_copy_is_independent(self):
        h = Histogram()
        h.record(1.0)
        c = h.copy()
        h.record(100.0)
        assert c.count == 1 and h.count == 2


class TestRegistry:
    def test_get_histogram_is_singleton(self):
        a = metrics.get_histogram("test.reg.one", "ns")
        b = metrics.get_histogram("test.reg.one")
        assert a is b

    def test_observe_sum_counter_feeds_legacy_counter(self):
        before = counters.snapshot()
        metrics.observe("test.reg.lat_ns", 1000, unit="ns", sum_counter=True)
        metrics.observe("test.reg.lat_ns", 2500, unit="ns", sum_counter=True)
        d = counters.delta(before)
        assert d.get("test.reg.lat_ns") == 3500
        h = metrics.get_histogram("test.reg.lat_ns")
        assert h.count >= 2 and h.sum >= 3500

    def test_observe_extra_records_caller_scoped(self):
        local = Histogram("test.reg.extra", "ns")
        metrics.observe("test.reg.extra", 42.0, extra=local)
        assert local.count == 1 and local.sum == 42.0
        assert metrics.get_histogram("test.reg.extra").count >= 1

    def test_histogram_summaries_skips_empty(self):
        metrics.get_histogram("test.reg.never_recorded")
        metrics.observe("test.reg.recorded", 7.0)
        s = metrics.histogram_summaries()
        assert "test.reg.never_recorded" not in s
        assert s["test.reg.recorded"]["count"] >= 1


class TestMetricsLogger:
    def test_jsonl_schema_series_and_summary(self, tmp_path):
        path = tmp_path / "train.jsonl"
        with MetricsLogger(path, run="r0") as log:
            log.log(step=1, loss=2.5, lr=1e-4)
            log.log(step=2, loss=2.0, lr=1e-4, grad_norm=0.7)
            log.log(step=3, loss=1.5, lr=1e-4, mfu=None)  # None dropped
        lines = [json.loads(l) for l in path.read_text().splitlines()]
        assert len(lines) == 3
        for rec, step in zip(lines, (1, 2, 3)):
            assert rec["run"] == "r0" and rec["step"] == step
            assert isinstance(rec["ts"], float) and "loss" in rec
        assert "mfu" not in lines[2]
        assert log.series("loss") == [(1, 2.5), (2, 2.0), (3, 1.5)]
        assert log.latest("loss") == 1.5
        assert log.latest("absent", default=-1) == -1
        assert log.names() == ["grad_norm", "loss", "lr"]
        s = log.summary()
        assert s["loss"] == {"count": 3, "last": 1.5, "mean": 2.0,
                             "min": 1.5, "max": 2.5}
        assert s["grad_norm"]["count"] == 1

    def test_memory_only_logger(self):
        log = MetricsLogger()
        log.log(step=0, loss=1.0)
        assert log.path is None and log.latest("loss") == 1.0

    def test_prometheus_text_exposition(self):
        counters.inc("test.prom.counter", 3)
        metrics.observe("test.prom.hist_ns", 1e6, unit="ns")
        metrics.observe("test.prom.hist_ns", 3e6, unit="ns")
        log = MetricsLogger()
        log.log(step=5, loss=1.25)
        text = metrics.prometheus_text(log)
        assert "# TYPE ptpu_test_prom_counter counter" in text
        assert "ptpu_test_prom_counter 3" in text
        # spec-conformant histogram: cumulative le-buckets + sum/count
        # (aggregatable across replicas), quantiles as a gauge family
        assert "# TYPE ptpu_test_prom_hist_ns histogram" in text
        assert 'ptpu_test_prom_hist_ns_bucket{le="+Inf"} 2' in text
        assert "ptpu_test_prom_hist_ns_sum 4000000.0" in text
        assert "ptpu_test_prom_hist_ns_count 2" in text
        assert "# TYPE ptpu_test_prom_hist_ns_quantile gauge" in text
        assert 'ptpu_test_prom_hist_ns_quantile{quantile="0.5"}' in text
        assert "# TYPE ptpu_metric_loss gauge" in text
        assert "ptpu_metric_loss 1.25" in text
        # cumulative bucket counts are monotone and end at the count
        cums = [int(line.rsplit(" ", 1)[1]) for line in text.splitlines()
                if line.startswith("ptpu_test_prom_hist_ns_bucket")]
        assert cums == sorted(cums) and cums[-1] == 2


class TestConcurrency:
    N_THREADS, N_ITERS = 8, 400

    def test_counters_and_histograms_exact_under_threads(self):
        before = counters.snapshot()
        local = Histogram("test.conc.local")

        def worker(tid):
            for i in range(self.N_ITERS):
                counters.inc("test.conc.total")
                metrics.observe("test.conc.lat_ns", i + 1,
                                sum_counter=True, extra=local)

        ts = [threading.Thread(target=worker, args=(t,))
              for t in range(self.N_THREADS)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        n = self.N_THREADS * self.N_ITERS
        per_thread_sum = self.N_ITERS * (self.N_ITERS + 1) // 2
        d = counters.delta(before)
        assert d.get("test.conc.total") == n
        assert d.get("test.conc.lat_ns") == self.N_THREADS * per_thread_sum
        assert local.count == n
        assert local.sum == self.N_THREADS * per_thread_sum
        assert local.min == 1.0 and local.max == self.N_ITERS
        g = metrics.get_histogram("test.conc.lat_ns")
        assert g.count == n

    def test_tracer_spans_and_counters_concurrent(self):
        core_flags.set_flags({"FLAGS_host_trace_level": 1})
        host_tracer.start()
        before = counters.snapshot()

        barrier = threading.Barrier(4)  # overlap: tids stay distinct

        def worker():
            barrier.wait()
            for _ in range(50):
                with host_tracer.span("conc_span"):
                    counters.inc("test.conc.spans")

        try:
            ts = [threading.Thread(target=worker) for _ in range(4)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        finally:
            evts = host_tracer.stop()
        spans = [e for e in evts if e[0] == "conc_span"]
        assert len(spans) == 200
        assert len({e[1] for e in spans}) == 4      # one tid per thread
        assert counters.delta(before).get("test.conc.spans") == 200


class TestFlightRecorder:
    def test_ring_capacity_and_dump_schema(self, tmp_path):
        flight.configure(directory=tmp_path, capacity=4)
        try:
            flight.clear()
            for i in range(6):
                flight.record("test.ev", i=i)
            evs = flight.events()
            assert len(evs) == 4                    # ring dropped oldest
            assert [f["i"] for _, _, f in evs] == [2, 3, 4, 5]
            counters.inc("test.flight.moved", 9)
            metrics.observe("test.flight.hist", 3.0)
            before = counters.snapshot()
            path = flight.dump("unit_test", {"answer": 42})
            assert flight.last_dump_path() == path
            d = counters.delta(before)
            assert d.get("flight.dumps") == 1
            assert d.get("flight.dumps.unit_test") == 1
            b = flight.load(path)
            assert b["reason"] == "unit_test"
            assert b["context"] == {"answer": 42}
            assert b["counters_delta"].get("test.flight.moved") == 9
            assert b["histograms"]["test.flight.hist"]["count"] >= 1
            assert [e["kind"] for e in b["events"]] == ["test.ev"] * 4
            assert all("ts_ns" in e for e in b["events"])
        finally:
            flight.configure(directory="", capacity=flight._DEFAULT_CAPACITY)
            flight.clear()

    def test_record_point_feeds_ring(self):
        flight.clear()
        flight.record_point("loss", 2.5, step=7)
        ts, kind, fields = flight.events()[-1]
        assert kind == "metric"
        assert fields == {"name": "loss", "value": 2.5, "step": 7}


def _tiny_step(metrics_arg, fused_steps=1):
    paddle.seed(5)
    net = nn.Sequential(nn.Linear(8, 16), nn.GELU(), nn.Linear(16, 4))
    opt = paddle.optimizer.AdamW(1e-3, parameters=net.parameters())

    def loss_fn(m, a, b):
        return ((m(a) - b) ** 2).mean()

    step = pjit.CompiledTrainStep(net, loss_fn, opt,
                                  fused_steps=fused_steps,
                                  metrics=metrics_arg)
    x = paddle.randn([4, 8])
    y = paddle.randn([4, 4])
    return step, x, y


class TestTrainStepMetrics:
    def test_metrics_on_adds_zero_syncs_or_traces(self, tmp_path):
        log = MetricsLogger(tmp_path / "t.jsonl")
        step, x, y = _tiny_step(log)
        step(x, y)                     # warm: hydrate + compile
        step(x, y)                     # accumulator-structure retrace
        step.metrics_flush()
        before = counters.snapshot()
        step(x, y)
        step(x, y)
        step.metrics_flush()           # harvest inside the steady window
        d = counters.delta(before)
        assert d.get("jit.syncs", 0) == 0
        assert d.get("jit.traces", 0) == 0
        assert d.get("jit.hydrates", 0) == 0
        assert d.get("jit.host.dispatches", 0) == 2
        # the harvest delivered real per-step series anyway
        assert len(log.series("loss")) == 4
        assert all(np.isfinite(v) for _, v in log.series("loss"))
        assert len(log.series("grad_norm")) == 4
        assert log.latest("lr") == pytest.approx(1e-3)

    def test_donated_accumulator_gauges(self):
        log = MetricsLogger()
        step, x, y = _tiny_step(log)
        for _ in range(3):
            step(x, y)
        step.metrics_flush()
        assert counters.get("train.steps_accum") == 3
        loss_mean = counters.get("train.loss_mean")
        series_mean = np.mean([v for _, v in log.series("loss")])
        assert loss_mean == pytest.approx(series_mean, rel=1e-5)

    def test_fused_window_per_step_records(self):
        from paddle_tpu.io import Window
        k = 2
        log = MetricsLogger()
        step, x, y = _tiny_step(log, fused_steps=k)
        wx = paddle.to_tensor(np.stack([np.asarray(x.numpy())] * k))
        wy = paddle.to_tensor(np.stack([np.asarray(y.numpy())] * k))
        win = Window((wx, wy), k)
        step(win)                      # priming single-step fallback
        step(win)                      # scan compile
        step.metrics_flush()
        n0 = len(log.series("loss"))
        before = counters.snapshot()
        step(win)                      # steady: ONE dispatch, k records
        step.metrics_flush()
        d = counters.delta(before)
        assert d.get("jit.host.dispatches", 0) == 1
        assert d.get("jit.syncs", 0) == 0 and d.get("jit.traces", 0) == 0
        pts = log.series("loss")[n0:]
        assert len(pts) == k
        steps = [s for s, _ in pts]
        assert steps == sorted(steps) and len(set(steps)) == k

    def test_sync_boundary_flushes_automatically(self):
        log = MetricsLogger()
        step, x, y = _tiny_step(log)
        step(x, y)
        step.sync()                    # existing boundary harvests pending
        assert len(log.series("loss")) == 1


@pytest.fixture(scope="module")
def model():
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=32,
                    use_flash_attention=False)
    paddle.seed(31)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


def _fleet(m, **kw):
    from paddle_tpu.serving import ServingFleet
    kw.setdefault("replicas", 2)
    kw.setdefault("threaded", False)
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_seq_len", 32)
    kw.setdefault("min_bucket", 4)
    kw.setdefault("queue_size", 16)
    kw.setdefault("heartbeat_timeout_s", 30.0)
    return ServingFleet(m, **kw)


class TestServingTelemetry:
    def test_engine_latency_histograms(self, model):
        from paddle_tpu.serving import LLMEngine
        eng = LLMEngine(model, max_slots=2, max_seq_len=32, min_bucket=4)
        rng = np.random.default_rng(7)
        prompts = [rng.integers(0, 64, size=n).tolist() for n in (5, 3)]
        for _ in eng.generate(prompts, max_new_tokens=4):
            pass
        snap = eng.histogram_snapshot()
        assert snap["serving.ttft_ns"].count == 2       # one TTFT/request
        assert snap["serving.itl_ns"].count == 2 * 3    # max_new-1 each
        assert snap["serving.queue_wait_ns"].count == 2
        assert snap["serving.prefill_occupancy"].count >= 1
        assert 0.0 < snap["serving.decode_occupancy"].max <= 1.0
        # snapshot copies are decoupled from the live engine histograms
        snap["serving.ttft_ns"].record(1.0)
        assert eng.hists["serving.ttft_ns"].count == 2

    def test_fleet_chaos_dump_names_inflight_rids(self, model, tmp_path):
        """THE chaos acceptance hook: kill a replica mid-decode and the
        flight dump must name the killed replica and the request ids it
        had in flight — while the fleet still finishes every request."""
        from paddle_tpu.resilience import faultinject
        rng = np.random.default_rng(9)
        p0 = rng.integers(0, 64, size=5).tolist()
        p1 = rng.integers(0, 64, size=6).tolist()
        fleet = _fleet(model, max_slots=1, warm_buckets=(5,))
        flight.configure(directory=tmp_path)
        flight.clear()
        try:
            h0 = fleet.submit(p0, max_new_tokens=6)
            h1 = fleet.submit(p1, max_new_tokens=6)
            killed_idx = h0.replica_idx    # retry may reassign h0 later
            with faultinject.fault_schedule(f"replica_crash@{h0.rid}"):
                fleet.join([h0, h1], timeout_s=120)
            assert h0.finish_reason == "length"
            assert h1.finish_reason == "length"
            path = flight.last_dump_path()
            assert path is not None, "replica death left no flight dump"
            b = flight.load(path)
            assert b["reason"] == "replica_died"
            ctx = b["context"]
            assert ctx["reason"] == "crash"
            assert ctx["replica"] == killed_idx
            assert h0.rid in ctx["fleet_rids"]
            assert ctx["in_flight_rids"], "dump lost the in-flight set"
            # fleet-wide latency aggregation still sees both requests
            lat = fleet.stats()["latency"]
            assert lat["serving.ttft_ns"]["count"] >= 2
        finally:
            flight.configure(directory="")
            flight.clear()
            fleet.drain()
